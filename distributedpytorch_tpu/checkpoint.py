"""Checkpoint / resume (ref utils.py:112-140 + classif.py:141-147,176-192).

Same five logical fields as the reference's torch.save dict
(ref utils.py:114-120): model_name, model state (params + batch_stats),
optimizer state, epoch, best valid loss — serialized with flax msgpack
into a single self-describing file.  Contract parity:

  * ``test -f FILE`` discovers the architecture from the file's
    ``model_name`` field (ref classif.py:214, utils.py:138-140);
  * resume restores model+optimizer and continues at ``epoch + 1`` with the
    saved best loss (ref utils.py:123-136, classif.py:143-147);
  * rolling per-epoch file + separate best file (ref classif.py:182-192),
    with the rotation actually deleting the previous epoch's file —
    the reference's delete path omits the model name from the filename and
    never matches (SURVEY defect #5).

Divergences (improvements, documented): writes are atomic (tmp+rename);
checkpoints are written from *unwrapped, replicated* state, so a checkpoint
trained on N chips loads anywhere (the reference saves DDP ``module.``-
prefixed keys that only load back into a DDP wrapper — SURVEY defect #11).

Two formats behind one API (``--ckpt-format``):
  * ``msgpack`` (default): single self-describing file, the
    reference-contract format above; sharded state is all-gathered
    (collectively) before the main process writes.
  * ``orbax``: a checkpoint DIRECTORY written by orbax's
    StandardCheckpointer — sharded params/optimizer state are saved
    AS-LAID-OUT, no gather, which is the TPU-native shape of
    checkpointing once --model-parallel states outgrow one host.  The
    five logical fields are preserved (meta.json + the state tree);
    ``test -f DIR`` and resume work identically.  Multi-process
    coordination (every host writing shards into the SAME directory, with
    the barrier'd atomic swap below) is exercised for real — 2 processes
    x 2 devices with model-parallel sharding, including kill-and-resume
    and cross-topology restores — in tests/test_ckpt_topology.py; the
    path must live on a filesystem all hosts share (warned at save time).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue as queue_mod
import re
import shutil
import threading
from typing import Callable, List, Optional, Tuple

import jax
import numpy as np
from flax import serialization
from jax.sharding import NamedSharding, PartitionSpec

from . import faults, goodput, runtime, telemetry
from .models import scan as model_scan
from .train.engine import TrainState

_FORMAT_VERSION = 1
_ORBAX_META = "meta.json"
_LINEAGE = "ckpt-lineage.json"

# Restore-path transient classification: FileNotFoundError (an OSError)
# must NOT be retried — a missing file never appears by waiting — and
# the read sites use this narrowed tuple instead of faults.TRANSIENT.
_READ_TRANSIENT = (PermissionError, InterruptedError, TimeoutError,
                   faults.InjectedIOError)


def gather_replicated(state: TrainState) -> TrainState:
    """Make every array fully replicated before host transfer.

    With --model-parallel, params/opt-state live sharded over the 'model'
    mesh axis; on multi-host meshes ``jax.device_get`` of such arrays would
    fail (non-addressable shards).  A jitted identity with replicated
    out_shardings performs the all-gather as an XLA program.  No-op (and no
    dispatch) for the default replicated layout.

    COLLECTIVE on multi-host meshes: when any leaf is sharded over a mesh
    spanning multiple processes, EVERY process must call this (the program
    runs on all the mesh's devices) — drivers call it un-gated and then
    gate only the file write on ``is_main()``.
    """
    leaves = [a for a in jax.tree_util.tree_leaves(state)
              if isinstance(a, jax.Array)]
    if all(getattr(a, "is_fully_replicated", True) for a in leaves):
        return state
    mesh = next(a.sharding.mesh for a in leaves
                if isinstance(a.sharding, NamedSharding))
    replicated = NamedSharding(mesh, PartitionSpec())
    gather = jax.jit(lambda x: x, out_shardings=replicated)

    def _one(a):
        # Leaf-by-leaf, not one whole-tree program: bounds the transient
        # HBM spike to sharded-state + ONE replicated tensor, instead of
        # re-materializing the full unsharded state (the exact footprint
        # --model-parallel exists to avoid) on every device at save time.
        if isinstance(a, jax.Array) and not a.is_fully_replicated:
            with runtime.sanctioned_host_transfer():  # snapshot sync
                return jax.device_get(gather(a))
        return a

    return jax.tree_util.tree_map(_one, state)


def checkpoint_path(rsl_path: str, dataset: str, model_name: str,
                    epoch: int) -> str:
    # ref classif.py:186: rsl/checkpoint-mnist-{model}-{epoch:03d}.pt.tar
    return os.path.join(
        rsl_path, f"checkpoint-{dataset}-{model_name}-{epoch:03d}.ckpt")


def best_model_path(rsl_path: str, dataset: str, model_name: str) -> str:
    # ref classif.py:191: rsl/bestmodel-mnist-{model}.pt.tar
    return os.path.join(rsl_path, f"bestmodel-{dataset}-{model_name}.ckpt")


# -- checkpoint lineage: checksums, verify-on-load, fallback (ISSUE 5) --
#
# Every write records (file, epoch, checksum, bytes) into a rolling
# ledger next to the checkpoints (RSL_PATH/ckpt-lineage.json); loads
# verify the content against the recorded checksum BEFORE trusting it,
# and the resume path can walk the lineage back to the newest VALID
# snapshot when the head is torn or corrupt (loud log + telemetry event,
# never silent).  msgpack files get a full-content sha256; orbax
# directories get a structural checksum (sorted relpath:size listing of
# the payload files) in their meta.json — cheap at any scale and exactly
# what detects the realistic corruption (torn/partial/missing shard
# files), though not in-place bit flips of equal length.

_lineage_lock = threading.Lock()


def lineage_path(dirname: str) -> str:
    return os.path.join(dirname, _LINEAGE)


def _lineage_load(dirname: str) -> dict:
    try:
        with open(lineage_path(dirname)) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and isinstance(doc.get("records"), list):
            return doc
    except (OSError, ValueError):
        # absent or torn ledger: lineage degrades to "nothing recorded"
        # (loads then skip verification) rather than blocking a resume
        pass
    return {"records": []}


def _lineage_record(path: str, epoch: int, checksum: str,
                    nbytes: int) -> None:
    """Record one written checkpoint in the ledger (atomic rewrite);
    entries whose file has since been rotated away are pruned."""
    path = os.path.abspath(path)
    dirname, name = os.path.split(path)
    with _lineage_lock:
        doc = _lineage_load(dirname)
        records = [r for r in doc["records"]
                   if r.get("file") != name
                   and os.path.exists(os.path.join(dirname,
                                                   str(r.get("file"))))]
        records.append({"file": name, "epoch": int(epoch),
                        "sha256": checksum, "bytes": int(nbytes)})
        tmp = lineage_path(dirname) + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"records": records}, f, indent=1)
            os.replace(tmp, lineage_path(dirname))
        except OSError as e:
            # the ledger is recovery metadata, not training state: losing
            # an entry weakens verification, it must not fail the save
            logging.warning(f"cannot update checkpoint lineage ledger "
                            f"{lineage_path(dirname)!r}: {e}")


def _lineage_forget(path: str) -> None:
    """Drop a rotated-away checkpoint's ledger entry (atomic rewrite,
    same best-effort contract as ``_lineage_record``) so the ledger
    always mirrors what is actually on disk."""
    dirname, name = os.path.split(os.path.abspath(path))
    with _lineage_lock:
        doc = _lineage_load(dirname)
        records = [r for r in doc["records"] if r.get("file") != name]
        if len(records) == len(doc["records"]):
            return
        tmp = lineage_path(dirname) + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"records": records}, f, indent=1)
            os.replace(tmp, lineage_path(dirname))
        except OSError as e:
            # stale entry, not wrong results: the file is gone, so the
            # fallback walk never offers it anyway
            logging.warning(f"cannot update checkpoint lineage ledger "
                            f"{lineage_path(dirname)!r}: {e}")


def _lineage_entry(path: str) -> Optional[dict]:
    dirname, name = os.path.split(os.path.abspath(path))
    with _lineage_lock:
        doc = _lineage_load(dirname)
    for r in doc["records"]:
        if r.get("file") == name:
            return r
    return None


def _orbax_checksum(root: str) -> str:
    """Structural checksum of an orbax checkpoint directory: sha256 over
    the sorted relpath:size listing of every payload file (meta.json
    excluded, so the value can live inside meta.json itself)."""
    entries = []
    for dirpath, _, fnames in os.walk(root):
        for fn in fnames:
            if dirpath == root and fn == _ORBAX_META:
                continue
            full = os.path.join(dirpath, fn)
            entries.append(f"{os.path.relpath(full, root)}:"
                           f"{os.path.getsize(full)}")
    h = hashlib.sha256()
    for line in sorted(entries):
        h.update(line.encode())
        h.update(b"\n")
    return h.hexdigest()


def verify_checkpoint(path: str) -> Optional[str]:
    """None when the checkpoint matches its recorded checksum (or none
    was recorded — pre-lineage files stay loadable); otherwise a one-line
    reason string.  Never raises."""
    if os.path.isdir(path):
        try:
            with open(os.path.join(path, _ORBAX_META)) as f:
                meta = json.load(f)
        except (OSError, ValueError) as e:
            return f"unreadable {_ORBAX_META} ({e})"
        want = meta.get("checksum") if isinstance(meta, dict) else None
        if want is None:
            return None
        got = _orbax_checksum(os.path.abspath(path))
        if got != want:
            return (f"content checksum mismatch (recorded "
                    f"{want[:12]}…, found {got[:12]}…)")
        return None
    rec = _lineage_entry(path)
    if rec is None:
        return None
    try:
        with open(path, "rb") as f:
            got = hashlib.sha256(f.read()).hexdigest()
    except OSError as e:
        return f"cannot read ({e.strerror or e})"
    if got != rec.get("sha256"):
        return (f"content checksum mismatch (lineage records "
                f"{str(rec.get('sha256'))[:12]}…, found {got[:12]}…)")
    return None


def lineage_info(path: str) -> Optional[dict]:
    """The served-model identity for ``path``: ``{"file", "path",
    "sha256", "epoch"}`` (ISSUE 19 satellite).  The sha comes from the
    lineage ledger when recorded, else is computed from the content
    (pre-lineage files still get an identity); orbax directories use
    their meta.json structural checksum.  Surfaced on the serving
    tier's /livez + /healthz and stamped into trace records, so the
    front door and the canary verdict can see WHICH checkpoint each
    replica actually runs.  None only when the path is unreadable."""
    path = os.path.abspath(path)
    name = os.path.basename(path.rstrip(os.sep))
    if os.path.isdir(path):
        try:
            with open(os.path.join(path, _ORBAX_META)) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(meta, dict):
            return None
        sha = meta.get("checksum") or _orbax_checksum(path)
        return {"file": name, "path": path, "sha256": sha,
                "epoch": meta.get("epoch")}
    rec = _lineage_entry(path)
    if rec is not None and rec.get("sha256"):
        return {"file": name, "path": path,
                "sha256": rec["sha256"], "epoch": rec.get("epoch")}
    try:
        with open(path, "rb") as f:
            sha = hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return None
    return {"file": name, "path": path, "sha256": sha, "epoch": None}


def list_checkpoints(rsl_path: str, dataset: str,
                     model_name: str) -> List[str]:
    """Rolling checkpoint paths for (dataset, model) under ``rsl_path``,
    newest epoch first — the fallback candidates."""
    pat = re.compile(rf"checkpoint-{re.escape(dataset)}-"
                     rf"{re.escape(model_name)}-(\d+)\.ckpt")
    found = []
    try:
        names = os.listdir(rsl_path)
    except OSError:
        return []
    for name in names:
        m = pat.fullmatch(name)
        if m:
            found.append((int(m.group(1)),
                          os.path.join(rsl_path, name)))
    return [p for _, p in sorted(found, reverse=True)]


def newest_checkpoint(rsl_path: str, dataset: str,
                      model_name: str) -> Optional[str]:
    """Path of the newest rolling snapshot, or None when there is none.

    The elastic resume entry point (cli.py reconfigure path): survivors
    of a rank loss restore from here after re-initializing the smaller
    world.  This works across a WORLD-SIZE CHANGE by construction —
    snapshots are written from ``gather_replicated`` state
    (fully-replicated host arrays, no per-rank sharding in the file),
    so a checkpoint written by N ranks restores bit-identically into
    N-1; only the data sharding is re-derived, by the loader.
    Verification (lineage checksum) happens downstream in
    ``load_checkpoint_with_fallback`` — this just names the head.
    """
    ckpts = list_checkpoints(rsl_path, dataset, model_name)
    return ckpts[0] if ckpts else None


def load_checkpoint_with_fallback(path: str, state: TrainState,
                                  rsl_path: str, dataset: str,
                                  model_name: str,
                                  restore_optimizer: bool = True
                                  ) -> Tuple[TrainState, int, float]:
    """``load_checkpoint`` with lineage recovery: when the requested
    checkpoint is torn or corrupt, fall back — LOUDLY (error log +
    ``ckpt_fallback`` telemetry event per skipped snapshot, never
    silent) — to the newest valid earlier rolling snapshot."""
    tel = telemetry.get()
    seen = {os.path.abspath(path)}
    candidates = [path]
    for cand in list_checkpoints(rsl_path, dataset, model_name):
        if os.path.abspath(cand) not in seen:
            seen.add(os.path.abspath(cand))
            candidates.append(cand)
    errors = []
    for cand in candidates:
        reason = verify_checkpoint(cand)
        if reason is None:
            try:
                return load_checkpoint(cand, state, restore_optimizer)
            except ValueError as e:
                reason = str(e)
        errors.append(f"{cand}: {reason}")
        logging.error(f"CHECKPOINT REJECTED {cand!r}: {reason}"
                      + ("; falling back to an earlier snapshot"
                         if cand != candidates[-1] else ""))
        tel.event("ckpt_fallback", skipped=os.path.basename(cand),
                  reason=reason)
    detail = "; ".join(errors)
    raise ValueError(
        f"no valid checkpoint to resume from under {rsl_path!r} "
        f"(tried {len(candidates)}: {detail})")


def _msgpack_payload(model_name: str, state: TrainState, epoch: int,
                     best_valid_loss: float) -> dict:
    """The host-side snapshot: everything the file needs, with no live
    device buffers left in it (donation-safe once this returns)."""
    with runtime.sanctioned_host_transfer():  # checkpoint snapshot sync
        state_host = jax.device_get(gather_replicated(state))
    return {
        "format_version": _FORMAT_VERSION,
        "model_name": model_name,
        "epoch": int(epoch),
        "loss": float(best_valid_loss),
        "state": serialization.to_state_dict(state_host),
    }


def _write_msgpack(path: str, payload: dict) -> None:
    """Serialize + atomic tmp->rename write.  Pure host/file work — safe
    to run on a background thread (AsyncSaver); a crash at any point
    leaves the previous file at ``path`` intact.  Transient write errors
    are retried under the process retry policy; the full-content sha256
    is recorded in the lineage ledger for verify-on-load."""
    blob = serialization.msgpack_serialize(payload)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"

    def _attempt():
        faults.fire("ckpt.save", path=path)
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)

    faults.retry(_attempt, "ckpt.save")
    # Post-rename hook: the torn/preempt chaos kinds act on the FINAL
    # file, exactly like a failure landing after the atomic swap.
    faults.fire("ckpt.finalize", path=path)
    _lineage_record(path, payload["epoch"],
                    hashlib.sha256(blob).hexdigest(), len(blob))
    logging.info(f"epoch:{payload['epoch']:04d}: model saved to {path}")


def save_checkpoint(path: str, model_name: str, state: TrainState,
                    epoch: int, best_valid_loss: float,
                    fmt: str = "msgpack") -> None:
    """ref saveCheckpoint (utils.py:112-121); for msgpack the caller gates
    on is_main() — but on multi-host meshes the caller must run
    ``gather_replicated`` on every process FIRST and pass the gathered
    state (the internal call below is then a no-op; it only covers
    single-host callers).  For orbax, EVERY process calls this (each host
    writes its own shards) and no gather happens at all."""
    # Goodput: the sync save blocks the driver for its whole duration
    # (ckpt_blocking); the ledger only counts main-thread time, so the
    # same code running on the AsyncSaver worker is correctly excluded.
    with goodput.get().timed("ckpt_blocking"), \
            telemetry.get().span("ckpt_save", fmt=fmt, epoch=int(epoch),
                                 file=os.path.basename(path)):
        if fmt == "orbax":
            return _save_orbax(path, model_name, state, epoch,
                               best_valid_loss)
        _write_msgpack(path, _msgpack_payload(model_name, state, epoch,
                                              best_valid_loss))


_SAVER_SHUTDOWN = object()


class AsyncSaver:
    """Ordered background checkpoint I/O (--ckpt-async).

    One daemon worker thread drains a FIFO job queue, so every submitted
    job (rolling write, best-model write, rotation delete) runs in
    exactly the order the driver issued it — a newer save can never race
    an older one onto the same path, and a rotation can never delete a
    file whose (earlier-submitted) write is still pending.  ``submit``
    returns immediately; the driver's critical path holds only the
    snapshot work done before submitting.

    A background exception is captured and re-raised from the NEXT
    ``submit``/``wait``/``close`` on the driver thread, so a failing
    write cannot pass silently.  Drivers must ``wait()`` (or ``close()``)
    before process exit — and before telemetry close, so the background
    spans land in the JSONL.

    ``on_error='degrade'`` (what the training driver passes, ISSUE 5):
    instead of re-raising, the first background failure is logged +
    emitted as a ``ckpt_async_degraded`` telemetry event and the saver
    switches to SYNCHRONOUS execution of every later job on the driver
    thread — the run keeps checkpointing (a persistent failure then
    surfaces from the synchronous write itself) rather than dying at
    close over an already-finished epoch.  The default stays 'raise':
    library callers keep the must-not-pass-silently contract.
    """

    def __init__(self, on_error: str = "raise"):
        if on_error not in ("raise", "degrade"):
            raise ValueError(
                f"AsyncSaver on_error must be 'raise' or 'degrade', "
                f"got {on_error!r}")
        self.on_error = on_error
        self.degraded = False
        self._queue = queue_mod.Queue()
        # graftlint: guarded-by=_queue.join -- single writer thread sets
        # it before task_done(); the driver reads it from submit()/wait()
        # /close(), where a post-join read is ordered by Queue.join and a
        # pre-join read can at worst miss an exception that the very
        # next call re-raises (reference assignment is atomic in Python)
        self._exc: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    def _worker(self) -> None:
        while True:
            fn = self._queue.get()
            try:
                if fn is _SAVER_SHUTDOWN:
                    return
                fn()
            except BaseException as e:  # captured for the driver: the
                # next submit()/wait()/close() re-raises it there
                self._exc = e
            finally:
                self._queue.task_done()

    def _raise_pending(self) -> None:
        if self._exc is None:
            return
        exc, self._exc = self._exc, None
        if self.on_error != "degrade":
            raise exc
        if not self.degraded:
            self.degraded = True
            logging.error(
                f"async checkpoint writer FAILED ({exc!r}); degrading "
                "to synchronous saves for the rest of the run")
            telemetry.get().event("ckpt_async_degraded", error=str(exc))

    @property
    def in_flight(self) -> bool:
        return self._thread is not None \
            and self._queue.unfinished_tasks > 0

    def submit(self, fn: Callable[[], None]) -> None:
        self._raise_pending()
        if self.degraded:
            fn()  # synchronous fallback: ordering preserved, run survives
            return
        if self._thread is None:
            self._thread = threading.Thread(target=self._worker,
                                            name="dpt-ckpt-writer",
                                            daemon=True)
            self._thread.start()
        self._queue.put(fn)

    def wait(self) -> None:
        """Block until every submitted job finished; re-raise failures."""
        if self._thread is not None:
            self._queue.join()
        self._raise_pending()

    def close(self) -> None:
        """wait() + retire the worker thread (no leak across runs)."""
        if self._thread is not None:
            self._queue.put(_SAVER_SHUTDOWN)
            self._queue.join()
            self._thread.join()
            self._thread = None
        self._raise_pending()


_warned_async_multihost = False


def save_checkpoint_async(saver: AsyncSaver, path: str, model_name: str,
                          state: TrainState, epoch: int,
                          best_valid_loss: float,
                          fmt: str = "msgpack") -> None:
    """--ckpt-async: only the snapshot blocks the driver; serialization
    and file I/O happen on ``saver``'s background thread, joined at the
    next save / preemption / exit.

    msgpack: the blocking part is the (possibly collective — same caller
    contract as ``save_checkpoint``) gather + device_get snapshot; the
    background part is msgpack serialize + tmp write + atomic rename.

    orbax: the blocking part is orbax's own synchronous D2H copy inside
    ``AsyncCheckpointer.save`` (donation-safe: the arrays are on host
    before it returns) — plus a join of any still-pending job, because
    consecutive saves to the SAME path share a ``.tmp`` directory and
    must not overlap; the background part waits for the shard writes
    and then runs the meta + atomic swap finalize.  Multi-host orbax
    falls back to the synchronous path: the finalize barriers are
    COLLECTIVE and must not run on a background thread concurrently
    with training collectives.

    Both formats produce byte-identical files to their sync paths and
    keep the tmp->rename crash-safety protocol: a kill mid-background-
    write leaves the previous checkpoint at ``path`` loadable.
    """
    tel = telemetry.get()
    if fmt == "orbax" and jax.process_count() > 1:
        global _warned_async_multihost
        if not _warned_async_multihost:
            logging.warning(
                "--ckpt-async with --ckpt-format orbax on a multi-host "
                "mesh falls back to synchronous saves (the finalize "
                "barrier is collective and cannot run on a background "
                "thread)")
            _warned_async_multihost = True
        saver.wait()  # ordering with any earlier async save
        return save_checkpoint(path, model_name, state, epoch,
                               best_valid_loss, fmt=fmt)

    attrs = dict(fmt=fmt, epoch=int(epoch), file=os.path.basename(path))
    if fmt == "orbax":
        with goodput.get().timed("ckpt_blocking"), \
                tel.span("ckpt_save_blocking", **attrs):
            saver.wait()
            import orbax.checkpoint as ocp

            abs_path = os.path.abspath(path)
            tmp = abs_path + ".tmp"
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            ckptr = ocp.StandardCheckpointer()
            state_sd = serialization.to_state_dict(state)
            faults.fire("ckpt.save", path=path)
            ckptr.save(os.path.join(tmp, "state"), state_sd)
            meta = _orbax_meta(model_name, epoch, best_valid_loss,
                               state_sd)

        def finalize():
            with telemetry.get().span("ckpt_save_background", **attrs):
                ckptr.wait_until_finished()
                _orbax_finalize(abs_path, tmp, meta)

        saver.submit(finalize)
        return

    with goodput.get().timed("ckpt_blocking"), \
            tel.span("ckpt_save_blocking", **attrs):
        payload = _msgpack_payload(model_name, state, epoch,
                                   best_valid_loss)

    def write():
        with telemetry.get().span("ckpt_save_background", **attrs):
            _write_msgpack(path, payload)

    saver.submit(write)


def require_orbax() -> None:
    """Raise the CLI-catchable ValueError when orbax is unavailable —
    checked up front (run_train/run_test) so --ckpt-format orbax cannot
    traceback after a full epoch of training."""
    try:
        import orbax.checkpoint  # noqa: F401
    except ImportError as e:
        raise ValueError(
            "--ckpt-format orbax requires the 'orbax-checkpoint' package "
            "(pip install orbax-checkpoint)") from e


_warned_shared_fs = False


def _save_orbax(path: str, model_name: str, state: TrainState,
                epoch: int, best_valid_loss: float) -> None:
    import orbax.checkpoint as ocp

    from . import runtime

    global _warned_shared_fs
    if jax.process_count() > 1 and not _warned_shared_fs:
        # The .tmp cleanup, meta write and atomic swap below run on
        # process 0 only — every host MUST see the same filesystem at
        # ``path`` (true on the shared storage multi-host TPU setups
        # mount; NOT true for per-host local disks, where the other
        # hosts' shards would be stranded under .tmp).  Exercised for
        # real in tests/test_ckpt_topology.py.
        logging.warning(
            f"orbax checkpoint {path!r} is written by {jax.process_count()}"
            " processes: the path must be on a filesystem shared by all"
            " hosts (per-host local disks will strand non-main shards)")
        _warned_shared_fs = True

    path = os.path.abspath(path)
    tmp = path + ".tmp"
    # Atomic-ish overwrite, mirroring the msgpack tmp+rename: the COMPLETE
    # checkpoint (state + meta) is assembled under .tmp, then swapped in.
    # A crash mid-save leaves the previous bestmodel intact.
    if jax.process_index() == 0 and os.path.exists(tmp):
        shutil.rmtree(tmp)
    runtime.barrier()  # nobody saves into .tmp until the cleanup is done
    ckptr = ocp.StandardCheckpointer()
    state_sd = serialization.to_state_dict(state)
    faults.fire("ckpt.save", path=path)
    ckptr.save(os.path.join(tmp, "state"), state_sd)
    ckptr.wait_until_finished()
    runtime.barrier()  # every host's shards are on disk before the swap
    if jax.process_index() == 0:
        _orbax_finalize(path, tmp,
                        _orbax_meta(model_name, epoch, best_valid_loss,
                                    state_sd))
    runtime.barrier()  # no host proceeds until the swap is visible


def _orbax_meta(model_name: str, epoch: int, best_valid_loss: float,
                state_sd: dict) -> dict:
    # params_layout (vit 'stacked'/'blocks'/'scan', the per-family
    # '*_scan'/'*_layers' pairs, or null) lets the loader restore a
    # directory saved under one block layout into a model built with
    # another without guessing the on-disk tree shape.
    return {"format_version": _FORMAT_VERSION,
            "model_name": model_name, "epoch": int(epoch),
            "loss": float(best_valid_loss),
            "params_layout": model_scan.params_layout(
                state_sd.get("params")),
            # lets the loader refuse a cross-layout restore
            # into/out of a MoE tree with a clear message
            # instead of an opaque structure mismatch
            "moe": _has_moe_blocks(state_sd.get("params"))}


def _orbax_finalize(path: str, tmp: str, meta: dict) -> None:
    """meta.json write + the atomic tmp->dir swap (single writer).  The
    COMPLETE checkpoint exists under .tmp before this runs, so a crash
    before/inside it leaves the previous checkpoint at ``path`` intact.
    The structural content checksum (see ``_orbax_checksum``) goes into
    meta.json here — the payload is final once the shard writes landed —
    and the swap is retried under the process retry policy."""
    meta = dict(meta, checksum=_orbax_checksum(tmp))

    def _attempt():
        with open(os.path.join(tmp, _ORBAX_META), "w") as f:
            json.dump(meta, f)
        if os.path.exists(path):
            shutil.rmtree(path)
        os.replace(tmp, path)

    faults.retry(_attempt, "ckpt.finalize")
    # Post-swap hook: torn/preempt chaos kinds act on the FINAL
    # directory, like a failure landing right after the swap.
    faults.fire("ckpt.finalize", path=path)
    total = sum(os.path.getsize(os.path.join(dp, fn))
                for dp, _, fns in os.walk(path) for fn in fns)
    _lineage_record(path, meta["epoch"], meta["checksum"], total)
    logging.info(f"epoch:{meta['epoch']:04d}: model saved to {path}")


def _has_moe_blocks(params) -> bool:
    """True when a params(-shaped) dict holds mixture-of-experts blocks
    (block0/moe) — those cannot round-trip through the stacked<->blocks
    dense-MLP conversion."""
    if not isinstance(params, dict):
        return False
    blk = params.get("block0")
    if isinstance(blk, dict) and "moe" in blk:
        return True
    # scan layout: params/blocks/block holds the stacked body, moe
    # blocks included (models/scan.py)
    run = params.get("blocks")
    if isinstance(run, dict):
        blk = run.get("block")
        return isinstance(blk, dict) and "moe" in blk
    return False


def _check_layouts_convertible(path: str, src: str, dst: str,
                               template_params, saved_params=None,
                               saved_is_moe: bool = False) -> None:
    """A stacked<->blocks conversion is about to run: refuse with a clear
    message when either side holds MoE blocks (the conversion would
    fabricate dense mlp_up/mlp_down entries that cannot match a MoE
    template, surfacing as an opaque structure mismatch otherwise).
    The orbax path can't read the saved tree cheaply — it passes the
    meta.json ``moe`` flag as ``saved_is_moe`` instead."""
    ckpt_moe = saved_is_moe or _has_moe_blocks(saved_params)
    if ckpt_moe or _has_moe_blocks(template_params):
        side = ("the checkpoint holds" if ckpt_moe
                else "the requested model uses")
        raise ValueError(
            f"checkpoint at {path} has {src!r}-layout transformer "
            f"params, the requested model the {dst!r} layout, and "
            f"{side} mixture-of-experts blocks; stacked<->blocks "
            "conversion only covers dense MLPs (MoE is not a "
            "pipeline stage architecture) — load with a matching "
            "--moe-experts / --pipeline-parallel configuration")


def _read_orbax_meta(path: str) -> dict:
    """Read + validate ``meta.json`` in a checkpoint directory.  Failure
    is a ONE-LINE actionable ValueError naming the path and the expected
    producer (ISSUE 5 satellite) — not a raw traceback."""
    meta_path = os.path.join(path, _ORBAX_META)
    if not os.path.exists(meta_path):
        raise ValueError(
            f"{path}: missing {_ORBAX_META} — not an orbax checkpoint "
            f"directory; expected one produced by this framework's "
            f"--ckpt-format orbax save (or pass a .ckpt msgpack file)")

    def _attempt():
        faults.fire("ckpt.restore", path=path)
        with open(meta_path) as f:
            return f.read()

    try:
        raw = faults.retry(_attempt, "ckpt.restore",
                           transient=_READ_TRANSIENT)
    except OSError as e:
        raise ValueError(
            f"{path}: cannot read {_ORBAX_META} ({e.strerror or e}) — "
            f"expected the metadata written by this framework's "
            f"--ckpt-format orbax save") from e
    try:
        meta = json.loads(raw)
        if not isinstance(meta, dict):
            raise ValueError("not a JSON object")
    except ValueError as e:
        raise ValueError(
            f"{path}: garbage {_ORBAX_META} ({e}) — expected the JSON "
            f"metadata written by this framework's --ckpt-format orbax "
            f"save; the directory is corrupt or foreign, restore from "
            f"an earlier snapshot") from e
    return meta


def _load_orbax(path: str, state: TrainState, restore_optimizer: bool
                ) -> Tuple[TrainState, int, float]:
    path = os.path.abspath(path)
    # meta.json first (plain JSON, no orbax needed): a missing/corrupt
    # directory surfaces its actionable error even where orbax isn't
    # installed; only an actual restore requires the dependency.
    meta = _read_orbax_meta(path)
    if meta.get("format_version") != _FORMAT_VERSION:
        raise ValueError(f"{path}: unsupported checkpoint format "
                         f"{meta.get('format_version')!r}")
    # Verify-on-load (ISSUE 5): never trust a torn/corrupt snapshot.
    reason = verify_checkpoint(path)
    if reason is not None:
        raise ValueError(f"{path}: corrupt checkpoint — {reason}")
    # Loading auto-detects orbax by directory-ness, without --ckpt-format
    # orbax ever being passed — so the availability check must happen
    # here, surfacing the CLI-catchable ValueError rather than a raw
    # ImportError traceback.
    require_orbax()
    import orbax.checkpoint as ocp
    # Shapes/dtypes only — no device_get: the template may hold sharded
    # (multi-host: non-addressable) arrays, and copying params+opt_state
    # to host just to read .shape would be waste anyway.  Restore target
    # shardings, per leaf: a template already PLACED on a global mesh
    # restores as-laid-out (a --model-parallel state never transiently
    # replicates — the drivers place the template before loading a
    # directory checkpoint for exactly this reason); anything else
    # restores replicated over every device, which is what makes a
    # checkpoint saved on one process topology resumable on another
    # (orbax requires a concrete global sharding per leaf whenever
    # process_count > 1; tests/test_ckpt_topology.py).
    from jax.sharding import Mesh

    template = serialization.to_state_dict(state)
    n_devices = len(jax.devices())
    replicated = NamedSharding(
        Mesh(np.asarray(jax.devices()).reshape(-1), ("_all",)),
        PartitionSpec())

    # Cross-layout restore (self-describing-checkpoint parity, ref
    # classif.py:214, same contract the msgpack path has): when the
    # directory was saved with the other vit block layout (meta
    # params_layout, absent in old checkpoints -> no conversion), build
    # the restore target in the SAVED layout — convert_layout works at
    # shape level on ShapeDtypeStruct trees — then convert the restored
    # arrays to the template's layout.  Converted leaves change shape,
    # so the whole target restores replicated (the plain-model
    # ``test -f`` case is replicated anyway).
    src = meta.get("params_layout")
    dst = model_scan.params_layout(template.get("params"))
    convert = src in model_scan.KNOWN_LAYOUTS and dst is not None \
        and src != dst

    def leaf_target(x):
        s = getattr(x, "sharding", None)
        if convert:
            return replicated
        if isinstance(s, NamedSharding) and len(s.device_set) == n_devices:
            return s  # placed on the global mesh: restore as-laid-out
        return replicated

    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(
            tuple(np.shape(x)), getattr(x, "dtype", np.asarray(x).dtype),
            sharding=leaf_target(x)),
        template)
    if convert:
        _check_layouts_convertible(path, src, dst, template.get("params"),
                                   saved_is_moe=bool(meta.get("moe")))
        abstract = model_scan.convert_layout(abstract, src)
        logging.info(f"checkpoint params will be converted: {src} -> "
                     f"{dst} block layout")
    try:
        if restore_optimizer:
            restored_dict = ocp.StandardCheckpointer().restore(
                os.path.join(path, "state"), abstract)
        else:
            # test / resume-under-a-different-optimizer: the saved
            # opt_state may not structurally match the current
            # optimizer's — and its bytes are not wanted either way, so
            # it is excluded from the restore entirely (partial restore:
            # no disk read, no transient device copies); the fresh
            # template opt_state is grafted back below.  The msgpack
            # path gets the same semantics by overwriting before
            # from_state_dict.
            abstract.pop("opt_state", None)
            with ocp.Checkpointer(ocp.PyTreeCheckpointHandler()) as ptc:
                try:
                    args = ocp.args.PyTreeRestore(item=abstract,
                                                  partial_restore=True)
                except TypeError:
                    # older orbax spells partial restore via transforms:
                    # an empty mapping with default-to-original restores
                    # exactly the item's keys and drops the rest (the
                    # saved opt_state) without reading it; restore_args
                    # (sharding/dtype per leaf) are mandatory with
                    # transforms and derived from the abstract target
                    args = ocp.args.PyTreeRestore(
                        item=abstract,
                        restore_args=ocp.checkpoint_utils
                        .construct_restore_args(abstract),
                        transforms={})
                restored_dict = ptc.restore(
                    os.path.join(path, "state"), args=args)
    except Exception as e:  # any orbax failure -> CLI-catchable ValueError
        raise ValueError(f"cannot restore orbax checkpoint {path!r}: "
                         f"{e}") from e
    if convert:
        restored_dict = model_scan.convert_layout(restored_dict, dst)
    if not restore_optimizer:
        restored_dict["opt_state"] = template.get("opt_state", {})
    # loss_scale compat — same shim as the msgpack path (see
    # _load_checkpoint_inner): pre-field checkpoints get the template's
    # value; a saved scale is dropped when the policy doesn't scale.
    tmpl_ls = template.get("loss_scale")
    if tmpl_ls is None:
        restored_dict["loss_scale"] = None
    elif restored_dict.get("loss_scale") is None:
        # absent (pre-field) or saved as None (non-scaling policy wrote
        # it): either way the template's fresh scale applies
        restored_dict["loss_scale"] = tmpl_ls
    restored = serialization.from_state_dict(state, restored_dict)
    epoch = int(meta["epoch"]) + 1
    logging.info(f"epoch:{epoch:04d}: model loaded from {path}")
    return restored, epoch, float(meta["loss"])


def _read(path: str) -> dict:
    """Read + validate a checkpoint; all failure modes surface as ValueError
    so the CLI can log-and-exit (ref classif.py:119-120 style) instead of
    tracebacking on a missing or corrupt file.  Transient read errors are
    retried; the content is verified against the lineage ledger's
    recorded sha256 (when one exists) BEFORE it is trusted."""

    def _attempt() -> bytes:
        faults.fire("ckpt.restore", path=path)
        with open(path, "rb") as f:
            return f.read()

    try:
        blob = faults.retry(_attempt, "ckpt.restore",
                            transient=_READ_TRANSIENT)
    except OSError as e:
        raise ValueError(f"cannot read checkpoint file {path!r}: "
                         f"{e.strerror or e}") from e
    rec = _lineage_entry(path)
    if rec is not None:
        got = hashlib.sha256(blob).hexdigest()
        if got != rec.get("sha256"):
            raise ValueError(
                f"{path}: corrupt checkpoint — content checksum mismatch "
                f"(lineage records {str(rec.get('sha256'))[:12]}…, found "
                f"{got[:12]}…)")
    try:
        payload = serialization.msgpack_restore(blob)
    except Exception as e:  # any decode failure -> CLI-catchable ValueError
        raise ValueError(f"corrupt checkpoint file {path!r}: {e}") from e
    if not isinstance(payload, dict) \
            or payload.get("format_version") != _FORMAT_VERSION:
        raise ValueError(f"{path}: unsupported checkpoint format"
                         + (f" {payload.get('format_version')!r}"
                            if isinstance(payload, dict) else ""))
    return payload


def load_checkpoint(path: str, state: TrainState,
                    restore_optimizer: bool = True
                    ) -> Tuple[TrainState, int, float]:
    """ref loadCheckpoint (utils.py:123-136): returns (state, next_epoch,
    best_valid_loss).  ``state`` is a template with the right structure
    (fresh Engine.init_state output); restored arrays replace its leaves.
    Format is auto-detected: an orbax checkpoint is a directory."""
    with goodput.get().timed("ckpt_blocking"), \
            telemetry.get().span("ckpt_restore",
                                 file=os.path.basename(path)):
        return _load_checkpoint_inner(path, state, restore_optimizer)


def restore_for_serving(path: str, state: TrainState
                        ) -> Tuple[TrainState, int]:
    """The serving tier's restore (cli.run_serve): any lineage-verified
    checkpoint, any ``params_layout`` — the regular load path already
    checksums against the lineage ledger and converts scan/blocks/
    pipeline layouts into the template's.  Serving never wants the
    optimizer state (a replica holds params + batch_stats only), and it
    records WHAT it is serving as a ``serve_restore`` telemetry event —
    the audit line tying every answered request back to a checkpoint.
    Returns (state, last_trained_epoch)."""
    restored, next_epoch, _best = load_checkpoint(
        path, state, restore_optimizer=False)
    layout = None
    try:
        with runtime.sanctioned_host_transfer():
            layout = model_scan.params_layout(
                serialization.to_state_dict(
                    jax.device_get(gather_replicated(restored))).get(
                        "params"))
    except Exception:
        pass  # the layout tag is audit metadata, never load-blocking
    telemetry.get().event("serve_restore",
                          file=os.path.basename(path),
                          epoch=next_epoch - 1,
                          layout=layout or "unknown")
    logging.info(f"serving checkpoint {path} "
                 f"(trained through epoch {next_epoch - 1}, "
                 f"layout {layout or 'unknown'})")
    return restored, next_epoch - 1


def _load_checkpoint_inner(path: str, state: TrainState,
                           restore_optimizer: bool
                           ) -> Tuple[TrainState, int, float]:
    if os.path.isdir(path):
        return _load_orbax(path, state, restore_optimizer)
    payload = _read(path)
    with runtime.sanctioned_host_transfer():  # restore-template snapshot
        template = jax.device_get(gather_replicated(state))
    template_sd = serialization.to_state_dict(template)
    if not restore_optimizer:  # test path passes optimizer=None (ref :232)
        payload["state"]["opt_state"] = template_sd.get("opt_state", {})
    # loss_scale compat (PrecisionPolicy): checkpoints written before the
    # field existed have no entry — graft the template's (None for every
    # preset but f16, a fresh LossScaleState for f16: the scale is a
    # runtime adaption, losing it across restarts only costs a few
    # re-adaptation steps).  And a scale saved by an f16 run restoring
    # into a non-scaling policy is dropped the same way.
    tmpl_ls = template_sd.get("loss_scale")
    if tmpl_ls is None:
        payload["state"]["loss_scale"] = None
    elif payload["state"].get("loss_scale") is None:
        # absent (pre-field checkpoint) or saved as None (non-scaling
        # policy): either way the template's fresh scale applies
        payload["state"]["loss_scale"] = tmpl_ls
    # A vit checkpoint serves both block layouts: PipelinedViT saves its
    # transformer params STACKED on (depth,); the plain ViT saves
    # per-block submodules.  When the saved layout differs from the
    # requested model's, convert in place — params and the optimizer
    # moments that mirror them — so `test -f` (and resume) work on a
    # pipeline-trained checkpoint without a pipeline mesh, and vice
    # versa (self-describing-checkpoint parity, ref classif.py:214).
    # The orbax path does the same via meta.json's params_layout
    # (_load_orbax converts the abstract restore target, then the
    # restored arrays).
    src = model_scan.params_layout(payload["state"].get("params"))
    dst = model_scan.params_layout(template_sd.get("params"))
    if src is not None and dst is not None and src != dst:
        _check_layouts_convertible(path, src, dst,
                                   template_sd.get("params"),
                                   payload["state"].get("params"))
        payload["state"] = model_scan.convert_layout(payload["state"],
                                                     dst)
        logging.info(f"checkpoint params converted: {src} -> {dst} "
                     "block layout")
    restored = serialization.from_state_dict(template, payload["state"])
    epoch = int(payload["epoch"]) + 1
    best_valid_loss = float(payload["loss"])
    logging.info(f"epoch:{epoch:04d}: model loaded from {path}")
    return restored, epoch, best_valid_loss


def get_checkpoint_model_name(path: str) -> str:
    """ref getCheckpointModelName (utils.py:138-140); both formats."""
    if os.path.isdir(path):
        # meta.json is plain JSON — sniffing needs no orbax; only the
        # actual restore (_load_orbax) requires the dependency.
        meta = _read_orbax_meta(os.path.abspath(path))
        if "model_name" not in meta:
            raise ValueError(
                f"{path}: {_ORBAX_META} has no model_name — expected "
                f"the metadata written by this framework's --ckpt-format "
                f"orbax save")
        return str(meta["model_name"])
    return str(_read(path)["model_name"])


def rotate_checkpoint(rsl_path: str, dataset: str, model_name: str,
                      epoch: int, keep: int = 1) -> None:
    """Delete the rolling file/dir ``keep`` epochs back, retaining the
    newest ``keep`` snapshots (ref classif.py:182-184, fixed; keep=1 is
    the original delete-previous behavior, keep>1 is the keep-K lineage
    the corruption-fallback resume walks)."""
    prev = checkpoint_path(rsl_path, dataset, model_name,
                           epoch - max(1, keep))
    if os.path.isdir(prev):
        shutil.rmtree(prev)
    elif os.path.exists(prev):
        os.remove(prev)
    else:
        return
    _lineage_forget(prev)
