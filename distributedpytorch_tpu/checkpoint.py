"""Checkpoint / resume (ref utils.py:112-140 + classif.py:141-147,176-192).

Same five logical fields as the reference's torch.save dict
(ref utils.py:114-120): model_name, model state (params + batch_stats),
optimizer state, epoch, best valid loss — serialized with flax msgpack
into a single self-describing file.  Contract parity:

  * ``test -f FILE`` discovers the architecture from the file's
    ``model_name`` field (ref classif.py:214, utils.py:138-140);
  * resume restores model+optimizer and continues at ``epoch + 1`` with the
    saved best loss (ref utils.py:123-136, classif.py:143-147);
  * rolling per-epoch file + separate best file (ref classif.py:182-192),
    with the rotation actually deleting the previous epoch's file —
    the reference's delete path omits the model name from the filename and
    never matches (SURVEY defect #5).

Divergences (improvements, documented): writes are atomic (tmp+rename);
checkpoints are written from *unwrapped, replicated* state, so a checkpoint
trained on N chips loads anywhere (the reference saves DDP ``module.``-
prefixed keys that only load back into a DDP wrapper — SURVEY defect #11).
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Tuple

import jax
import numpy as np
from flax import serialization
from jax.sharding import NamedSharding, PartitionSpec

from .train.engine import TrainState

_FORMAT_VERSION = 1


def gather_replicated(state: TrainState) -> TrainState:
    """Make every array fully replicated before host transfer.

    With --model-parallel, params/opt-state live sharded over the 'model'
    mesh axis; on multi-host meshes ``jax.device_get`` of such arrays would
    fail (non-addressable shards).  A jitted identity with replicated
    out_shardings performs the all-gather as an XLA program.  No-op (and no
    dispatch) for the default replicated layout.

    COLLECTIVE on multi-host meshes: when any leaf is sharded over a mesh
    spanning multiple processes, EVERY process must call this (the program
    runs on all the mesh's devices) — drivers call it un-gated and then
    gate only the file write on ``is_main()``.
    """
    leaves = [a for a in jax.tree_util.tree_leaves(state)
              if isinstance(a, jax.Array)]
    if all(getattr(a, "is_fully_replicated", True) for a in leaves):
        return state
    mesh = next(a.sharding.mesh for a in leaves
                if isinstance(a.sharding, NamedSharding))
    replicated = NamedSharding(mesh, PartitionSpec())
    gather = jax.jit(lambda x: x, out_shardings=replicated)

    def _one(a):
        # Leaf-by-leaf, not one whole-tree program: bounds the transient
        # HBM spike to sharded-state + ONE replicated tensor, instead of
        # re-materializing the full unsharded state (the exact footprint
        # --model-parallel exists to avoid) on every device at save time.
        if isinstance(a, jax.Array) and not a.is_fully_replicated:
            return jax.device_get(gather(a))
        return a

    return jax.tree_util.tree_map(_one, state)


def checkpoint_path(rsl_path: str, dataset: str, model_name: str,
                    epoch: int) -> str:
    # ref classif.py:186: rsl/checkpoint-mnist-{model}-{epoch:03d}.pt.tar
    return os.path.join(
        rsl_path, f"checkpoint-{dataset}-{model_name}-{epoch:03d}.ckpt")


def best_model_path(rsl_path: str, dataset: str, model_name: str) -> str:
    # ref classif.py:191: rsl/bestmodel-mnist-{model}.pt.tar
    return os.path.join(rsl_path, f"bestmodel-{dataset}-{model_name}.ckpt")


def save_checkpoint(path: str, model_name: str, state: TrainState,
                    epoch: int, best_valid_loss: float) -> None:
    """ref saveCheckpoint (utils.py:112-121); caller gates on is_main() —
    but on multi-host meshes the caller must run ``gather_replicated`` on
    every process FIRST and pass the gathered state (the internal call
    below is then a no-op; it only covers single-host callers)."""
    payload = {
        "format_version": _FORMAT_VERSION,
        "model_name": model_name,
        "epoch": int(epoch),
        "loss": float(best_valid_loss),
        "state": serialization.to_state_dict(
            jax.device_get(gather_replicated(state))),
    }
    blob = serialization.msgpack_serialize(payload)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)
    logging.info(f"epoch:{epoch:04d}: model saved to {path}")


def _read(path: str) -> dict:
    """Read + validate a checkpoint; all failure modes surface as ValueError
    so the CLI can log-and-exit (ref classif.py:119-120 style) instead of
    tracebacking on a missing or corrupt file."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise ValueError(f"cannot read checkpoint file {path!r}: "
                         f"{e.strerror or e}") from e
    try:
        payload = serialization.msgpack_restore(blob)
    except Exception as e:
        raise ValueError(f"corrupt checkpoint file {path!r}: {e}") from e
    if not isinstance(payload, dict) \
            or payload.get("format_version") != _FORMAT_VERSION:
        raise ValueError(f"{path}: unsupported checkpoint format"
                         + (f" {payload.get('format_version')!r}"
                            if isinstance(payload, dict) else ""))
    return payload


def load_checkpoint(path: str, state: TrainState,
                    restore_optimizer: bool = True
                    ) -> Tuple[TrainState, int, float]:
    """ref loadCheckpoint (utils.py:123-136): returns (state, next_epoch,
    best_valid_loss).  ``state`` is a template with the right structure
    (fresh Engine.init_state output); restored arrays replace its leaves."""
    payload = _read(path)
    template = jax.device_get(gather_replicated(state))
    if not restore_optimizer:  # test path passes optimizer=None (ref :232)
        payload["state"]["opt_state"] = serialization.to_state_dict(
            template).get("opt_state", {})
    restored = serialization.from_state_dict(template, payload["state"])
    epoch = int(payload["epoch"]) + 1
    best_valid_loss = float(payload["loss"])
    logging.info(f"epoch:{epoch:04d}: model loaded from {path}")
    return restored, epoch, best_valid_loss


def get_checkpoint_model_name(path: str) -> str:
    """ref getCheckpointModelName (utils.py:138-140)."""
    return str(_read(path)["model_name"])


def rotate_checkpoint(rsl_path: str, dataset: str, model_name: str,
                      epoch: int) -> None:
    """Delete epoch-1's rolling file (ref classif.py:182-184, fixed)."""
    prev = checkpoint_path(rsl_path, dataset, model_name, epoch - 1)
    if os.path.exists(prev):
        os.remove(prev)
