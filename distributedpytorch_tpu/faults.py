"""L1: deterministic fault injection + retry/backoff policy.

The happy path of this framework is well tested; this module makes the
FAILURE paths testable (ISSUE 5).  Two halves:

**Fault plans.**  A seeded, deterministic plan of faults to inject at
named sites threaded through the runtime.  Sites currently wired:

  data.read        data/io.py load_raw dispatch (dataset fetch)
  data.host_batch  data/pipeline.py producer per-step host work
  ckpt.save        checkpoint.py serialize+write (msgpack / orbax save)
  ckpt.finalize    checkpoint.py post-rename/post-swap hook (receives the
                   final path — the only site where kind=torn applies)
  ckpt.restore     checkpoint.py read/restore
  runtime.init     runtime.py jax.distributed.initialize
  elastic.reinit   elastic.py shrunken-world re-initialization
  elastic.join     elastic.py join-claim write (grow rendezvous entry;
                   receives the claim path — torn/rank_join apply)
  elastic.grow_reinit  elastic.py grown-world re-initialization (both
                   the joiner's connect and the survivors' grow reinit)
  telemetry.write  telemetry.py JSONL writer
  serve.request    serving/server.py per-request handler entry (an
                   injected ioerror answers that request with a 500)
  serve.admit      serving/server.py queue admission (shed-path tests)
  serve.infer      serving/server.py driver per-micro-batch dispatch —
                   ioerror fails one batch and the tier keeps serving;
                   rank_loss vanishes the replica mid-serve (chaos
                   stage G: survivors must reconfigure and answer)

Plan forms (``--fault-plan``):

  DSL string   "site:kind:after_n[:count[:stall_s]]" — ';'-separated for
               multiple specs; fires on the (after_n+1)-th ..
               (after_n+count)-th hit of the site (count defaults to 1;
               stall_s only applies to kind=stall).
  JSON file    path to {"seed": S, "faults": [{"site": ..., "kind": ...,
               "after_n": N, "count": C, "rank": R, "path_match": "sub",
               "stall_s": T}, ...]} — rank restricts a spec to one
               process, path_match to fire() calls whose path contains
               the substring.

Kinds: ``ioerror`` (raise InjectedIOError — an OSError, i.e. transient
under the default retry classification), ``fatal`` (raise
FatalFaultError — never retried; drives the multi-host failure
agreement), ``preempt`` (SIGTERM to self — deterministic mid-run
preemption), ``torn`` (truncate the file/meta at the ``path`` the site
passed — simulates a torn write discovered at the next load; only
meaningful at ckpt.finalize), ``stall`` (sleep ``stall_s`` seconds at
the site and carry on — a deterministic straggler/slow-I/O injection;
this is how the flight recorder's anomaly trigger path is proven:
one stalled step must produce exactly one profiler capture, see
scripts/anomaly_gate.py), ``rank_loss`` (``os._exit(113)`` — the
process vanishes mid-collective with no cleanup, no SIGTERM handler,
no flushed buffers: the shape of a preempted/oom-killed host its
peers must detect and survive; this is how the elastic reconfigure
path is proven, see scripts/chaos_gate.py --stage elastic),
``rank_join`` (drop a DUPLICATE of the join claim at ``path`` — the
shape of a joiner that retried its claim write after a partition and
left two files behind; only meaningful at elastic.join, where the
rendezvous must dedupe claims by claimant identity, not filename).

Every firing emits a ``fault_injected`` telemetry event and a flight-
recorder event (flightrec.py), so chaos runs are auditable from the
JSONL alone and fault timing lands on the step timeline.  Zero-cost when disabled: with no
plan installed ``fire()`` is one global load + None check, and the
producer hot path doesn't even pay that — pipeline.py wraps its
per-step host work only when ``targets(site)`` is true at epoch setup.

**RetryPolicy.**  Bounded retries with exponential backoff and
deterministic jitter (seeded per site, so a fixed plan seed reproduces
the exact schedule), transient-vs-fatal classification, and a per-site
wall-clock deadline.  The deadline bounds RETRYING, not the call itself:
an in-flight call is never interrupted (Python offers no safe
preemption), but no new attempt starts past the deadline.  Wrapped
around dataset reads, checkpoint write/restore/finalize, and
jax.distributed init.  ``retry/attempts`` counts extra attempts,
``retry/giveups`` exhausted policies — both land in the telemetry
report.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import random
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple, TypeVar

from . import flightrec, goodput, telemetry

T = TypeVar("T")

KINDS = ("ioerror", "fatal", "preempt", "torn", "stall", "rank_loss",
         "rank_join")

SITES = ("data.read", "data.host_batch", "ckpt.save", "ckpt.finalize",
         "ckpt.restore", "runtime.init", "elastic.reinit",
         "elastic.join", "elastic.grow_reinit", "telemetry.write",
         "serve.request", "serve.infer", "serve.admit", "sim.step")
# "sim.step" is consumed by the fleet simulator (sim/scenario.py), which
# reuses this plan DSL with a time-based reading: after_n = virtual
# seconds, count = replicas (rank_loss/preempt/rank_join) or requests
# (ioerror) affected.  fire() never targets it in a live process.

# Exit code of a rank killed by kind=rank_loss: distinguishable in the
# harness from a crash (1), a fatal-agreement exit (CHILD_EXIT) and a
# SIGTERM death, so the chaos gate can assert the RIGHT rank vanished.
RANK_LOSS_EXIT = 113


class InjectedIOError(OSError):
    """A transient injected failure (kind=ioerror): an OSError, so the
    default retry classification treats it exactly like a real flaky
    read/write."""


class FatalFaultError(RuntimeError):
    """A non-transient injected failure (kind=fatal): never retried;
    the rank that hits it must fail loudly and notify its peers."""


class PeerFailureError(RuntimeError):
    """Raised on HEALTHY ranks after the failure-agreement all-reduce
    reports that some other rank hit a fatal error: every rank leaves
    the training loop at the same boundary instead of hanging in the
    dead rank's next collective."""


class HealthTimeoutError(RuntimeError):
    """The bounded health agreement (--health-timeout) did not complete
    in time: a peer is gone (or wedged) and never reached the boundary
    collective.  The local rank converts the hang it WOULD have suffered
    into this verdict — under --elastic the trigger for reconfiguring
    into the surviving world, otherwise a loud exit instead of a
    deadlock.  Lives here (not elastic.py) so runtime.py can raise it
    without an import cycle."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One planned fault: fires on hits (after_n, after_n+count] of
    ``site``, optionally restricted to one rank / a path substring."""

    site: str
    kind: str
    after_n: int = 0
    count: int = 1
    rank: Optional[int] = None
    path_match: Optional[str] = None
    stall_s: float = 0.25  # kind=stall only: injected sleep seconds

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r} "
                             f"(expected one of {', '.join(SITES)})")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(expected one of {', '.join(KINDS)})")
        if self.after_n < 0 or self.count < 1:
            raise ValueError(
                f"fault {self.site}:{self.kind}: after_n must be >= 0 "
                f"and count >= 1 (got {self.after_n}, {self.count})")
        if self.stall_s <= 0:
            raise ValueError(
                f"fault {self.site}:{self.kind}: stall_s must be > 0 "
                f"(got {self.stall_s})")


class FaultPlan:
    """An installed set of FaultSpecs plus per-site hit counters.

    Hit counting is per (site, path_match-bucket)-free: one counter per
    site, shared by all specs targeting it, incremented on every
    ``fire(site)`` call that any spec targets — deterministic for a
    fixed plan because the framework's call sequence is deterministic.
    Thread-safe: producer threads and the driver share the counters.
    """

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = int(seed)
        self._sites = frozenset(s.site for s in self.specs)
        self._hits: Dict[str, int] = {}
        # REENTRANT on purpose: fire() is called from telemetry's write
        # path, which the GracefulShutdown signal handler re-enters on
        # the very thread that may already be inside fire() — a plain
        # Lock self-deadlocks there (same class as the PR 12 preempt-
        # handler bug; caught by graftlint lock-order-cycle).
        self._lock = threading.RLock()
        self._rank: Optional[int] = None

    def targets(self, site: str) -> bool:
        return site in self._sites

    def _current_rank(self) -> int:
        if self._rank is None:
            try:
                import jax

                self._rank = int(jax.process_index())
            except Exception:  # jax absent/uninitializable: single rank
                self._rank = 0
        return self._rank

    def fire(self, site: str, path: Optional[str] = None) -> None:
        """Count a hit of ``site`` and act on any spec that matches.

        Raises for ioerror/fatal kinds; preempt signals self; torn
        truncates the file at ``path`` and returns (the site carries on
        — the damage is discovered at the next load, like a real torn
        write).
        """
        if site not in self._sites:
            return
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
        for spec in self.specs:
            if spec.site != site:
                continue
            if not (spec.after_n < hit <= spec.after_n + spec.count):
                continue
            if spec.rank is not None \
                    and spec.rank != self._current_rank():
                continue
            if spec.path_match is not None \
                    and (path is None or spec.path_match not in path):
                continue
            self._act(spec, hit, path)

    def _act(self, spec: FaultSpec, hit: int,
             path: Optional[str]) -> None:
        tel = telemetry.get()
        tel.event("fault_injected", site=spec.site, kind=spec.kind,
                  hit=hit, **({"path": path} if path else {}))
        # "fault_kind", not "kind": flightrec reserves "kind" for its
        # record schema ("event"/"step")
        flightrec.get().record_event("fault_injected", site=spec.site,
                                     fault_kind=spec.kind, hit=hit)
        logging.warning(f"FAULT INJECTED at {spec.site} "
                        f"(kind={spec.kind}, hit #{hit}"
                        + (f", path={path}" if path else "") + ")")
        if spec.kind == "stall":
            # A deterministic straggler: the site just goes slow.  The
            # anomaly detector must notice on its own — nothing else
            # about the step changes.
            time.sleep(spec.stall_s)
            return
        if spec.kind == "ioerror":
            raise InjectedIOError(
                f"injected transient I/O error at {spec.site} "
                f"(hit #{hit})")
        if spec.kind == "fatal":
            raise FatalFaultError(
                f"injected fatal fault at {spec.site} (hit #{hit})")
        if spec.kind == "preempt":
            os.kill(os.getpid(), signal.SIGTERM)
            return
        if spec.kind == "rank_loss":
            # Vanish NOW: no atexit, no SIGTERM handler, no cleanup —
            # peers find out when their next collective to us fails.
            # Only the fault_injected line above is flushed first so
            # the injection itself stays auditable from the JSONL.
            try:
                tel.flush()
            except Exception:  # broad: the point is to die regardless
                pass
            os._exit(RANK_LOSS_EXIT)
        if spec.kind == "torn":
            _tear(path)
            return
        if spec.kind == "rank_join":
            _duplicate_claim(path)


def _duplicate_claim(path: Optional[str]) -> None:
    """Simulate a joiner whose claim write was retried across a
    partition and left TWO files behind: copy the claim at ``path`` to
    a sibling ``*-dup.json`` and let the site carry on.  The grow
    rendezvous must dedupe by the claimant id inside the claim, so the
    duplicate admits exactly one rank, not two."""
    if path is None or not os.path.exists(path):
        logging.warning(f"rank_join fault: no claim to duplicate at "
                        f"{path!r}")
        return
    dup = (path[:-len(".json")] if path.endswith(".json") else path) \
        + "-dup.json"
    with open(path, "rb") as src, open(dup, "wb") as dst:
        dst.write(src.read())


def _tear(path: Optional[str]) -> None:
    """Simulate a torn write: truncate the file at ``path`` to half its
    size (an orbax directory gets ONE of its payload files torn), then
    let the site carry on — the corruption is only discovered when the
    checkpoint is next read and its checksum verified."""
    if path is None or not os.path.exists(path):
        logging.warning(f"torn fault: nothing to tear at {path!r}")
        return
    target = path
    if os.path.isdir(path):
        candidates = sorted(
            os.path.join(dirpath, fn)
            for dirpath, _, fns in os.walk(path) for fn in fns
            if fn != "meta.json" and os.path.getsize(
                os.path.join(dirpath, fn)) > 1)
        if not candidates:
            logging.warning(f"torn fault: no payload files under {path!r}")
            return
        target = candidates[0]
    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.truncate(max(1, size // 2))
    logging.warning(f"torn fault: truncated {target!r} "
                    f"{size} -> {max(1, size // 2)} bytes")


# -- plan parsing ------------------------------------------------------


def parse_plan(text: str, seed: int = 0) -> FaultPlan:
    """``--fault-plan`` argument -> FaultPlan.

    A path to an existing ``.json`` file (or any existing file) is the
    JSON form; anything else is the inline DSL.
    """
    if text.endswith(".json") or os.path.exists(text):
        try:
            with open(text) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            raise ValueError(
                f"cannot read fault plan file {text!r}: {e}") from e
        if not isinstance(doc, dict) or not isinstance(
                doc.get("faults"), list):
            raise ValueError(
                f"fault plan file {text!r} must be a JSON object with a "
                "'faults' list (and an optional 'seed')")
        specs = []
        for i, entry in enumerate(doc["faults"]):
            if not isinstance(entry, dict):
                raise ValueError(
                    f"fault plan file {text!r}: faults[{i}] is not an "
                    "object")
            unknown = set(entry) - {"site", "kind", "after_n", "count",
                                    "rank", "path_match", "stall_s"}
            if unknown:
                raise ValueError(
                    f"fault plan file {text!r}: faults[{i}] has unknown "
                    f"key(s) {sorted(unknown)}")
            specs.append(FaultSpec(**entry))
        return FaultPlan(specs, seed=int(doc.get("seed", seed)))
    specs = []
    for part in text.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) not in (3, 4, 5):
            raise ValueError(
                f"bad fault spec {part!r}: expected "
                "'site:kind:after_n[:count[:stall_s]]'")
        try:
            after_n = int(fields[2])
            count = int(fields[3]) if len(fields) >= 4 else 1
            stall_s = float(fields[4]) if len(fields) == 5 else 0.25
        except ValueError as e:
            raise ValueError(
                f"bad fault spec {part!r}: after_n/count must be "
                "integers (and stall_s a float)") from e
        specs.append(FaultSpec(site=fields[0], kind=fields[1],
                               after_n=after_n, count=count,
                               stall_s=stall_s))
    if not specs:
        raise ValueError(f"empty fault plan {text!r}")
    return FaultPlan(specs, seed=seed)


# -- module-level installation (zero-cost when absent) -----------------

_plan: Optional[FaultPlan] = None


def install(plan: Optional[FaultPlan]) -> None:
    """Install (or clear, with None) the process's fault plan."""
    global _plan
    _plan = plan


def installed() -> Optional[FaultPlan]:
    return _plan


def targets(site: str) -> bool:
    """True when the installed plan has a spec for ``site`` — hot paths
    check this ONCE at setup and skip all fault plumbing otherwise."""
    return _plan is not None and _plan.targets(site)


def fire(site: str, path: Optional[str] = None) -> None:
    """Injection point: no-op (one None check) without a plan."""
    plan = _plan
    if plan is not None:
        plan.fire(site, path)


# -- retry policy ------------------------------------------------------

# Transient by default: OS-level I/O errors and timeouts (includes
# InjectedIOError and ConnectionError, both OSError subclasses).
TRANSIENT = (OSError, TimeoutError)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with deterministic jittered exponential backoff.

    ``timeout_s`` is a per-call-site retry deadline: once the first
    attempt started more than ``timeout_s`` ago, no further attempt is
    made (the in-flight attempt itself is never interrupted).
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    timeout_s: float = 60.0
    seed: int = 0

    def _delay(self, site: str, attempt: int) -> float:
        backoff = min(self.max_delay_s,
                      self.base_delay_s * (2.0 ** (attempt - 1)))
        # Deterministic per (seed, site, attempt): a fixed plan seed
        # reproduces the exact retry schedule on every run.
        h = hashlib.sha256(
            f"{self.seed}:{site}:{attempt}".encode()).digest()
        rng = random.Random(int.from_bytes(h[:8], "big"))
        return backoff * (0.5 + 0.5 * rng.random())

    def call(self, fn: Callable[[], T], site: str,
             transient: Tuple[type, ...] = TRANSIENT) -> T:
        """Run ``fn`` under this policy.  Exceptions outside
        ``transient`` (FatalFaultError in particular) propagate
        immediately, attempt 1 included."""
        tel = telemetry.get()
        deadline = time.monotonic() + self.timeout_s
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except FatalFaultError:
                raise
            except transient as e:
                out_of_time = time.monotonic() >= deadline
                if attempt >= self.max_attempts or out_of_time:
                    tel.counter("retry/giveups").add(1)
                    tel.event("retry_giveup", site=site, attempts=attempt,
                              error=str(e), timed_out=out_of_time)
                    flightrec.get().record_event("retry_giveup",
                                                 site=site,
                                                 attempts=attempt)
                    logging.error(
                        f"{site}: giving up after {attempt} attempt(s)"
                        + (" (retry deadline exceeded)" if out_of_time
                           else "") + f": {e}")
                    raise
                delay = self._delay(site, attempt)
                tel.counter("retry/attempts").add(1)
                tel.event("retry", site=site, attempt=attempt,
                          delay_s=delay, error=str(e))
                flightrec.get().record_event("retry", site=site,
                                             attempt=attempt)
                logging.warning(
                    f"{site}: transient failure (attempt {attempt}/"
                    f"{self.max_attempts}), retrying in {delay:.3f}s: {e}")
                # The backoff sleep is goodput retry_backoff — attributed
                # here, at the one place every retry sleeps, so ledger
                # windows that enclose a retried call (ckpt_blocking,
                # data_wait) shrink by it instead of double-counting.
                with goodput.get().timed("retry_backoff"):
                    time.sleep(delay)


_default_policy = RetryPolicy()


def configure(fault_plan: Optional[str] = None, fault_seed: int = 0,
              retry_max_attempts: int = 3,
              retry_base_delay_s: float = 0.05,
              retry_timeout_s: float = 60.0) -> None:
    """Install the process's fault plan + default retry policy from the
    run Config (drivers call this once, before runtime init so the
    runtime.init site is live).  ``fault_plan=None`` clears any plan —
    re-invocation safe, same convention as telemetry.configure."""
    global _default_policy
    install(parse_plan(fault_plan, seed=fault_seed)
            if fault_plan else None)
    _default_policy = RetryPolicy(max_attempts=retry_max_attempts,
                                  base_delay_s=retry_base_delay_s,
                                  timeout_s=retry_timeout_s,
                                  seed=fault_seed)


def policy() -> RetryPolicy:
    """The process's default retry policy (library call sites use this
    so they never see the Config)."""
    return _default_policy


def retry(fn: Callable[[], T], site: str,
          transient: Tuple[type, ...] = TRANSIENT) -> T:
    """``policy().call`` shorthand for library call sites."""
    return _default_policy.call(fn, site, transient)
