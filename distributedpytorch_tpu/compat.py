"""Version-compatibility shims for the jax API surface the kernels use.

The framework is written against current jax — ``jax.shard_map`` (with
``check_vma``) and ``jax.typeof``'s vma-typed avals — but deployment
images carry a range of jaxlibs, and older ones still have shard_map in
``jax.experimental`` (with the checker spelled ``check_rep``) and no vma
typing at all.  XLA-level differences are probed the same way in
``__graft_entry__`` (collective-timeout flags); the jax-level ones live
here so kernel/model code keeps the modern spelling.
"""

from __future__ import annotations

import jax

# Layout-invariant PRNG: the framework's determinism story (utils.root_key
# fold_in streams feeding on-device augmentation) assumes random bits do
# NOT depend on how the consuming computation is sharded — current jax
# defaults to the partitionable threefry that guarantees this; older
# versions default to the layout-dependent lowering, where e.g. a
# model-parallel step draws different augmentation noise than the
# replicated step (tests pin them equal).  Opt in explicitly so both
# behave alike.
try:
    jax.config.update("jax_threefry_partitionable", True)
except Exception:  # config retired (newer jax: always on)
    pass


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` when available, else the jax.experimental one
    (same semantics; the replication checker kwarg was named
    ``check_rep`` there)."""
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def vma_of(x) -> frozenset:
    """Varying-manual-axes of ``x``'s aval; empty on jaxes without vma
    typing (there the strict checker doesn't exist either, so nothing
    needs declaring)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return frozenset(getattr(typeof(x), "vma", ()) or ())


def out_struct(shape, dtype, vma: frozenset) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct carrying ``vma`` when non-empty (a non-empty set
    can only come from a vma-typed jax, where the kwarg exists)."""
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)
