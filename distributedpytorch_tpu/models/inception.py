"""Inception v3 with auxiliary logits (ref utils.py:87-99).

Faithful to torchvision's inception_v3 topology: BasicConv (conv+BN+ReLU)
stem, Mixed_5x (InceptionA), Mixed_6a (B), Mixed_6b-e (C), Mixed_7a (D),
Mixed_7b-c (E), with AuxLogits branched off Mixed_6e during training.
Both classifier heads are replaced to ``num_classes`` (ref utils.py:93-98):
``head`` (primary fc) and ``aux_head`` (AuxLogits fc).  299x299 input
(ref utils.py:89: "Be careful, expects (299,299) sized images").

Train-mode call returns (logits, aux_logits) — consumed by the engine as
``loss1 + 0.4 * loss2`` exactly like ref classif.py:49-53.
"""

from __future__ import annotations

from typing import Any, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from .common import adaptive_avg_pool


class BasicConv(nn.Module):
    filters: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "VALID"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(self.filters, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding=[(1, 1), (1, 1)])


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train):
        c = lambda f, k, p="VALID": BasicConv(f, k, padding=p,  # noqa: E731
                                              dtype=self.dtype)
        b1 = c(64, (1, 1))(x, train)
        b5 = c(48, (1, 1))(x, train)
        b5 = c(64, (5, 5), [(2, 2), (2, 2)])(b5, train)
        b3 = c(64, (1, 1))(x, train)
        b3 = c(96, (3, 3), [(1, 1), (1, 1)])(b3, train)
        b3 = c(96, (3, 3), [(1, 1), (1, 1)])(b3, train)
        bp = c(self.pool_features, (1, 1))(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    dtype: Any

    @nn.compact
    def __call__(self, x, train):
        b3 = BasicConv(384, (3, 3), (2, 2), dtype=self.dtype)(x, train)
        bd = BasicConv(64, (1, 1), dtype=self.dtype)(x, train)
        bd = BasicConv(96, (3, 3), padding=[(1, 1), (1, 1)],
                       dtype=self.dtype)(bd, train)
        bd = BasicConv(96, (3, 3), (2, 2), dtype=self.dtype)(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train):
        c7 = self.channels_7x7
        h = [(0, 0), (3, 3)]   # padding for 1x7
        v = [(3, 3), (0, 0)]   # padding for 7x1
        b1 = BasicConv(192, (1, 1), dtype=self.dtype)(x, train)
        b7 = BasicConv(c7, (1, 1), dtype=self.dtype)(x, train)
        b7 = BasicConv(c7, (1, 7), padding=h, dtype=self.dtype)(b7, train)
        b7 = BasicConv(192, (7, 1), padding=v, dtype=self.dtype)(b7, train)
        bd = BasicConv(c7, (1, 1), dtype=self.dtype)(x, train)
        bd = BasicConv(c7, (7, 1), padding=v, dtype=self.dtype)(bd, train)
        bd = BasicConv(c7, (1, 7), padding=h, dtype=self.dtype)(bd, train)
        bd = BasicConv(c7, (7, 1), padding=v, dtype=self.dtype)(bd, train)
        bd = BasicConv(192, (1, 7), padding=h, dtype=self.dtype)(bd, train)
        bp = BasicConv(192, (1, 1), dtype=self.dtype)(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    dtype: Any

    @nn.compact
    def __call__(self, x, train):
        b3 = BasicConv(192, (1, 1), dtype=self.dtype)(x, train)
        b3 = BasicConv(320, (3, 3), (2, 2), dtype=self.dtype)(b3, train)
        b7 = BasicConv(192, (1, 1), dtype=self.dtype)(x, train)
        b7 = BasicConv(192, (1, 7), padding=[(0, 0), (3, 3)],
                       dtype=self.dtype)(b7, train)
        b7 = BasicConv(192, (7, 1), padding=[(3, 3), (0, 0)],
                       dtype=self.dtype)(b7, train)
        b7 = BasicConv(192, (3, 3), (2, 2), dtype=self.dtype)(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    dtype: Any

    @nn.compact
    def __call__(self, x, train):
        b1 = BasicConv(320, (1, 1), dtype=self.dtype)(x, train)
        b3 = BasicConv(384, (1, 1), dtype=self.dtype)(x, train)
        b3 = jnp.concatenate([
            BasicConv(384, (1, 3), padding=[(0, 0), (1, 1)],
                      dtype=self.dtype)(b3, train),
            BasicConv(384, (3, 1), padding=[(1, 1), (0, 0)],
                      dtype=self.dtype)(b3, train),
        ], axis=-1)
        bd = BasicConv(448, (1, 1), dtype=self.dtype)(x, train)
        bd = BasicConv(384, (3, 3), padding=[(1, 1), (1, 1)],
                       dtype=self.dtype)(bd, train)
        bd = jnp.concatenate([
            BasicConv(384, (1, 3), padding=[(0, 0), (1, 1)],
                      dtype=self.dtype)(bd, train),
            BasicConv(384, (3, 1), padding=[(1, 1), (0, 0)],
                      dtype=self.dtype)(bd, train),
        ], axis=-1)
        bp = BasicConv(192, (1, 1), dtype=self.dtype)(_avg_pool_same(x), train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class AuxHead(nn.Module):
    num_classes: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train):
        if x.shape[1] < 17 or x.shape[2] < 17:
            # Below 17x17 the 5x5-VALID conv after the pool receives an
            # empty tensor and XLA silently yields NaN logits (torchvision's
            # InceptionAux has the same floor and errors; ref utils.py:89
            # "expects (299,299) sized images").  Fail at trace time with an
            # actionable message instead.
            raise ValueError(
                f"inception aux head needs a >=17x17 feature map, which "
                f"requires >=299px inputs; got a {x.shape[1]}x{x.shape[2]} "
                f"map — use 299x299 inputs for train mode")
        x = nn.avg_pool(x, (5, 5), strides=(3, 3))
        x = BasicConv(128, (1, 1), dtype=self.dtype)(x, train)
        x = BasicConv(768, (5, 5), dtype=self.dtype)(x, train)
        x = adaptive_avg_pool(x, 1).reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        name="aux_head")(x)


class InceptionV3(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.bfloat16
    # --remat blocks: recompute each Mixed block's interior in backward.
    # The 299px stem stays un-checkpointed (it is a handful of convs; the
    # activation bulk sits in the 35x35/17x17 Mixed blocks).
    remat: bool = False
    # --scan-layers: the one homogeneous Mixed run (InceptionC_1 and
    # InceptionC_2 — both 768-in/768-out with c7=160) runs under
    # lax.scan as InceptionCScan_0 (models/scan.py); every other block
    # keeps its exact historical name.  Checkpoints convert across the
    # flag ('inception_scan' <-> 'inception_blocks').
    scan_layers: bool = False

    def _block(self, cls):
        """Block class, nn.remat-wrapped under --remat blocks.  Call sites
        pass explicit name= matching the historical auto-names so the
        param tree is identical either way."""
        if not self.remat:
            return cls
        # static_argnums=(2,): ``train`` (self is 0, x is 1).
        return nn.remat(
            cls, static_argnums=(2,),
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)

    @nn.compact
    def __call__(self, x, train: bool = False
                 ) -> Union[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
        inc_a = self._block(InceptionA)
        inc_b = self._block(InceptionB)
        inc_c = self._block(InceptionC)
        inc_d = self._block(InceptionD)
        inc_e = self._block(InceptionE)
        x = x.astype(self.dtype)
        x = BasicConv(32, (3, 3), (2, 2), dtype=self.dtype)(x, train)
        x = BasicConv(32, (3, 3), dtype=self.dtype)(x, train)
        x = BasicConv(64, (3, 3), padding=[(1, 1), (1, 1)],
                      dtype=self.dtype)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = BasicConv(80, (1, 1), dtype=self.dtype)(x, train)
        x = BasicConv(192, (3, 3), dtype=self.dtype)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = inc_a(32, self.dtype, name="InceptionA_0")(x, train)
        x = inc_a(64, self.dtype, name="InceptionA_1")(x, train)
        x = inc_a(64, self.dtype, name="InceptionA_2")(x, train)
        x = inc_b(self.dtype, name="InceptionB_0")(x, train)
        if self.scan_layers:
            from . import scan

            x = inc_c(128, self.dtype, name="InceptionC_0")(x, train)
            x = scan.scan_run(
                inc_c, 2, dict(channels_7x7=160, dtype=self.dtype),
                train, name="InceptionCScan_0")(x)
            x = inc_c(192, self.dtype, name="InceptionC_3")(x, train)
        else:
            for i, c7 in enumerate((128, 160, 160, 192)):
                x = inc_c(c7, self.dtype, name=f"InceptionC_{i}")(x, train)
        aux = AuxHead(self.num_classes, self.dtype)(x, train) if train \
            else None
        x = inc_d(self.dtype, name="InceptionD_0")(x, train)
        x = inc_e(self.dtype, name="InceptionE_0")(x, train)
        x = inc_e(self.dtype, name="InceptionE_1")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        x = x.astype(jnp.float32)
        if train:
            return x, aux.astype(jnp.float32)
        return x
