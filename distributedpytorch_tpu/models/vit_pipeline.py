"""Pipeline-parallel ViT: GPipe-style stage parallelism over the 'model'
mesh axis — the PP leg of the framework's parallelism taxonomy (dp /
ZeRO / TP / sequence-parallel ring / PP; the reference has data
parallelism ONLY, SURVEY §2 checklist).

TPU-native design:

  * the transformer blocks' parameters are STACKED on a leading (depth,)
    axis and sharded over 'model' — P pipeline stages each hold depth/P
    blocks' weights; nothing is replicated but the small embed/head ends;
  * execution is one `jax.shard_map` program: a `lax.scan` over
    P + M - 1 GPipe ticks, each tick applying this stage's blocks to its
    current microbatch and handing the activation to the next stage with
    `lax.ppermute` — neighbor-only ICI traffic, the same pattern as ring
    attention (ops/attention.py);
  * every stage computes every tick (idle ticks produce masked garbage) —
    the standard SPMD-GPipe trade that keeps control flow static for XLA;
  * the data axis is untouched: batches stay sharded over 'data', so PP
    composes with data parallelism on the same 2-D mesh;
  * PP also composes with RING sequence parallelism on a 3-D
    (data, model, seq) mesh (make_pipeline_fn(ring=True), CLI
    --seq-parallel N): tokens are sharded over 'seq' and each stage's
    attention runs the per-device ring body
    (ops.attention._ring_attention_local) — K/V rotate over 'seq'
    while microbatches flow over 'model';
  * backward is plain jax AD through the scan + ppermute — the reverse
    schedule (activations flowing backward through stages) falls out of
    the transpose of ppermute.

Numerics: the pipeline is EXACTLY a re-scheduling of the sequential
block chain — tests/test_pipeline.py pins pipelined forward AND
gradients to the same stacked-parameter blocks applied one after another
on one device, and trains it end-to-end through the CLI
(--pipeline-parallel P, vit only).

Blocks are hand-rolled pure functions (not nn sub-modules): the pipeline
body runs under shard_map over raw stacked arrays, so the math lives in
`_block_apply` and the module only declares the stacked parameters.
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .. import compat
from ..runtime import DATA_AXIS, MODEL_AXIS

_LN_EPS = 1e-6


def _layernorm(x, scale, bias):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + _LN_EPS)
    return (y * scale + bias).astype(x.dtype)


def _block_apply(p, x, heads: int, attn_fn=None):
    """One pre-LN transformer block; p holds THIS block's (unstacked)
    params.  Same math as models/vit.py TransformerBlock.  ``attn_fn``
    ((b,s,h,d) q/k/v -> (b,s,h,d)) replaces the inline softmax attention
    — the ring x pipeline composition injects the per-device ring body
    here (ops.attention._ring_attention_local over the 'seq' axis)."""
    b, s, dim = x.shape
    head_dim = dim // heads
    dtype = x.dtype

    h = _layernorm(x, p["ln1_scale"], p["ln1_bias"])
    qkv = h @ p["qkv_kernel"].astype(dtype) + p["qkv_bias"].astype(dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, heads, head_dim)
    k = k.reshape(b, s, heads, head_dim)
    v = v.reshape(b, s, heads, head_dim)
    if attn_fn is not None:
        attn = attn_fn(q, k, v).astype(dtype).reshape(b, s, dim)
    else:
        scale = 1.0 / np.sqrt(head_dim)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * scale
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
        attn = attn.astype(dtype).reshape(b, s, dim)
    x = x + (attn @ p["proj_kernel"].astype(dtype)
             + p["proj_bias"].astype(dtype))

    h = _layernorm(x, p["ln2_scale"], p["ln2_bias"])
    h = h @ p["up_kernel"].astype(dtype) + p["up_bias"].astype(dtype)
    h = nn.gelu(h)
    h = h @ p["down_kernel"].astype(dtype) + p["down_bias"].astype(dtype)
    return x + h


def _slice_block(stacked, i):
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
        stacked)


def sequential_blocks(stacked, x, heads: int, depth: int):
    """The unpipelined reference schedule: blocks applied in order."""

    def body(h, i):
        return _block_apply(_slice_block(stacked, i), h, heads), None

    out, _ = jax.lax.scan(body, x, jnp.arange(depth))
    return out


def _pipeline_local(stacked_local, x, *, heads: int, n_stages: int,
                    blocks_per_stage: int, n_micro: int, attn_fn=None):
    """Per-device GPipe body (runs under shard_map): ``stacked_local`` is
    this stage's (blocks_per_stage, ...) slice; ``x`` the device-local
    batch (B_local, S, dim).  Returns this device's (B_local, S, dim)
    output — only the LAST stage's is real; shard_map's out spec reads it
    from there."""
    stage = jax.lax.axis_index(MODEL_AXIS)
    b, s, dim = x.shape
    mb = b // n_micro
    micro = x.reshape(n_micro, mb, s, dim)
    n_ticks = n_stages + n_micro - 1

    def stage_fn(h):
        def body(a, i):
            return _block_apply(_slice_block(stacked_local, i), a,
                                heads, attn_fn), None

        out, _ = jax.lax.scan(body, h, jnp.arange(blocks_per_stage))
        return out

    def tick(carry, t):
        act, out = carry
        mb_idx = t - stage
        fresh = jax.lax.dynamic_index_in_dim(
            micro, jnp.clip(mb_idx, 0, n_micro - 1), 0, keepdims=False)
        x_in = jnp.where(stage == 0, fresh, act)
        y = stage_fn(x_in)
        # hand to the next stage (stage P-1 keeps its result)
        received = jax.lax.ppermute(
            y, MODEL_AXIS, [(i, i + 1) for i in range(n_stages - 1)])
        # last stage stores finished microbatches; inactive ticks write
        # to the scratch slot n_micro
        active = ((stage == n_stages - 1) & (mb_idx >= 0)
                  & (mb_idx < n_micro))
        slot = jnp.where(active, jnp.clip(mb_idx, 0, n_micro - 1), n_micro)
        out = jax.lax.dynamic_update_index_in_dim(out, y, slot, 0)
        return (received, out), None

    # Initial carries must already carry the varying type the loop outputs
    # have: varying over 'model' (axis_index/ppermute products) AND over
    # 'data' (the microbatches come from the data-sharded input) — lax.scan
    # under shard_map requires carry in/out vma types to match exactly, so
    # seed them with a zero derived from BOTH sources (same trick as
    # ops/attention.py's ring carry, extended to the second mesh axis).
    vzero = (micro[0, :1, :1, :1] * 0 + stage * 0).astype(x.dtype)
    out0 = jnp.zeros((n_micro + 1, mb, s, dim), x.dtype) + vzero
    (_, out), _ = jax.lax.scan(
        tick, (jnp.zeros((mb, s, dim), x.dtype) + vzero, out0),
        jnp.arange(n_ticks))
    result = out[:n_micro].reshape(b, s, dim)
    # Only the last stage holds real results; the psum over the masked
    # values broadcasts them to every stage, making the output provably
    # replicated over MODEL_AXIS (required by the out spec) — one
    # activation-sized all-reduce per forward.
    mask = (stage == n_stages - 1).astype(result.dtype)
    return jax.lax.psum(result * mask, MODEL_AXIS)


def make_pipeline_fn(mesh, n_stages: int, depth: int, heads: int,
                     n_micro: Optional[int] = None, ring: bool = False):
    """(stacked_params, tokens (B,S,dim)) -> (B,S,dim), pipelined over
    ``mesh``'s 'model' axis.  Closure injected into PipelinedViT.

    ``ring=True`` composes GPipe with ring sequence parallelism on a
    3-D (data, model, seq) mesh (VERDICT r5 item 7): the token axis is
    sharded over 'seq', and each stage's attention runs the per-device
    ring body (ops.attention._ring_attention_local) — K/V blocks rotate
    over the 'seq' axis while microbatches flow over 'model'.  Tokens
    are padded to a 'seq' multiple with the padded keys masked
    (kv_valid), exactly like the standalone ring path."""
    from jax.sharding import PartitionSpec as P

    if depth % n_stages:
        raise ValueError(f"depth {depth} not divisible by "
                         f"--pipeline-parallel {n_stages}")
    n_micro = n_micro or n_stages
    blocks_per_stage = depth // n_stages
    seq_n = 1
    if ring:
        from ..runtime import SEQ_AXIS

        if SEQ_AXIS not in mesh.shape or mesh.shape[SEQ_AXIS] < 2:
            raise ValueError(
                "--attention ring with --pipeline-parallel runs on a "
                "3-D mesh: pass --seq-parallel >= 2")
        seq_n = mesh.shape[SEQ_AXIS]

    def fn(stacked, tokens):
        b, s, _dim = tokens.shape
        dp = mesh.shape[DATA_AXIS]
        shard_batch = b % dp == 0          # init-time dummies are smaller
        b_local = b // dp if shard_batch else b
        if b_local < n_micro:
            # tiny tracing batches (model init): identical math, no
            # pipeline — keeps shapes unconstrained where perf is moot
            # (init only creates params, so the ring is skipped too)
            if b_local > 2:
                logging.getLogger(__name__).warning(
                    "pipeline: per-device batch %d < %d microbatches; "
                    "running the sequential schedule (no pipelining)",
                    b_local, n_micro)
            return sequential_blocks(stacked, tokens, heads, depth)
        if b_local % n_micro:
            # A REAL batch that doesn't divide must not silently fall
            # back to the sequential schedule (the user asked for a
            # pipeline); cli.py validates this up front for product runs.
            raise ValueError(
                f"per-device batch {b_local} not divisible by "
                f"pipeline microbatches {n_micro}")
        attn_fn = None
        if ring:
            from ..ops.attention import _ring_attention_local
            from ..runtime import SEQ_AXIS

            pad = (-s) % seq_n
            if pad:
                tokens = jnp.pad(tokens, ((0, 0), (0, pad), (0, 0)))
            attn_fn = functools.partial(
                _ring_attention_local, axis_name=SEQ_AXIS, n_dev=seq_n,
                s_local=(s + pad) // seq_n, causal=False,
                kv_valid=s if pad else None)
            data_spec = (P(DATA_AXIS, SEQ_AXIS, None) if shard_batch
                         else P(None, SEQ_AXIS, None))
        else:
            data_spec = (P(DATA_AXIS, None, None) if shard_batch
                         else P(None, None, None))
        param_specs = jax.tree_util.tree_map(
            lambda leaf: P(MODEL_AXIS, *([None] * (leaf.ndim - 1))),
            stacked)
        body = functools.partial(
            _pipeline_local, heads=heads, n_stages=n_stages,
            blocks_per_stage=blocks_per_stage, n_micro=n_micro,
            attn_fn=attn_fn)
        out = compat.shard_map(
            body, mesh=mesh,
            in_specs=(param_specs, data_spec),
            out_specs=data_spec)(stacked, tokens)
        return out[:, :s] if out.shape[1] != s else out

    return fn


# ---------------------------------------------------------------------------
# Checkpoint layout conversion: PipelinedViT stores block params STACKED on
# a leading (depth,) axis; the plain ViT (models/vit.py) stores them as
# per-block submodules block{i}/{qkv,proj,mlp_up,mlp_down,LayerNorm_0,_1}.
# The math is identical (tests/test_pipeline.py pins the schedules equal),
# so a checkpoint from either can serve the other: checkpoint.py calls
# convert_layout at load time when the saved layout differs from the
# requested model's (ref parity anchor: self-describing checkpoints,
# classif.py:214 — eval must work from the file alone).

# stacked name -> (block submodule, leaf) in plain-ViT naming
_STACK_TO_BLOCK = {
    "ln1_scale": ("LayerNorm_0", "scale"),
    "ln1_bias": ("LayerNorm_0", "bias"),
    "qkv_kernel": ("qkv", "kernel"),
    "qkv_bias": ("qkv", "bias"),
    "proj_kernel": ("proj", "kernel"),
    "proj_bias": ("proj", "bias"),
    "ln2_scale": ("LayerNorm_1", "scale"),
    "ln2_bias": ("LayerNorm_1", "bias"),
    "up_kernel": ("mlp_up", "kernel"),
    "up_bias": ("mlp_up", "bias"),
    "down_kernel": ("mlp_down", "kernel"),
    "down_bias": ("mlp_down", "bias"),
}


def params_layout(sd) -> Optional[str]:
    """'stacked' (PipelinedViT) | 'blocks' (ViT) | None for a params-like
    mapping (state dict or live tree)."""
    if not isinstance(sd, dict):
        return None
    if all(k in sd for k in _STACK_TO_BLOCK):
        return "stacked"
    if "block0" in sd and isinstance(sd["block0"], dict) \
            and "qkv" in sd["block0"]:
        return "blocks"
    return None


def _leaf_slice(v, i: int):
    """v[i] for arrays; shape-level slice for abstract
    jax.ShapeDtypeStruct leaves (orbax restore targets)."""
    if isinstance(v, jax.ShapeDtypeStruct):
        return jax.ShapeDtypeStruct(v.shape[1:], v.dtype,
                                    sharding=v.sharding)
    return np.asarray(v)[i]


def _leaf_stack(leaves):
    first = leaves[0]
    if isinstance(first, jax.ShapeDtypeStruct):
        return jax.ShapeDtypeStruct((len(leaves),) + tuple(first.shape),
                                    first.dtype, sharding=first.sharding)
    return np.stack([np.asarray(v) for v in leaves])


def _stacked_to_blocks(sd: dict) -> dict:
    depth = int(sd["qkv_kernel"].shape[0])
    out = {k: v for k, v in sd.items() if k not in _STACK_TO_BLOCK}
    for i in range(depth):
        blk: dict = {}
        for stacked_name, (sub, leaf) in _STACK_TO_BLOCK.items():
            blk.setdefault(sub, {})[leaf] = _leaf_slice(sd[stacked_name], i)
        out[f"block{i}"] = blk
    return out


def _blocks_to_stacked(sd: dict) -> dict:
    blocks = sorted((k for k in sd if k.startswith("block")
                     and k[5:].isdigit()), key=lambda s: int(s[5:]))
    out = {k: v for k, v in sd.items() if k not in blocks}
    for stacked_name, (sub, leaf) in _STACK_TO_BLOCK.items():
        out[stacked_name] = _leaf_stack([sd[b][sub][leaf] for b in blocks])
    return out


def convert_layout(tree, target: str):
    """Recursively convert every params-shaped subtree of ``tree`` (a
    checkpoint state dict: params AND the optimizer moments, which mirror
    the params structure) to ``target`` ('stacked' | 'blocks').  Subtrees
    already in the target layout — and non-params leaves like step/count —
    pass through untouched."""
    if target not in ("stacked", "blocks"):
        raise ValueError(f"unknown layout {target!r}")
    layout = params_layout(tree)
    if layout == target:
        return tree
    if layout == "stacked":
        return _stacked_to_blocks(tree)
    if layout == "blocks":
        return _blocks_to_stacked(tree)
    if isinstance(tree, dict):
        return {k: convert_layout(v, target) for k, v in tree.items()}
    return tree


class PipelinedViT(nn.Module):
    """ViT with stacked-block parameters and an injectable block
    executor: ``pipeline_fn`` (make_pipeline_fn) runs the blocks GPipe-
    style; None runs them sequentially (the numerics reference and the
    single-device fallback).  Same patch-embed/mean-pool/head structure
    as models/vit.py, but block params live as (depth, ...) stacks, so
    its checkpoints are a distinct (documented) layout."""

    num_classes: int = 10
    patch: int = 4
    dim: int = 128
    depth: int = 4
    heads: int = 4
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    pipeline_fn: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        d, dep = self.dim, self.depth
        x = x.astype(self.dtype)
        x = nn.Conv(d, (self.patch, self.patch),
                    strides=(self.patch, self.patch), padding="VALID",
                    dtype=self.dtype, name="patch_embed")(x)
        b, gh, gw, _ = x.shape
        x = x.reshape(b, gh * gw, d)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, gh * gw, d), jnp.float32)
        x = x + pos.astype(self.dtype)

        # batch_axis=0: fan-in/out computed per block, not across the
        # stacked (depth,) axis
        init = nn.initializers.lecun_normal(batch_axis=0)
        zeros, ones = nn.initializers.zeros, nn.initializers.ones

        def stacked(name, initfn, shape):
            return self.param(name, initfn, shape, jnp.float32)

        blocks = {
            "ln1_scale": stacked("ln1_scale", ones, (dep, d)),
            "ln1_bias": stacked("ln1_bias", zeros, (dep, d)),
            "qkv_kernel": stacked("qkv_kernel", init, (dep, d, 3 * d)),
            "qkv_bias": stacked("qkv_bias", zeros, (dep, 3 * d)),
            "proj_kernel": stacked("proj_kernel", init, (dep, d, d)),
            "proj_bias": stacked("proj_bias", zeros, (dep, d)),
            "ln2_scale": stacked("ln2_scale", ones, (dep, d)),
            "ln2_bias": stacked("ln2_bias", zeros, (dep, d)),
            "up_kernel": stacked("up_kernel", init,
                                 (dep, d, self.mlp_ratio * d)),
            "up_bias": stacked("up_bias", zeros, (dep, self.mlp_ratio * d)),
            "down_kernel": stacked("down_kernel", init,
                                   (dep, self.mlp_ratio * d, d)),
            "down_bias": stacked("down_bias", zeros, (dep, d)),
        }
        if self.pipeline_fn is not None:
            x = self.pipeline_fn(blocks, x)
        else:
            x = sequential_blocks(blocks, x, self.heads, dep)

        x = nn.LayerNorm(epsilon=_LN_EPS, dtype=self.dtype)(x)
        x = jnp.mean(x, axis=1)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)
