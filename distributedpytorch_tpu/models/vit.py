"""ViT-style patch-transformer classifier — the framework's attention
model family.

The reference zoo is CNN-only (ref utils.py:38-105); this model is
framework-added capability and the consumer of the sequence-parallel
attention in ops/attention.py.  Built TPU-first:

  * patch embedding is a strided conv (one im2col matmul on the MXU);
  * pre-LN transformer blocks with GELU MLPs — all dense matmuls,
    bfloat16 compute / float32 params like the rest of the zoo;
  * mean-pool over tokens (no CLS token: one less ragged concat to shard),
    classifier uniformly named ``head`` so feature-extract freezing and
    head replacement work exactly like every other zoo model;
  * ``attention_fn`` is injectable: the default is the standard fused
    softmax attention (XLA's flash kernels on TPU); passing a closure over
    ``ops.attention.ring_attention`` runs the same model sequence-parallel
    for sequences too long for one device (tests/test_attention.py pins
    the two paths equal).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import full_attention

AttentionFn = Callable[..., jnp.ndarray]  # (q, k, v) -> out, all (B,S,H,D)


class TransformerBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int
    dtype: Any
    attention_fn: AttentionFn

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, s, _ = x.shape
        head_dim = self.dim // self.heads

        h = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.dim, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.heads, head_dim)
        k = k.reshape(b, s, self.heads, head_dim)
        v = v.reshape(b, s, self.heads, head_dim)
        attn = self.attention_fn(q, k, v).reshape(b, s, self.dim)
        x = x + nn.Dense(self.dim, dtype=self.dtype, name="proj")(attn)

        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_ratio * self.dim, dtype=self.dtype,
                     name="mlp_up")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")(h)
        return x + h


class ViT(nn.Module):
    """Small vision transformer; defaults size it for 28x28 inputs
    (patch 4 -> 49 tokens) at ~1.6M params."""

    num_classes: int = 10
    patch: int = 4
    dim: int = 128
    depth: int = 4
    heads: int = 4
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        attn_fn = self.attention_fn or full_attention
        x = x.astype(self.dtype)
        x = nn.Conv(self.dim, (self.patch, self.patch),
                    strides=(self.patch, self.patch), padding="VALID",
                    dtype=self.dtype, name="patch_embed")(x)
        b, gh, gw, c = x.shape
        x = x.reshape(b, gh * gw, c)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, gh * gw, self.dim), jnp.float32)
        x = x + pos.astype(self.dtype)
        for i in range(self.depth):
            x = TransformerBlock(self.dim, self.heads, self.mlp_ratio,
                                 self.dtype, attn_fn,
                                 name=f"block{i}")(x, train=train)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = jnp.mean(x, axis=1)  # mean-pool tokens
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)
