"""ViT-style patch-transformer classifier — the framework's attention
model family.

The reference zoo is CNN-only (ref utils.py:38-105); this model is
framework-added capability and the consumer of the sequence-parallel
attention in ops/attention.py.  Built TPU-first:

  * patch embedding is a strided conv (one im2col matmul on the MXU);
  * pre-LN transformer blocks with GELU MLPs — all dense matmuls,
    bfloat16 compute / float32 params like the rest of the zoo;
  * mean-pool over tokens (no CLS token: one less ragged concat to shard),
    classifier uniformly named ``head`` so feature-extract freezing and
    head replacement work exactly like every other zoo model;
  * ``attention_fn`` is injectable: the default is the standard fused
    softmax attention (XLA's flash kernels on TPU); passing a closure over
    ``ops.attention.ring_attention`` runs the same model sequence-parallel
    for sequences too long for one device (tests/test_attention.py pins
    the two paths equal);
  * ``tp_constrain`` is injectable (parallel.make_tp_constrain): when set,
    activation sharding constraints pin attention heads and the MLP hidden
    axis to the 'model' mesh axis — Megatron-style tensor parallelism with
    GSPMD doing the matmul partitioning and inserting the per-block
    all-reduce (see parallel.py's strategy-2 docs).  Constraints never
    change the math, only the layout (tests/test_tensor_parallel.py).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

import jax

from ..ops.attention import full_attention
from ..runtime import DATA_AXIS, MODEL_AXIS

AttentionFn = Callable[..., jnp.ndarray]  # (q, k, v) -> out, all (B,S,H,D)
ConstrainFn = Callable[..., jnp.ndarray]  # (x, partition-spec tuple) -> x


class TransformerBlock(nn.Module):
    dim: int
    heads: int
    mlp_ratio: int
    dtype: Any
    attention_fn: AttentionFn
    tp_constrain: Optional[ConstrainFn] = None
    # > 0 replaces the dense MLP with a switch mixture-of-experts of that
    # many experts (models/moe.py) — the expert-parallel family member.
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    # sharding-constraint fn for the expert axis (expert parallelism);
    # separate from tp_constrain so EP does not imply head/hidden TP
    moe_constrain: Optional[ConstrainFn] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, s, _ = x.shape
        head_dim = self.dim // self.heads
        tp = self.tp_constrain or (lambda a, _spec: a)

        h = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.dim, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # Heads on MODEL_AXIS: the qkv matmul becomes column-parallel
        # (each device computes its own heads' slice) and attention runs
        # fully locally per head-shard.
        spec_bshd = (DATA_AXIS, None, MODEL_AXIS, None)
        q = tp(q.reshape(b, s, self.heads, head_dim), spec_bshd)
        k = tp(k.reshape(b, s, self.heads, head_dim), spec_bshd)
        v = tp(v.reshape(b, s, self.heads, head_dim), spec_bshd)
        attn = self.attention_fn(q, k, v).reshape(b, s, self.dim)
        # proj is then row-parallel; the residual sum is the block's one
        # all-reduce point (GSPMD inserts it to satisfy this constraint).
        x = x + nn.Dense(self.dim, dtype=self.dtype, name="proj")(attn)
        x = tp(x, (DATA_AXIS, None, None))

        h = nn.LayerNorm(dtype=self.dtype)(x)
        if self.moe_experts > 0:
            from .moe import SwitchMLP

            h = SwitchMLP(dim=self.dim,
                          hidden=self.mlp_ratio * self.dim,
                          num_experts=self.moe_experts,
                          capacity_factor=self.moe_capacity_factor,
                          dtype=self.dtype, ep_constrain=self.moe_constrain,
                          name="moe")(h, train=train)
            x = x + h
        else:
            h = nn.Dense(self.mlp_ratio * self.dim, dtype=self.dtype,
                         name="mlp_up")(h)
            # MLP hidden on MODEL_AXIS: column-parallel up, row-parallel
            # down.
            h = tp(nn.gelu(h), (DATA_AXIS, None, MODEL_AXIS))
            h = nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")(h)
            x = x + h
        return tp(x, (DATA_AXIS, None, None))


class ViT(nn.Module):
    """Small vision transformer; defaults size it for 28x28 inputs
    (patch 4 -> 49 tokens) at ~1.6M params."""

    num_classes: int = 10
    patch: int = 4
    dim: int = 128
    depth: int = 4
    heads: int = 4
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[AttentionFn] = None
    tp_constrain: Optional[ConstrainFn] = None
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_constrain: Optional[ConstrainFn] = None
    # --remat blocks: rematerialize each transformer block's interior in
    # backward, keeping matmul outputs (the MXU work is not recomputed,
    # only the cheap elementwise/normalization ops are).
    remat: bool = False
    # --scan-layers: run all ``depth`` blocks under one lax.scan with
    # block params stacked on a leading (depth,) axis — O(1) HLO in
    # depth instead of O(depth) (models/scan.py; checkpoints convert
    # across the flag via the 'scan' <-> 'blocks' layout pair).
    scan_layers: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        attn_fn = self.attention_fn or full_attention
        block_cls = TransformerBlock
        if self.remat:
            # static_argnums=(2,): ``train`` (self is 0, x is 1).  The
            # explicit name= below keeps the param tree identical to the
            # unwrapped module (nn.remat would otherwise auto-name
            # instances CheckpointTransformerBlock_i).
            block_cls = nn.remat(
                TransformerBlock, static_argnums=(2,),
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        x = x.astype(self.dtype)
        x = nn.Conv(self.dim, (self.patch, self.patch),
                    strides=(self.patch, self.patch), padding="VALID",
                    dtype=self.dtype, name="patch_embed")(x)
        b, gh, gw, c = x.shape
        x = x.reshape(b, gh * gw, c)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, gh * gw, self.dim), jnp.float32)
        x = x + pos.astype(self.dtype)
        if self.scan_layers:
            from . import scan

            x = scan.scan_run(
                block_cls, self.depth,
                dict(dim=self.dim, heads=self.heads,
                     mlp_ratio=self.mlp_ratio, dtype=self.dtype,
                     attention_fn=attn_fn, tp_constrain=self.tp_constrain,
                     moe_experts=self.moe_experts,
                     moe_capacity_factor=self.moe_capacity_factor,
                     moe_constrain=self.moe_constrain),
                train, name="blocks")(x)
        else:
            for i in range(self.depth):
                x = block_cls(self.dim, self.heads, self.mlp_ratio,
                              self.dtype, attn_fn, self.tp_constrain,
                              moe_experts=self.moe_experts,
                              moe_capacity_factor=self.moe_capacity_factor,
                              moe_constrain=self.moe_constrain,
                              name=f"block{i}")(x, train)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = jnp.mean(x, axis=1)  # mean-pool tokens
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)
