"""DenseNet-121 (ref utils.py:78-85 wraps torchvision densenet121).

Growth rate 32, block config (6, 12, 24, 16), bn_size 4, 0.5 transition
compression — torchvision's densenet121 exactly; final dense layer (the one
the reference replaces at utils.py:83-84) named ``head``.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class DenseLayer(nn.Module):
    growth: int
    bn_size: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = functools.partial(nn.BatchNorm, use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype)
        y = nn.relu(norm()(x))
        y = nn.Conv(self.bn_size * self.growth, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.relu(norm()(y))
        y = nn.Conv(self.growth, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        return jnp.concatenate([x, y], axis=-1)


class DenseNet(nn.Module):
    block_config: Sequence[int] = (6, 12, 24, 16)
    growth: int = 32
    bn_size: int = 4
    num_init_features: int = 64
    num_classes: int = 10
    dtype: Any = jnp.bfloat16
    # --remat blocks: recompute each DenseLayer's interior in backward.
    # DenseNet is the zoo's worst activation hog (every layer's input is
    # the concat of all earlier features), so this is the model the knob
    # was built for.
    remat: bool = False
    # --scan-layers: each dense block's DenseLayer chain runs under one
    # lax.scan over a zero-padded channel buffer (models/scan.py
    # _DenseStep) — 58 inlined layers collapse to 4 scan bodies, the
    # biggest compile-time win in the zoo.  Checkpoints convert across
    # the flag ('dense_scan' <-> 'dense_layers').
    scan_layers: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = functools.partial(nn.BatchNorm, use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype)
        layer_cls = DenseLayer
        if self.remat:
            # static_argnums=(2,): ``train`` (self is 0, x is 1).
            layer_cls = nn.remat(
                DenseLayer, static_argnums=(2,),
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        layer_idx = 0
        x = x.astype(self.dtype)
        x = nn.Conv(self.num_init_features, (7, 7), strides=(2, 2),
                    padding=[(3, 3), (3, 3)], use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.relu(norm()(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, n_layers in enumerate(self.block_config):
            if self.scan_layers:
                from . import scan

                x = scan.scan_dense_block(
                    n_layers, x.shape[-1], self.growth, self.bn_size,
                    self.dtype, train, name=f"DenseBlockScan_{i}",
                    remat=self.remat)(x)
                layer_idx += n_layers
            else:
                for _ in range(n_layers):
                    # Explicit name matching the historical auto-name, so
                    # the param tree (and every checkpoint) is identical
                    # with and without remat.
                    x = layer_cls(self.growth, self.bn_size, self.dtype,
                                  name=f"DenseLayer_{layer_idx}")(x, train)
                    layer_idx += 1
            if i != len(self.block_config) - 1:  # transition
                x = nn.relu(norm()(x))
                x = nn.Conv(x.shape[-1] // 2, (1, 1), use_bias=False,
                            dtype=self.dtype)(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(norm()(x))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


def densenet121(num_classes: int, dtype=jnp.bfloat16, remat: bool = False,
                scan_layers: bool = False) -> DenseNet:
    return DenseNet(num_classes=num_classes, dtype=dtype, remat=remat,
                    scan_layers=scan_layers)
