"""DenseNet-121 (ref utils.py:78-85 wraps torchvision densenet121).

Growth rate 32, block config (6, 12, 24, 16), bn_size 4, 0.5 transition
compression — torchvision's densenet121 exactly; final dense layer (the one
the reference replaces at utils.py:83-84) named ``head``.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class DenseLayer(nn.Module):
    growth: int
    bn_size: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = functools.partial(nn.BatchNorm, use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype)
        y = nn.relu(norm()(x))
        y = nn.Conv(self.bn_size * self.growth, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.relu(norm()(y))
        y = nn.Conv(self.growth, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        return jnp.concatenate([x, y], axis=-1)


class DenseNet(nn.Module):
    block_config: Sequence[int] = (6, 12, 24, 16)
    growth: int = 32
    bn_size: int = 4
    num_init_features: int = 64
    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = functools.partial(nn.BatchNorm, use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.Conv(self.num_init_features, (7, 7), strides=(2, 2),
                    padding=[(3, 3), (3, 3)], use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.relu(norm()(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, n_layers in enumerate(self.block_config):
            for _ in range(n_layers):
                x = DenseLayer(self.growth, self.bn_size, self.dtype)(x, train)
            if i != len(self.block_config) - 1:  # transition
                x = nn.relu(norm()(x))
                x = nn.Conv(x.shape[-1] // 2, (1, 1), use_bias=False,
                            dtype=self.dtype)(x)
                x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(norm()(x))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


def densenet121(num_classes: int, dtype=jnp.bfloat16) -> DenseNet:
    return DenseNet(num_classes=num_classes, dtype=dtype)
