"""Small MNIST-scale models: the benchmark flagships.

These are this framework's additions beyond the reference zoo (BASELINE.md
configs 1-3 name "MNIST CNN" and "MNIST MLP" as the primary benchmark
models): they run at native 28x28 so the north-star samples/sec/chip metric
measures the framework, not a 224x224 upsample.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ..ops.pooling import max_pool_2x2


class SmallCNN(nn.Module):
    """Conv-conv-pool x2 + dense.  Channel widths are multiples of 32/64 so
    XLA tiles the im2col matmuls cleanly onto the 128x128 MXU; pooling uses
    the select-and-scatter-free max_pool_2x2 (ops/pooling.py).

    ``pallas_dw=True`` swaps the multi-channel convs' WEIGHT-GRADIENT
    computation for the patch-reuse Pallas kernel (ops/conv.py) — same
    forward, same dx, same param tree (explicit ``Conv_i`` name slots),
    so checkpoints are interchangeable.  Conv_0 (Ci=1) stays on nn.Conv:
    its 9-row patch matrix can't fill a sublane tile and XLA's native dW
    is already fine there."""

    num_classes: int = 10
    dtype: Any = jnp.bfloat16
    pallas_dw: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        i = 0
        for width in (32, 64):
            for _ in range(2):
                if self.pallas_dw and x.shape[-1] >= 32:
                    from ..ops.conv import Conv3x3

                    x = Conv3x3(width, dtype=self.dtype,
                                name=f"Conv_{i}")(x)
                else:
                    x = nn.Conv(width, (3, 3), padding="SAME",
                                dtype=self.dtype, name=f"Conv_{i}")(x)
                x = nn.relu(x)
                i += 1
            x = max_pool_2x2(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(256, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


class MLP(nn.Module):
    """784->512->256->classes; exercises pure-dense allreduce
    (BASELINE.md config 3: 'non-conv param allreduce')."""

    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, dtype=self.dtype)(x))
        x = nn.relu(nn.Dense(256, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)
