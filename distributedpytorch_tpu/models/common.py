"""Shared model building blocks."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def adaptive_avg_pool(x: jax.Array, out_hw: int) -> jax.Array:
    """NHWC adaptive average pool to (out_hw, out_hw).

    Equivalent of torch's AdaptiveAvgPool2d for the exact-divisor case the
    zoo hits at its canonical input sizes; falls back to a bilinear resize
    of the mean-pooled map otherwise.
    """
    b, h, w, c = x.shape
    if h == out_hw and w == out_hw:
        return x
    if h % out_hw == 0 and w % out_hw == 0:
        kh, kw = h // out_hw, w // out_hw
        return jnp.mean(
            x.reshape(b, out_hw, kh, out_hw, kw, c), axis=(2, 4))
    return jax.image.resize(x, (b, out_hw, out_hw, c), method="bilinear")
