"""VGG11 with BatchNorm (ref utils.py:60-67 wraps torchvision vgg11_bn).

Config 'A': convs (64, M, 128, M, 256, 256, M, 512, 512, M, 512, 512, M),
each conv followed by BN+ReLU; adaptive 7x7 pool; 4096-4096 classifier with
dropout and the final layer (the one the reference replaces at
utils.py:65-66) named ``head``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from .common import adaptive_avg_pool
from ..ops.pooling import max_pool_2x2

_VGG11 = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M")


class VGG11BN(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.bfloat16
    # --scan-layers: the one homogeneous conv run (the trailing 512->512
    # pair, historical names Conv_6/Conv_7) runs under lax.scan as
    # ConvScan_0 (models/scan.py); earlier convs keep their exact names.
    # Checkpoints convert across the flag ('vgg_scan' <-> 'vgg_layers').
    scan_layers: bool = False

    # index of the first conv of the scannable homogeneous run, and its
    # length, within _VGG11's conv sequence
    _SCAN_START, _SCAN_LEN = 6, 2

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        conv_idx = 0
        for v in _VGG11:
            if v == "M":
                # select-and-scatter-free backward (ops/pooling.py)
                x = max_pool_2x2(x)
                continue
            if self.scan_layers and conv_idx == self._SCAN_START:
                from . import scan

                x = scan.scan_vgg_run(self._SCAN_LEN, v, self.dtype,
                                      train, name="ConvScan_0")(x)
            elif not (self.scan_layers
                      and self._SCAN_START < conv_idx
                      < self._SCAN_START + self._SCAN_LEN):
                # bias kept despite the following BN: torchvision's
                # make_layers leaves Conv2d bias on in vgg11_bn, and exact
                # param/state_dict parity matters for pretrained loading.
                x = nn.Conv(v, (3, 3), padding="SAME", use_bias=True,
                            dtype=self.dtype)(x)
                x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 dtype=self.dtype)(x)
                x = nn.relu(x)
            conv_idx += 1
        x = adaptive_avg_pool(x, 7)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)
