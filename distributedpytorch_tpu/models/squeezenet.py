"""SqueezeNet 1.0 (ref utils.py:69-76 wraps torchvision squeezenet1_0).

Fire modules (squeeze 1x1 -> expand 1x1 + 3x3 concat); the classifier is a
dropout + 1x1 conv to ``num_classes`` + ReLU + global average pool — the
conv is exactly the layer the reference replaces (ref utils.py:74), named
``head`` here.

Max-pools replicate torchvision's ``ceil_mode=True`` (MaxPool2d(3, 2,
ceil_mode=True)): when (dim - 3) is odd the window grid is padded one
element on the bottom/right, so feature-map sizes — and therefore converted
pretrained weights' activations — match torchvision exactly (e.g. 54 -> 27,
not 26, at the second pool on a 224 input).

Compatibility note: this geometry (VALID stem + ceil pools) replaced an
earlier SAME-stem/floor-pool variant; param shapes are identical, so a
checkpoint from the old variant still loads but its activations flow
through a shifted grid.  No released checkpoint predates the fix.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


def _max_pool_ceil(x, window: int = 3, stride: int = 2):
    """torchvision MaxPool2d(window, stride, ceil_mode=True)."""
    pads = []
    for dim in (x.shape[1], x.shape[2]):
        rem = (dim - window) % stride
        pads.append((0, (stride - rem) % stride if rem else 0))
    return nn.max_pool(x, (window, window), strides=(stride, stride),
                       padding=pads)


class Fire(nn.Module):
    squeeze: int
    expand1: int
    expand3: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Conv(self.squeeze, (1, 1), dtype=self.dtype)(x))
        e1 = nn.relu(nn.Conv(self.expand1, (1, 1), dtype=self.dtype)(x))
        e3 = nn.relu(nn.Conv(self.expand3, (3, 3), padding="SAME",
                             dtype=self.dtype)(x))
        return jnp.concatenate([e1, e3], axis=-1)


class SqueezeNet(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(96, (7, 7), strides=(2, 2), padding="VALID",
                            dtype=self.dtype)(x))
        x = _max_pool_ceil(x)
        x = Fire(16, 64, 64, self.dtype)(x)
        x = Fire(16, 64, 64, self.dtype)(x)
        x = Fire(32, 128, 128, self.dtype)(x)
        x = _max_pool_ceil(x)
        x = Fire(32, 128, 128, self.dtype)(x)
        x = Fire(48, 192, 192, self.dtype)(x)
        x = Fire(48, 192, 192, self.dtype)(x)
        x = Fire(64, 256, 256, self.dtype)(x)
        x = _max_pool_ceil(x)
        x = Fire(64, 256, 256, self.dtype)(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Conv(self.num_classes, (1, 1), dtype=self.dtype,
                            name="head")(x))
        x = jnp.mean(x, axis=(1, 2))
        return x.astype(jnp.float32)
