"""Model registry: name -> module / input size / freeze mask.

Replaces ref utils.py getModel (:38-105), getModelInputSize (:24-36) and
setParameterRequiresGrad (:107-110).  Invalid names raise ValueError (the
reference logs and exit()s; callers map this to the same behavior).
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import flax.linen as nn
import jax.numpy as jnp

from .alexnet import AlexNet
from .densenet import densenet121
from .inception import InceptionV3
from .resnet import resnet18
from .simple import MLP, SmallCNN
from .squeezenet import SqueezeNet
from .vgg import VGG11BN
from .vit import ViT

MODEL_REGISTRY: Dict[str, Callable[..., nn.Module]] = {
    "cnn": lambda n, d, r, s: SmallCNN(num_classes=n, dtype=d),
    "mlp": lambda n, d, r, s: MLP(num_classes=n, dtype=d),
    "resnet": lambda n, d, r, s: resnet18(n, d),     # ref utils.py:42-49
    "alexnet": lambda n, d, r, s: AlexNet(num_classes=n, dtype=d),  # :51-58
    "vgg": lambda n, d, r, s: VGG11BN(num_classes=n, dtype=d,
                                      scan_layers=s),        # :60-67
    "squeezenet": lambda n, d, r, s: SqueezeNet(num_classes=n, dtype=d),
    "densenet": lambda n, d, r, s: densenet121(n, d, remat=r,
                                               scan_layers=s),  # :78-85
    "inception": lambda n, d, r, s: InceptionV3(num_classes=n, dtype=d,
                                                remat=r,
                                                scan_layers=s),  # :87-99
    # Framework addition beyond the reference zoo (which is CNN-only):
    # the attention model family, see models/vit.py + ops/attention.py.
    "vit": lambda n, d, r, s: ViT(num_classes=n, dtype=d, remat=r,
                                  scan_layers=s),
}

# Models that implement --remat blocks THEMSELVES via nn.remat at their
# block boundaries (param-tree-preserving: the wrapped instances carry the
# same explicit names the unwrapped modules get).  For everything else the
# engine falls back to jax.checkpoint around the whole apply with a
# save-matmul-outputs policy.
REMAT_BLOCK_MODELS = frozenset({"vit", "densenet", "inception"})

# name -> input resolution (ref getModelInputSize, utils.py:24-36: 224 for
# all but inception=299; cnn/mlp/vit run at the dataset-native 28).
_INPUT_SIZES = {
    "cnn": 28, "mlp": 28, "resnet": 224, "alexnet": 224, "vgg": 224,
    "squeezenet": 224, "densenet": 224, "inception": 299, "vit": 28,
}

# Models with homogeneous repeated blocks that --scan-layers stacks
# under lax.scan (models/scan.py): O(depth) HLO collapses to O(1).
SCAN_LAYER_MODELS = frozenset({"vit", "vgg", "densenet", "inception"})

# Models whose train-mode forward also returns auxiliary logits
# (ref classif.py:49-53 special-cases 'inception').
AUX_LOGIT_MODELS = frozenset({"inception"})

# Models using dropout (their apply() needs a 'dropout' rng in train mode).
DROPOUT_MODELS = frozenset({"alexnet", "vgg", "squeezenet", "inception"})


def _require_model_axis(mesh, what: str) -> None:
    from ..runtime import MODEL_AXIS

    if mesh is None or MODEL_AXIS not in mesh.shape \
            or mesh.shape[MODEL_AXIS] < 2:
        raise ValueError(
            f"{what} uses the mesh's 'model' axis: pass "
            "--model-parallel >= 2 (and a mesh)")


def get_model(name: str, num_classes: int, half_precision: bool = True,
              attention: str = "full", mesh=None,
              tensor_parallel: bool = False,
              pipeline_parallel: bool = False,
              pipeline_microbatches: int = 0,
              moe_experts: int = 0, pallas_dw: bool = False,
              precision=None, remat: str = "none",
              scan_layers: bool = False) -> nn.Module:
    """``attention``: 'full' (default, XLA-fused softmax attention),
    'ring' (sequence-parallel over ``mesh``'s 'model' axis via
    lax.ppermute — ops/attention.py), 'flash' (the Pallas kernel,
    ops/flash_attention.py), or 'ring_flash' (the composition: ring
    sequence parallelism running the Pallas kernel within each
    shard).  ``tensor_parallel``: Megatron-style
    sharded-activation TP over the same axis (parallel.make_tp_constrain).
    ``pipeline_parallel``: GPipe stage parallelism over the same axis
    (models/vit_pipeline.py).  All are vit-family features; requesting
    them for a CNN is a user error surfaced the CLI way (ValueError ->
    log-and-exit)."""
    if name not in MODEL_REGISTRY:
        raise ValueError(f"Invalid model name {name!r} "
                         f"(choices: {sorted(MODEL_REGISTRY)})")
    if attention not in ("full", "ring", "flash", "ring_flash"):
        raise ValueError(f"attention must be 'full', 'ring', 'flash' or "
                         f"'ring_flash', got {attention!r}")
    if remat not in ("none", "blocks", "full"):
        raise ValueError(f"remat must be none|blocks|full, got {remat!r}")
    if precision is not None:
        dtype = precision.compute_dtype
    else:
        dtype = jnp.bfloat16 if half_precision else jnp.float32
    # Model-internal block remat only for --remat blocks; --remat full is
    # handled by the engine (whole-apply jax.checkpoint), not the model.
    remat_blocks = remat == "blocks"
    if scan_layers:
        if name not in SCAN_LAYER_MODELS:
            raise ValueError(
                f"--scan-layers applies to the repeated-block models "
                f"only ({sorted(SCAN_LAYER_MODELS)}); {name!r} has no "
                "homogeneous block run to stack")
        if pipeline_parallel:
            raise ValueError(
                "--scan-layers is exclusive with --pipeline-parallel "
                "(the pipelined vit already stacks its blocks and "
                "hand-rolls the schedule)")
        if moe_experts:
            raise ValueError(
                "--scan-layers is exclusive with --moe-experts (expert "
                "dispatch does not stack under lax.scan, and MoE "
                "checkpoints have no scan layout conversion)")
    if pipeline_parallel and remat != "none":
        raise ValueError(
            "--remat composes with the plain vit, not --pipeline-parallel "
            "(the pipelined vit hand-rolls its stage loop and manages "
            "per-stage memory itself)")
    if pallas_dw:
        # API-only knob (bench.py A/B path, no CLI flag): the measured
        # closure in BASELINE.md found XLA's native dW at its roofline,
        # so the kernel is kept as a tested experimental path, not a
        # product default.
        if name != "cnn":
            raise ValueError(
                "pallas_dw applies to the cnn model only (the "
                "patch-reuse conv-dW kernel covers its 3x3/SAME convs)")
        # Incompatible-feature validation BEFORE the early return
        # (ADVICE #1): the vit-family flags below would otherwise be
        # silently ignored instead of raising as the non-pallas path does.
        if (moe_experts or attention != "full" or tensor_parallel
                or pipeline_parallel):
            raise ValueError(
                "pallas_dw is exclusive with the vit-family features; got "
                f"moe_experts={moe_experts}, attention={attention!r}, "
                f"tensor_parallel={tensor_parallel}, "
                f"pipeline_parallel={pipeline_parallel}")
        from .simple import SmallCNN

        return SmallCNN(num_classes=num_classes, dtype=dtype,
                        pallas_dw=True)
    if moe_experts:
        if name != "vit":
            raise ValueError(
                "--moe-experts applies to the attention model family "
                f"only (--model vit); {name!r} has no MLP blocks to "
                "replace")
        if moe_experts < 2:
            raise ValueError(
                f"--moe-experts must be >= 2, got {moe_experts}")
        if tensor_parallel or pipeline_parallel:
            raise ValueError(
                "--moe-experts is exclusive with --tensor-parallel "
                "(both shard the MLP over 'model') and "
                "--pipeline-parallel (the pipelined vit hand-rolls "
                "dense blocks); it composes with --attention "
                "full/ring/flash")
    if pipeline_parallel:
        if name != "vit":
            raise ValueError(
                "--pipeline-parallel applies to the attention model "
                f"family only (--model vit); {name!r} has no stages")
        if attention not in ("full", "ring") or tensor_parallel:
            raise ValueError(
                "--pipeline-parallel is exclusive with --attention "
                "flash/ring_flash and --tensor-parallel (the pipelined "
                "vit hand-rolls its blocks); it composes with "
                "--attention ring on a 3-D mesh (--seq-parallel >= 2)")
        from .vit_pipeline import PipelinedViT, make_pipeline_fn
        from ..runtime import MODEL_AXIS

        _require_model_axis(mesh, "--pipeline-parallel (stage axis)")
        if pipeline_microbatches < 0:
            raise ValueError("--pipeline-microbatches must be >= 0, got "
                             f"{pipeline_microbatches}")
        # single source of truth: the model's own field defaults
        depth, heads = PipelinedViT.depth, PipelinedViT.heads
        return PipelinedViT(
            num_classes=num_classes, dtype=dtype, depth=depth, heads=heads,
            pipeline_fn=make_pipeline_fn(mesh, mesh.shape[MODEL_AXIS],
                                         depth, heads,
                                         n_micro=pipeline_microbatches
                                         or None,
                                         ring=attention == "ring"))
    if attention != "full" or tensor_parallel or moe_experts:
        if name != "vit":
            feature = (f"--attention {attention}" if attention != "full"
                       else "--tensor-parallel")
            raise ValueError(
                f"{feature} applies to the attention model family "
                f"only (--model vit); {name!r} has no attention")
        if attention != "full" and tensor_parallel:
            raise ValueError(
                "--tensor-parallel composes only with --attention full "
                "(ring shards the same 'model' axis; the flash Pallas "
                "kernel is not GSPMD-partitionable over heads) — pick one")
        from .vit import ViT

        attn_fn = None
        if attention in ("ring", "ring_flash"):
            from ..ops.attention import make_ring_attention

            _require_model_axis(mesh, f"--attention {attention} "
                                      "(token axis)")
            attn_fn = make_ring_attention(
                mesh, use_flash=attention == "ring_flash")
        elif attention == "flash":
            # the Pallas flash kernel (ops/flash_attention.py): O(S)
            # memory, single-device; no mesh requirement
            from ..ops.flash_attention import flash_attention

            attn_fn = flash_attention
        if tensor_parallel:
            from ..parallel import make_tp_constrain

            _require_model_axis(mesh, "--tensor-parallel (head/hidden "
                                      "axes)")
            return ViT(num_classes=num_classes, dtype=dtype,
                       attention_fn=attn_fn,
                       tp_constrain=make_tp_constrain(mesh),
                       remat=remat_blocks, scan_layers=scan_layers)
        if moe_experts:
            # Expert parallelism when a model axis exists (>= 2 devices
            # on 'model'): the expert batches' leading E axis is pinned
            # there (models/moe.py).  Without one, MoE still runs —
            # experts replicated — so single-device training/eval works.
            from ..runtime import MODEL_AXIS

            moe_constrain = None
            if mesh is not None and MODEL_AXIS in mesh.shape \
                    and mesh.shape[MODEL_AXIS] >= 2:
                from ..parallel import make_tp_constrain

                mp = mesh.shape[MODEL_AXIS]
                if moe_experts % mp:
                    # the constrain helper silently skips non-divisible
                    # axes, which would leave every expert replicated —
                    # the user asked for EP, so refuse loudly instead
                    raise ValueError(
                        f"--moe-experts {moe_experts} must be divisible "
                        f"by --model-parallel {mp} for expert "
                        "parallelism (each device holds E/mp experts)")
                moe_constrain = make_tp_constrain(mesh)
            return ViT(num_classes=num_classes, dtype=dtype,
                       attention_fn=attn_fn, moe_experts=moe_experts,
                       moe_constrain=moe_constrain, remat=remat_blocks)
        return ViT(num_classes=num_classes, dtype=dtype,
                   attention_fn=attn_fn, remat=remat_blocks,
                   scan_layers=scan_layers)
    return MODEL_REGISTRY[name](num_classes, dtype, remat_blocks,
                                scan_layers)


def get_model_input_size(name: str) -> int:
    if name not in _INPUT_SIZES:
        raise ValueError(f"Invalid model name {name!r}")
    return _INPUT_SIZES[name]


def head_mask_label(path: tuple, _leaf: Any = None) -> str:
    """'head' for classifier-head params, 'backbone' otherwise.

    Every zoo model names its replaced classifier ``head`` (and inception's
    auxiliary classifier ``aux_head``), so the freeze decision is purely
    structural — the JAX analogue of the reference replacing layers *after*
    the requires_grad=False sweep (ref utils.py:46-48 etc.).
    """
    in_head = any(
        isinstance(k, str) and (k == "head" or k == "aux_head")
        or getattr(k, "key", None) in ("head", "aux_head")
        for k in path)
    return "head" if in_head else "backbone"


def trainable_mask(params) -> Any:
    """Pytree of {'head','backbone'} labels for optax.multi_transform.

    feature_extract=True (ref config.py:48, utils.py:107-110) maps
    'backbone' to optax.set_to_zero() so only the head trains.
    """
    import jax

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: head_mask_label(path, leaf), params)
