"""Scan-over-layers: homogeneous zoo blocks stacked and run under
``lax.scan`` (--scan-layers), collapsing O(depth) HLO into O(1).

Why: XLA unrolls a Python-loop model into one instruction stream per
block — densenet121's 58 DenseLayers each contribute their convs, norms
and concats, so program size (and compile time, and the AOT-warmup cost
the goodput ledger charges to ``compile``) grows linearly with depth.
``nn.scan`` emits ONE while-loop body holding a single block's program
with the per-block parameters stacked on a leading (depth,) axis —
compile cost becomes O(1) in depth, and the whole-program optimizer
sees a small graph it can actually fuse.

What stacks, per model (the rest of each model is untouched, and every
non-scanned parameter keeps its exact historical name):

  * vit — all ``depth`` TransformerBlocks under one scan ("blocks");
  * densenet — each dense block's DenseLayer chain (6/12/24/16 layers)
    under one scan per block ("DenseBlockScan_{b}") via a zero-padded
    channel buffer (see _DenseStep: the growing concat becomes a
    fixed-width carry + dynamic_update_slice);
  * inception — the homogeneous InceptionC_1/InceptionC_2 pair
    (same 768-in/768-out, c7=160) as "InceptionCScan_0";
  * vgg — the trailing 512->512 conv+BN pair as "ConvScan_0".

Composition with --remat blocks: callers pass an ``nn.remat``-wrapped
block class (vit/inception) or set ``remat=True`` here (densenet) — the
scan body is then rematerialized per step, the scan-over-remat memory
shape (O(sqrt)-style: live activations are one block deep).

Checkpoint layouts: scanned trees are a DIFFERENT on-disk shape, so this
module is also the layout registry checkpoint.py consults —
``params_layout`` names the layout a params(-shaped) tree is in, and
``convert_layout`` converts any state dict (params, batch_stats, AND the
optimizer moments that mirror params) across layouts in both directions,
working at shape level on jax.ShapeDtypeStruct trees too (orbax abstract
restore targets).  The vit-family 'stacked'/'blocks' layouts remain in
models/vit_pipeline.py; this module subsumes them for dispatch.

Numerics: scan == loop exactly, given converted parameters — pinned by
tests/test_scan_layers.py (forward AND gradients) and gated in CI
(scripts/scan_gate.py).  The densenet padded-buffer trick masks the
padded channels after norm1 (see _DenseStep) so no gradient ever reaches
a padded parameter entry; padding is therefore inert and zero-filled.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from . import vit_pipeline

# ---------------------------------------------------------------------------
# scan runners


class _BlockStep(nn.Module):
    """nn.scan body adapter: applies one homogeneous zoo block to the
    carried activation.  ``block_cls`` may already be nn.remat-wrapped
    (vit/inception --remat blocks); the inner instance is always named
    "block" so the stacked subtree is {scan_name}/block/{...}."""

    block_cls: Any
    block_kwargs: Tuple[Tuple[str, Any], ...]
    train: bool

    @nn.compact
    def __call__(self, x, _i):
        y = self.block_cls(**dict(self.block_kwargs),
                           name="block")(x, self.train)
        return y, None


def scan_run(block_cls, length: int, block_kwargs: dict, train: bool,
             name: str):
    """``length`` applications of one block class under lax.scan; returns
    a callable x -> x.  Params: {name}/block/{leaf} with a leading
    (length,) axis (variable_axes=0), per-step init rngs (split_rngs)."""
    scanned = nn.scan(
        _BlockStep,
        variable_axes={"params": 0, "batch_stats": 0},
        split_rngs={"params": True},
        in_axes=0, length=length)
    mod = scanned(block_cls=block_cls,
                  block_kwargs=tuple(block_kwargs.items()),
                  train=train, name=name)

    def run(x):
        y, _ = mod(x, jnp.arange(length))
        return y

    return run


class _DenseStep(nn.Module):
    """One DenseLayer as a fixed-shape scan step over a padded channel
    buffer.

    The loop model concatenates each layer's ``growth`` new channels onto
    a growing feature map — shapes change per layer, which lax.scan
    cannot carry.  Instead the carry is a zero-padded buffer of the
    block's FINAL width (c_in + length*growth); step i reads the buffer,
    masks everything past its valid width c_i = c_in + i*growth after
    norm1+relu, and writes its ``growth`` outputs at offset c_i with
    ``dynamic_update_slice`` (traced offset — one program for all steps).

    The mask is load-bearing for exactness, not cosmetics: BatchNorm over
    the padded channels emits relu(bias) > 0 garbage there, and without
    the mask those values would feed conv1 through its (trainable!)
    padded kernel rows — forward would diverge from the loop model and
    gradients would flow into padding.  Masked, the padded inputs are
    identically zero, so the padded kernel rows and the padded norm
    scale/bias entries receive exactly zero gradient and stay at their
    (zero) converted values — the scanned model IS the loop model.
    """

    growth: int
    bn_size: int
    in_features: int
    dtype: Any
    train: bool

    @nn.compact
    def __call__(self, buf, i):
        norm = functools.partial(nn.BatchNorm,
                                 use_running_average=not self.train,
                                 momentum=0.9, dtype=self.dtype)
        c_i = self.in_features + i * self.growth
        valid = jax.lax.broadcasted_iota(
            jnp.int32, (buf.shape[-1],), 0) < c_i
        y = nn.relu(norm()(buf))
        y = jnp.where(valid, y, jnp.zeros_like(y))
        y = nn.Conv(self.bn_size * self.growth, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.relu(norm()(y))
        y = nn.Conv(self.growth, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        buf = jax.lax.dynamic_update_slice(
            buf, y.astype(buf.dtype), (0, 0, 0, c_i))
        return buf, None


def scan_dense_block(length: int, in_features: int, growth: int,
                     bn_size: int, dtype, train: bool, name: str,
                     remat: bool = False):
    """One densenet dense block (``length`` DenseLayers) under lax.scan;
    returns a callable x -> x with the full concatenated width."""
    step_cls = _DenseStep
    if remat:
        step_cls = nn.remat(
            _DenseStep, prevent_cse=False,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    scanned = nn.scan(
        step_cls,
        variable_axes={"params": 0, "batch_stats": 0},
        split_rngs={"params": True},
        in_axes=0, length=length)
    mod = scanned(growth=growth, bn_size=bn_size, in_features=in_features,
                  dtype=dtype, train=train, name=name)

    def run(x):
        c_end = in_features + length * growth
        buf = jnp.pad(x, ((0, 0), (0, 0), (0, 0),
                          (0, c_end - x.shape[-1])))
        buf, _ = mod(buf, jnp.arange(length))
        return buf

    return run


class _VGGStep(nn.Module):
    """One vgg conv+BN+relu unit as a scan step (homogeneous 512->512
    runs only; bias kept on the conv for torchvision state_dict parity,
    same as the unscanned path)."""

    filters: int
    dtype: Any
    train: bool

    @nn.compact
    def __call__(self, x, _i):
        x = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=True,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not self.train, momentum=0.9,
                         dtype=self.dtype)(x)
        return nn.relu(x), None


def scan_vgg_run(length: int, filters: int, dtype, train: bool,
                 name: str):
    scanned = nn.scan(
        _VGGStep,
        variable_axes={"params": 0, "batch_stats": 0},
        split_rngs={"params": True},
        in_axes=0, length=length)
    mod = scanned(filters=filters, dtype=dtype, train=train, name=name)

    def run(x):
        y, _ = mod(x, jnp.arange(length))
        return y

    return run


# ---------------------------------------------------------------------------
# layout registry (checkpoint.py's single dispatch point)
#
# Layout names:
#   'stacked' / 'blocks'           vit family (models/vit_pipeline.py)
#   'scan'                         vit with --scan-layers
#   'dense_layers' / 'dense_scan'  densenet plain / scanned
#   'vgg_layers' / 'vgg_scan'      vgg plain / scanned
#   'inception_blocks' / 'inception_scan'
#
# Same-family pairs are convertible both ways ('scan' also reaches
# 'stacked' transitively via 'blocks'); cross-family targets raise.

_VIT_FAMILY = ("stacked", "blocks", "scan")
_PAIRS = {
    "dense_scan": "dense_layers", "dense_layers": "dense_scan",
    "vgg_scan": "vgg_layers", "vgg_layers": "vgg_scan",
    "inception_scan": "inception_blocks",
    "inception_blocks": "inception_scan",
}
KNOWN_LAYOUTS = frozenset(_VIT_FAMILY) | frozenset(_PAIRS)

# densenet121 block geometry (models/densenet.py defaults — the only
# densenet the zoo instantiates): per scanned block, the flat DenseLayer
# index offset, layer count, entry width and padded carry width.
_DN_GROWTH, _DN_BN_SIZE = 32, 4


def _densenet_specs(block_config=(6, 12, 24, 16), growth=_DN_GROWTH,
                    init_features=64):
    specs, c, offset = [], init_features, 0
    for b, length in enumerate(block_config):
        specs.append({"name": f"DenseBlockScan_{b}", "offset": offset,
                      "length": length, "c_in": c,
                      "c_end": c + length * growth})
        offset += length
        c += length * growth
        if b != len(block_config) - 1:
            c //= 2  # transition compression
    return specs


def params_layout(sd) -> Optional[str]:
    """Name the layout of a params(-shaped) mapping — a live tree, a
    state-dict subtree, optimizer moments, or batch_stats (all mirror
    the module structure).  None: not a convertible layout."""
    vp = vit_pipeline.params_layout(sd)
    if vp is not None:
        return vp
    if not isinstance(sd, dict):
        return None
    blk = sd.get("blocks")
    if isinstance(blk, dict) and "block" in blk:
        return "scan"
    if "DenseBlockScan_0" in sd:
        return "dense_scan"
    if "DenseLayer_0" in sd:
        return "dense_layers"
    if "InceptionCScan_0" in sd:
        return "inception_scan"
    if "InceptionC_1" in sd:
        return "inception_blocks"
    if "ConvScan_0" in sd:
        return "vgg_scan"
    if "BatchNorm_7" in sd and "BatchNorm_8" not in sd \
            and ("Conv_7" in sd or "Conv_0" not in sd):
        # vgg11_bn is the only zoo model with exactly 8 top-level BN
        # units; the second arm admits batch_stats trees (no Conv keys).
        return "vgg_layers"
    return None


# -- shape-level leaf ops (arrays AND ShapeDtypeStruct restore targets) --

_leaf_slice = vit_pipeline._leaf_slice
_leaf_stack = vit_pipeline._leaf_stack


def _leaf_crop(v, axis: int, size: int):
    if isinstance(v, jax.ShapeDtypeStruct):
        shape = list(v.shape)
        shape[axis] = size
        return jax.ShapeDtypeStruct(tuple(shape), v.dtype,
                                    sharding=v.sharding)
    a = np.asarray(v)
    idx = [slice(None)] * a.ndim
    idx[axis] = slice(0, size)
    return a[tuple(idx)]


def _leaf_pad(v, axis: int, size: int):
    """Zero-pad ``axis`` up to ``size``.  Zeros are correct for EVERY
    leaf kind (params, running stats, optimizer moments): the padded
    entries are masked out of the forward (see _DenseStep), receive zero
    gradient, and zero moments make the optimizer leave them alone."""
    if isinstance(v, jax.ShapeDtypeStruct):
        shape = list(v.shape)
        shape[axis] = size
        return jax.ShapeDtypeStruct(tuple(shape), v.dtype,
                                    sharding=v.sharding)
    a = np.asarray(v)
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, size - a.shape[axis])
    return np.pad(a, pad)


def _tree_slice(tree, i: int):
    return jax.tree_util.tree_map(lambda v: _leaf_slice(v, i), tree)


def _tree_stack(trees):
    return jax.tree_util.tree_map(lambda *vs: _leaf_stack(list(vs)),
                                  *trees)


def _tree_width(tree, fn):
    """Apply a per-leaf width op to the channel axis of a dense-layer
    subtree: BatchNorm_0 leaves (params scale/bias, stats mean/var) on
    axis 0; Conv_0's kernel(-shaped) leaves on their input-channel axis
    (ndim-2).  Other submodules (BatchNorm_1, Conv_1) have fixed widths
    and pass through."""
    out = {}
    for key, sub in tree.items():
        if key == "BatchNorm_0":
            out[key] = {k: fn(v, 0) for k, v in sub.items()}
        elif key == "Conv_0":
            out[key] = {k: fn(v, max(0, _leaf_ndim(v) - 2))
                        for k, v in sub.items()}
        else:
            out[key] = sub
    return out


def _leaf_ndim(v) -> int:
    if isinstance(v, jax.ShapeDtypeStruct):
        return len(v.shape)
    return np.asarray(v).ndim


# -- vit: scan <-> blocks --

def _scan_depth(stacked) -> int:
    leaves = jax.tree_util.tree_leaves(
        stacked, is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))
    return int(leaves[0].shape[0])


def _vit_scan_to_blocks(sd: dict) -> dict:
    stacked = sd["blocks"]["block"]
    out = {k: v for k, v in sd.items() if k != "blocks"}
    for i in range(_scan_depth(stacked)):
        out[f"block{i}"] = _tree_slice(stacked, i)
    return out


def _vit_blocks_to_scan(sd: dict) -> dict:
    blocks = sorted((k for k in sd if k.startswith("block")
                     and k[5:].isdigit()), key=lambda s: int(s[5:]))
    out = {k: v for k, v in sd.items() if k not in blocks}
    out["blocks"] = {"block": _tree_stack([sd[b] for b in blocks])}
    return out


# -- densenet: dense_scan <-> dense_layers --

def _dense_scan_to_layers(sd: dict) -> dict:
    specs = [s for s in _densenet_specs() if s["name"] in sd]
    out = {k: v for k, v in sd.items()
           if k not in {s["name"] for s in specs}}
    for s in specs:
        for i in range(s["length"]):
            c_i = s["c_in"] + i * _DN_GROWTH
            layer = _tree_width(_tree_slice(sd[s["name"]], i),
                                lambda v, ax: _leaf_crop(v, ax, c_i))
            out[f"DenseLayer_{s['offset'] + i}"] = layer
    return out


def _dense_layers_to_scan(sd: dict) -> dict:
    specs = [s for s in _densenet_specs()
             if f"DenseLayer_{s['offset']}" in sd]
    names = {f"DenseLayer_{s['offset'] + i}"
             for s in specs for i in range(s["length"])}
    out = {k: v for k, v in sd.items() if k not in names}
    for s in specs:
        padded = [
            _tree_width(sd[f"DenseLayer_{s['offset'] + i}"],
                        lambda v, ax: _leaf_pad(v, ax, s["c_end"]))
            for i in range(s["length"])
        ]
        out[s["name"]] = _tree_stack(padded)
    return out


# -- vgg: vgg_scan <-> vgg_layers (the trailing Conv_6/Conv_7 run) --

_VGG_RUN = ("6", "7")  # plain indices covered by ConvScan_0


def _vgg_scan_to_layers(sd: dict) -> dict:
    out = {k: v for k, v in sd.items() if k != "ConvScan_0"}
    run = sd["ConvScan_0"]
    for i, idx in enumerate(_VGG_RUN):
        for kind, sub in run.items():  # Conv_0 and/or BatchNorm_0
            out[f"{kind[:-2]}_{idx}"] = _tree_slice(sub, i)
    return out


def _vgg_layers_to_scan(sd: dict) -> dict:
    kinds = [k for k in ("Conv", "BatchNorm")
             if f"{k}_{_VGG_RUN[0]}" in sd]
    names = {f"{k}_{i}" for k in kinds for i in _VGG_RUN}
    out = {k: v for k, v in sd.items() if k not in names}
    out["ConvScan_0"] = {
        f"{k}_0": _tree_stack([sd[f"{k}_{i}"] for i in _VGG_RUN])
        for k in kinds}
    return out


# -- inception: inception_scan <-> inception_blocks (C_1/C_2 pair) --

_INC_RUN = ("InceptionC_1", "InceptionC_2")


def _inception_scan_to_blocks(sd: dict) -> dict:
    out = {k: v for k, v in sd.items() if k != "InceptionCScan_0"}
    stacked = sd["InceptionCScan_0"]["block"]
    for i, name in enumerate(_INC_RUN):
        out[name] = _tree_slice(stacked, i)
    return out


def _inception_blocks_to_scan(sd: dict) -> dict:
    out = {k: v for k, v in sd.items() if k not in _INC_RUN}
    out["InceptionCScan_0"] = {
        "block": _tree_stack([sd[n] for n in _INC_RUN])}
    return out


_CONVERTERS = {
    ("scan", "blocks"): _vit_scan_to_blocks,
    ("blocks", "scan"): _vit_blocks_to_scan,
    ("dense_scan", "dense_layers"): _dense_scan_to_layers,
    ("dense_layers", "dense_scan"): _dense_layers_to_scan,
    ("vgg_scan", "vgg_layers"): _vgg_scan_to_layers,
    ("vgg_layers", "vgg_scan"): _vgg_layers_to_scan,
    ("inception_scan", "inception_blocks"): _inception_scan_to_blocks,
    ("inception_blocks", "inception_scan"): _inception_blocks_to_scan,
}


def convert_layout(tree, target: str):
    """Recursively convert every convertible subtree of ``tree`` (a
    checkpoint state dict: params, batch_stats, AND the optimizer
    moments mirroring the params structure) to ``target``.  Subtrees
    already in the target layout — and unrelated leaves — pass through
    untouched; an impossible (cross-family) conversion raises."""
    if target not in KNOWN_LAYOUTS:
        raise ValueError(f"unknown layout {target!r}")
    layout = params_layout(tree)
    if layout == target:
        return tree
    if layout is not None:
        if layout in ("stacked", "blocks") \
                and target in ("stacked", "blocks"):
            return vit_pipeline.convert_layout(tree, target)
        if layout in _VIT_FAMILY and target in _VIT_FAMILY:
            # transitive via 'blocks' (scan <-> stacked)
            mid = tree
            if layout == "stacked":
                mid = vit_pipeline.convert_layout(tree, "blocks")
            elif layout == "scan":
                mid = _vit_scan_to_blocks(tree)
            if target == "blocks":
                return mid
            return (_vit_blocks_to_scan(mid) if target == "scan"
                    else vit_pipeline.convert_layout(mid, "stacked"))
        conv = _CONVERTERS.get((layout, target))
        if conv is None:
            raise ValueError(
                f"cannot convert a {layout!r}-layout tree to {target!r} "
                "(different model families)")
        return conv(tree)
    if isinstance(tree, dict):
        return {k: convert_layout(v, target) for k, v in tree.items()}
    return tree


def scan_layout_for(layout: Optional[str]) -> Optional[str]:
    """The scanned twin of a plain layout (and vice versa); None when
    the layout has no twin."""
    if layout in ("blocks", "stacked"):
        return "scan"
    if layout == "scan":
        return "blocks"
    return _PAIRS.get(layout)
