"""AlexNet (ref utils.py:51-58 wraps torchvision alexnet).

Parity with torchvision's alexnet: five-conv feature stack, adaptive 6x6
pool, dropout-4096-4096 classifier with the final layer replaced to
``num_classes`` (the layer the reference swaps at utils.py:56-57 —
named ``head`` here).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from .common import adaptive_avg_pool


class AlexNet(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        conv = lambda f, k, s, p: nn.Conv(  # noqa: E731
            f, (k, k), strides=(s, s), padding=[(p, p), (p, p)],
            dtype=self.dtype)
        x = nn.relu(conv(64, 11, 4, 2)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(192, 5, 1, 2)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.relu(conv(384, 3, 1, 1)(x))
        x = nn.relu(conv(256, 3, 1, 1)(x))
        x = nn.relu(conv(256, 3, 1, 1)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = adaptive_avg_pool(x, 6)  # torchvision AdaptiveAvgPool2d((6,6))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)
