"""Pretrained-weight loading: torch state_dicts -> Flax params.

The reference's fine-tuning story is ``use_pretrained=True``: every factory
in ref utils.py:38-105 loads torchvision ImageNet weights, then replaces
the classifier head (ref utils.py:42-49 for resnet18), optionally freezing
the backbone (``feature_extract``, ref utils.py:107-110, config.py:48-51).

TPU-native equivalent: convert a torchvision ``state_dict`` (a ``.pth``
file the user provides — this framework never downloads) into the Flax
param/batch_stats trees, leaving the freshly-initialized ``head`` in place
(exactly the reference's replace-after-load semantics).  Conversion rules:

  * torch conv weight (O,I,kH,kW)  -> flax kernel (kH,kW,I,O)
  * torch linear weight (O,I)      -> flax kernel (I,O)
  * the FIRST linear after a flatten additionally permutes its input axis
    from torch's NCHW flatten order (C,H,W) to NHWC flatten order (H,W,C)
  * BatchNorm weight/bias          -> scale/bias (params)
    running_mean/running_var       -> mean/var  (batch_stats)

Supported: all six reference architectures — resnet18, alexnet, vgg11_bn,
squeezenet1_0, densenet121, inception_v3 (both inception fc heads stay
fresh, ref utils.py:93-98).  Unsupported architectures RAISE —
``use_pretrained=True`` must never silently no-op.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

SUPPORTED = ("resnet", "alexnet", "vgg", "squeezenet", "densenet",
             "inception")


def _t_conv(w) -> np.ndarray:
    return np.asarray(w, np.float32).transpose(2, 3, 1, 0)


def _t_linear(w, spatial: Optional[Tuple[int, int, int]] = None) -> np.ndarray:
    """(O,I) -> (I,O); with ``spatial=(C,H,W)`` also permute the input axis
    from CHW-flatten order to HWC-flatten order."""
    w = np.asarray(w, np.float32)
    if spatial is not None:
        c, h, wd = spatial
        w = w.reshape(-1, c, h, wd).transpose(0, 2, 3, 1).reshape(w.shape[0], -1)
    return w.T


def _vec(v) -> np.ndarray:
    return np.asarray(v, np.float32)


def _bn(sd: Dict[str, Any], prefix: str):
    """(params {scale,bias}, stats {mean,var}) for one torch BN layer."""
    return (
        {"scale": _vec(sd[f"{prefix}.weight"]),
         "bias": _vec(sd[f"{prefix}.bias"])},
        {"mean": _vec(sd[f"{prefix}.running_mean"]),
         "var": _vec(sd[f"{prefix}.running_var"])},
    )


def _convert_resnet18(sd: Dict[str, Any]):
    """torchvision resnet18 state_dict -> (params, batch_stats), no head."""
    params: Dict[str, Any] = {"Conv_0": {"kernel": _t_conv(sd["conv1.weight"])}}
    stats: Dict[str, Any] = {}
    params["BatchNorm_0"], stats["BatchNorm_0"] = _bn(sd, "bn1")
    # torchvision layer{1..4}.{0,1} -> BasicBlock_{0..7}; downsample
    # projections exist at layer{2,3,4}.0 and are our Conv_2/BatchNorm_2.
    for layer in range(1, 5):
        for block in range(2):
            i = (layer - 1) * 2 + block
            t = f"layer{layer}.{block}"
            b_params: Dict[str, Any] = {
                "Conv_0": {"kernel": _t_conv(sd[f"{t}.conv1.weight"])},
                "Conv_1": {"kernel": _t_conv(sd[f"{t}.conv2.weight"])},
            }
            b_stats: Dict[str, Any] = {}
            b_params["BatchNorm_0"], b_stats["BatchNorm_0"] = _bn(sd, f"{t}.bn1")
            b_params["BatchNorm_1"], b_stats["BatchNorm_1"] = _bn(sd, f"{t}.bn2")
            if f"{t}.downsample.0.weight" in sd:
                b_params["Conv_2"] = {
                    "kernel": _t_conv(sd[f"{t}.downsample.0.weight"])}
                b_params["BatchNorm_2"], b_stats["BatchNorm_2"] = _bn(
                    sd, f"{t}.downsample.1")
            params[f"BasicBlock_{i}"] = b_params
            stats[f"BasicBlock_{i}"] = b_stats
    return params, stats


def _convert_alexnet(sd: Dict[str, Any]):
    """torchvision alexnet: features.{0,3,6,8,10} convs,
    classifier.{1,4} linears (classifier.6 is the replaced head)."""
    params: Dict[str, Any] = {}
    for i, t in enumerate((0, 3, 6, 8, 10)):
        params[f"Conv_{i}"] = {
            "kernel": _t_conv(sd[f"features.{t}.weight"]),
            "bias": _vec(sd[f"features.{t}.bias"])}
    params["Dense_0"] = {
        "kernel": _t_linear(sd["classifier.1.weight"], spatial=(256, 6, 6)),
        "bias": _vec(sd["classifier.1.bias"])}
    params["Dense_1"] = {"kernel": _t_linear(sd["classifier.4.weight"]),
                         "bias": _vec(sd["classifier.4.bias"])}
    return params, {}


def _convert_vgg11_bn(sd: Dict[str, Any]):
    """torchvision vgg11_bn: features conv/BN pairs at
    (0,1),(4,5),(8,9),(11,12),(15,16),(18,19),(22,23),(25,26);
    classifier.{0,3} linears (classifier.6 is the replaced head)."""
    pairs = ((0, 1), (4, 5), (8, 9), (11, 12), (15, 16), (18, 19),
             (22, 23), (25, 26))
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    for i, (c, b) in enumerate(pairs):
        params[f"Conv_{i}"] = {
            "kernel": _t_conv(sd[f"features.{c}.weight"]),
            "bias": _vec(sd[f"features.{c}.bias"])}
        params[f"BatchNorm_{i}"], stats[f"BatchNorm_{i}"] = _bn(
            sd, f"features.{b}")
    params["Dense_0"] = {
        "kernel": _t_linear(sd["classifier.0.weight"], spatial=(512, 7, 7)),
        "bias": _vec(sd["classifier.0.bias"])}
    params["Dense_1"] = {"kernel": _t_linear(sd["classifier.3.weight"]),
                         "bias": _vec(sd["classifier.3.bias"])}
    return params, stats


def _convert_squeezenet(sd: Dict[str, Any]):
    """torchvision squeezenet1_0: features.0 stem conv; Fire modules at
    features.{3,4,5,7,8,9,10,12} with squeeze/expand1x1/expand3x3 convs
    (classifier.1 is the replaced head, ref utils.py:74)."""
    def conv(prefix):
        return {"kernel": _t_conv(sd[f"{prefix}.weight"]),
                "bias": _vec(sd[f"{prefix}.bias"])}

    params: Dict[str, Any] = {"Conv_0": conv("features.0")}
    for i, t in enumerate((3, 4, 5, 7, 8, 9, 10, 12)):
        params[f"Fire_{i}"] = {
            "Conv_0": conv(f"features.{t}.squeeze"),
            "Conv_1": conv(f"features.{t}.expand1x1"),
            "Conv_2": conv(f"features.{t}.expand3x3"),
        }
    return params, {}


def _convert_densenet121(sd: Dict[str, Any]):
    """torchvision densenet121: conv0/norm0 stem; denseblock{1..4} of
    denselayer{n} (norm1/conv1/norm2/conv2); transition{1..3} (norm/conv);
    norm5 (classifier is the replaced head, ref utils.py:83-84).

    Flax numbering: DenseLayer_{0..57} run cumulatively across blocks;
    transitions are the top-level BatchNorm_{1..3}/Conv_{1..3}; the final
    norm is BatchNorm_4."""
    params: Dict[str, Any] = {
        "Conv_0": {"kernel": _t_conv(sd["features.conv0.weight"])}}
    stats: Dict[str, Any] = {}
    params["BatchNorm_0"], stats["BatchNorm_0"] = _bn(sd, "features.norm0")
    li = 0
    block_config = (6, 12, 24, 16)
    for b, n_layers in enumerate(block_config, start=1):
        for n in range(1, n_layers + 1):
            t = f"features.denseblock{b}.denselayer{n}"
            lp: Dict[str, Any] = {}
            ls: Dict[str, Any] = {}
            lp["BatchNorm_0"], ls["BatchNorm_0"] = _bn(sd, f"{t}.norm1")
            lp["Conv_0"] = {"kernel": _t_conv(sd[f"{t}.conv1.weight"])}
            lp["BatchNorm_1"], ls["BatchNorm_1"] = _bn(sd, f"{t}.norm2")
            lp["Conv_1"] = {"kernel": _t_conv(sd[f"{t}.conv2.weight"])}
            params[f"DenseLayer_{li}"] = lp
            stats[f"DenseLayer_{li}"] = ls
            li += 1
        if b < len(block_config):
            t = f"features.transition{b}"
            params[f"BatchNorm_{b}"], stats[f"BatchNorm_{b}"] = _bn(
                sd, f"{t}.norm")
            params[f"Conv_{b}"] = {"kernel": _t_conv(sd[f"{t}.conv.weight"])}
    params["BatchNorm_4"], stats["BatchNorm_4"] = _bn(sd, "features.norm5")
    return params, stats


def _basic_conv(sd: Dict[str, Any], prefix: str):
    """torchvision BasicConv2d (conv bias-free + bn) -> our BasicConv
    submodule trees."""
    p = {"Conv_0": {"kernel": _t_conv(sd[f"{prefix}.conv.weight"])}}
    p["BatchNorm_0"], bn_stats = _bn(sd, f"{prefix}.bn")
    return p, {"BatchNorm_0": bn_stats}


# Branch creation order inside each Flax Inception{A..E} module == the
# torchvision submodule order (models/inception.py mirrors it).
_INCEPTION_BRANCHES = {
    "A": ("branch1x1", "branch5x5_1", "branch5x5_2", "branch3x3dbl_1",
          "branch3x3dbl_2", "branch3x3dbl_3", "branch_pool"),
    "B": ("branch3x3", "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3"),
    "C": ("branch1x1", "branch7x7_1", "branch7x7_2", "branch7x7_3",
          "branch7x7dbl_1", "branch7x7dbl_2", "branch7x7dbl_3",
          "branch7x7dbl_4", "branch7x7dbl_5", "branch_pool"),
    "D": ("branch3x3_1", "branch3x3_2", "branch7x7x3_1", "branch7x7x3_2",
          "branch7x7x3_3", "branch7x7x3_4"),
    "E": ("branch1x1", "branch3x3_1", "branch3x3_2a", "branch3x3_2b",
          "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3a",
          "branch3x3dbl_3b", "branch_pool"),
}

_INCEPTION_MIXED = (
    ("InceptionA_0", "Mixed_5b", "A"), ("InceptionA_1", "Mixed_5c", "A"),
    ("InceptionA_2", "Mixed_5d", "A"), ("InceptionB_0", "Mixed_6a", "B"),
    ("InceptionC_0", "Mixed_6b", "C"), ("InceptionC_1", "Mixed_6c", "C"),
    ("InceptionC_2", "Mixed_6d", "C"), ("InceptionC_3", "Mixed_6e", "C"),
    ("InceptionD_0", "Mixed_7a", "D"), ("InceptionE_0", "Mixed_7b", "E"),
    ("InceptionE_1", "Mixed_7c", "E"),
)


def _convert_inception_v3(sd: Dict[str, Any]):
    """torchvision inception_v3 (aux_logits=True): stem Conv2d_* BasicConvs,
    Mixed_5b..7c blocks, AuxLogits conv0/conv1.  BOTH fc heads (fc and
    AuxLogits.fc) stay fresh — the reference replaces both
    (ref utils.py:93-98)."""
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    stem = ("Conv2d_1a_3x3", "Conv2d_2a_3x3", "Conv2d_2b_3x3",
            "Conv2d_3b_1x1", "Conv2d_4a_3x3")
    for i, t in enumerate(stem):
        params[f"BasicConv_{i}"], stats[f"BasicConv_{i}"] = _basic_conv(sd, t)
    for flax_name, torch_name, kind in _INCEPTION_MIXED:
        mp: Dict[str, Any] = {}
        ms: Dict[str, Any] = {}
        for i, branch in enumerate(_INCEPTION_BRANCHES[kind]):
            mp[f"BasicConv_{i}"], ms[f"BasicConv_{i}"] = _basic_conv(
                sd, f"{torch_name}.{branch}")
        params[flax_name] = mp
        stats[flax_name] = ms
    aux_p: Dict[str, Any] = {}
    aux_s: Dict[str, Any] = {}
    aux_p["BasicConv_0"], aux_s["BasicConv_0"] = _basic_conv(
        sd, "AuxLogits.conv0")
    aux_p["BasicConv_1"], aux_s["BasicConv_1"] = _basic_conv(
        sd, "AuxLogits.conv1")
    params["AuxHead_0"] = aux_p
    stats["AuxHead_0"] = aux_s
    return params, stats


_CONVERTERS = {
    "resnet": _convert_resnet18,
    "alexnet": _convert_alexnet,
    "vgg": _convert_vgg11_bn,
    "squeezenet": _convert_squeezenet,
    "densenet": _convert_densenet121,
    "inception": _convert_inception_v3,
}


def convert_state_dict(model_name: str, sd: Dict[str, Any],
                       params: Any, batch_stats: Any):
    """Merge a torch state_dict into fresh Flax trees.

    Backbone leaves are replaced by the converted torch weights; the
    ``head`` (and any other key the converter does not produce) keeps its
    fresh initialization — the reference's replace-head-after-load
    semantics (ref utils.py:46-48).  Shapes are validated leaf-by-leaf.
    """
    if model_name not in _CONVERTERS:
        raise ValueError(
            f"use_pretrained is not supported for {model_name!r} "
            f"(supported: {', '.join(SUPPORTED)})")
    sd = {k[len("module."):] if k.startswith("module.") else k: v
          for k, v in sd.items()}
    try:
        conv_params, conv_stats = _CONVERTERS[model_name](sd)
    except KeyError as e:
        raise ValueError(
            f"state_dict is missing key {e.args[0]!r} — is this really a "
            f"torchvision {model_name} state_dict?") from e

    def merge(fresh, converted, path=""):
        out = dict(fresh)
        for k, v in converted.items():
            if k not in fresh:
                raise ValueError(f"converted key {path}/{k} not in model")
            if isinstance(v, dict):
                out[k] = merge(fresh[k], v, f"{path}/{k}")
            else:
                if tuple(np.shape(fresh[k])) != tuple(v.shape):
                    raise ValueError(
                        f"shape mismatch at {path}/{k}: model "
                        f"{tuple(np.shape(fresh[k]))} vs weights {v.shape}")
                out[k] = v
        return out

    return merge(params, conv_params), merge(batch_stats, conv_stats)


def validate_request(model_name: str, path: Optional[str]) -> None:
    """Cheap use_pretrained precondition check — callable before any data
    or model work so user mistakes fail in milliseconds."""
    if model_name not in _CONVERTERS:
        raise ValueError(
            f"use_pretrained is not supported for {model_name!r} "
            f"(supported: {', '.join(SUPPORTED)})")
    if not path:
        raise ValueError(
            "use_pretrained requires --pretrained-path FILE (a torchvision "
            f"{model_name} state_dict saved with torch.save); this "
            "framework never downloads weights")


def load_pretrained(model_name: str, path: Optional[str],
                    params: Any, batch_stats: Any):
    """Load a user-provided ``.pth``/``.pt`` torch checkpoint and convert.

    Accepts a bare state_dict or a dict with a ``state_dict`` field.  A
    missing path raises — this framework has no network access and never
    downloads weights (the torchvision download that ref utils.py relies
    on is replaced by an explicit file contract, documented in README).
    """
    validate_request(model_name, path)
    try:
        import torch
    except ImportError as e:
        raise ValueError(
            "use_pretrained needs the 'torch' package to read the .pth "
            "state_dict (pip install torch, CPU build is enough)") from e

    try:
        obj = torch.load(path, map_location="cpu", weights_only=True)
    except Exception as e:  # any torch.load failure -> CLI ValueError
        raise ValueError(f"cannot load pretrained weights {path!r}: {e}") \
            from e
    if not isinstance(obj, dict):
        # e.g. a bare tensor or scripted module: surface as ValueError so
        # the CLI log-and-exits instead of tracebacking (ref error style).
        raise ValueError(
            f"pretrained weights {path!r} did not contain a state_dict "
            f"(got {type(obj).__name__})")
    sd = obj.get("state_dict", obj)
    sd = {k: v.numpy() if hasattr(v, "numpy") else v for k, v in sd.items()}
    return convert_state_dict(model_name, sd, params, batch_stats)
