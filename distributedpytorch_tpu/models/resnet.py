"""ResNet-18 (ref utils.py:42-49 wraps torchvision resnet18).

Architecture parity with torchvision resnet18: 7x7/2 stem + 3x3/2 maxpool,
four stages of two BasicBlocks at widths (64,128,256,512), stride-2
downsampling with 1x1 projection at each stage entry, global average pool,
dense ``head`` (the layer the reference replaces, ref utils.py:47-48).
NHWC; BN stats are global under SPMD (the jit step sees the globally-
sharded batch — sync-BN semantics, a documented divergence from DDP's
per-replica BN).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    stride: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = functools.partial(nn.BatchNorm, use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype)
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        # Explicit symmetric (1,1) padding, not "SAME": with stride 2 XLA's
        # SAME pads (0,1) while torch pads (1,1) — same output shape,
        # different alignment — and pretrained-weight parity needs torch's.
        y = conv(self.filters, (3, 3), strides=(self.stride, self.stride),
                 padding=[(1, 1), (1, 1)])(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)])(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            strides=(self.stride, self.stride))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 10
    width: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for stage, n_blocks in enumerate(self.stage_sizes):
            for block in range(n_blocks):
                stride = 2 if stage > 0 and block == 0 else 1
                x = BasicBlock(self.width * (2 ** stage), stride,
                               self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x)
        return x.astype(jnp.float32)


def resnet18(num_classes: int, dtype=jnp.bfloat16) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), num_classes=num_classes,
                  dtype=dtype)
