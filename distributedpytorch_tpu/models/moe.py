"""Switch-style mixture-of-experts MLP — the expert-parallel (EP) leg of
the framework's parallelism taxonomy.

The reference has no MoE anywhere (SURVEY §2 parallelism checklist:
"Expert parallel (EP/MoE): ABSENT"); this module is TPU-first framework
capability completing the taxonomy (dp / ZeRO / TP / sequence-parallel
ring / PP / EP) on the same 2-D (data, model) mesh.

Design (the classic dense-dispatch TPU formulation — static shapes,
every op an einsum the MXU can run; no gather/scatter, no ragged
shapes):

  * top-1 routing: a float32 router picks one expert per token, the
    winning softmax probability scales the expert's output (so routing
    receives gradient through the gate);
  * fixed expert capacity C = ceil(tokens/E * capacity_factor): each
    expert processes exactly C token slots; tokens beyond an expert's
    capacity are DROPPED (contribute zero — the standard switch
    trade that keeps shapes static for XLA);
  * dispatch/combine are one-hot einsums: tokens (N, D) are scattered
    into (E, C, D) expert batches and gathered back with gate weights,
    all as matmuls;
  * expert FFNs are E-batched matmuls on (E, C, D) x (E, D, H) — ONE
    einsum for all experts;
  * EXPERT PARALLELISM: sharding constraints (the injected
    ``ep_constrain``, same mechanism as tensor parallelism's
    parallel.make_tp_constrain) pin the leading E axis of the expert
    batches to the mesh's 'model' axis — GSPMD then partitions the
    expert matmuls so each device computes only its experts, and
    inserts the dispatch/combine all-to-alls between the data-sharded
    token axis and the expert-sharded batches.  Constraints never
    change the math (tests pin sharded == replicated bitwise-close);
  * the load-balancing auxiliary loss (Switch Transformer form:
    E * sum_e f_e * P_e, with f_e the dispatched-token fraction and
    P_e the mean router probability of expert e) is exposed through
    flax's ``sow`` into the 'losses' collection; the train engine adds
    every sown loss into the optimized scalar (train/engine.py).

Numerics are pinned in tests/test_moe.py: the dispatch/combine path
equals a direct per-token computation through the argmax expert
(capacity permitting), dropped tokens contribute exactly zero, and the
expert-sharded program equals the replicated one.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..runtime import MODEL_AXIS

ConstrainFn = Callable[..., jnp.ndarray]  # (x, partition-spec tuple) -> x


class SwitchMLP(nn.Module):
    """Drop-in replacement for a transformer block's dense MLP."""

    dim: int
    hidden: int
    num_experts: int
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    dtype: Any = jnp.bfloat16
    ep_constrain: Optional[ConstrainFn] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, s, d = x.shape
        n_tok = b * s
        e = self.num_experts
        cap = max(1, math.ceil(n_tok / e * self.capacity_factor))
        ep = self.ep_constrain or (lambda a, _spec: a)
        tokens = x.reshape(n_tok, d)

        # Router in float32: small, and routing decisions should not
        # flap with bf16 rounding.
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)            # (N, E)
        expert = jnp.argmax(probs, axis=-1)                # (N,)
        gate = jnp.max(probs, axis=-1)                     # (N,)

        onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # (N, E)
        # position of each token within its expert's queue (1-based)
        pos = jnp.cumsum(onehot, axis=0) * onehot
        keep = (pos > 0) & (pos <= cap)
        slot = jnp.clip(pos - 1, 0, cap - 1).astype(jnp.int32)
        # (N, E, C) one-hot dispatch mask; combine adds the gate weight
        disp = (jax.nn.one_hot(jnp.sum(slot, axis=-1), cap,
                               dtype=jnp.float32)[:, None, :]
                * (onehot * keep)[:, :, None])
        combine = disp * gate[:, None, None]

        if train and self.aux_loss_coef > 0:
            # Switch load-balancing loss: E * sum_e f_e * P_e — minimized
            # (= 1) by a uniform dispatch; keeps top-1 routing from
            # collapsing onto few experts.  Computed over ALL tokens,
            # including rows the engine's valid-mask excludes from the
            # CE loss: this framework's sampler pads batches by
            # WRAPAROUND-DUPLICATING real samples (data/sampler.py,
            # torch DistributedSampler parity), so those rows carry the
            # real input distribution and only overweight duplicates
            # slightly — not garbage.  Threading the valid mask down
            # here would shave that residual bias at the cost of a
            # model-signature change; documented trade, not taken.
            # f_e is the PRE-capacity routing fraction (the Switch
            # formula): capping it at capacity/N would weaken the
            # anti-collapse gradient exactly when an expert overloads.
            f = jnp.mean(onehot, axis=0)                   # (E,)
            p = jnp.mean(probs, axis=0)                    # (E,)
            self.sow("losses", "moe_load_balance",
                     self.aux_loss_coef * e * jnp.sum(f * p))

        cdt = self.dtype
        # dispatch: (N,E,C) x (N,D) -> (E,C,D), the first all-to-all
        # point under EP (tokens data-sharded -> expert-sharded)
        expert_in = jnp.einsum("nec,nd->ecd", disp.astype(cdt),
                               tokens.astype(cdt))
        expert_in = ep(expert_in, (MODEL_AXIS, None, None))

        init = nn.initializers.lecun_normal(batch_axis=0)
        w_up = self.param("w_up", init, (e, d, self.hidden), jnp.float32)
        b_up = self.param("b_up", nn.initializers.zeros,
                          (e, self.hidden), jnp.float32)
        w_down = self.param("w_down", init, (e, self.hidden, d),
                            jnp.float32)
        b_down = self.param("b_down", nn.initializers.zeros, (e, d),
                            jnp.float32)

        h = jnp.einsum("ecd,edh->ech", expert_in, w_up.astype(cdt))
        h = nn.gelu(h + b_up.astype(cdt)[:, None, :])
        h = ep(h, (MODEL_AXIS, None, None))
        out = jnp.einsum("ech,ehd->ecd", h, w_down.astype(cdt))
        out = out + b_down.astype(cdt)[:, None, :]
        out = ep(out, (MODEL_AXIS, None, None))

        # combine: (N,E,C) x (E,C,D) -> (N,D), the second all-to-all;
        # dropped tokens have an all-zero combine row -> exactly zero
        y = jnp.einsum("nec,ecd->nd", combine.astype(cdt), out)
        return y.reshape(b, s, d)
