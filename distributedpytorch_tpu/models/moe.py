"""Switch-style mixture-of-experts MLP — the expert-parallel (EP) leg of
the framework's parallelism taxonomy.

The reference has no MoE anywhere (SURVEY §2 parallelism checklist:
"Expert parallel (EP/MoE): ABSENT"); this module is TPU-first framework
capability completing the taxonomy (dp / ZeRO / TP / sequence-parallel
ring / PP / EP) on the same 2-D (data, model) mesh.

Design (the classic dense-dispatch TPU formulation — static shapes,
every op an einsum the MXU can run; no gather/scatter, no ragged
shapes):

  * top-1 routing: a float32 router picks one expert per token, the
    winning softmax probability scales the expert's output (so routing
    receives gradient through the gate);
  * tokens are split into GROUPS of whole batch rows (~GROUP_TOKENS
    tokens per group) and capacity is per group:
    C = ceil(group_tokens/E * capacity_factor).  Dispatch/combine cost
    is then N*E*C ~ cf * N * group_tokens — LINEAR in total tokens,
    not the cf*N^2 a single global capacity gives (the round-4
    advisor's medium finding; this is the standard TPU switch
    formulation, cf. Switch Transformer's per-group expert capacity).
    Tokens beyond an expert's per-group capacity are DROPPED
    (contribute zero — the standard switch trade that keeps shapes
    static for XLA);
  * dispatch/combine are one-hot einsums: grouped tokens (G, N_g, D)
    are scattered into (G, E, C, D) expert batches and gathered back
    with gate weights, all as matmuls;
  * expert FFNs are (G, E)-batched matmuls on (G, E, C, D) x (E, D, H)
    — ONE einsum for all experts;
  * EXPERT PARALLELISM: sharding constraints (the injected
    ``ep_constrain``, same mechanism as tensor parallelism's
    parallel.make_tp_constrain) pin the leading E axis of the expert
    batches to the mesh's 'model' axis — GSPMD then partitions the
    expert matmuls so each device computes only its experts, and
    inserts the dispatch/combine all-to-alls between the data-sharded
    token axis and the expert-sharded batches.  Constraints never
    change the math (tests pin sharded == replicated bitwise-close);
  * the load-balancing auxiliary loss (Switch Transformer form:
    E * sum_e f_e * P_e, with f_e the dispatched-token fraction and
    P_e the mean router probability of expert e) is exposed through
    flax's ``sow`` into the 'losses' collection; the train engine adds
    every sown loss into the optimized scalar (train/engine.py).

Numerics are pinned in tests/test_moe.py: the dispatch/combine path
equals a direct per-token computation through the argmax expert
(capacity permitting), dropped tokens contribute exactly zero, and the
expert-sharded program equals the replicated one.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..runtime import MODEL_AXIS

ConstrainFn = Callable[..., jnp.ndarray]  # (x, partition-spec tuple) -> x

# Target tokens per dispatch group.  Capacity (and so dispatch-mask
# width) is computed per group, keeping the (G, N_g, E, C) dispatch
# tensor ~ cf * N * GROUP_TOKENS elements — linear in total tokens.
# Groups are whole batch rows so they follow the batch's data sharding.
GROUP_TOKENS = 1024


def _rows_per_group(b: int, s: int) -> int:
    """Largest divisor of ``b`` whose group holds <= ~GROUP_TOKENS
    tokens (at least one row; static Python, shapes are static)."""
    from ..utils import largest_divisor_leq

    return largest_divisor_leq(b, max(1, GROUP_TOKENS // max(1, s)))


class SwitchMLP(nn.Module):
    """Drop-in replacement for a transformer block's dense MLP."""

    dim: int
    hidden: int
    num_experts: int
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    dtype: Any = jnp.bfloat16
    ep_constrain: Optional[ConstrainFn] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, s, d = x.shape
        n_tok = b * s
        e = self.num_experts
        rows = _rows_per_group(b, s)
        g, n_g = b // rows, rows * s
        cap = max(1, math.ceil(n_g / e * self.capacity_factor))
        ep = self.ep_constrain or (lambda a, _spec: a)
        tokens = x.reshape(n_tok, d)

        # Router in float32: small, and routing decisions should not
        # flap with bf16 rounding.
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)            # (N, E)
        expert = jnp.argmax(probs, axis=-1)                # (N,)
        gate = jnp.max(probs, axis=-1)                     # (N,)

        onehot = jax.nn.one_hot(expert, e,
                                dtype=jnp.float32).reshape(g, n_g, e)
        # position of each token within its expert's PER-GROUP queue
        # (1-based); capacity applies within the group
        pos = jnp.cumsum(onehot, axis=1) * onehot
        keep = (pos > 0) & (pos <= cap)
        slot = jnp.clip(pos - 1, 0, cap - 1).astype(jnp.int32)
        # (G, N_g, E, C) one-hot dispatch mask; combine adds the gate
        disp = (jax.nn.one_hot(jnp.sum(slot, axis=-1), cap,
                               dtype=jnp.float32)[:, :, None, :]
                * (onehot * keep)[:, :, :, None])
        combine = disp * gate.reshape(g, n_g)[:, :, None, None]

        if train and self.aux_loss_coef > 0:
            # Switch load-balancing loss: E * sum_e f_e * P_e — minimized
            # (= 1) by a uniform dispatch; keeps top-1 routing from
            # collapsing onto few experts.  Computed over ALL tokens,
            # including rows the engine's valid-mask excludes from the
            # CE loss: this framework's sampler pads batches by
            # WRAPAROUND-DUPLICATING real samples (data/sampler.py,
            # torch DistributedSampler parity), so those rows carry the
            # real input distribution and only overweight duplicates
            # slightly — not garbage.  Threading the valid mask down
            # here would shave that residual bias at the cost of a
            # model-signature change; documented trade, not taken.
            # f_e is the PRE-capacity routing fraction (the Switch
            # formula): capping it at capacity/N would weaken the
            # anti-collapse gradient exactly when an expert overloads.
            f = jnp.mean(onehot, axis=(0, 1))              # (E,)
            p = jnp.mean(probs, axis=0)                    # (E,)
            self.sow("losses", "moe_load_balance",
                     self.aux_loss_coef * e * jnp.sum(f * p))

        cdt = self.dtype
        # dispatch: (G,N_g,E,C) x (G,N_g,D) -> (G,E,C,D), the first
        # all-to-all point under EP (tokens data-sharded -> expert-
        # sharded).  The group axis is left unconstrained: it inherits
        # the batch's data sharding by propagation, and pinning only E
        # to 'model' is what makes each device compute its experts.
        grouped = tokens.reshape(g, n_g, d).astype(cdt)
        expert_in = jnp.einsum("gnec,gnd->gecd", disp.astype(cdt),
                               grouped)
        expert_in = ep(expert_in, (None, MODEL_AXIS, None, None))

        init = nn.initializers.lecun_normal(batch_axis=0)
        w_up = self.param("w_up", init, (e, d, self.hidden), jnp.float32)
        b_up = self.param("b_up", nn.initializers.zeros,
                          (e, self.hidden), jnp.float32)
        w_down = self.param("w_down", init, (e, self.hidden, d),
                            jnp.float32)
        b_down = self.param("b_down", nn.initializers.zeros, (e, d),
                            jnp.float32)

        h = jnp.einsum("gecd,edh->gech", expert_in, w_up.astype(cdt))
        h = nn.gelu(h + b_up.astype(cdt)[None, :, None, :])
        h = ep(h, (None, MODEL_AXIS, None, None))
        out = jnp.einsum("gech,ehd->gecd", h, w_down.astype(cdt))
        out = out + b_down.astype(cdt)[None, :, None, :]
        out = ep(out, (None, MODEL_AXIS, None, None))

        # combine: (G,N_g,E,C) x (G,E,C,D) -> (G,N_g,D), the second
        # all-to-all; dropped tokens have an all-zero combine row ->
        # exactly zero
        y = jnp.einsum("gnec,gecd->gnd", combine.astype(cdt), out)
        return y.reshape(b, s, d)
