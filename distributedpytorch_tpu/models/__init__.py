"""L3: model zoo (TPU-native replacement for ref utils.py:24-110).

The reference wraps torchvision architectures and swaps their classifier
heads to ``num_classes`` (ref utils.py:38-105).  Here each architecture is a
Flax module built NHWC (XLA/TPU's native conv layout) with the final
classifier uniformly named ``head`` — which makes the reference's
``feature_extract`` backbone-freezing (ref utils.py:107-110) a one-line
optax mask instead of a requires_grad walk (see registry.trainable_mask).

BatchNorm statistics are GLOBAL (sync-BN semantics): the train step is one
jit program over the globally-sharded batch, so batch stats are computed
over the global batch — a deliberate divergence from DDP's per-replica BN
(SURVEY §7 step 4 decision point).  It is also what makes the
sharded == single-device-big-batch equivalence in tests/test_distributed.py
hold exactly for BN models.

``pretrained`` converts user-provided torchvision state_dicts into these
modules' param trees (ref use_pretrained, utils.py:38-105).
"""

from . import pretrained, registry
from .registry import (get_model, get_model_input_size, head_mask_label,
                       trainable_mask, MODEL_REGISTRY)

__all__ = ["get_model", "get_model_input_size", "head_mask_label",
           "trainable_mask", "MODEL_REGISTRY", "pretrained", "registry"]
