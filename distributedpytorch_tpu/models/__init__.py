"""L3: model zoo (TPU-native replacement for ref utils.py:24-110).

The reference wraps torchvision architectures and swaps their classifier
heads to ``num_classes`` (ref utils.py:38-105).  Here each architecture is a
Flax module built NHWC (XLA/TPU's native conv layout) with the final
classifier uniformly named ``head`` — which makes the reference's
``feature_extract`` backbone-freezing (ref utils.py:107-110) a one-line
optax mask instead of a requires_grad walk (see registry.trainable_mask).

BatchNorm uses per-replica statistics — deliberately matching DDP, which
does not synchronize BN across ranks (SURVEY §7 step 4 decision point).
"""

from .registry import (get_model, get_model_input_size, head_mask_label,
                       trainable_mask, MODEL_REGISTRY)

__all__ = ["get_model", "get_model_input_size", "head_mask_label",
           "trainable_mask", "MODEL_REGISTRY"]
