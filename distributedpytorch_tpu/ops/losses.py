"""Loss zoo: cross_entropy | weighted_cross_entropy | focal_loss.

The reference dispatches on config.LOSS (ref classif.py:109-120) but only
the default cross_entropy path actually runs — the weighted/focal paths
read a ``classWeights`` attribute the dataset never defines (SURVEY defect
#4).  Here all three work; weights come from Dataset.class_weights().

Each loss returns *per-example* (numerator, denominator) pairs rather than
a scalar, so the engine can form a globally-correct masked mean across all
replicas and wraparound padding:

    loss = sum(numer * valid) / sum(denom * valid)     (psum'd under SPMD)

Denominator semantics match torch reductions exactly:
  * cross_entropy / focal_loss: denom = 1 per example (plain mean — the
    reference's FocalLossN ends in .mean(), ref utils.py:155);
  * weighted_cross_entropy: denom = w_{y_n} (torch CrossEntropyLoss with
    weights divides by the sum of target weights).

Precision contract (precision.PrecisionPolicy): every loss here upcasts
the logits to ``accum_dtype`` (f32 for every shipped preset) BEFORE the
log-softmax, so the numer/denom pairs the engine sums — per step and
across a whole scanned epoch — are f32 regardless of the model's compute
dtype.  A bf16 log-softmax has ~8 bits of mantissa; summing thousands of
such terms is exactly the silent-accuracy-rot failure mode the
``mixed-precision-accum`` graftlint rule exists to catch.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

LossFn = Callable[[jax.Array, jax.Array], Tuple[jax.Array, jax.Array]]


def _log_softmax_gather(logits: jax.Array, labels: jax.Array,
                        accum_dtype=jnp.float32) -> jax.Array:
    # The upcast is the accumulation guarantee (module docstring): the
    # softmax normalizer and the gathered log-prob are computed in
    # accum_dtype even when the model emits bf16/f16 logits.
    logp = jax.nn.log_softmax(logits.astype(accum_dtype), axis=-1)
    return jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]


def cross_entropy(logits: jax.Array, labels: jax.Array
                  ) -> Tuple[jax.Array, jax.Array]:
    """torch.nn.CrossEntropyLoss() (ref classif.py:106,110)."""
    nll = -_log_softmax_gather(logits, labels)
    return nll, jnp.ones_like(nll)


def weighted_cross_entropy(logits: jax.Array, labels: jax.Array,
                           class_weights: jax.Array
                           ) -> Tuple[jax.Array, jax.Array]:
    """torch.nn.CrossEntropyLoss(weight=...) (ref classif.py:111-112, fixed)."""
    nll = -_log_softmax_gather(logits, labels)
    w = class_weights[labels]
    return w * nll, w


def focal_loss(logits: jax.Array, labels: jax.Array,
               class_weights: Optional[jax.Array] = None,
               gamma: float = 2.0) -> Tuple[jax.Array, jax.Array]:
    """FocalLossN (ref utils.py:142-156): (1-p)^gamma * log p through
    nll_loss(weight=w, reduction='none') then a plain mean — i.e. the
    per-example value is w_y * (1-p_y)^gamma * (-log p_y), denominator 1."""
    logp = _log_softmax_gather(logits, labels)
    p = jnp.exp(logp)
    per_ex = -((1.0 - p) ** gamma) * logp
    if class_weights is not None:
        per_ex = class_weights[labels] * per_ex
    return per_ex, jnp.ones_like(per_ex)


def get_loss_fn(name: str, class_weights: Optional[jax.Array] = None,
                focal_gamma: float = 2.0) -> LossFn:
    """Dispatch mirroring ref classif.py:109-120 (invalid -> ValueError;
    the CLI maps it to the reference's log-and-exit)."""
    if name == "cross_entropy":
        return cross_entropy
    if name == "weighted_cross_entropy":
        if class_weights is None:
            raise ValueError("weighted_cross_entropy requires class weights")
        cw = jnp.asarray(class_weights)
        return lambda lg, lb: weighted_cross_entropy(lg, lb, cw)
    if name == "focal_loss":
        cw = None if class_weights is None else jnp.asarray(class_weights)
        return lambda lg, lb: focal_loss(lg, lb, cw, focal_gamma)
    raise ValueError(f"Invalid loss {name!r}")
