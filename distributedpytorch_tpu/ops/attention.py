"""Attention ops: full (XLA-fused) and ring (sequence-parallel) attention.

The reference has no attention or sequence models at all (SURVEY §2:
image CNNs only — this module is framework-added capability, built
TPU-first): long sequences are sharded along the mesh's 'model' axis and
attended with RING attention — each device holds its local Q/K/V sequence
block, K/V blocks rotate around the ring via `lax.ppermute` (ICI
neighbor-to-neighbor traffic, the topology TPUs are built for), and
softmax is accumulated streamingly with the flash-attention
log-sum-exp merge, so the full S x S score matrix never materializes and
per-device memory stays O(S_local).

`ring_attention` is written against named axes (`shard_map`); numerics —
outputs AND gradients — are pinned to `full_attention` in
tests/test_attention.py on the 8-device virtual mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import compat


def full_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = False) -> jax.Array:
    """Reference scaled-dot-product attention.

    q/k/v: (B, S, H, D).  Computed in float32 for a stable softmax, cast
    back to the input dtype (the matmuls still feed the MXU in bf16 when
    inputs are bf16 — XLA keeps the mixed-precision contraction).
    """
    dtype = q.dtype
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(dtype)


# Finite "masked" sentinel: keeps every exp()/subtraction finite so both
# the forward AND the backward pass are NaN-free (a -inf sentinel turns
# exp(-inf - -inf) into NaN for not-yet-attended rows).
_MASKED = -1e30


def _ring_body(carry, t, *, axis_name: str, n_dev: int, s_local: int,
               scale: float, q_pos, causal: bool, kv_valid, idx):
    """One ring step: attend local Q against the currently-held K/V block,
    merge into the running flash accumulator, rotate K/V to the next
    device.  The held block's GLOBAL positions are a pure function of
    (device index, step) — block t came from device (idx - t) mod n_dev —
    so they are computed locally rather than carried and ppermuted (one
    fewer collective per step).  ``kv_valid`` (static int or None) masks
    padded key positions >= kv_valid — the ragged-sequence support that
    lets callers pad S up to a multiple of the ring size (see
    make_ring_attention)."""
    k_cur, v_cur, acc, m, l = carry
    k_pos = ((idx - t) % n_dev) * s_local + jnp.arange(s_local)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q_pos[1], k_cur) * scale
    mask = None
    if causal:
        mask = (q_pos[0][:, None] >= k_pos[None, :])[None, None]
    if kv_valid is not None:
        kv_mask = (k_pos < kv_valid)[None, None, None, :]
        mask = kv_mask if mask is None else mask & kv_mask
    if mask is not None:
        scores = jnp.where(mask, scores, _MASKED)

    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)  # masked entries contribute exactly 0
    l_new = l * alpha + jnp.sum(p, axis=-1)
    acc_new = (acc * alpha[..., None]
               + jnp.einsum("bhqk,bkhd->bhqd", p, v_cur))

    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    k_next = jax.lax.ppermute(k_cur, axis_name, perm)
    v_next = jax.lax.ppermute(v_cur, axis_name, perm)
    return (k_next, v_next, acc_new, m_new, l_new), None


def _ring_attention_local(q, k, v, *, axis_name: str, n_dev: int,
                          s_local: int, causal: bool, kv_valid):
    """Per-device body (runs under shard_map): q/k/v are the LOCAL blocks
    (B, S_local, H, D); returns the local output block."""
    dtype = q.dtype
    b, s, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    idx = jax.lax.axis_index(axis_name)
    q_glob = idx * s_local + jnp.arange(s_local)

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)

    # Initial accumulators are derived from qf (not fresh constants) so
    # they carry the same varying-over-mesh-axes type as the loop outputs
    # — lax.scan under shard_map requires carry in/out types to match.
    qt = jnp.einsum("bqhd->bhqd", qf)
    acc = qt * 0.0
    m = qt[..., 0] * 0.0 + _MASKED
    l = qt[..., 0] * 0.0

    body = functools.partial(_ring_body, axis_name=axis_name, n_dev=n_dev,
                             s_local=s_local, scale=scale,
                             q_pos=(q_glob, qf), causal=causal,
                             kv_valid=kv_valid, idx=idx)
    (_, _, acc, m, l), _ = jax.lax.scan(
        body, (kf, vf, acc, m, l), jnp.arange(n_dev))

    # Fully-masked rows (padded queries) have l == 0 -> output exactly 0.
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(dtype)


def _merge_partials(o_run, lse_run, o_blk, lse_blk):
    """Exact flash combine of two softmax partials over disjoint key
    sets: each o is its own softmax-normalized result, each lse the
    log-sum-exp over its keys.  Returns the merged (o, lse)."""
    lse_new = jnp.logaddexp(lse_run, lse_blk)
    w_run = jnp.exp(lse_run - lse_new)[..., None]
    w_blk = jnp.exp(lse_blk - lse_new)[..., None]
    return o_run * w_run + o_blk.astype(o_run.dtype) * w_blk, lse_new


_FAR = 2 ** 30  # padded-position sentinel (>= any kv_valid); plain int —
#                 a module-level jnp constant would init a backend at import


def _ring_local_flash(q, k, v, *, axis_name: str, n_dev: int,
                      s_local: int, causal: bool, kv_valid, block: int):
    """Flash-kernel ring body (ring x flash composition): same rotation
    and flash-merge as _ring_attention_local, but each local block pair
    is attended by the Pallas kernel (flash_attention_partial) instead
    of an einsum — the S x S_local score tile now never exists even in
    VMEM-sized pieces outside the kernel's (128, block) registers.
    Masking moves to GLOBAL positions carried alongside the rotating
    K/V (the kernel's _pos_mask), so causal and ragged (kv_valid)
    support is identical to the einsum ring."""
    from .flash_attention import flash_attention_partial

    dtype = q.dtype
    b, s, h, d = q.shape                                # s == s_local
    idx = jax.lax.axis_index(axis_name)
    pad = (-s_local) % block
    s_pad = s_local + pad

    def to_bh(x):
        x = jnp.einsum("bshd->bhsd", x).reshape(b * h, s, d)
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        return x

    qbh, kbh, vbh = to_bh(q), to_bh(k), to_bh(v)
    pos = idx * s_local + jnp.arange(s_local, dtype=jnp.int32)
    pad_tail = jnp.full((pad,), _FAR, jnp.int32)
    if pad:
        pos = jnp.concatenate([pos, pad_tail])
    # Padded key columns must always be masked out; when the caller has
    # no ragged length, the global S works (every real position < S).
    kv_eff = kv_valid
    if kv_eff is None and pad:
        kv_eff = n_dev * s_local

    # Carry seeds derive from the varying inputs (qbh / idx) so scan
    # carry in/out vma types match under shard_map.
    o0 = qbh.astype(jnp.float32) * 0.0
    lse0 = o0[..., 0] + _MASKED

    def body(carry, t):
        k_cur, v_cur, o_run, lse_run = carry
        # block t came from device (idx - t) mod n_dev: its positions
        # are a pure local function — no need to rotate them
        k_pos = (((idx - t) % n_dev) * s_local
                 + jnp.arange(s_local, dtype=jnp.int32))
        if pad:
            k_pos = jnp.concatenate([k_pos, pad_tail])
        o_blk, lse_blk = flash_attention_partial(
            qbh, k_cur, v_cur, pos, k_pos, causal, kv_eff, block)
        o_run, lse_run = _merge_partials(o_run, lse_run, o_blk, lse_blk)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, o_run, lse_run), None

    (_, _, o_run, _), _ = jax.lax.scan(
        body, (kbh, vbh, o0, lse0), jnp.arange(n_dev))
    out = o_run[:, :s_local].reshape(b, h, s, d)
    return jnp.einsum("bhsd->bshd", out).astype(dtype)


def _seq_spec(mesh: Mesh, axis_name: str, shard_batch: bool = True) -> P:
    """(B, S, H, D) partition spec: S over the sequence axis, B over the
    single remaining data axis when there is exactly one (and the caller's
    batch is divisible by it — init-time dummy batches are not)."""
    data_axes = tuple(a for a in mesh.axis_names if a != axis_name)
    batch_spec = (data_axes[0]
                  if shard_batch and len(data_axes) == 1 else None)
    return P(batch_spec, axis_name, None, None)


@functools.lru_cache(maxsize=32)
def _ring_jitted(mesh: Mesh, axis_name: str, n_dev: int, s_local: int,
                 causal: bool, kv_valid, shard_batch: bool,
                 use_flash: bool = False):
    spec = _seq_spec(mesh, axis_name, shard_batch)
    if use_flash:
        from .flash_attention import BLOCK, _use_interpret

        # Kernel block policy (probed on the real chip, round 4):
        #   * hardware: Mosaic only lowers the full 128-row tile
        #     (sub-128 blocks fail to compile), so the kernel engages
        #     when the local sequence fills a tile; shorter shards fall
        #     back to the einsum ring — identical numerics, and at
        #     s_local << 128 the padded tile would be mostly-wasted
        #     FLOPs anyway (the kernel's regime is long S);
        #   * interpret mode (the CPU-mesh tests): an adaptive small
        #     block (sublane multiple of 8) keeps the REAL kernel code
        #     exercised at test-sized shards without 16x padding.
        if _use_interpret():
            blk = min(BLOCK, -(-s_local // 8) * 8)
        elif s_local >= BLOCK:
            blk = BLOCK
        else:
            use_flash = False
            blk = None
    if use_flash:
        fn = functools.partial(_ring_local_flash, axis_name=axis_name,
                               n_dev=n_dev, s_local=s_local, causal=causal,
                               kv_valid=kv_valid, block=blk)
        # check_vma=False: pallas_call's interpret-mode executor (the CPU
        # mesh tests) does block fetches whose index operands are
        # unvarying, which the strict varying-manual-axes checker rejects
        # (JAX's own error suggests this exact workaround).  Correctness
        # is pinned value-wise against full_attention in
        # tests/test_attention.py instead.
        return jax.jit(compat.shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False))
    fn = functools.partial(_ring_attention_local, axis_name=axis_name,
                           n_dev=n_dev, s_local=s_local, causal=causal,
                           kv_valid=kv_valid)
    return jax.jit(compat.shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))


def _batch_shardable(mesh: Mesh, axis_name: str, b: int) -> bool:
    data_axes = tuple(a for a in mesh.axis_names if a != axis_name)
    return len(data_axes) == 1 and b % mesh.shape[data_axes[0]] == 0


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh: Mesh,
                   axis_name: str = "model", causal: bool = False,
                   kv_valid: Optional[int] = None,
                   use_flash: bool = False) -> jax.Array:
    """Sequence-parallel attention over `mesh`'s `axis_name` axis.

    q/k/v: GLOBAL (B, S, H, D) arrays with S sharded over `axis_name`
    (other axes replicated/data-sharded as the caller likes along 'data').
    Exact same math as `full_attention` — the flash merge is numerically
    stable and the ring visits every K/V block exactly once.  Communication
    is 2 x (S/n) x H x D per step x n steps of neighbor `ppermute` — the
    all-to-all-free pattern that rides ICI neighbor links.

    ``kv_valid`` (static) masks key positions >= kv_valid, so callers may
    zero-pad S up to a multiple of the ring size and still get exactly
    full_attention's result on the first kv_valid positions
    (make_ring_attention packages that pattern).

    ``use_flash`` computes each ring step's local attention with the
    Pallas flash kernel (flash_attention_partial) instead of einsum —
    same numerics, O(S_local) memory AND kernel speed within a shard
    (the ring x flash composition; see _ring_local_flash).  On hardware
    the kernel engages when S_local >= 128 (a full MXU tile — also the
    regime where it pays); shorter shards run the einsum ring body with
    identical numerics (see the block policy in _ring_jitted).

    The jitted shard_map program is cached on (mesh, axis, shape, causal,
    kv_valid, use_flash), so repeated calls (e.g. every ViT block, every
    step) are cache hits.
    """
    n_dev = mesh.shape[axis_name]
    s = q.shape[1]
    if s % n_dev:
        raise ValueError(f"sequence length {s} not divisible by "
                         f"{axis_name} axis size {n_dev}")
    if kv_valid is not None and not 0 < kv_valid <= s:
        raise ValueError(f"kv_valid={kv_valid} out of range (0, {s}]")
    return _ring_jitted(mesh, axis_name, n_dev, s // n_dev, causal,
                        kv_valid,
                        _batch_shardable(mesh, axis_name, q.shape[0]),
                        use_flash)(q, k, v)


def make_ring_attention(mesh: Mesh, axis_name: str = "model",
                        causal: bool = False, use_flash: bool = False):
    """An ``attention_fn`` closure for models (models/vit.py): pads the
    token axis up to a multiple of the ring size, runs ring attention with
    the padded keys masked (kv_valid), and slices the padding back off —
    so ANY sequence length works, and the result equals full_attention on
    the real tokens (ViT at 28x28/patch-4 has 49 tokens; the 8-device ring
    pads to 56).  This is what the CLI's ``--attention ring`` installs
    (``--attention ring_flash`` passes use_flash=True)."""
    n_dev = mesh.shape[axis_name]

    def attn(q, k, v):
        s = q.shape[1]
        pad = (-s) % n_dev
        if pad == 0:
            return ring_attention(q, k, v, mesh, axis_name, causal=causal,
                                  use_flash=use_flash)
        width = ((0, 0), (0, pad), (0, 0), (0, 0))
        out = ring_attention(
            jnp.pad(q, width), jnp.pad(k, width), jnp.pad(v, width),
            mesh, axis_name, causal=causal, kv_valid=s,
            use_flash=use_flash)
        return out[:, :s]

    return attn


def sequence_sharding(mesh: Mesh, axis_name: str = "model"
                      ) -> NamedSharding:
    """Sharding for (B, S, H, D) activations: S over the sequence axis,
    B over 'data' when present."""
    return NamedSharding(mesh, _seq_spec(mesh, axis_name, True))
