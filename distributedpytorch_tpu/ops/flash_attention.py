"""Flash attention as a Pallas TPU kernel — the fused, O(S) -memory
attention for the framework's attention model family.

The reference has no attention at all (SURVEY §2: image CNNs only); this
is TPU-first framework capability, written against the Pallas TPU
programming model (/opt/skills/guides/pallas_guide.md):

  * the S x S score matrix NEVER exists in HBM: each (batch*head,
    q-block) program streams K/V blocks through VMEM, carrying the
    flash running-max/denominator in registers (jax.lax.fori_loop);
  * Q/K/V blocks are (128, D) tiles, so the q @ k^T and p @ v
    contractions land on the 128x128 MXU at full tile width;
  * the backward pass is the standard two-kernel flash scheme (one
    program per q-block for dq, one per k-block for dk/dv), recomputing
    p from the saved log-sum-exp instead of storing probabilities;
  * causal masking and ragged lengths (kv_valid) are fused into the
    same kernels, so any sequence length works: callers zero-pad S up
    to a block multiple and the padded key columns are masked out
    (padded query rows produce zeros and are sliced off).

Numerics are pinned against ops.attention.full_attention — outputs AND
gradients, causal and ragged included — in tests/test_flash_attention.py
(Pallas interpret mode, so the same kernels are exercised on the CPU
mesh), and again on the real chip by bench.py's attention suite.

Scope bound: K and V for one (batch, head) must fit in VMEM in the INPUT
dtype (~16 MB/core => 2 * S * D * itemsize within a few MB): bf16 — the
product path — reaches S=16384 at D=128 in 8 MB, f32 half that.  That
covers the long-context regime this model family targets on ONE chip;
beyond it, ops.attention.ring_attention shards S across chips and can
use this kernel per-shard.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30  # finite masked-score sentinel (keeps exp/sub NaN-free)
BLOCK = 128   # q/k block rows: one MXU tile of lanes


def _use_interpret() -> bool:
    # Real Mosaic lowering on TPU; interpreter everywhere else (CPU mesh
    # tests run the SAME kernel logic).
    return jax.default_backend() != "tpu"


def _masks(iq, kb, bq, bk, causal, kv_valid):
    """(bq, bk) boolean mask of VALID score entries, or None."""
    need = causal or kv_valid is not None
    if not need:
        return None
    rows = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = kb * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return _pos_mask(rows, cols, causal, kv_valid)


def _pos_mask(rows, cols, causal, kv_valid):
    """Mask from (bq, 1) row / (1, bk) col GLOBAL positions (broadcasts
    to (bq, bk)), or None.  kv_valid compares against the global
    position, so it composes with arbitrary position layouts (the ring's
    rotating K/V blocks)."""
    mask = None
    if causal:
        mask = rows >= cols
    if kv_valid is not None:
        kvm = cols < kv_valid
        mask = kvm if mask is None else mask & kvm
    return mask


# ---------------------------------------------------------------- forward --

def _fwd_kernel(q_ref, k_ref, v_ref, *rest, block_k: int,
                causal: bool, kv_valid, scale: float, use_pos: bool = False):
    if use_pos:
        qpos_ref, kpos_ref, o_ref, lse_ref = rest
        rows = qpos_ref[0][:, 0:1]                      # (bq, 1) global pos
    else:
        o_ref, lse_ref = rest
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    s = k_ref.shape[1]
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale            # (bq, d)

    n_kb = s // block_k
    if causal and not use_pos:
        # blocks strictly above the diagonal contribute nothing (valid
        # only for the aligned 0-based layout; positions are arbitrary)
        n_kb = jnp.minimum(n_kb, ((iq + 1) * bq + block_k - 1) // block_k)

    def body(kb, carry):
        acc, m, l = carry
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        sc = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if use_pos:
            cols = kpos_ref[0, 0:1, pl.ds(kb * block_k, block_k)]
            mask = _pos_mask(rows, cols, causal, kv_valid)
        else:
            mask = _masks(iq, kb, bq, block_k, causal, kv_valid)
        if mask is not None:
            sc = jnp.where(mask, sc, _NEG)
        m_blk = jnp.max(sc, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(sc - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc, m, l = jax.lax.fori_loop(
        0, n_kb, body,
        (jnp.zeros((bq, d), jnp.float32),
         jnp.full((bq, 1), _NEG, jnp.float32),
         jnp.zeros((bq, 1), jnp.float32)))
    l_safe = jnp.maximum(l, 1e-30)                      # padded rows: l == 0
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    # lse is stored (bq, 8): Mosaic block shapes need the last dim either
    # 128-divisible or equal to the array's — a (bq,) vector is neither,
    # so the scalar-per-row is broadcast across 8 lanes (sublane tile).
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(l_safe), (bq, 8))


def _out_struct(shape, dtype, *join_of):
    """ShapeDtypeStruct for a pallas output; under shard_map (vma-typed
    inputs) the output's varying-manual-axes must be declared explicitly
    — it is the join of the inputs'."""
    from .. import compat

    vma = frozenset()
    for x in join_of:
        vma = vma | compat.vma_of(x)
    return compat.out_struct(shape, dtype, vma)


def _pos_arrays(q_pos, k_pos, s: int):
    """(s,) i32 position vectors -> the (1, s, 8) / (1, 8, s) layouts the
    kernels read.  Rows ride the sublane-8 broadcast (same scheme as the
    lse output); cols live on the lane axis so a k-block slice of the
    LAST dim is Mosaic-legal (128-divisible block of the full array)."""
    qp = jnp.broadcast_to(q_pos.astype(jnp.int32)[None, :, None], (1, s, 8))
    kp = jnp.broadcast_to(k_pos.astype(jnp.int32)[None, None, :], (1, 8, s))
    return qp, kp


def _flash_fwd(q, k, v, causal: bool, kv_valid, block: int, positions=None,
               out_dtype=None):
    bh, s, d = q.shape
    scale = 1.0 / math.sqrt(d)
    grid = (bh, s // block)
    kv_spec = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0))
    in_specs = [pl.BlockSpec((1, block, d), lambda b, i: (b, i, 0)),
                kv_spec, kv_spec]
    args = [q, k, v]
    if positions is not None:
        qp, kp = _pos_arrays(*positions, s)
        in_specs += [pl.BlockSpec((1, block, 8), lambda b, i: (0, i, 0)),
                     pl.BlockSpec((1, 8, s), lambda b, i: (0, 0, 0))]
        args += [qp, kp]
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_k=block, causal=causal,
                          kv_valid=kv_valid, scale=scale,
                          use_pos=positions is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((1, block, d), lambda b, i: (b, i, 0)),
                   pl.BlockSpec((1, block, 8), lambda b, i: (b, i, 0))],
        out_shape=[_out_struct(q.shape, out_dtype or q.dtype, *args),
                   _out_struct((bh, s, 8), jnp.float32, *args)],
        interpret=_use_interpret(),
    )(*args)
    return o, lse


# --------------------------------------------------------------- backward --

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
               block_k: int, causal: bool, kv_valid, scale: float,
               use_pos: bool = False):
    if use_pos:
        qpos_ref, kpos_ref, dq_ref = rest
        rows = qpos_ref[0][:, 0:1]                      # (bq, 1)
    else:
        (dq_ref,) = rest
    bq = q_ref.shape[1]
    s = k_ref.shape[1]
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0:1]                            # (bq, 1)
    delta = delta_ref[0][:, 0:1]                        # rowsum(do * o)

    n_kb = s // block_k
    if causal and not use_pos:
        n_kb = jnp.minimum(n_kb, ((iq + 1) * bq + block_k - 1) // block_k)

    def body(kb, dq):
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        sc = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        if use_pos:
            cols = kpos_ref[0, 0:1, pl.ds(kb * block_k, block_k)]
            mask = _pos_mask(rows, cols, causal, kv_valid)
        else:
            mask = _masks(iq, kb, bq, block_k, causal, kv_valid)
        if mask is not None:
            sc = jnp.where(mask, sc, _NEG)
        p = jnp.exp(sc - lse)                           # (bq, bk)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if mask is not None:
            ds = jnp.where(mask, ds, 0.0)
        return dq + jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, n_kb, body, jnp.zeros((bq, q_ref.shape[2]), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                block_q: int, causal: bool, kv_valid, scale: float,
                use_pos: bool = False):
    if use_pos:
        qpos_ref, kpos_ref, dk_ref, dv_ref = rest
        cols = kpos_ref[0, 0:1, :]                      # (1, bk)
    else:
        dk_ref, dv_ref = rest
    bk = k_ref.shape[1]
    s = q_ref.shape[1]
    ik = pl.program_id(1)
    kblk = k_ref[0].astype(jnp.float32)
    vblk = v_ref[0].astype(jnp.float32)

    n_qb = s // block_q
    start_qb = jnp.int32(0)
    if causal and not use_pos:
        start_qb = (ik * bk) // block_q                 # earlier rows masked

    def body(qb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, pl.ds(qb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), 0:1]
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), 0:1]
        sc = jax.lax.dot_general(q, kblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        if use_pos:
            rows = qpos_ref[0, pl.ds(qb * block_q, block_q), 0:1]
            mask = _pos_mask(rows, cols, causal, kv_valid)
        else:
            mask = _masks(qb, ik, block_q, bk, causal, kv_valid)
        if mask is not None:
            sc = jnp.where(mask, sc, _NEG)
        p = jnp.exp(sc - lse)                           # (bq, bk)
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vblk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if mask is not None:
            ds = jnp.where(mask, ds, 0.0)
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    d = k_ref.shape[2]
    dk, dv = jax.lax.fori_loop(
        start_qb, n_qb, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_impl(causal, kv_valid, block, q, k, v, o, lse, do,
                    positions=None, dlse=None):
    """Two-kernel flash backward.  With ``dlse`` (the cotangent of the
    log-sum-exp output, used by the ring composition), the correction
    folds into the delta term: dbar(s_j) = p_j (v_j.do - delta + dlse)
    because d(lse)/d(s_j) = p_j — so delta := rowsum(do*o) - dlse and
    the kernels run unchanged."""
    bh, s, d = q.shape
    scale = 1.0 / math.sqrt(d)
    delta_rows = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                         axis=-1, keepdims=True)
    if dlse is not None:
        delta_rows = delta_rows - dlse.astype(jnp.float32)[..., None]
    delta = jnp.broadcast_to(delta_rows, (bh, s, 8))    # (bh, s, 8)
    grid = (bh, s // block)
    full_spec = pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0))
    blk_spec = pl.BlockSpec((1, block, d), lambda b, i: (b, i, 0))
    row_blk = pl.BlockSpec((1, block, 8), lambda b, i: (b, i, 0))
    row_full = pl.BlockSpec((1, s, 8), lambda b, i: (b, 0, 0))
    use_pos = positions is not None

    dq_in_specs = [blk_spec, full_spec, full_spec, blk_spec, row_blk,
                   row_blk]
    dkv_in_specs = [full_spec, blk_spec, blk_spec, full_spec, row_full,
                    row_full]
    dq_args = [q, k, v, do, lse, delta]
    dkv_args = [q, k, v, do, lse, delta]
    if use_pos:
        qp, kp = _pos_arrays(*positions, s)
        dq_in_specs += [pl.BlockSpec((1, block, 8), lambda b, i: (0, i, 0)),
                        pl.BlockSpec((1, 8, s), lambda b, i: (0, 0, 0))]
        dkv_in_specs += [pl.BlockSpec((1, s, 8), lambda b, i: (0, 0, 0)),
                         pl.BlockSpec((1, 8, block),
                                      lambda b, i: (0, 0, i))]
        dq_args += [qp, kp]
        dkv_args += [qp, kp]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block, causal=causal,
                          kv_valid=kv_valid, scale=scale, use_pos=use_pos),
        grid=grid,
        in_specs=dq_in_specs,
        out_specs=blk_spec,
        out_shape=_out_struct(q.shape, q.dtype, *dq_args),
        interpret=_use_interpret(),
    )(*dq_args)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block, causal=causal,
                          kv_valid=kv_valid, scale=scale, use_pos=use_pos),
        grid=grid,
        in_specs=dkv_in_specs,
        out_specs=[blk_spec, blk_spec],
        out_shape=[_out_struct(k.shape, k.dtype, *dkv_args),
                   _out_struct(v.shape, v.dtype, *dkv_args)],
        interpret=_use_interpret(),
    )(*dkv_args)
    return dq, dk, dv


def _flash_bwd(causal, kv_valid, block, res, do):
    q, k, v, o, lse = res
    return _flash_bwd_impl(causal, kv_valid, block, q, k, v, o, lse, do)


# ------------------------------------------------------------- public API --

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, kv_valid, block):
    o, _ = _flash_fwd(q, k, v, causal, kv_valid, block)
    return o


def _flash_vjp_fwd(q, k, v, causal, kv_valid, block):
    o, lse = _flash_fwd(q, k, v, causal, kv_valid, block)
    return o, (q, k, v, o, lse)


_flash.defvjp(_flash_vjp_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def flash_attention_partial(q, k, v, q_pos, k_pos, causal, kv_valid,
                            block=BLOCK):
    """Partial flash attention over one K/V block with GLOBAL positions:
    (bh, s, d) q/k/v + (s,) i32 row/col positions -> (o, lse) where o is
    the softmax-normalized local result and lse the per-row
    log-sum-exp over THIS block's keys.  Partials over disjoint key
    blocks merge exactly via the flash combine
    (ops.attention._merge_partials) — this is the per-shard kernel the
    ring calls, so sequence-parallel ring attention gets O(S_local)
    memory AND the MXU-tiled kernel.  Differentiable in q/k/v including
    the lse output (the merge weights depend on it; see
    _flash_bwd_impl's delta folding).  The output is FLOAT32 regardless
    of the input dtype: the caller merges n_dev partials in f32, and a
    bf16 round-trip per ring step would accumulate n_dev roundings
    where the plain kernel (and the einsum ring) pay exactly one."""
    o, lse = _flash_fwd(q, k, v, causal, kv_valid, block, (q_pos, k_pos),
                        out_dtype=jnp.float32)
    return o, lse[:, :, 0]


def _flash_partial_fwd(q, k, v, q_pos, k_pos, causal, kv_valid, block):
    o, lse = _flash_fwd(q, k, v, causal, kv_valid, block, (q_pos, k_pos),
                        out_dtype=jnp.float32)
    return (o, lse[:, :, 0]), (q, k, v, o, lse, q_pos, k_pos)


def _flash_partial_bwd(causal, kv_valid, block, res, cts):
    import numpy as np

    do, dlse = cts
    q, k, v, o, lse, q_pos, k_pos = res
    dq, dk, dv = _flash_bwd_impl(causal, kv_valid, block, q, k, v, o, lse,
                                 do, positions=(q_pos, k_pos), dlse=dlse)
    # integer position inputs take float0 cotangents
    zq = np.zeros(q_pos.shape, jax.dtypes.float0)
    zk = np.zeros(k_pos.shape, jax.dtypes.float0)
    return dq, dk, dv, zq, zk


flash_attention_partial.defvjp(_flash_partial_fwd, _flash_partial_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, block: int = BLOCK) -> jax.Array:
    """Pallas flash attention; q/k/v (B, S, H, D) -> (B, S, H, D).

    Any S works: inputs are zero-padded to a block multiple and the
    padded key columns are masked inside the kernels (padded query rows
    come back zero and are sliced off).  Same math as
    ops.attention.full_attention to float tolerance, forward and
    backward.
    """
    b, s, h, d = q.shape
    s_pad = -(-s // block) * block
    kv_valid = s if s_pad != s else None

    def to_bh(x):
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, s, d)
        if s_pad != s:
            x = jnp.pad(x, ((0, 0), (0, s_pad - s), (0, 0)))
        return x

    o = _flash(to_bh(q), to_bh(k), to_bh(v), causal, kv_valid, block)
    o = o[:, :s].reshape(b, h, s, d)
    return jnp.moveaxis(o, 1, 2)
