"""Metrics (ref utils.py:158-162 calculateAccuracy).

Returns per-example correctness; the engine masks padding and psums across
replicas so reported accuracy is *global* — a deliberate fix of SURVEY
defect #9 (the reference reports each rank's shard-local accuracy and
never reduces).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def per_example_correct(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """top-1 argmax vs labels -> float32 (B,) of 0/1."""
    pred = jnp.argmax(logits, axis=-1)
    return (pred == labels).astype(jnp.float32)
