"""Losses and metrics as pure functions (ref utils.py:142-162, classif.py:106-120)."""

from .losses import get_loss_fn, cross_entropy, weighted_cross_entropy, focal_loss
from .metrics import per_example_correct

__all__ = ["get_loss_fn", "cross_entropy", "weighted_cross_entropy",
           "focal_loss", "per_example_correct"]
