"""Analytic FLOP accounting (SURVEY §5 metrics/observability).

Counts multiply-add FLOPs (2 x MACs) of the matmul/conv primitives in a
function's jaxpr — the standard model-FLOPs convention (elementwise ops are
ignored; they are bandwidth-, not FLOP-bound on TPU).  Used by bench.py for
MFU: the TPU executable's own ``cost_analysis()`` reports per-partition
post-fusion estimates that undercount by orders of magnitude, so MFU must
come from the analytic model count, as every published MFU number does.

The reference has no FLOPs/MFU accounting anywhere (its only metrics are
wall-clock + accuracy, ref classif.py:171-178, utils.py:158-162) — this is
framework-added observability, flagged as a divergence-by-addition.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _dot_general_flops(eqn) -> float:
    (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
    lshape = eqn.invars[0].aval.shape
    rshape = eqn.invars[1].aval.shape
    batch = _prod(lshape[i] for i in lb)
    k = _prod(lshape[i] for i in lc)
    m = _prod(lshape[i] for i in range(len(lshape))
              if i not in set(lb) | set(lc))
    n = _prod(rshape[i] for i in range(len(rshape))
              if i not in set(_rb) | set(rc))
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    dn = eqn.params["dimension_numbers"]
    out_shape = eqn.outvars[0].aval.shape
    rhs_shape = eqn.invars[1].aval.shape
    # Kernel input-feature size is already divided by feature_group_count
    # in the kernel's shape, so no extra correction is needed.
    k_in = rhs_shape[dn.rhs_spec[1]]
    k_spatial = _prod(rhs_shape[i] for i in dn.rhs_spec[2:])
    return 2.0 * _prod(out_shape) * k_spatial * k_in


def jaxpr_flops(jaxpr) -> float:
    """Matmul+conv FLOPs of one (open) jaxpr, recursing into sub-jaxprs."""
    total = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            total += _dot_general_flops(eqn)
        elif name == "conv_general_dilated":
            total += _conv_flops(eqn)
        elif name == "scan":
            total += (eqn.params["length"]
                      * jaxpr_flops(eqn.params["jaxpr"].jaxpr))
        elif name == "while":
            # Unknown trip count: count one body iteration (callers that
            # need exactness should not hide matmuls in while loops).
            total += jaxpr_flops(eqn.params["body_jaxpr"].jaxpr)
        elif name == "cond":
            total += max((jaxpr_flops(b.jaxpr)
                          for b in eqn.params["branches"]), default=0.0)
        else:
            # Generic containers: pjit, remat/checkpoint, custom_jvp/vjp, …
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                sub = eqn.params.get(key)
                if sub is not None:
                    total += jaxpr_flops(getattr(sub, "jaxpr", sub))
                    break
    return total


def forward_flops(model: Any, params: Any, batch_stats: Any,
                  batch: int, input_size: int,
                  dtype=jnp.float32) -> float:
    """FLOPs of one inference forward pass at the given batch size.

    Traces abstractly (no compute, no device use).  ``batch_stats`` may be
    an empty dict for BN-free models.
    """
    x = jax.ShapeDtypeStruct((batch, input_size, input_size, 3), dtype)

    has_bn = len(jax.tree_util.tree_leaves(batch_stats)) > 0

    def fwd(p, bs, imgs):
        variables = {"params": p}
        if has_bn:
            variables["batch_stats"] = bs
        return model.apply(variables, imgs, train=False)

    closed = jax.make_jaxpr(fwd)(params, batch_stats, x)
    return jaxpr_flops(closed.jaxpr)


def train_flops_per_sample(model: Any, params: Any, batch_stats: Any,
                           batch: int, input_size: int,
                           dtype=jnp.float32) -> float:
    """Model FLOPs of one training step, per sample.

    The standard estimate: backward costs ~2x forward (grad wrt inputs +
    grad wrt weights), so train = 3 x forward.  Optimizer/elementwise work
    is excluded by convention (it is negligible next to the matmuls for
    conv nets and would not run on the MXU anyway).
    """
    fwd = forward_flops(model, params, batch_stats, batch, input_size,
                        dtype)
    return 3.0 * fwd / batch


# Published peak dense bf16 FLOP/s per chip, keyed by device_kind substring
# (lowercased).  Unknown kinds (incl. CPU) report None — callers (bench.py,
# the telemetry MFU gauge) then omit MFU rather than fabricate it.
PEAK_BF16_FLOPS = [
    ("v6e", 918e12), ("v6 lite", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12), ("v5 lite", 197e12), ("v5litepod", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

# Published HBM bandwidth per chip (bytes/s), keyed like PEAK_BF16_FLOPS.
# The roofline classifier (roofline.py) divides the FLOPs peak by this to
# place the ridge point: ops whose arithmetic intensity falls left of it
# are memory-bound at any achievable FLOP rate.  Unknown kinds (incl.
# CPU) report None — the classifier then falls back to a generic ridge
# and says so in the report.
PEAK_HBM_BYTES = [
    ("v6e", 1640e9), ("v6 lite", 1640e9),
    ("v5p", 2765e9),
    ("v5e", 819e9), ("v5 lite", 819e9), ("v5litepod", 819e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
]


def peak_membw(device_kind) -> Optional[float]:
    """Peak HBM bytes/s for a ``Device.device_kind``; None when unknown
    (CPU, future kinds) so callers degrade explicitly instead of
    fabricating a roofline."""
    if not device_kind:
        return None
    kind = str(device_kind).lower()
    for key, bw in PEAK_HBM_BYTES:
        if key in kind:
            return bw
    return None


# Repo convention for the f32 denominator: half the bf16 peak.  Cloud TPU
# datasheets publish only the bf16 (and int8) peak; XLA's default f32
# matmul path feeds the MXU at half the bf16 issue rate, so f32 MFU
# against the bf16 peak would be systematically understated by ~2x (and
# bf16 MFU against an f32 peak inflated by the same factor).  The /2
# convention is recorded as such (costs.record_mfu_denominator tags the
# table as the source) pending a measured closure per device kind.
F32_PEAK_FRACTION = 0.5

_DTYPE_LABELS = {
    "bfloat16": "bf16", "float32": "f32", "float16": "f16",
    "bf16": "bf16", "f32": "f32", "f16": "f16",
}


def dtype_label(dtype) -> str:
    """Canonical short label ('bf16'/'f32'/'f16') for a compute dtype.

    Accepts jnp dtypes, numpy dtypes, or the short label itself; unknown
    dtypes come back verbatim (lowercased) so callers can still record
    what was asked for."""
    name = str(jnp.dtype(dtype).name) if not isinstance(dtype, str) \
        else dtype
    return _DTYPE_LABELS.get(name.lower(), name.lower())


def peak_flops(device_kind: str, dtype="bf16") -> Optional[float]:
    """Peak dense FLOP/s for a ``Device.device_kind`` at ``dtype``.

    ``dtype`` may be a short label ('bf16'/'f32'/'f16') or an actual
    dtype.  Returns None for unknown device kinds AND for dtypes the MXU
    has no native path for (f16): callers must then omit MFU rather than
    fabricate a denominator.  The one-argument form keeps its historical
    meaning (bf16 peak)."""
    label = dtype_label(dtype)
    kind = device_kind.lower()
    for key, peak in PEAK_BF16_FLOPS:
        if key in kind:
            if label == "bf16":
                return peak
            if label == "f32":
                return peak * F32_PEAK_FRACTION
            return None
    return None


def human_flops(flops: float) -> str:
    if flops <= 0:
        return "0"
    exp = min(int(math.log10(flops)) // 3, 6)
    unit = ["", "K", "M", "G", "T", "P", "E"][exp]
    return f"{flops / 10 ** (3 * exp):.2f} {unit}FLOP"
