"""TPU-fast 2x2/stride-2 max pooling with an elementwise backward.

``flax.linen.max_pool``'s gradient lowers to an XLA ``select-and-scatter``
op, which is the single slowest HLO in the headline cnn/b64 train step on
a v5e: 52 us/step of the 322 us total for the two pool layers (measured,
scripts/trace_ops.py).  Select-and-scatter serializes window scans; TPUs
hate it.

For the non-overlapping 2x2/stride-2 case (window == stride) pooling is a
reshape + reduce-max, and the gradient is a per-window one-hot routing —
both pure elementwise/reduce work that XLA fuses into neighbouring ops.
This module implements that with a custom VJP that preserves the EXACT
semantics of torch/XLA maxpool backward: the gradient goes to the FIRST
maximal element in row-major window order (select-and-scatter's >=-select
picks the first match; torch's MaxPool2d backward routes to the first
argmax).  The first-max mask is recomputed in the backward pass from the
saved input and output — cheaper on TPU than materializing argmax indices
in the forward pass (measured: argmax variant 288 us/step, this 268
us/step, baseline 330 us/step on the cnn/b64 step).

Numerics: bit-identical to ``nn.max_pool((2,2), strides=(2,2))`` in both
forward and backward, ties included (tests/test_pooling.py pins both
against the flax op, plus the tie case).

The reference has no TPU analogue of this concern (its torch maxpool runs
on cuDNN, ref utils.py:38-105 models); this is pure TPU-first design.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.custom_vjp
def max_pool_2x2(x: jax.Array) -> jax.Array:
    """(B, H, W, C) -> (B, H/2, W/2, C) max pool; H and W must be even."""
    b, h, w, c = x.shape
    if h % 2 or w % 2:
        raise ValueError(f"max_pool_2x2 needs even H/W, got {h}x{w}")
    return jnp.max(x.reshape(b, h // 2, 2, w // 2, 2, c), axis=(2, 4))


def _fwd(x):
    m = max_pool_2x2(x)
    return m, (x, m)


def _bwd(res, g):
    x, m = res
    b, h, w, c = x.shape
    xr = x.reshape(b, h // 2, 2, w // 2, 2, c)
    eq = xr == m[:, :, None, :, None, :]
    e00, e01 = eq[:, :, 0, :, 0, :], eq[:, :, 0, :, 1, :]
    e10, e11 = eq[:, :, 1, :, 0, :], eq[:, :, 1, :, 1, :]
    # First max in row-major window order gets the whole gradient —
    # identical routing to select-and-scatter / torch MaxPool2d.
    f00 = e00
    f01 = e01 & ~e00
    f10 = e10 & ~(e00 | e01)
    f11 = e11 & ~(e00 | e01 | e10)
    z = jnp.zeros_like(g)
    rows = jnp.stack(
        [jnp.stack([jnp.where(f00, g, z), jnp.where(f01, g, z)], axis=3),
         jnp.stack([jnp.where(f10, g, z), jnp.where(f11, g, z)], axis=3)],
        axis=2)  # (b, h/2, 2, w/2, 2, c)
    return (rows.reshape(b, h, w, c),)


max_pool_2x2.defvjp(_fwd, _bwd)
