"""Patch-reuse Pallas conv-dW: the round-4 "known headroom" kernel.

BASELINE.md ("Conv-dW roofline closed") measured the headline cnn/b64
step bound by the conv weight gradients: XLA's native dW lowering runs at
~24 TF/s because it re-materializes the im2col patch expansion from HBM
(~32 MB/step of operand traffic for ~925 MFLOP on the 3x3/32-64-channel
shapes — bandwidth-bound).  The alternative it predicted — a kernel that
builds the patch matrix IN VMEM from the raw activations, cutting HBM
traffic ~5x, then runs one long-contraction matmul per batch chunk —
is this module.  The round-5 verdict (item 2) asked for the kernel to be
built and the recorded 10-15% whole-step headroom settled with on-chip
numbers either way; the measured outcome lives in BASELINE.md.

Formulation (NHWC, 3x3, stride 1, SAME — the only shapes the zoo's hot
convs use):

    dW[kh,kw,ci,co] = sum_{b,h,w} x_pad[b,h+kh,w+kw,ci] * dy[b,h,w,co]

Per grid step (one batch chunk resident in VMEM):
  * slice the padded activations at the 9 static (kh,kw) offsets and
    concatenate along lanes -> patches (bc*H*W, 9*Ci); the patch
    expansion exists only in VMEM, never in HBM;
  * ONE dot_general contracting the long bc*H*W axis against dy
    (bc*H*W, Co) -> (9*Ci, Co) in float32 (M = 9*Ci = 288/576 fills
    whole sublane tiles; N = Co = 32/64 is the lane-bound part the
    roofline already priced at <= Co/128 of peak);
  * accumulate across grid steps in the revisited f32 output block.

``Conv3x3`` is a drop-in for the zoo's ``nn.Conv(width, (3,3),
padding='SAME')`` layers: identical param tree (kernel HWIO + bias, same
auto-name slot when constructed with the same ``name=``), identical
forward (the XLA conv — fastest available), identical dx (the standard
transposed conv XLA autodiff emits); ONLY dW is replaced.  Numerics are
pinned against jax autodiff of the plain conv in tests/test_conv_dw.py.

The reference trains its convs through cuDNN (ref classif.py:59
``loss.backward()``); this kernel is the TPU-first answer to the same
backward, not a translation.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Keep the in-kernel patch buffer (bc * H*W * 9*Ci * 2 bytes, the largest
# VMEM resident) sized so the kernel's whole working set — Mosaic stages
# roughly 3-4x the raw patch bytes for the dot operands (measured: a
# 3.6 MB patch buffer needs a 16.91 MB scoped allocation) — fits the
# raised VMEM limit below.  Small chunks are poison: at bc=2 the
# per-grid-step overhead (9 relayout stores + a short-M dot) made the
# whole-step bench 3.2x SLOWER than XLA's native dW.
_PATCH_VMEM_BUDGET = 4 * 1024 * 1024
# v5e has 128 MiB of physical VMEM; the 16 MiB default is only XLA's
# conservative scoped-vmem setting.  Bigger chunks (bc=32, ~50 MB
# working set) sent Mosaic compile into the tens of minutes — the
# budget above keeps bc at 8 for the 28x28x32 shape, whose ~17 MB
# working set compiles in seconds.
_VMEM_LIMIT = 40 * 1024 * 1024

# jax renamed pltpu.TPUCompilerParams -> CompilerParams; support both so
# the kernel runs across the jaxlib versions the environments carry.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams


def _use_interpret() -> bool:
    # Real Mosaic lowering on TPU; interpreter everywhere else (the CPU
    # test mesh runs the same kernel logic).
    return jax.default_backend() != "tpu"


def _chunk(b: int, h: int, w: int, ci: int, itemsize: int) -> int:
    """Largest divisor of ``b`` whose patch buffer fits the budget.

    ``itemsize`` is the element width of the kernel's compute dtype
    (the scratch buffer is allocated in x.dtype): hardcoding 2
    (ADVICE #2) doubled the real scratch size under float32
    (half_precision=False), letting the chosen chunk push the working
    set past the scoped-VMEM limit on a real TPU."""
    from ..utils import largest_divisor_leq

    return largest_divisor_leq(
        b, max(1, _PATCH_VMEM_BUDGET // (h * w * 9 * ci * itemsize)))


def _dw_kernel(xp_ref, dy_ref, out_ref, patch_ref):
    bc, h, w, co = dy_ref.shape
    ci = xp_ref.shape[-1]
    dy = dy_ref[...].reshape(bc * h * w, co)
    # 9 static shifted views of the padded block, written side by side
    # into the VMEM patch scratch: the im2col patch matrix, built and
    # consumed on-chip.  (A lane-dim concatenate of the views trips
    # Mosaic's offset-mismatch check — the stores relayout instead.)
    for kh in range(3):
        for kw in range(3):
            i0 = (kh * 3 + kw) * ci
            patch_ref[:, :, :, i0:i0 + ci] = xp_ref[:, kh:kh + h,
                                                    kw:kw + w, :]
    patches = patch_ref[...].reshape(bc * h * w, 9 * ci)
    acc = jax.lax.dot_general(patches, dy, (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[...] = acc

    @pl.when(i != 0)
    def _accumulate():
        out_ref[...] += acc


def conv3x3_dw(x: jax.Array, dy: jax.Array) -> jax.Array:
    """Weight gradient of a 3x3/stride-1/SAME NHWC conv.

    x (B, H, W, Ci) conv input, dy (B, H, W, Co) output cotangent ->
    dW (3, 3, Ci, Co) in float32 (the caller casts to the kernel dtype,
    matching XLA autodiff's accumulate-in-f32 behavior).
    """
    b, h, w, ci = x.shape
    co = dy.shape[-1]
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    bc = _chunk(b, h, w, ci, jnp.dtype(x.dtype).itemsize)
    out = pl.pallas_call(
        _dw_kernel,
        grid=(b // bc,),
        in_specs=[
            pl.BlockSpec((bc, h + 2, w + 2, ci), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((bc, h, w, co), lambda i: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((9 * ci, co), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((9 * ci, co), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bc, h, w, 9 * ci), x.dtype)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_VMEM_LIMIT),
        interpret=_use_interpret(),
    )(xp, dy)
    # concat order above is kh-major/kw-minor, Ci per block -> HWIO
    return out.reshape(3, 3, ci, co)


@jax.custom_vjp
def conv3x3_same(x: jax.Array, w: jax.Array) -> jax.Array:
    """3x3/stride-1/SAME NHWC conv: XLA forward, XLA dx, Pallas dW."""
    return _conv(x, w)


def _conv(x, w):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _conv_fwd(x, w):
    return _conv(x, w), (x, w)


def _conv_bwd(res, dy):
    x, w = res
    # dx: the standard transposed conv XLA autodiff emits — spatially
    # reversed kernel with in/out channels swapped, SAME padding (exact
    # for odd kernels at stride 1).
    dx = _conv(dy, w[::-1, ::-1].swapaxes(2, 3))
    dw = conv3x3_dw(x, dy).astype(w.dtype)
    return dx.astype(x.dtype), dw


conv3x3_same.defvjp(_conv_fwd, _conv_bwd)


class Conv3x3(nn.Module):
    """Drop-in for ``nn.Conv(features, (3, 3), padding='SAME')`` with the
    Pallas dW backward.  Same param tree (kernel HWIO f32 + bias, same
    initializers), same forward math; construct with the same ``name=``
    slot to keep checkpoints interchangeable with the nn.Conv model."""

    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    padding: str = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        if tuple(self.kernel_size) != (3, 3) or self.padding != "SAME":
            raise ValueError("Conv3x3 supports 3x3/SAME only, got "
                             f"{self.kernel_size}/{self.padding}")
        ci = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (3, 3, ci, self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        y = conv3x3_same(x.astype(self.dtype), kernel.astype(self.dtype))
        return y + bias.astype(self.dtype)
