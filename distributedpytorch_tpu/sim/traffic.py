"""Traffic generators: seeded arrival-time sequences in virtual seconds.

Each generator is a pure function of (rng, spec, duration) returning a
sorted list of arrival times — non-homogeneous Poisson processes
realized by thinning against the spec's peak rate, so the diurnal ramp
and the burst are statistically honest, not staircases.  The shapes
mirror what a serving tier actually sees:

  constant     flat base-rate background (control scenarios),
  diurnal      sinusoidal ramp between base_rps and peak_rps — the load
               pattern that makes naive autoscalers flap,
  burst        base rate plus a rectangular surge window — the shape
               that cascades through the front door's pending budget,
  heavy_tail   Pareto interarrivals (bursty at every timescale) with
               the requested mean rate — the tail-risk generator.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List

KINDS = ("constant", "diurnal", "burst", "heavy_tail")


def _poisson(rng: random.Random, duration_s: float, peak_rps: float,
             rate_at) -> List[float]:
    """Thinning: candidate arrivals at ``peak_rps``, each kept with
    probability rate(t)/peak — an exact non-homogeneous Poisson
    realization as long as rate(t) <= peak everywhere."""
    out: List[float] = []
    t = 0.0
    while True:
        t += rng.expovariate(peak_rps)
        if t >= duration_s:
            return out
        if rng.random() <= rate_at(t) / peak_rps:
            out.append(t)


def constant(rng: random.Random, duration_s: float, rps: float
             ) -> List[float]:
    return _poisson(rng, duration_s, rps, lambda t: rps)


def diurnal(rng: random.Random, duration_s: float, base_rps: float,
            peak_rps: float, period_s: float) -> List[float]:
    mid = (base_rps + peak_rps) / 2.0
    amp = (peak_rps - base_rps) / 2.0

    def rate(t: float) -> float:
        return mid + amp * math.sin(2.0 * math.pi * t / period_s)

    return _poisson(rng, duration_s, peak_rps, rate)


def burst(rng: random.Random, duration_s: float, base_rps: float,
          burst_rps: float, burst_start_s: float, burst_len_s: float
          ) -> List[float]:
    def rate(t: float) -> float:
        if burst_start_s <= t < burst_start_s + burst_len_s:
            return burst_rps
        return base_rps

    return _poisson(rng, duration_s, max(base_rps, burst_rps), rate)


def heavy_tail(rng: random.Random, duration_s: float, rps: float,
               alpha: float = 1.5) -> List[float]:
    """Pareto(alpha) interarrivals scaled to mean 1/rps.  alpha must be
    > 1 (an infinite-mean process has no rate to scale to)."""
    if alpha <= 1.0:
        raise ValueError(f"heavy_tail: alpha must be > 1 (got {alpha})")
    # Pareto(alpha) with x_m=1 has mean alpha/(alpha-1); scale so the
    # interarrival mean is 1/rps.
    scale = (alpha - 1.0) / (alpha * rps)
    out: List[float] = []
    t = 0.0
    while True:
        t += rng.paretovariate(alpha) * scale
        if t >= duration_s:
            return out
        out.append(t)


def generate(rng: random.Random, spec: Dict[str, Any],
             duration_s: float) -> List[float]:
    """Dispatch on ``spec["kind"]``; unknown kinds and missing params
    fail loudly at scenario load, not mid-replay."""
    kind = spec.get("kind")
    try:
        if kind == "constant":
            return constant(rng, duration_s, float(spec["rps"]))
        if kind == "diurnal":
            return diurnal(rng, duration_s, float(spec["base_rps"]),
                           float(spec["peak_rps"]),
                           float(spec["period_s"]))
        if kind == "burst":
            return burst(rng, duration_s, float(spec["base_rps"]),
                         float(spec["burst_rps"]),
                         float(spec["burst_start_s"]),
                         float(spec["burst_len_s"]))
        if kind == "heavy_tail":
            return heavy_tail(rng, duration_s, float(spec["rps"]),
                              float(spec.get("alpha", 1.5)))
    except KeyError as e:
        raise ValueError(
            f"traffic spec kind {kind!r} is missing parameter {e}")
    raise ValueError(
        f"traffic spec kind must be one of {list(KINDS)}, got {kind!r}")
