"""Scenario definitions: what the simulated fleet is put through.

A scenario is one JSON-able dict — fleet size, virtual duration,
traffic spec (traffic.py), a fault plan in the EXISTING faults.py DSL
(site ``sim.step``), the policy configs handed verbatim to the real
deciders, the SLO objectives (slo.validate_spec shapes), and the
robustness floors scripts/sim_gate.py asserts.  Built-ins:

  control           over-provisioned fleet, flat light traffic — the
                    null hypothesis: zero scale actions, zero incidents.
  diurnal           sinusoidal load across the autoscaler's thresholds —
                    the flap test.
  burst             a rectangular surge through the front door's pending
                    budget — the bounded-shed test.
  preemption_wave   30% of the fleet vanishes at once — the rejoin-
                    thrash test.
  chaos             all of the above plus an ioerror burst, a stall
                    wave and a canary rollout, at N=100 — the gate's
                    headline scenario.

Fault-plan reading under the virtual clock (the DSL is unchanged; only
the interpretation is simulator-specific, documented here and next to
faults.SITES): ``after_n`` = virtual seconds at which the spec fires,
``count`` = replicas affected (rank_loss / preempt / rank_join / stall)
or requests failed (ioerror), ``stall_s`` = added service seconds per
stalled replica's next dispatch.
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Dict, List

from .. import faults, slo

#: Keys every scenario carries; load_scenario fills these from DEFAULTS
#: so user scenario files only state what they change.
DEFAULTS: Dict[str, Any] = {
    "replicas": 10,
    "duration_s": 120.0,
    "interval_s": 1.0,        # control tick / fleet scrape cadence
    "buckets": "1,4,8",       # the planner's compiled batch menu
    "flush_s": 0.05,          # idle-replica batch-formation wait
    "provision_delay_s": 8.0,  # scale-up launch -> join claim
    "rejoin_delay_s": 15.0,   # fault-killed replica -> rejoin claim
    "join_retry_s": 5.0,      # declined joiner -> next claim
    "max_attempts": 10,       # client retries before dropped-forever
    "trace_sample": 7,        # every Nth answered request gets a trace
    "goodput_window_s": 30.0,  # ledger epoch-row cadence
    "traffic": {"kind": "constant", "rps": 12.0},
    "fault_plan": "",
    "route": {},              # frontdoor.ROUTE_DEFAULTS overrides
    "scale": {},              # controller.SCALE_DEFAULTS overrides
    "elastic": {"target": "capacity", "min_world": 1},
    "slos": [],
    "rollout": None,          # {"at_s": T, ...ROLLOUT_DEFAULTS overrides}
    "floors": {},
}

_SLOS_STANDARD: List[Dict[str, Any]] = [
    {"name": "availability", "kind": "ratio",
     "bad": "dpt_serve_errors_total", "total": "dpt_serve_requests_total",
     "target": 0.99, "windows": [{"seconds": 30, "burn": 2.0}]},
    {"name": "shed-burn", "kind": "ratio",
     "bad": "dpt_frontdoor_shed_total",
     "total": "dpt_frontdoor_requests_total",
     "target": 0.98, "windows": [{"seconds": 20, "burn": 2.0}]},
    {"name": "p95-latency", "kind": "quantile",
     "series": "dpt_serve_request_latency_ms", "q": 0.95,
     "max": 15000.0, "windows": [{"seconds": 30}]},
]

SCENARIOS: Dict[str, Dict[str, Any]] = {
    "control": {
        "name": "control", "replicas": 10, "duration_s": 120.0,
        "traffic": {"kind": "constant", "rps": 12.0},
        "scale": {"min_world": 10, "max_world": 12, "queue_high": 60.0,
                  "queue_low": 0.5, "up_hold_s": 6.0,
                  "down_hold_s": 40.0, "cooldown_s": 15.0},
        "route": {"pending_budget": 400, "eject_after": 3,
                  "max_step_age_s": 30.0},
        "elastic": {"target": "capacity", "min_world": 10},
        "slos": _SLOS_STANDARD,
        "floors": {"scale_actions": 0, "incidents_exact": 0,
                   "dropped_forever": 0, "max_direction_changes": 0,
                   "max_shed_window_s": 0.0},
    },
    "diurnal": {
        "name": "diurnal", "replicas": 30, "duration_s": 180.0,
        "traffic": {"kind": "diurnal", "base_rps": 25.0,
                    "peak_rps": 55.0, "period_s": 60.0},
        "scale": {"min_world": 20, "max_world": 40, "queue_high": 60.0,
                  "queue_low": 2.0, "up_hold_s": 6.0,
                  "down_hold_s": 40.0, "cooldown_s": 15.0},
        "route": {"pending_budget": 500, "eject_after": 3,
                  "max_step_age_s": 45.0},
        "elastic": {"target": "capacity", "min_world": 20},
        "slos": _SLOS_STANDARD,
        "floors": {"dropped_forever": 0, "max_direction_changes": 2},
    },
    "burst": {
        "name": "burst", "replicas": 20, "duration_s": 120.0,
        "traffic": {"kind": "burst", "base_rps": 15.0,
                    "burst_rps": 120.0, "burst_start_s": 40.0,
                    "burst_len_s": 8.0},
        "scale": {"min_world": 15, "max_world": 30, "queue_high": 80.0,
                  "queue_low": 2.0, "up_hold_s": 6.0,
                  "down_hold_s": 40.0, "cooldown_s": 15.0},
        "route": {"pending_budget": 300, "retry_after_s": 2.0,
                  "eject_after": 3, "max_step_age_s": 45.0},
        "elastic": {"target": "capacity", "min_world": 15},
        "slos": _SLOS_STANDARD,
        "floors": {"dropped_forever": 0, "max_shed_window_s": 40.0},
    },
    "preemption_wave": {
        "name": "preemption_wave", "replicas": 50, "duration_s": 150.0,
        "traffic": {"kind": "constant", "rps": 60.0},
        "fault_plan": "sim.step:rank_loss:60:15",
        "scale": {"min_world": 35, "max_world": 60, "queue_high": 100.0,
                  "queue_low": 3.0, "up_hold_s": 6.0,
                  "down_hold_s": 40.0, "cooldown_s": 15.0},
        "route": {"pending_budget": 500, "eject_after": 3,
                  "max_step_age_s": 45.0},
        "elastic": {"target": "capacity", "min_world": 35},
        "slos": _SLOS_STANDARD,
        "floors": {"dropped_forever": 0,
                   "max_rejoin_admits_per_replica": 1},
    },
    "chaos": {
        "name": "chaos", "replicas": 100, "duration_s": 180.0,
        # Capacity math: one replica turns a full bucket-8 batch in
        # ~3.5s => ~2.3 rps; 100 replicas ~230 rps.  The diurnal band
        # below keeps utilization 0.45-0.75 — headroom at base, real
        # queueing at peak, and the pending budget (Little's law:
        # ~peak_rps x in-system seconds, plus a burst margin) only
        # trips when a fault eats capacity.
        "traffic": {"kind": "diurnal", "base_rps": 100.0,
                    "peak_rps": 170.0, "period_s": 120.0},
        # t=45 six replicas stall (+2.5s on their next dispatch);
        # t=100 a 30%-of-fleet preemption wave; t=130 a 300-request
        # ioerror burst on one replica.
        "fault_plan": ("sim.step:stall:45:6:2.5;"
                       "sim.step:rank_loss:100:30;"
                       "sim.step:ioerror:130:300"),
        "scale": {"min_world": 70, "max_world": 120,
                  "queue_high": 150.0, "queue_low": 5.0,
                  "up_hold_s": 6.0, "down_hold_s": 40.0,
                  "cooldown_s": 15.0},
        "route": {"pending_budget": 2000, "retry_after_s": 2.0,
                  "eject_after": 3, "max_step_age_s": 45.0},
        "elastic": {"target": "capacity", "min_world": 70},
        "slos": _SLOS_STANDARD,
        "rollout": {"at_s": 140.0, "fraction": 0.10, "hold_s": 15.0,
                    "min_requests": 40, "timeout_s": 35.0},
        "floors": {"dropped_forever": 0, "max_direction_changes": 2,
                   "max_shed_window_s": 60.0,
                   "max_rejoin_admits_per_replica": 1,
                   "recover_world_min": 70,
                   "rollout_outcome": "promote",
                   # The one SLO the fault plan is DESIGNED to trip:
                   # the spread ioerror burst at t=130.  The stall and
                   # the wave must ride through without an incident.
                   "incidents_exact": ["availability"]},
    },
}


def load_scenario(name_or_path: str, replicas: int = 0,
                  duration_s: float = 0.0) -> Dict[str, Any]:
    """Resolve a built-in name or a scenario JSON path, fill defaults,
    validate, and apply CLI overrides (0 = keep the scenario's own)."""
    if name_or_path in SCENARIOS:
        sc = copy.deepcopy(SCENARIOS[name_or_path])
    elif name_or_path.endswith(".json") or os.path.exists(name_or_path):
        try:
            with open(name_or_path, encoding="utf-8") as f:
                sc = json.load(f)
        except OSError as e:
            raise ValueError(
                f"cannot read scenario file {name_or_path!r}: {e}")
        except ValueError as e:
            raise ValueError(
                f"scenario file {name_or_path!r} is not valid JSON: {e}")
        if not isinstance(sc, dict):
            raise ValueError(
                f"scenario file {name_or_path!r} must hold a JSON "
                f"object")
        sc.setdefault("name", os.path.splitext(
            os.path.basename(name_or_path))[0])
    else:
        raise ValueError(
            f"unknown scenario {name_or_path!r}: expected one of "
            f"{sorted(SCENARIOS)} or a scenario JSON path")
    out = copy.deepcopy(DEFAULTS)
    out.update(sc)
    if replicas:
        out["replicas"] = int(replicas)
    if duration_s:
        out["duration_s"] = float(duration_s)
    if int(out["replicas"]) < 1:
        raise ValueError(f"scenario {out.get('name')!r}: replicas must "
                         f"be >= 1")
    if float(out["duration_s"]) <= 0:
        raise ValueError(f"scenario {out.get('name')!r}: duration_s "
                         f"must be > 0")
    if out["slos"]:
        slo.validate_spec({"slos": out["slos"]})
    timed_faults(out, seed=0)  # validate the plan shape up front
    return out


def timed_faults(scenario: Dict[str, Any], seed: int
                 ) -> List[Dict[str, Any]]:
    """The scenario's fault plan, parsed by the REAL faults.parse_plan
    and reinterpreted under the virtual clock (module docstring).
    Returns ``[{"t", "kind", "count", "stall_s"}, ...]`` sorted by t."""
    plan_text = scenario.get("fault_plan") or ""
    if not plan_text:
        return []
    plan = faults.parse_plan(plan_text, seed=seed)
    out: List[Dict[str, Any]] = []
    for spec in plan.specs:
        if spec.site != "sim.step":
            raise ValueError(
                f"scenario {scenario.get('name')!r}: simulator fault "
                f"plans use site 'sim.step' only (got {spec.site!r} — "
                f"other sites belong to live processes)")
        if spec.kind in ("fatal", "torn"):
            raise ValueError(
                f"scenario {scenario.get('name')!r}: fault kind "
                f"{spec.kind!r} has no fleet-level reading; use "
                f"rank_loss/preempt/stall/ioerror/rank_join")
        out.append({"t": float(spec.after_n), "kind": spec.kind,
                    "count": int(spec.count),
                    "stall_s": float(spec.stall_s)})
    return sorted(out, key=lambda f: (f["t"], f["kind"]))
