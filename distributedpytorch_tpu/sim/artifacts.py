"""Persist a finished FleetSim as the repo's LIVE artifact layout.

One rule: every file written here goes through the same schema factory
the live emitters use (telemetry.stamp_record/encode_line,
tracing.build_request_record/encode_record, goodput.build_ledger_doc,
fleet.encode_sample / write_incident_bundle), so ``main.py goodput``,
``timeline``, ``fleet`` and ``incidents`` render a simulated fleet with
zero simulator-specific code — and schema drift between sim and live is
structurally impossible.

On top of the live layout, two simulator-only files:

  sim-events.jsonl   the deterministic event log — same seed, same
                     scenario, same model => byte-identical file.  The
                     report pins its sha256.
  sim-report.json    the run summary scripts/sim_gate.py asserts
                     robustness floors against.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict

from .. import fleet, goodput, telemetry, tracing
from .engine import BASE_TS, FleetSim


def _encode_events(sim: FleetSim) -> bytes:
    lines = [json.dumps(ev, sort_keys=True, default=float)
             for ev in sim.events]
    return ("\n".join(lines) + "\n").encode("utf-8") if lines else b""


def event_log_sha256(sim: FleetSim) -> str:
    return hashlib.sha256(_encode_events(sim)).hexdigest()


def write_artifacts(rsl_path: str, sim: FleetSim,
                    report: Dict[str, Any]) -> Dict[str, Any]:
    """Write every stream; returns ``{"paths": [...], "report": ...}``
    with the report enriched with the event-log digest + provenance."""
    os.makedirs(rsl_path, exist_ok=True)
    paths = []

    # -- sim-events.jsonl (the byte-identity artifact) ----------------
    blob = _encode_events(sim)
    p = os.path.join(rsl_path, "sim-events.jsonl")
    with open(p, "wb") as f:
        f.write(blob)
    paths.append(p)

    # -- telemetry/rank<N>.jsonl --------------------------------------
    tdir = os.path.join(rsl_path, "telemetry")
    os.makedirs(tdir, exist_ok=True)
    for rank in sorted(sim.tel):
        p = os.path.join(tdir, f"rank{rank}.jsonl")
        with open(p, "w", encoding="utf-8") as f:
            for t, payload in sim.tel[rank]:
                rec = telemetry.stamp_record(payload, ts=BASE_TS + t,
                                             mono=t, rank=rank)
                f.write(telemetry.encode_line(rec) + "\n")
        paths.append(p)

    # -- trace-rank<N>.jsonl ------------------------------------------
    by_rank: Dict[int, list] = {}
    for rec in sim.traces:
        by_rank.setdefault(rec["rank"], []).append(rec)
    for rank in sorted(by_rank):
        p = os.path.join(rsl_path, f"trace-rank{rank}.jsonl")
        with open(p, "w", encoding="utf-8") as f:
            for rec in by_rank[rank]:
                f.write(tracing.encode_record(rec) + "\n")
        paths.append(p)

    # -- fleet-metrics.jsonl ------------------------------------------
    p = os.path.join(rsl_path, "fleet-metrics.jsonl")
    with open(p, "w", encoding="utf-8") as f:
        for sample in sim.samples:
            f.write(fleet.encode_sample(sample) + "\n")
    paths.append(p)

    # -- incident bundles ---------------------------------------------
    for seq, (name, bundle) in enumerate(sim.incidents, start=1):
        ip = fleet.write_incident_bundle(rsl_path, seq, name, bundle)
        if ip:
            paths.append(ip)

    # -- goodput ledgers ----------------------------------------------
    world = int(sim.sc["replicas"])
    for rank, r in sorted(sim.replicas.items()):
        rows = [goodput.build_epoch_row(
                    epoch=row["epoch"], wall_s=row["wall_s"],
                    mono=row["t_end"], ts=BASE_TS + row["t_end"],
                    residual_s=max(0.0, row["wall_s"] - row["compute_s"]),
                    categories={"compute": row["compute_s"]})
                for row in sim.gp_rows.get(rank, [])]
        doc = goodput.build_ledger_doc(
            rank=rank, world=world, started_ts=BASE_TS,
            wall_s=sim.duration, totals={"compute": r["busy_s"]},
            epochs=rows)
        gp = goodput.write_ledger_doc(rsl_path, doc)
        if gp:
            paths.append(gp)

    # -- sim-report.json ----------------------------------------------
    report = dict(report)
    report["event_log_sha256"] = hashlib.sha256(blob).hexdigest()
    report["latency_model_provenance"] = sim.model.get(
        "provenance", {"source": "unknown"})
    p = os.path.join(rsl_path, "sim-report.json")
    with open(p, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=float)
        f.write("\n")
    paths.append(p)
    return {"paths": paths, "report": report}
