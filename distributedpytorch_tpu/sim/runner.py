"""Entry points: ``run_scenario`` (library) and ``run_cli`` (main.py).

``python main.py sim --scenario chaos --rsl_path /tmp/simfleet`` replays
the scenario, writes the live-schema artifacts, prints the report, and
exits 0 — floor *enforcement* lives in scripts/sim_gate.py, not here,
so interactive replays of a failing fleet still produce artifacts to
read.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from . import latency as latmod
from . import scenario as scmod
from .engine import FleetSim


def run_scenario(name_or_path: str, *, seed: int = 0, replicas: int = 0,
                 duration_s: float = 0.0,
                 model_path: Optional[str] = None,
                 rsl_path: Optional[str] = None) -> Dict[str, Any]:
    """Load, replay, and (when ``rsl_path`` is given) persist one
    scenario.  Returns the report dict — with the event-log sha256
    stamped whether or not artifacts were written, so callers can pin
    byte-identity without touching a disk."""
    sc = scmod.load_scenario(name_or_path, replicas=replicas,
                             duration_s=duration_s)
    model = latmod.load_model(model_path) if model_path else None
    sim = FleetSim(sc, seed=seed, model=model)
    report = sim.run()
    if rsl_path:
        from . import artifacts
        report = artifacts.write_artifacts(rsl_path, sim,
                                           report)["report"]
    else:
        from .artifacts import event_log_sha256
        report["event_log_sha256"] = event_log_sha256(sim)
        report["latency_model_provenance"] = sim.model.get(
            "provenance", {"source": "unknown"})
    return report


def run_cli(cfg: Any) -> int:
    """The ``main.py sim`` action.  ValueErrors (unknown scenario, bad
    model file) propagate to main()'s uniform error path."""
    report = run_scenario(
        cfg.sim_scenario, seed=int(cfg.sim_seed),
        replicas=int(cfg.sim_replicas),
        duration_s=float(cfg.sim_duration),
        model_path=cfg.sim_model, rsl_path=cfg.rsl_path)
    import json
    print(json.dumps(report, indent=1, sort_keys=True, default=float))
    r = report["requests"]
    logging.info(
        f"sim: scenario={report['scenario']} seed={report['seed']} "
        f"replicas {report['replicas_start']}->{report['replicas_end']} "
        f"arrivals={r['arrivals']} answered={r['answered']} "
        f"shed={r['fd_shed']} dropped={r['dropped_forever']} "
        f"incidents={len(report['incidents'])} "
        f"log_sha256={report['event_log_sha256'][:12]}")
    return 0
