"""Latency / service-time model the simulator samples from.

A model is quantile sketches per named quantity — ``{"min", "p50",
"p90", "p95", "p99", "max"}`` in seconds — sampled by inverse-CDF
piecewise-linear interpolation against a SEEDED rng, so the draw
sequence is part of the deterministic replay.

Where the numbers come from: ``scripts/extract_latency_model.py`` fits
these sketches from real flightrec/goodput dumps (the committed
calibration fixture lives in tests/fixtures/sim/) and stamps the model
file with provenance, so simulated results name their calibration
source.  A model file may define any subset of quantities; the sampler
falls back per-quantity to the built-in defaults below.
"""

from __future__ import annotations

import json
import random
from typing import Any, Dict

#: CDF points the sketch pins, in order.
QUANTILES = (("min", 0.0), ("p50", 0.5), ("p90", 0.9), ("p95", 0.95),
             ("p99", 0.99), ("max", 1.0))

#: Built-in calibration: a large-model serving tier where one batch
#: dispatch is seconds, not milliseconds — the regime where queueing,
#: shed and autoscale dynamics actually bite.  Derived loosely from the
#: committed fixture; the gate re-extracts the real numbers from it.
DEFAULT_MODEL: Dict[str, Any] = {
    "version": 1,
    "provenance": {"source": "built-in defaults (sim/latency.py)"},
    "quantities": {
        # Fixed cost of one inference dispatch, whatever the bucket.
        "infer_base_s": {"min": 1.5, "p50": 2.4, "p90": 3.2,
                         "p95": 3.6, "p99": 4.4, "max": 6.0},
        # Marginal cost per padded row in the bucket.
        "infer_per_row_s": {"min": 0.08, "p50": 0.14, "p90": 0.20,
                            "p95": 0.22, "p99": 0.30, "max": 0.40},
        # Response write-back after the infer span.
        "respond_s": {"min": 0.004, "p50": 0.010, "p90": 0.025,
                      "p95": 0.035, "p99": 0.060, "max": 0.120},
        # One training step (timeline realism for simulated trainers).
        "step_s": {"min": 1.8, "p50": 2.6, "p90": 3.4, "p95": 3.8,
                   "p99": 4.6, "max": 6.5},
    },
}


def validate_model(doc: Any, where: str = "latency model") -> Dict[str, Any]:
    """Check a model document's shape; returns it.  Every rejection is
    one actionable line — a malformed calibration file must read like a
    fix, not a trace."""
    if not isinstance(doc, dict) or not isinstance(doc.get("quantities"),
                                                   dict):
        raise ValueError(f"{where}: must be an object with a "
                         f"'quantities' map")
    for name, q in doc["quantities"].items():
        if not isinstance(q, dict):
            raise ValueError(f"{where}: quantity {name!r} must be an "
                             f"object of quantile values")
        last = None
        for key, _ in QUANTILES:
            v = q.get(key)
            if not isinstance(v, (int, float)) or v < 0:
                raise ValueError(
                    f"{where}: quantity {name!r} needs numeric "
                    f"{key!r} >= 0 (got {v!r})")
            if last is not None and v < last:
                raise ValueError(
                    f"{where}: quantity {name!r} quantiles must be "
                    f"non-decreasing ({key} {v} < previous {last})")
            last = v
    return doc


def load_model(path: str) -> Dict[str, Any]:
    """Read + validate a model file (extract_latency_model.py output);
    errors carry the path."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        raise ValueError(f"cannot read latency model {path!r}: {e}")
    except ValueError as e:
        raise ValueError(f"latency model {path!r} is not valid JSON: "
                         f"{e}")
    return validate_model(doc, where=f"latency model {path!r}")


def sample(rng: random.Random, model: Dict[str, Any], name: str) -> float:
    """One draw of quantity ``name``: u ~ rng, inverse-CDF interpolated
    between the sketch's pinned quantiles.  Falls back to the built-in
    default when the model omits the quantity."""
    q = model.get("quantities", {}).get(name)
    if q is None:
        q = DEFAULT_MODEL["quantities"][name]
    u = rng.random()
    prev_key, prev_u = QUANTILES[0]
    for key, qu in QUANTILES[1:]:
        if u <= qu:
            lo, hi = float(q[prev_key]), float(q[key])
            frac = (u - prev_u) / (qu - prev_u)
            return lo + (hi - lo) * frac
        prev_key, prev_u = key, qu
    return float(q["max"])
