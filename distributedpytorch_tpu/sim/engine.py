"""The discrete-event core: a virtual-clock fleet driving real policies.

One ``FleetSim`` owns a seeded event heap keyed ``(t, seq)`` — virtual
seconds and a monotone push counter, so simultaneous events replay in
push order and the whole run is a pure function of (scenario, seed,
latency model).  The clock contract for every artifact this emits:
``mono = t`` and ``ts = BASE_TS + t`` — a fixed epoch, never the wall
clock, so ``ts - mono`` is one constant for every simulated rank and
``main.py timeline``'s per-rank wall alignment holds trivially.

What is real and what is simulated, precisely:

  real    plan_batch / parse_buckets, admission / routable_ids /
          pick_upstream / decide_health, decide_scale / pick_retire,
          evaluate_join_policy, decide_rollout / choose_canaries,
          slo.evaluate, faults.parse_plan + RetryPolicy._delay (the
          deterministic backoff schedule), the sample/incident/trace/
          telemetry/goodput schema factories.
  fake    only the physics: request arrival times (traffic.py), batch
          service times (latency.py), and the fault schedule's effect
          on replica state (scenario.timed_faults).

Replica state is the dict shape the pure deciders already consume
(``{"id", "alive", "ejected", "draining", "consecutive_failures",
"last_step_age_s"}``) plus simulator bookkeeping keys the policies
never read.
"""

from __future__ import annotations

import heapq
import random
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .. import elastic, faults, slo, telemetry
from ..serving import controller, frontdoor, planner
from ..serving import rollout as ro
from . import latency as latmod
from . import scenario as scmod
from . import traffic

#: Fixed virtual epoch: every emitted ``ts`` is BASE_TS + t.  Chosen
#: inside the plausible-unix-time range so renderers treat it like a
#: real run; NEVER derived from the wall clock (rule 21).
BASE_TS = 1_700_000_000.0

#: Ports are cosmetic in a simulated fleet sample, but the schema has
#: the field; replica rank r "listens" here.
_PORT_BASE = 9100

#: The live front door exports telemetry as FRONTDOOR_RANK (90).
#: Simulated replica ranks are dense from 0 and routinely pass 90 at
#: N=100+, so the simulated front door parks at a rank no fleet will
#: reach — same role, collision-free.
FD_RANK = 9000 + frontdoor.FRONTDOOR_RANK


class FleetSim:
    """One scenario replay.  ``run()`` returns the report dict; the
    artifact streams (event log, telemetry, traces, samples, incidents,
    goodput rows) accumulate on the instance for artifacts.py."""

    def __init__(self, sc: Dict[str, Any], seed: int,
                 model: Optional[Dict[str, Any]] = None):
        self.sc = sc
        self.seed = int(seed)
        self.model = model or latmod.DEFAULT_MODEL
        self.t = 0.0
        self._heap: List[Tuple[float, int, str, Any]] = []
        self._push_seq = 0
        self.duration = float(sc["duration_s"])
        self.interval = float(sc["interval_s"])
        self.buckets = planner.parse_buckets(sc["buckets"])
        self.route_cfg = dict(sc["route"])
        self.scale_cfg = dict(sc["scale"])
        self.rollout_cfg = dict(sc["rollout"] or {})
        self.rng_traffic = random.Random(f"{self.seed}:traffic")
        self.rng_lat = random.Random(f"{self.seed}:latency")
        self.retry = faults.RetryPolicy(
            max_attempts=int(sc["max_attempts"]), base_delay_s=0.5,
            max_delay_s=8.0, timeout_s=1e9, seed=self.seed)

        # -- fleet state ----------------------------------------------
        self.replicas: Dict[int, Dict[str, Any]] = {}
        self._next_rank = 0
        self._routable: Optional[List[int]] = None  # cache
        self.pending: Dict[int, int] = {}   # rank -> queued + in-flight
        self.pending_total = 0
        self.rr = 0                         # pick_upstream tie-breaker
        self.generation = 0
        self.pending_joins: List[str] = []  # jids awaiting a tick
        self.joiners: Dict[str, Dict[str, Any]] = {}
        self._join_seq = 0
        self.scale_state: Dict[str, Any] = {"last_action_t": None}
        self.canary_ids: List[int] = []
        self.ro_state: Optional[Dict[str, Any]] = None
        self.ro_group: Dict[str, Any] = {}
        self.rollout_outcome: Optional[str] = None

        # -- counters / series ----------------------------------------
        self.c: Dict[str, int] = {
            "arrivals": 0, "admitted": 0, "fd_shed": 0, "answered": 0,
            "failed": 0, "retries": 0, "dropped_forever": 0,
            "requeued": 0, "lost_inflight": 0}
        self.lat_hist = telemetry.Histogram("dpt_serve_request_latency_ms")
        self.first_shed_t: Optional[float] = None
        self.last_shed_t: Optional[float] = None

        # -- artifact streams -----------------------------------------
        self.events: List[Dict[str, Any]] = []   # sim-events.jsonl
        self.tel: Dict[int, List[Tuple[float, Dict[str, Any]]]] = {}
        self.traces: List[Dict[str, Any]] = []
        self._trace_seq: Dict[int, int] = {}     # per-rank trace seq
        self.bad_trace: List[Tuple[float, str]] = []  # (ts, id)
        window = max(float(w["seconds"]) for s in sc["slos"]
                     for w in s["windows"]) if sc["slos"] else 30.0
        self.samples: deque = deque(
            maxlen=max(8, int(window * 3.0 / self.interval) + 2))
        self.cycle = 0
        self._slo_firing: set = set()
        self.incidents: List[Tuple[str, Dict[str, Any]]] = []
        self.scale_actions: List[Tuple[float, str]] = []
        self.health_actions: Dict[str, int] = {"eject": 0, "readmit": 0}
        self.join_admits: Dict[str, int] = {}
        self.join_claims: Dict[str, int] = {}
        self.gp_rows: Dict[int, List[Dict[str, Any]]] = {}
        self._gp_last: Dict[int, float] = {}
        self._gp_epoch = 0

    # -- plumbing ------------------------------------------------------

    def _push(self, t: float, kind: str, payload: Any = None) -> None:
        self._push_seq += 1
        heapq.heappush(self._heap, (t, self._push_seq, kind, payload))

    def _log(self, ev: str, **fields: Any) -> None:
        self.events.append({"t": round(self.t, 6), "ev": ev, **fields})

    def _tel_event(self, rank: int, name: str, **attrs: Any) -> None:
        payload: Dict[str, Any] = {"kind": "event", "name": name}
        if attrs:
            payload["attrs"] = attrs
        self.tel.setdefault(rank, []).append((self.t, payload))

    def ts(self, t: Optional[float] = None) -> float:
        return BASE_TS + (self.t if t is None else t)

    # -- replica lifecycle ---------------------------------------------

    def _new_replica(self, origin: str) -> Dict[str, Any]:
        rank = self._next_rank
        self._next_rank += 1
        r = {"id": rank, "alive": True, "ejected": False,
             "draining": False, "consecutive_failures": 0,
             "last_step_age_s": 0.0,
             # simulator bookkeeping (never read by the deciders):
             "last_step_t": self.t, "queue": deque(), "busy": False,
             "inflight": 0, "requests_total": 0, "errors_total": 0,
             "busy_s": 0.0, "ioerror_pending": 0, "stall_pending_s": 0.0,
             "version": "stable", "origin": origin, "_batch": None}
        self.replicas[rank] = r
        self.pending[rank] = 0
        self._routable = None
        self._gp_last[rank] = 0.0
        self._tel_event(rank, "sim/replica_start", origin=origin)
        return r

    def _snapshot_ids(self) -> List[int]:
        if self._routable is None:
            self._routable = frontdoor.routable_ids(
                list(self.replicas.values()))
        return self._routable

    def _alive_ranks(self) -> List[int]:
        return sorted(r["id"] for r in self.replicas.values()
                      if r["alive"])

    # -- request flow --------------------------------------------------

    def _arrive(self, req: Dict[str, Any]) -> None:
        self.c["arrivals"] += 1
        req["attempts"] += 1
        verdict = frontdoor.admission(self.route_cfg, self.pending_total)
        if not verdict["admit"]:
            self._shed(req, verdict["retry_after_s"])
            return
        self.c["admitted"] += 1
        ids = self._snapshot_ids()
        self.rr += 1
        rank = frontdoor.pick_upstream(ids, self.pending, self.rr)
        if rank is None:
            # Nothing routable (whole fleet dead/ejected): same client
            # experience as a shed.
            self._shed(req, float(self.route_cfg.get(
                "retry_after_s",
                frontdoor.ROUTE_DEFAULTS["retry_after_s"])))
            return
        self._enqueue(rank, req)

    def _shed(self, req: Dict[str, Any], retry_after_s: float) -> None:
        self.c["fd_shed"] += 1
        if self.first_shed_t is None:
            self.first_shed_t = self.t
        self.last_shed_t = self.t
        self._trace(FD_RANK, req, status=503,
                    outcome="shed", spans={"shed": 0.0005})
        self._log("shed", rid=req["rid"], attempts=req["attempts"])
        self._retry_later(req, extra_s=float(retry_after_s))

    def _retry_later(self, req: Dict[str, Any], extra_s: float = 0.0
                     ) -> None:
        if req["attempts"] >= int(self.sc["max_attempts"]):
            self.c["dropped_forever"] += 1
            self._trace(FD_RANK, req, status=504,
                        outcome="timeout", spans={"timeout": 0.0005})
            self._log("drop", rid=req["rid"], attempts=req["attempts"])
            return
        self.c["retries"] += 1
        delay = extra_s + self.retry._delay(f"sim.retry:{req['rid']}",
                                            req["attempts"])
        self._push(self.t + delay, "arrival", req)

    def _enqueue(self, rank: int, req: Dict[str, Any]) -> None:
        r = self.replicas[rank]
        r["queue"].append((req, self.t))
        self.pending[rank] += 1
        self.pending_total += 1
        if not r["busy"]:
            self._push(self.t + float(self.sc["flush_s"]), "dispatch",
                       rank)

    def _dispatch(self, rank: int) -> None:
        r = self.replicas.get(rank)
        if r is None or not r["alive"] or r["busy"] or not r["queue"]:
            return
        take, bucket, padding = planner.plan_batch(len(r["queue"]),
                                                   self.buckets)
        reqs = [r["queue"].popleft() for _ in range(take)]
        service = (latmod.sample(self.rng_lat, self.model, "infer_base_s")
                   + bucket * latmod.sample(self.rng_lat, self.model,
                                            "infer_per_row_s"))
        if r["stall_pending_s"] > 0.0:
            service += r["stall_pending_s"]
            self._log("stall_applied", rank=rank,
                      stall_s=round(r["stall_pending_s"], 6))
            r["stall_pending_s"] = 0.0
        r["busy"] = True
        r["inflight"] = take
        batch = {"rank": rank, "t_start": self.t, "service": service,
                 "reqs": reqs, "bucket": bucket, "padding": padding}
        r["_batch"] = batch  # so _kill can re-route a dying replica's work
        self._push(self.t + service, "done", batch)

    def _done(self, batch: Dict[str, Any]) -> None:
        rank = batch["rank"]
        r = self.replicas.get(rank)
        if r is None or not r["alive"] or r.get("_batch") is not batch:
            return  # the replica died mid-service; _kill re-routed
        r["_batch"] = None
        r["busy"] = False
        r["inflight"] = 0
        r["last_step_t"] = self.t
        r["busy_s"] += batch["service"]
        respond = latmod.sample(self.rng_lat, self.model, "respond_s")
        for req, t_enq in batch["reqs"]:
            self.pending[rank] -= 1
            self.pending_total -= 1
            r["requests_total"] += 1
            if r["ioerror_pending"] > 0:
                r["ioerror_pending"] -= 1
                r["errors_total"] += 1
                self.c["failed"] += 1
                self._trace(rank, req, status=500, outcome="failed",
                            spans={"queue_wait": batch["t_start"] - t_enq,
                                   "infer": batch["service"]})
                self._log("fail", rid=req["rid"], rank=rank)
                self._retry_later(req)
                continue
            queue_wait = batch["t_start"] - t_enq
            spans = {"queue_wait": queue_wait, "batch_form": 0.0005,
                     "infer": batch["service"], "respond": respond}
            latency_ms = (queue_wait + 0.0005 + batch["service"]) * 1000.0
            self.c["answered"] += 1
            self.lat_hist.observe(latency_ms)
            if self.ro_state is not None:
                g = self.ro_group[r["version"]]
                g["requests"] += 1
                g["hist"].observe(latency_ms)
            if req["rid"] % int(self.sc["trace_sample"]) == 0:
                self._trace(rank, req, status=200, outcome="answered",
                            spans=spans, latency_ms=latency_ms,
                            bucket=batch["bucket"])
                self._log("answered", rid=req["rid"], rank=rank,
                          latency_ms=round(latency_ms, 3))
        if r["draining"] and not r["queue"]:
            self._retire(r)
        elif r["queue"]:
            self._dispatch(rank)

    def _trace(self, rank: int, req: Dict[str, Any], *, status: int,
               outcome: str, spans: Dict[str, float],
               latency_ms: Optional[float] = None,
               bucket: Optional[int] = None) -> None:
        from .. import tracing
        seq = self._trace_seq.get(rank, 0)
        self._trace_seq[rank] = seq + 1
        rec = tracing.build_request_record(
            rank=rank, seq=seq, ts_admit=self.ts(req["t0"]),
            mono_admit=req["t0"], status=status, outcome=outcome,
            spans=spans, ts=self.ts(), mono=self.t,
            bucket=bucket, latency_ms=latency_ms,
            attrs={"sim": True, "attempts": req["attempts"]})
        self.traces.append(rec)
        if outcome in ("failed", "shed", "timeout"):
            self.bad_trace.append((rec["ts"], rec["id"]))

    # -- faults --------------------------------------------------------

    def _fault(self, f: Dict[str, Any]) -> None:
        kind, count = f["kind"], int(f["count"])
        self._tel_event(FD_RANK, "fault_injected",
                        site="sim.step", kind=kind, count=count)
        if kind in ("rank_loss", "preempt"):
            victims = [self.replicas[i] for i in
                       sorted(self._alive_ranks(), reverse=True)[:count]]
            for r in victims:
                if kind == "rank_loss":
                    self._kill(r, reason="rank_loss")
                else:
                    r["draining"] = True
                    r["_preempted"] = True
                    self._routable = None
                    if not r["busy"]:
                        self._retire(r, rejoin=True)
            self._log("fault", kind=kind, count=count,
                      victims=[r["id"] for r in victims])
        elif kind == "stall":
            targets = [self.replicas[i] for i in
                       sorted(self._alive_ranks(), reverse=True)[:count]]
            for r in targets:
                r["stall_pending_s"] += float(f["stall_s"])
            self._log("fault", kind=kind, count=count,
                      stall_s=f["stall_s"],
                      victims=[r["id"] for r in targets])
        elif kind == "ioerror":
            # Spread the failing requests across the fleet so the burst
            # is an error-RATE spike (the availability SLO's input),
            # not a single slow replica's backlog.
            alive = self._alive_ranks()
            if alive:
                per, extra = divmod(count, len(alive))
                for i, rank in enumerate(alive):
                    self.replicas[rank]["ioerror_pending"] += (
                        per + (1 if i < extra else 0))
                self._log("fault", kind=kind, count=count,
                          victims=alive)
        elif kind == "rank_join":
            for _ in range(count):
                self._claim_join(origin="plan")
            self._log("fault", kind=kind, count=count)

    def _kill(self, r: Dict[str, Any], reason: str) -> None:
        """Abrupt loss: in-flight work is gone, queued work re-routes,
        the slot rejoins through the real admission policy later."""
        rank = r["id"]
        r["alive"] = False
        r["busy"] = False
        self._routable = None
        lost = r["inflight"]
        r["inflight"] = 0
        self.pending_total -= self.pending[rank]
        self.pending[rank] = 0
        self.c["lost_inflight"] += lost
        queued = list(r["queue"])
        r["queue"].clear()
        self._log("rank_loss", rank=rank, reason=reason,
                  lost_inflight=lost, requeued=len(queued))
        # Queued requests re-route immediately (the front door re-sends
        # on connection failure); in-flight ones are client retries
        # with backoff — either way NOTHING is silently forgotten,
        # which is what lets the gate assert dropped_forever exactly.
        for req, _ in queued:
            self.c["requeued"] += 1
            self._push(self.t, "arrival", req)
        batch = r.get("_batch")
        r["_batch"] = None
        if batch is not None:
            for req, _ in batch["reqs"]:
                self._retry_later(req)
        self._push(self.t + float(self.sc["rejoin_delay_s"]),
                   "claim_join", {"origin": f"rejoin:{rank}"})

    def _retire(self, r: Dict[str, Any], rejoin: bool = False) -> None:
        rank = r["id"]
        r["alive"] = False
        r["draining"] = False
        self._routable = None
        self._log("retired", rank=rank, rejoin=rejoin)
        if rejoin or r.pop("_preempted", False):
            self._push(self.t + float(self.sc["rejoin_delay_s"]),
                       "claim_join", {"origin": f"rejoin:{rank}"})

    # -- elastic joins -------------------------------------------------

    def _claim_join(self, origin: str) -> str:
        self._join_seq += 1
        jid = f"j{self._join_seq:04d}"
        self.joiners[jid] = {"origin": origin}
        self.pending_joins.append(jid)
        self.join_claims[jid] = self.join_claims.get(jid, 0) + 1
        self._log("join_claim", jid=jid, origin=origin)
        return jid

    def _process_joins(self) -> None:
        if not self.pending_joins:
            return
        el = self.sc["elastic"]
        live = len(self._alive_ranks())
        admit, declined = elastic.evaluate_join_policy(
            live, list(self.pending_joins), str(el["target"]),
            int(el["min_world"]))
        self.pending_joins = []
        if admit:
            self.generation += 1
        for jid in admit:
            origin = self.joiners[jid]["origin"]
            key = origin if origin.startswith("rejoin:") else jid
            self.join_admits[key] = self.join_admits.get(key, 0) + 1
            r = self._new_replica(origin=origin)
            self._tel_event(r["id"], "elastic/join",
                            generation=self.generation,
                            new_world=len(self._alive_ranks()),
                            new_rank=r["id"], jid=jid)
            self._log("join_admit", jid=jid, rank=r["id"],
                      generation=self.generation, origin=origin)
        for jid, reason in declined:
            self._log("join_decline", jid=jid, reason=reason)
            self._tel_event(FD_RANK,
                            "elastic/join_declined", jid=jid)
            # A declined joiner claims again — the thrash the floors
            # watch for would show up here as an admit/decline loop.
            info = self.joiners[jid]
            self._push(self.t + float(self.sc["join_retry_s"]),
                       "claim_join", {"origin": info["origin"]})

    # -- control tick --------------------------------------------------

    def _tick(self) -> None:
        self.cycle += 1
        # 1. health bookkeeping: ages + probe failure streaks.
        for r in self.replicas.values():
            r["last_step_age_s"] = self.t - r["last_step_t"]
            if r["alive"]:
                r["consecutive_failures"] = 0
            else:
                r["consecutive_failures"] += 1
        # 2. join admissions (the coordinator's health-boundary scan).
        self._process_joins()
        # 3. ejection / readmission via the real decider.
        for action in frontdoor.decide_health(
                self.route_cfg, list(self.replicas.values())):
            r = self.replicas[action["id"]]
            if action["action"] == "eject":
                r["ejected"] = True
                self.health_actions["eject"] += 1
                self._requeue_queued(r)
                self._tel_event(FD_RANK,
                                "frontdoor/eject", id=r["id"],
                                reason=action["reason"])
            else:
                r["ejected"] = False
                self.health_actions["readmit"] += 1
                self._tel_event(FD_RANK,
                                "frontdoor/readmit", id=r["id"])
            self._routable = None
            self._log(action["action"], rank=r["id"],
                      reason=action["reason"])
        # 4. fleet sample + SLO verdicts + incident edge detection.
        sample = self._sample()
        self.samples.append(sample)
        verdicts = (slo.evaluate(self.sc["slos"], list(self.samples))
                    if self.sc["slos"] else [])
        sample["verdicts"] = verdicts
        self._alert(verdicts, sample)
        # 5. autoscale ladder.
        self._autoscale(sample)
        # 6. canary rollout verdict.
        self._rollout_tick()
        # 7. goodput epoch boundary.
        gp_every = max(1, int(float(self.sc["goodput_window_s"])
                              / self.interval))
        if self.cycle % gp_every == 0:
            self._gp_boundary()
        self._log("tick", cycle=self.cycle,
                  world=len(self._alive_ranks()),
                  queued=sum(len(r["queue"])
                             for r in self.replicas.values()),
                  pending=self.pending_total,
                  shed=self.c["fd_shed"], answered=self.c["answered"])

    def _requeue_queued(self, r: Dict[str, Any]) -> None:
        queued = list(r["queue"])
        r["queue"].clear()
        n = len(queued)
        self.pending[r["id"]] -= n
        self.pending_total -= n
        for req, _ in queued:
            self.c["requeued"] += 1
            self._push(self.t, "arrival", req)

    def _sample(self) -> Dict[str, Any]:
        from .. import fleet
        alive = self._alive_ranks()
        merged = {
            "counters": {
                "dpt_serve_requests_total": float(sum(
                    r["requests_total"]
                    for r in self.replicas.values())),
                "dpt_serve_errors_total": float(sum(
                    r["errors_total"] for r in self.replicas.values())),
                "dpt_serve_shed_total": 0.0,
                "dpt_frontdoor_requests_total": float(
                    self.c["arrivals"]),
                controller.FD_SHED_COUNTER: float(self.c["fd_shed"]),
            },
            "gauges": {controller.QUEUE_GAUGE: float(sum(
                len(r["queue"]) for r in self.replicas.values()
                if r["alive"]))},
            "histograms": {self.lat_hist.name: self.lat_hist},
        }
        targets = {str(rank): {
            "port": _PORT_BASE + rank,
            "counters": {
                "dpt_serve_requests_total": float(
                    self.replicas[rank]["requests_total"]),
                "dpt_serve_errors_total": float(
                    self.replicas[rank]["errors_total"]),
            },
            "health": {"status": "ok",
                       "last_step_age_s": round(
                           self.replicas[rank]["last_step_age_s"], 3)},
        } for rank in alive}
        return fleet.build_fleet_sample(
            ts=self.ts(), mono=self.t, cycle=self.cycle, alive=alive,
            merged=merged, targets=targets)

    def _alert(self, verdicts: List[Dict[str, Any]],
               sample: Dict[str, Any]) -> None:
        from .. import fleet
        for v in verdicts:
            name = v["name"]
            if not v["firing"]:
                self._slo_firing.discard(name)
                continue
            if name in self._slo_firing:
                continue  # one bundle per episode, same as fleet.py
            self._slo_firing.add(name)
            spec = next(s for s in self.sc["slos"] if s["name"] == name)
            bundle = fleet.build_incident(
                name=name, spec=spec, verdict=v, cycle=self.cycle,
                ts=sample["ts"], alive=sample["alive"],
                suspect_ranks=self._suspects(spec),
                offending_requests=self._offenders(v),
                healthz={rank: doc.get("health")
                         for rank, doc in sample["targets"].items()})
            self.incidents.append((name, bundle))
            self._tel_event(FD_RANK, "fleet/incident",
                            slo=name, cycle=self.cycle)
            self._log("incident", slo=name, cycle=self.cycle,
                      suspects=bundle["suspect_ranks"])

    def _suspects(self, spec: Dict[str, Any]) -> List[int]:
        """fleet._suspects, over the simulator's sample window: per-
        target bad-counter movement inside the widest window."""
        samples = list(self.samples)
        if spec.get("kind") != "ratio" or len(samples) < 2:
            return sorted(int(r) for s in samples
                          for r in s.get("targets", {}))
        seconds = max(float(w["seconds"]) for w in spec["windows"])
        base, latest = slo._window(samples, seconds)
        key = spec["bad"]
        out = []
        for rank, doc in latest.get("targets", {}).items():
            end = float(doc.get("counters", {}).get(key, 0.0))
            start = float(base.get("targets", {}).get(rank, {})
                          .get("counters", {}).get(key, 0.0))
            if end - start > 0:
                out.append(int(rank))
        return sorted(out)

    def _offenders(self, verdict: Dict[str, Any]) -> List[str]:
        samples = list(self.samples)
        if len(samples) < 2:
            return []
        seconds = max(float(w["seconds"]) for w in verdict["windows"])
        base, latest = slo._window(samples, seconds)
        lo = float(base["ts"]) - self.interval
        hi = float(latest["ts"]) + self.interval
        return [rid for ts, rid in self.bad_trace if lo <= ts <= hi]

    def _autoscale(self, sample: Dict[str, Any]) -> None:
        decision = controller.decide_scale(self.scale_cfg,
                                           self.scale_state,
                                           list(self.samples))
        if decision["action"] == "none":
            return
        self.scale_state["last_action_t"] = float(sample["t"])
        self.scale_actions.append((self.t, decision["action"]))
        self._tel_event(FD_RANK,
                        f"controller/scale_{decision['action']}",
                        reason=decision["reason"],
                        world=decision["world"],
                        target=decision["target"])
        self._log("scale", action=decision["action"],
                  world=decision["world"], target=decision["target"],
                  reason=decision["reason"])
        if decision["action"] == "up":
            self._push(self.t + float(self.sc["provision_delay_s"]),
                       "claim_join", {"origin": "scale"})
        else:
            victim = controller.pick_retire(self._snapshot_ids(),
                                            protected=self.canary_ids)
            if victim is not None:
                r = self.replicas[victim]
                r["draining"] = True
                self._routable = None
                self._log("drain", rank=victim)
                if not r["busy"] and not r["queue"]:
                    self._retire(r)

    # -- rollout -------------------------------------------------------

    def _start_rollout(self) -> None:
        ids = self._snapshot_ids()
        self.canary_ids = ro.choose_canaries(
            ids, float(self.rollout_cfg.get(
                "fraction", ro.ROLLOUT_DEFAULTS["fraction"])))
        if not self.canary_ids:
            self._log("rollout_skip", reason="fewer than 2 routable")
            return
        for rank in self.canary_ids:
            self.replicas[rank]["version"] = "canary"
        self.ro_state = {"since_t": self.t}
        self.ro_group = {
            "canary": {"requests": 0, "errors": 0,
                       "hist": telemetry.Histogram("sim/canary_ms")},
            "stable": {"requests": 0, "errors": 0,
                       "hist": telemetry.Histogram("sim/stable_ms")}}
        self._tel_event(FD_RANK, "rollout/start",
                        canaries=list(self.canary_ids))
        self._log("rollout_start", canaries=list(self.canary_ids))

    def _rollout_tick(self) -> None:
        if self.ro_state is None:
            return

        def group(name: str) -> Dict[str, Any]:
            g = self.ro_group[name]
            s = g["hist"].summary() if g["hist"].count else {}
            return {"requests": g["requests"], "errors": g["errors"],
                    "p95_ms": s.get("p95")}

        obs = {"t": self.t,
               "canary_alive": any(
                   r["alive"] and not r["ejected"]
                   for r in self.replicas.values()
                   if r["id"] in self.canary_ids),
               "canary": group("canary"), "stable": group("stable")}
        verdict = ro.decide_rollout(self.rollout_cfg, self.ro_state, obs)
        if verdict["action"] == "continue":
            return
        self.rollout_outcome = verdict["action"]
        for r in self.replicas.values():
            r["version"] = "stable"
        self._tel_event(FD_RANK,
                        f"rollout/{verdict['action']}",
                        reason=verdict["reason"])
        self._log(f"rollout_{verdict['action']}",
                  reason=verdict["reason"])
        self.canary_ids = []
        self.ro_state = None

    # -- goodput -------------------------------------------------------

    def _gp_boundary(self) -> None:
        self._gp_epoch += 1
        window = float(self.sc["goodput_window_s"])
        for rank, r in sorted(self.replicas.items()):
            delta = r["busy_s"] - self._gp_last.get(rank, 0.0)
            self._gp_last[rank] = r["busy_s"]
            self.gp_rows.setdefault(rank, []).append(
                {"epoch": self._gp_epoch, "t_end": self.t,
                 "wall_s": window, "compute_s": delta})

    # -- main loop -----------------------------------------------------

    def run(self) -> Dict[str, Any]:
        sc = self.sc
        for _ in range(int(sc["replicas"])):
            self._new_replica(origin="seed")
        self._tel_event(FD_RANK, "sim/frontdoor_start",
                        scenario=sc["name"], seed=self.seed,
                        replicas=int(sc["replicas"]))
        rid = 0
        for at in traffic.generate(self.rng_traffic, sc["traffic"],
                                   self.duration):
            rid += 1
            self._push(at, "arrival",
                       {"rid": rid, "t0": at, "attempts": 0})
        for f in scmod.timed_faults(sc, self.seed):
            self._push(f["t"], "fault", f)
        n_ticks = int(self.duration / self.interval)
        for k in range(1, n_ticks + 1):
            self._push(k * self.interval, "tick", None)
        if self.rollout_cfg:
            self._push(float(self.rollout_cfg["at_s"]), "ckpt", None)

        handlers = {"arrival": self._arrive, "dispatch": self._dispatch,
                    "done": self._done, "fault": self._fault,
                    "claim_join":
                        lambda p: self._claim_join(p["origin"]),
                    "tick": lambda p: self._tick(),
                    "ckpt": lambda p: self._start_rollout()}
        while self._heap and self._heap[0][0] <= self.duration:
            self.t, _, kind, payload = heapq.heappop(self._heap)
            handlers[kind](payload)
        self.t = self.duration
        return self.report()

    # -- report --------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        directions = [a for _, a in self.scale_actions]
        changes = sum(1 for a, b in zip(directions, directions[1:])
                      if a != b)
        rejoin_admits = {k: v for k, v in self.join_admits.items()
                        if k.startswith("rejoin:")}
        shed_window = (0.0 if self.first_shed_t is None
                       else self.last_shed_t - self.first_shed_t)
        return {
            "kind": "sim_report", "scenario": self.sc["name"],
            "seed": self.seed, "replicas_start": int(self.sc["replicas"]),
            "replicas_end": len(self._alive_ranks()),
            "duration_s": self.duration,
            "requests": dict(self.c),
            "in_flight_at_end": self.pending_total,
            "scale": {"actions": len(self.scale_actions),
                      "ups": directions.count("up"),
                      "downs": directions.count("down"),
                      "direction_changes": changes},
            "health": dict(self.health_actions),
            "elastic": {
                "claims": len(self.join_claims),
                "admits": sum(self.join_admits.values()),
                "rejoin_admits": sum(rejoin_admits.values()),
                "max_rejoin_admits_per_replica": max(
                    rejoin_admits.values(), default=0),
                "generation": self.generation},
            "rollout_outcome": self.rollout_outcome,
            "incidents": [name for name, _ in self.incidents],
            "shed_window_s": round(shed_window, 6),
            "trace_records": len(self.traces),
            "event_log_lines": len(self.events),
        }
