"""Deterministic fleet simulator (ISSUE 20 tentpole).

A seeded discrete-event simulator that proves the control planes at
N=100+ replicas — the scale where the interesting policy failures live
(autoscale oscillation under diurnal traffic, shed cascades through the
front door's pending budget, rejoin thrash after a preemption wave) and
which no gloo subprocess harness on this container can afford.

The design rule, and the reason every decider in this repo is a pure
clock-free function of (config, sample window): the simulator composes
the REAL policy code, never reimplementations.  What runs in here is

  * ``serving/planner.py``      batch planning per simulated dispatch,
  * ``serving/frontdoor.py``    admission / routing / health ejection,
  * ``serving/controller.py``   the autoscale ladder over fleet samples,
  * ``serving/rollout.py``      canary promote/rollback verdicts,
  * ``elastic.py``              join admission (evaluate_join_policy),
  * ``slo.py``                  burn-rate evaluation over the samples,
  * ``faults.py``               the fault-plan DSL and RetryPolicy's
                                deterministic backoff schedule,

driven by a virtual clock: time exists only as the event heap's ``t``.
No module in sim/ reads a wall clock or an unseeded RNG (graftlint rule
21 ``nondeterminism-in-policy`` pins this), so the same seed and
scenario produce a byte-identical event log — replayable, diffable,
bisectable.

Artifacts come out in the repo's live JSONL schemas (via the shared
schema factories in telemetry/tracing/goodput/fleet), so ``main.py
goodput``, ``timeline``, ``fleet`` and ``incidents`` render a simulated
fleet unchanged.  Entry points: ``python main.py sim --scenario ...``
and ``scripts/sim_gate.py`` (the robustness-floor gate).
"""

from .engine import BASE_TS, FleetSim  # noqa: F401
from .runner import run_cli, run_scenario  # noqa: F401
