"""L1: flight recorder + anomaly-triggered profiling (ISSUE 7 tentpole).

The telemetry JSONL (telemetry.py) answers "how did the run go" at
epoch/summary granularity; it cannot answer "what were the last 2k steps
doing when rank 3 died".  This module is that black box: a fixed-memory
per-rank ring buffer of per-step records — dispatch wall time, data-wait,
queue depth, retry/fault events — each stamped with the same paired
``ts`` (wall) + ``mono`` (monotonic) contract as telemetry, dumped to
``RSL_PATH/flightrec-rank{N}.json`` when something goes wrong:

  * crash           — the driver's ``finally`` calls ``close()`` while an
                      exception is propagating (reason="crash")
  * preempt         — ``utils.GracefulShutdown`` dumps from the signal
                      handler (reason="preempt_signal"), so the record
                      survives even if the grace window is cut short
  * peer failure    — ``cli._health_boundary`` dumps when the health
                      allgather reports another rank failed: the healthy
                      ranks' view of the minutes before is exactly what
                      post-mortems need (reason="peer_failure")
  * on demand       — ``dump(reason)`` / end-of-run ``close()``

The recorder is cheap enough to leave on (a dict append into a bounded
deque per step; the overhead budget is gated by scripts/anomaly_gate.py)
and, like telemetry, is a process-local singleton: ``get()`` returns a
disabled no-op until ``configure()`` installs the real one.

Anomaly-triggered profiling: ``AnomalyDetector`` watches per-step wall
time with a rolling median/MAD window plus two structural triggers
(data starvation, retry bursts) and — a bounded number of times per run —
fires a *programmatic* ``jax.profiler.start_trace`` capture of the next K
steps into ``RSL_PATH/anomaly_traces/capture-<n>``, emitting an
``anomaly`` telemetry event with the trigger's evidence.  Profiling
happens exactly when a step goes anomalous, not when a human remembers to
pass ``--profile``.  The trigger path is deterministically testable via
the ``stall`` fault kind (faults.py): a canned plan such as
``data.host_batch:stall:8`` makes exactly one step slow, which must
produce exactly one capture (scripts/anomaly_gate.py proves it).

Trigger semantics (all windows/thresholds are Config knobs):

  step-time   window of the last W step times is full AND
              step_s > rel_factor * median AND
              step_s - median > max(mad_k * MAD, min_excess_s).
              The MAD term adapts to the run's own jitter; the absolute
              ``min_excess_s`` floor keeps micro-jitter (CPU scheduler,
              GC) from triggering on millisecond steps.
  starvation  the step's data-wait alone exceeds the same excess bound —
              the queue went empty and the producer is the straggler.
  retry-burst ≥ ``retry_burst`` retry/fault events landed since the last
              observed step — I/O is failing faster than it succeeds.

Capture lifecycle: start_trace at the triggering step, stop_trace K steps
later (or at ``close()``, in a ``finally`` — the graftlint rule
``profiler-trace-leak`` checks this shape); at most ``max_captures`` per
run so a pathological run cannot fill the disk with traces.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import statistics
import threading
import time
from typing import Any, Deque, Dict, List, Optional

from . import goodput, telemetry


class AnomalyDetector:
    """Rolling median/MAD step-time monitor that owns the bounded
    programmatic profiler captures.  One instance per run, driven from
    the streaming train loop via ``observe_step``; NOT thread-safe by
    design (only the driver thread observes steps)."""

    def __init__(self, *, trace_dir: str, window: int = 32,
                 mad_k: float = 8.0, rel_factor: float = 3.0,
                 min_excess_s: float = 0.05, retry_burst: int = 3,
                 capture_steps: int = 4, max_captures: int = 2):
        self.trace_dir = trace_dir
        self.window = max(int(window), 4)
        self.mad_k = float(mad_k)
        self.rel_factor = float(rel_factor)
        self.min_excess_s = float(min_excess_s)
        self.retry_burst = max(int(retry_burst), 1)
        self.capture_steps = max(int(capture_steps), 1)
        self.max_captures = int(max_captures)
        self._times: Deque[float] = collections.deque(maxlen=self.window)
        self._retries_since_step = 0
        self.anomalies = 0
        self.captures_started = 0
        self._capture_left = 0  # >0 while a trace capture is running

    # -- trigger evaluation -------------------------------------------

    def note_retry(self) -> None:
        """Called (via the recorder) for every retry/fault event; feeds
        the retry-burst trigger."""
        self._retries_since_step += 1

    def _trigger(self, step_s: float, wait_s: Optional[float]
                 ) -> Optional[Dict[str, Any]]:
        retries = self._retries_since_step
        self._retries_since_step = 0
        if retries >= self.retry_burst:
            return {"trigger": "retry_burst", "retries": retries}
        if len(self._times) < self.window:
            # Window not yet full: the baseline isn't trustworthy (it
            # would include compile steps) — observe, don't judge.
            self._times.append(step_s)
            return None
        med = statistics.median(self._times)
        mad = statistics.median(abs(t - med) for t in self._times)
        excess = step_s - med
        bound = max(self.mad_k * mad, self.min_excess_s)
        evidence = {"median_s": med, "mad_s": mad, "step_s": step_s}
        self._times.append(step_s)
        if step_s > self.rel_factor * med and excess > bound:
            return {"trigger": "step_time", **evidence}
        if wait_s is not None and wait_s > bound \
                and wait_s > self.rel_factor * med:
            return {"trigger": "starvation", "wait_s": wait_s, **evidence}
        return None

    # -- capture state machine ----------------------------------------

    def observe_step(self, *, epoch: int, step: int, step_s: float,
                     wait_s: Optional[float] = None) -> Optional[str]:
        """Feed one completed step; returns the trigger name when this
        step was judged anomalous (the caller records/emits the event).
        Manages the start/stop of the bounded profiler captures."""
        if self._capture_left > 0:
            self._capture_left -= 1
            if self._capture_left == 0:
                self._stop_capture()
            # While capturing, keep feeding the window but don't re-judge:
            # the anomalous region itself must not retrain the baseline
            # into silence nor trigger overlapping captures.
            self._times.append(step_s)
            self._retries_since_step = 0
            return None
        verdict = self._trigger(step_s, wait_s)
        if verdict is None:
            return None
        self.anomalies += 1
        if self.captures_started < self.max_captures:
            self._start_capture(verdict, epoch=epoch, step=step)
        return str(verdict["trigger"])

    def _start_capture(self, verdict: Dict[str, Any], *, epoch: int,
                       step: int) -> None:
        import jax

        path = os.path.join(self.trace_dir,
                            f"capture-{self.captures_started}")
        try:
            # The profiler's own start cost is goodput anomaly_capture
            # overhead — the capture is diagnosis, not training.
            with goodput.get().timed("anomaly_capture"):
                os.makedirs(path, exist_ok=True)
                jax.profiler.start_trace(path)
        except Exception as e:  # profiling is advisory, never fatal
            logging.warning(f"flightrec: start_trace failed ({e}); "
                            f"anomaly recorded without a capture")
            return
        self.captures_started += 1
        self._capture_left = self.capture_steps
        # A manifest beside the raw trace makes the capture
        # self-describing: `main.py roofline --from-anomaly` reports
        # WHY the profiler fired next to the op-level blame, without
        # re-joining telemetry.  Atomic + advisory, like the dump.
        try:
            manifest = {"trigger": verdict, "epoch": epoch, "step": step,
                        "capture": self.captures_started - 1,
                        "capture_steps": self.capture_steps}
            tmp = os.path.join(path, "manifest.json.tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(manifest, f, indent=2, default=float)
            os.replace(tmp, os.path.join(path, "manifest.json"))
        except (OSError, TypeError, ValueError) as e:
            logging.warning(f"flightrec: capture manifest not written "
                            f"({e})")
        logging.info(f"flightrec: anomaly ({verdict['trigger']}) at "
                     f"epoch {epoch} step {step} — capturing next "
                     f"{self.capture_steps} step(s) to {path}")

    def _stop_capture(self) -> None:
        """End-of-budget stop for the normal K-step path."""
        import jax

        try:
            # stop_trace serializes the capture to disk — goodput
            # anomaly_capture overhead, same as the start.
            with goodput.get().timed("anomaly_capture"):
                jax.profiler.stop_trace()
        except Exception as e:
            # advisory: a failed stop (backend died mid-capture) must
            # not take the training loop down with it
            logging.warning(f"flightrec: stop_trace failed ({e})")

    def close(self) -> None:
        """End-of-run cleanup: an in-flight capture is stopped with
        ``stop_trace`` in a ``finally``, so the profiler can never be
        left running past the detector's lifetime (the graftlint
        profiler-trace-leak rule keys on this guarantee)."""
        if self._capture_left <= 0:
            return
        import jax

        try:
            self._capture_left = 0
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                # close() runs inside the driver's finally — swallow
                # everything so cleanup cannot mask the real exception
                logging.warning(f"flightrec: close stop_trace "
                                f"failed ({e})")


class FlightRecorder:
    """Fixed-memory ring buffer of step records + point events.

    Disabled instances (the default singleton) are no-ops on every
    method; enabled ones append bounded dicts — no file I/O until
    ``dump``.  Append/dump are locked: producer threads and the signal
    handler may record events concurrently with the driver."""

    def __init__(self, enabled: bool = False, rsl_path: str = ".",
                 rank: int = 0, ring_size: int = 4096):
        self.enabled = enabled
        self.rank = rank
        self.ring_size = int(ring_size)
        self._path = os.path.join(rsl_path,
                                  f"flightrec-rank{rank}.json")
        self._ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=max(self.ring_size, 16))
        # REENTRANT on purpose: the preempt signal handler
        # (utils.GracefulShutdown) fires record_event() + dump() on the
        # main thread and may interrupt a frame already inside this
        # lock (record_step, an anomaly capture) — a plain Lock
        # self-deadlocks the whole process there.
        self._lock = threading.RLock()
        self._dump_reasons: List[str] = []
        self.detector: Optional[AnomalyDetector] = None

    # -- recording ----------------------------------------------------

    def record_step(self, *, epoch: int, step: int, step_s: float,
                    dispatch_s: Optional[float] = None,
                    wait_s: Optional[float] = None,
                    queue_depth: Optional[int] = None,
                    category: Optional[str] = None) -> None:
        """One completed train step: total step wall time, the dispatch
        slice of it, the data-wait slice, the prefetch queue depth
        sampled after the fetch, and the step's dominant goodput
        category — so a crash/preempt dump shows where the rank was
        spending its time when it died, not just how long steps took."""
        if not self.enabled:
            return
        rec: Dict[str, Any] = {"kind": "step", "epoch": epoch,
                               "step": step, "ts": time.time(),
                               "mono": time.monotonic(),
                               "step_s": step_s}
        if dispatch_s is not None:
            rec["dispatch_s"] = dispatch_s
        if wait_s is not None:
            rec["wait_s"] = wait_s
        if queue_depth is not None:
            rec["queue_depth"] = queue_depth
        if category is not None:
            rec["category"] = category
        with self._lock:
            self._ring.append(rec)

    def record_event(self, name: str, **attrs: Any) -> None:
        """Point event (retry, fault_injected, anomaly, preempt...).
        Retry-ish events additionally feed the detector's burst
        trigger."""
        if not self.enabled:
            return
        # attrs first, reserved fields last: a caller attr named "kind"
        # (e.g. a fault kind) must never clobber the record schema
        rec = {**attrs, "kind": "event", "name": name, "ts": time.time(),
               "mono": time.monotonic()}
        with self._lock:
            self._ring.append(rec)
        if name in ("retry", "fault_injected") and self.detector:
            self.detector.note_retry()

    # -- dumping ------------------------------------------------------

    def dump(self, reason: str) -> Optional[str]:
        """Write the ring to ``flightrec-rank{N}.json`` (latest dump
        wins; ``reasons`` accumulates so a preempt dump followed by the
        end-of-run dump is visible).  Never raises: the recorder is
        called from signal handlers and ``finally`` blocks."""
        if not self.enabled:
            return None
        with self._lock:
            self._dump_reasons.append(reason)
            doc = {
                "rank": self.rank,
                "ring_size": self.ring_size,
                "reason": reason,
                "reasons": list(self._dump_reasons),
                # The dump's own paired stamp anchors the records' mono
                # values to this host's wall clock at dump time.
                "dumped_at": {"ts": time.time(), "mono": time.monotonic()},
                "records": list(self._ring),
            }
        try:
            tmp = self._path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, default=float)
            os.replace(tmp, self._path)  # never leave a torn dump
            return self._path
        except Exception as e:
            # dump() is called from signal handlers and finally blocks:
            # a full disk must degrade to a logged error, never raise
            logging.error(f"flightrec: cannot write {self._path!r} ({e})")
            return None

    def close(self, reason: str = "run_end") -> None:
        """Final dump + detector cleanup; idempotent (disables self)."""
        if not self.enabled:
            return
        if self.detector is not None:
            self.detector.close()
        self.dump(reason)
        self.enabled = False


_active = FlightRecorder(enabled=False)


def get() -> FlightRecorder:
    """The process's active flight recorder (disabled no-op by
    default)."""
    return _active


def configure(rsl_path: str, enabled: bool, rank: int = 0,
              ring_size: int = 4096) -> FlightRecorder:
    """Install the process's recorder (drivers call this once, after
    runtime init so the rank is the global process index).  A previous
    enabled instance is closed first — re-invocation safe."""
    global _active
    if _active.enabled:
        _active.close("reconfigure")
    _active = FlightRecorder(enabled=enabled, rsl_path=rsl_path,
                             rank=rank, ring_size=ring_size)
    return _active


def attach_detector(rec: FlightRecorder, *, trace_dir: str,
                    **knobs: Any) -> Optional[AnomalyDetector]:
    """Create + attach the anomaly detector to an enabled recorder and
    return it (None on a disabled recorder — anomaly capture requires
    the flight recorder, since the captures are explained by its
    records)."""
    if not rec.enabled:
        return None
    rec.detector = AnomalyDetector(trace_dir=trace_dir, **knobs)
    return rec.detector


def observe_step(rec: FlightRecorder, *, epoch: int, step: int,
                 step_s: float, dispatch_s: Optional[float] = None,
                 wait_s: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 category: Optional[str] = None) -> None:
    """Hot-loop helper: record the step and, if a detector is attached,
    judge it — emitting the ``anomaly`` event on both sinks when it
    fires."""
    rec.record_step(epoch=epoch, step=step, step_s=step_s,
                    dispatch_s=dispatch_s, wait_s=wait_s,
                    queue_depth=queue_depth, category=category)
    det = rec.detector
    if det is None:
        return
    trigger = det.observe_step(epoch=epoch, step=step, step_s=step_s,
                               wait_s=wait_s)
    if trigger is not None:
        rec.record_event("anomaly", trigger=trigger, epoch=epoch,
                         step=step, step_s=step_s)
        telemetry.get().event("anomaly", trigger=trigger, epoch=epoch,
                              step=step, step_s=step_s,
                              captures=det.captures_started)


def load_dumps(rsl_path: str) -> Dict[int, Dict[str, Any]]:
    """All ``flightrec-rank*.json`` dumps under a run dir, keyed by rank.
    Unreadable/torn dumps are skipped (the timeline merger degrades to
    telemetry-only for that rank)."""
    out: Dict[int, Dict[str, Any]] = {}
    try:
        names = sorted(os.listdir(rsl_path))
    except OSError:
        return out
    for fn in names:
        if not (fn.startswith("flightrec-rank")
                and fn.endswith(".json")):
            continue
        try:
            with open(os.path.join(rsl_path, fn), encoding="utf-8") as f:
                doc = json.load(f)
            out[int(doc["rank"])] = doc
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out
