"""Bench-trend regression ledger over the committed BENCH history
(ISSUE 12 satellite).

``BENCH_r*.json`` is the repo's perf trajectory — one headline row per
driver round — and ``BENCH_SUITE.json`` the latest per-model sweep.
This module turns them into a machine-checkable trend: samples/s/chip
and MFU per round, with deltas computed ONLY between provenance-clean
rows (``fresh: true``, or pre-flag legacy rows without an ``error`` —
the exact tolerance scripts/check_bench.py codified).  Replayed rounds
(``fresh: false``, e.g. the TPU tunnel was down) are SHOWN but never
used as a delta endpoint: a stale number differenced against a fresh
one is not a regression, it is provenance noise.

The verdict gates on the LATEST eligible delta only.  Historical
rounds legitimately regressed (r01->r02 was -5.1% and was accepted at
the time); a CI gate that re-litigates history would be permanently
red, so the gate asks the only actionable question: did the newest
fresh measurement regress against the previous fresh one?

Exit contract (scripts/bench_trend.py, ``main.py bench-trend``):
exit 0 = no regression beyond the threshold, 1 = regression.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

SCHEMA = 1
DEFAULT_THRESHOLD = 0.05

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def headline_row(doc: Any) -> Optional[dict]:
    """The bench headline inside a BENCH file: either the row itself or
    the last JSON-looking line of a driver round file's log tail (same
    rule as scripts/check_bench.py)."""
    if isinstance(doc, dict) and "metric" in doc:
        return doc
    if isinstance(doc, dict) and isinstance(doc.get("tail"), str):
        for line in reversed(doc["tail"].strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    row = json.loads(line)
                except ValueError:
                    return None
                return row if isinstance(row, dict) else None
    return None


def delta_eligible(row: dict) -> bool:
    """May this row serve as a delta endpoint?

    ``fresh: true`` rows qualify; rows explicitly flagged ``fresh:
    false`` never do; legacy rows (written before the flag existed)
    qualify unless they carry an ``error`` — mirroring check_bench.py's
    tolerance, which keeps rounds 1-4 in the trajectory while excluding
    the round-5 replay that predates the flag."""
    if "fresh" in row:
        return row["fresh"] is True
    return not row.get("error")


def load_rounds(bench_dir: Optional[str] = None
                ) -> List[Tuple[int, str, Optional[dict]]]:
    """All ``BENCH_r*.json`` as (round_number, filename, headline_row),
    sorted by round.  Unparseable files yield a None row (reported,
    never fatal)."""
    root = bench_dir or repo_root()
    out = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        m = _ROUND_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
            row = headline_row(doc)
        except (OSError, ValueError):
            row = None
        out.append((int(m.group(1)), os.path.basename(path), row))
    out.sort()
    return out


def load_suite(bench_dir: Optional[str] = None) -> Dict[str, dict]:
    """Per-model rows of BENCH_SUITE.json (empty when absent)."""
    root = bench_dir or repo_root()
    try:
        with open(os.path.join(root, "BENCH_SUITE.json")) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    suite = doc.get("suite")
    return suite if isinstance(suite, dict) else {}


def _metric_series(rounds, key: str) -> List[Optional[float]]:
    out = []
    for _n, _fn, row in rounds:
        v = row.get(key) if row else None
        out.append(float(v) if isinstance(v, (int, float)) else None)
    return out


def build_trend(bench_dir: Optional[str] = None,
                threshold: float = DEFAULT_THRESHOLD) -> Dict[str, Any]:
    """The full trend report + verdict.  Raises ValueError when there
    is no BENCH history at all (nothing to trend)."""
    rounds = load_rounds(bench_dir)
    if not rounds:
        raise ValueError(
            f"no BENCH_r*.json under {bench_dir or repo_root()!r}; "
            f"run the bench driver first")
    values = _metric_series(rounds, "value")
    mfus = _metric_series(rounds, "mfu")
    rows: List[Dict[str, Any]] = []
    prev_eligible: Optional[int] = None
    for i, (n, fn, row) in enumerate(rounds):
        eligible = bool(row) and delta_eligible(row) \
            and values[i] is not None
        entry: Dict[str, Any] = {
            "round": n, "file": fn,
            "value": values[i], "mfu": mfus[i],
            "fresh": (row.get("fresh") if row and "fresh" in row
                      else None),
            "replay": bool(row.get("error")) if row else None,
            "eligible": eligible,
            "delta": None, "mfu_delta": None,
        }
        if row is None:
            entry["note"] = "unreadable or headline-less file"
        elif not eligible:
            entry["note"] = ("replayed measurement — shown, excluded "
                             "from deltas")
        if eligible:
            if prev_eligible is not None:
                pv, pm = values[prev_eligible], mfus[prev_eligible]
                if pv:
                    entry["delta"] = values[i] / pv - 1.0
                if pm and mfus[i] is not None:
                    entry["mfu_delta"] = mfus[i] / pm - 1.0
            prev_eligible = i
        rows.append(entry)
    eligible_rows = [r for r in rows if r["eligible"]]
    latest_delta = next((r["delta"] for r in reversed(rows)
                         if r["delta"] is not None), None)
    latest_mfu_delta = next((r["mfu_delta"] for r in reversed(rows)
                             if r["mfu_delta"] is not None), None)
    regression = latest_delta is not None and latest_delta < -threshold
    notes: List[str] = []
    if len(eligible_rows) < 2:
        notes.append(f"only {len(eligible_rows)} delta-eligible "
                     f"round(s) — no trend to gate yet")
    suite = load_suite(bench_dir)
    suite_out = {}
    for name, row in sorted(suite.items()):
        if not isinstance(row, dict):
            continue
        suite_out[name] = {
            "samples_per_sec_per_chip":
                row.get("samples_per_sec_per_chip"),
            "mfu": row.get("mfu"),
            "top_ops": row.get("top_ops"),
            "compile_warmup_s": row.get("compile_warmup_s"),
            "hlo_instructions": row.get("hlo_instructions"),
        }
    return {
        "schema": SCHEMA,
        "metric": "mnist_cnn_train_samples_per_sec_per_chip",
        "threshold": threshold,
        "rounds": rows,
        "n_eligible": len(eligible_rows),
        "latest_delta": latest_delta,
        "latest_mfu_delta": latest_mfu_delta,
        "regression": regression,
        "ok": not regression,
        "suite": suite_out,
        "scan_pairs": _scan_pairs(suite_out),
        "notes": notes,
    }


def _scan_pairs(suite_out: Dict[str, dict]) -> Dict[str, dict]:
    """Compile-time deltas for every ``<row>_scan`` / ``<row>`` pair in
    the suite (the --scan-layers A/B bench.py emits): how much program
    and compile time the stacked-lax.scan form saves, and whether
    steady-state throughput held.  Advisory — pairs missing either side
    are skipped, never an error (older suites predate the scan rows)."""
    out: Dict[str, dict] = {}
    for name, row in suite_out.items():
        if not name.endswith("_scan"):
            continue
        base = suite_out.get(name[:-len("_scan")])
        if not base:
            continue
        pair: Dict[str, Any] = {}
        cs, cb = row.get("compile_warmup_s"), base.get("compile_warmup_s")
        if cs and cb:
            pair["compile_speedup"] = cb / cs
        hs, hb = row.get("hlo_instructions"), base.get("hlo_instructions")
        if hs and hb:
            pair["hlo_reduction"] = hb / hs
        ts = row.get("samples_per_sec_per_chip")
        tb = base.get("samples_per_sec_per_chip")
        if ts and tb:
            pair["throughput_ratio"] = ts / tb
        if pair:
            out[name[:-len("_scan")]] = pair
    return out


def render_trend(trend: Dict[str, Any]) -> str:
    lines = ["== bench trend =="]
    lines.append(f"headline metric: {trend['metric']} "
                 f"(threshold {trend['threshold'] * 100:.1f}%)")
    lines.append(f"  {'round':>5} {'samples/s/chip':>15} {'MFU':>7} "
                 f"{'fresh':>6} {'delta':>8}")
    for r in trend["rounds"]:
        v = f"{r['value']:,.1f}" if r["value"] is not None else "-"
        m = f"{r['mfu'] * 100:.2f}%" if r["mfu"] is not None else "-"
        fresh = {True: "yes", False: "NO", None: "n/a"}[r["fresh"]]
        if r["delta"] is not None:
            d = f"{r['delta'] * 100:+.1f}%"
        elif not r["eligible"]:
            d = "excl"
        else:
            d = "-"
        lines.append(f"  {r['round']:>5} {v:>15} {m:>7} {fresh:>6} "
                     f"{d:>8}")
    if trend["latest_delta"] is not None:
        lines.append(
            f"latest fresh-vs-fresh delta: "
            f"{trend['latest_delta'] * 100:+.2f}% samples/s"
            + (f", {trend['latest_mfu_delta'] * 100:+.2f}% MFU"
               if trend["latest_mfu_delta"] is not None else ""))
    for n in trend["notes"]:
        lines.append(f"note: {n}")
    if trend["suite"]:
        lines.append("suite snapshot (BENCH_SUITE.json):")
        for name, row in trend["suite"].items():
            sps = row["samples_per_sec_per_chip"]
            sps_s = f"{sps:,.1f}/chip" if sps is not None else "-"
            mfu_s = f"MFU {row['mfu'] * 100:.1f}%" \
                if row.get("mfu") is not None else "MFU -"
            cw = row.get("compile_warmup_s")
            cw_s = f"  compile {cw:.1f}s" if cw is not None else ""
            hi = row.get("hlo_instructions")
            hi_s = f" ({hi} HLO)" if hi is not None else ""
            tops = row.get("top_ops") or []
            top_s = ("; top: " + ", ".join(
                f"{t['name']} ({t['bound']})" for t in tops[:3]
                if isinstance(t, dict))) if tops else ""
            lines.append(f"  {name:<22} {sps_s:>15}  {mfu_s}"
                         f"{cw_s}{hi_s}{top_s}")
    if trend.get("scan_pairs"):
        lines.append("scan-vs-noscan (--scan-layers A/B, compile-side):")
        for name, pair in sorted(trend["scan_pairs"].items()):
            parts = []
            if "compile_speedup" in pair:
                parts.append(f"compile {pair['compile_speedup']:.1f}x "
                             "faster")
            if "hlo_reduction" in pair:
                parts.append(f"{pair['hlo_reduction']:.1f}x fewer HLO "
                             "instructions")
            if "throughput_ratio" in pair:
                parts.append("throughput "
                             f"{pair['throughput_ratio'] * 100:.0f}% of "
                             "unrolled")
            lines.append(f"  {name:<22} " + ", ".join(parts))
    lines.append("verdict: " + ("OK — no regression beyond threshold"
                                if trend["ok"] else
                                f"REGRESSION — latest delta "
                                f"{trend['latest_delta'] * 100:+.2f}% "
                                f"exceeds -{trend['threshold'] * 100:.1f}%"))
    return "\n".join(lines)


def run_cli(bench_dir: Optional[str] = None,
            threshold: float = DEFAULT_THRESHOLD,
            as_json: bool = False) -> Tuple[bool, str]:
    """(ok, printable output) for ``main.py bench-trend`` and
    scripts/bench_trend.py; callers exit 1 when ok is False."""
    trend = build_trend(bench_dir, threshold=threshold)
    if as_json:
        return trend["ok"], json.dumps(trend, indent=2, sort_keys=True,
                                       default=float)
    return trend["ok"], render_trend(trend)
