"""Zero-downtime checkpoint rollout: canary, verdict, automatic
rollback (ISSUE 19 tentpole 3).

The train->checkpoint->serve loop closes here: when the checkpoint
lineage ledger (checkpoint.py's ``ckpt-lineage.json``) grows a newer
head than the sha the replicas report serving (their ``/healthz``
lineage block, satellite a), the rollout manager hot-swaps a CANARY
FRACTION of the fleet onto it via each replica's ``/admin/reload``
(server.py's swap seam -> ``restore_for_serving``), then compares
canary vs stable error-rate and p95 over the same window and either
promotes the rest of the fleet or rolls the canaries back — no
process ever restarts, no listener ever closes.

Split of responsibilities:

  pure core   ``decide_rollout`` (the verdict state machine) and
              ``choose_canaries`` are clock-free functions of (config,
              state, observation) in the ``slo.evaluate`` style — the
              observation carries its own ``t``, the module never
              imports ``time``, and a rejected sha is remembered so a
              bad checkpoint cannot canary-loop forever.
  ledger      ``newest_lineage_entry`` / ``verify_sha`` read the
              lineage ledger directly (JSON + sha256) so the front
              door process stays JAX-free — checkpoint.py, which
              WRITES the ledger, imports the full runtime.
  manager     ``RolloutManager`` is the impure shell the front door
              ticks: it learns the stable sha from the replicas'
              healthz lineage, snapshots per-upstream counters at
              canary start (so the verdict sees deltas, not lifetime
              totals), executes reloads through an injected
              ``reload_fn``, and emits every transition as a
              ``rollout/*`` telemetry event for ``main.py timeline``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import os
from typing import Any, Callable, Dict, List, Optional, Sequence

#: ledger filename, mirrored from checkpoint.py (which owns writes).
LINEAGE_FILE = "ckpt-lineage.json"

ROLLOUT_DEFAULTS: Dict[str, Any] = {
    "fraction": 0.34,          # canary share of the routable fleet
    "hold_s": 5.0,             # healthy canary soak before promotion
    "min_requests": 20,        # verdict needs at least this much signal
    "max_error_ratio": 0.05,   # absolute canary error budget
    "error_ratio_factor": 3.0,  # ...or this multiple of stable's ratio
    "p95_factor": 3.0,         # canary p95 regression multiple
    "p95_floor_ms": 50.0,      # ignore p95 noise below this
    "timeout_s": 120.0,        # canary that never gathers signal dies
}


def _cfg(cfg: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    out = dict(ROLLOUT_DEFAULTS)
    out.update(cfg or {})
    return out


# -- pure core ---------------------------------------------------------

def choose_canaries(ids: Sequence[int], fraction: float) -> List[int]:
    """Deterministic canary pick: the first ``floor(fraction * N)`` of
    the sorted routable ids, at least one, never the whole fleet (a
    1-replica fleet cannot canary — there would be no stable side to
    compare against)."""
    pool = sorted(set(int(i) for i in ids))
    if len(pool) < 2:
        return []
    n = max(1, int(math.floor(float(fraction) * len(pool))))
    n = min(n, len(pool) - 1)
    return pool[:n]


def decide_rollout(cfg: Optional[Dict[str, Any]],
                   state: Dict[str, Any],
                   obs: Dict[str, Any]) -> Dict[str, Any]:
    """Pure canary verdict.  ``state`` holds ``since_t`` (sample-clock
    time the canary started); ``obs`` is the window since then:

      {"t": <sample clock>, "canary_alive": bool,
       "canary": {"requests": n, "errors": n, "p95_ms": x|None},
       "stable": {"requests": n, "errors": n, "p95_ms": x|None}}

    Returns ``{"action": "continue"|"promote"|"rollback", "reason"}``.
    Rollback triggers: a dead canary, an error ratio over both the
    absolute budget and ``error_ratio_factor`` x stable's ratio, a p95
    regression past ``p95_factor`` x stable (above the noise floor), or
    a canary that cannot gather ``min_requests`` inside ``timeout_s``.
    Promotion requires the full ``hold_s`` soak WITH enough signal and
    no regression."""
    c = _cfg(cfg)
    t = float(obs["t"])
    since = float(state["since_t"])
    can = obs.get("canary", {})
    stab = obs.get("stable", {})
    creq = int(can.get("requests", 0))
    cerr = int(can.get("errors", 0))
    sreq = int(stab.get("requests", 0))
    serr = int(stab.get("errors", 0))

    if not obs.get("canary_alive", True):
        return {"action": "rollback",
                "reason": "canary replica died or was ejected"}

    if creq >= int(c["min_requests"]):
        cratio = cerr / creq
        sratio = (serr / sreq) if sreq else 0.0
        if cratio > float(c["max_error_ratio"]) \
                and cratio > sratio * float(c["error_ratio_factor"]):
            return {"action": "rollback",
                    "reason": f"canary error ratio {cratio:.3f} vs "
                              f"stable {sratio:.3f} (budget "
                              f"{c['max_error_ratio']:g})"}
        cp95, sp95 = can.get("p95_ms"), stab.get("p95_ms")
        if cp95 is not None and sp95 is not None \
                and float(cp95) > float(c["p95_floor_ms"]) \
                and float(cp95) > float(sp95) * float(c["p95_factor"]):
            return {"action": "rollback",
                    "reason": f"canary p95 {float(cp95):.1f}ms vs "
                              f"stable {float(sp95):.1f}ms (factor "
                              f"{c['p95_factor']:g})"}
        if t - since >= float(c["hold_s"]):
            return {"action": "promote",
                    "reason": f"healthy for {t - since:.1f}s over "
                              f"{creq} canary requests (error ratio "
                              f"{cratio:.3f})"}
    elif t - since >= float(c["timeout_s"]):
        return {"action": "rollback",
                "reason": f"only {creq} canary requests in "
                          f"{t - since:.0f}s (< min_requests "
                          f"{c['min_requests']})"}

    return {"action": "continue",
            "reason": f"soaking ({creq} canary requests, "
                      f"{t - since:.1f}s of {c['hold_s']:g}s)"}


# -- lineage ledger readers (JAX-free by construction) -----------------

def newest_lineage_entry(watch_dir: str) -> Optional[Dict[str, Any]]:
    """The newest verifiable checkpoint the ledger names: highest
    epoch, ties broken by ledger order (later write wins).  Only plain
    checkpoint FILES qualify — the rollout reload path feeds
    ``restore_for_serving`` a path, and the gates serve ``.ckpt``
    files.  None when there is no ledger or no live entry."""
    path = os.path.join(watch_dir, LINEAGE_FILE)
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    records = doc.get("records") if isinstance(doc, dict) else None
    best: Optional[Dict[str, Any]] = None
    for rec in records or []:
        if not isinstance(rec, dict) or not rec.get("sha256"):
            continue
        fpath = os.path.join(watch_dir, str(rec.get("file", "")))
        if not os.path.isfile(fpath):
            continue
        if best is None or int(rec.get("epoch", -1)) \
                >= int(best.get("epoch", -1)):
            best = dict(rec, path=fpath)
    return best


def verify_sha(path: str, sha256: str) -> bool:
    """Content check before a canary reload: the file still hashes to
    what the ledger recorded (a torn or half-rotated checkpoint must
    never reach a serving replica)."""
    try:
        with open(path, "rb") as f:
            got = hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return False
    return got == str(sha256)


# -- the impure shell --------------------------------------------------

class RolloutManager:
    """Drives stable -> canary -> promote/rollback over live replicas.

    Ticked by the front door's control loop with the sample-clock time,
    the replica snapshots, and the ledger head; everything external is
    injected (``reload_fn(replica_id, path) -> bool`` and
    ``event_fn(name, **attrs)``), so tests drive the whole state
    machine with stubs and no sockets."""

    def __init__(self, cfg: Optional[Dict[str, Any]],
                 reload_fn: Callable[[int, str], bool],
                 event_fn: Callable[..., None]):
        self.cfg = _cfg(cfg)
        self._reload = reload_fn
        self._event = event_fn
        self.phase = "stable"
        self.stable_sha: Optional[str] = None
        self.stable_path: Optional[str] = None
        self.candidate: Optional[Dict[str, Any]] = None
        self.canary_ids: List[int] = []
        self.since_t = 0.0
        self._baseline: Dict[int, Dict[str, float]] = {}
        self.rejected: set = set()      # shas that already rolled back
        self._verified: set = set()     # shas content-checked this run
        self.rollbacks = 0
        self.promotions = 0

    # -- helpers -------------------------------------------------------

    def _learn_stable(self, replicas: List[Dict[str, Any]]) -> None:
        """The stable lineage is whatever the (majority of the) fleet
        reports serving — learned, not configured, so the manager can
        attach to a running tier."""
        counts: Dict[str, int] = {}
        paths: Dict[str, str] = {}
        for rep in replicas:
            lin = rep.get("lineage") or {}
            sha = lin.get("sha256")
            if not sha:
                continue
            counts[sha] = counts.get(sha, 0) + 1
            if lin.get("path"):
                paths[sha] = lin["path"]
        if counts:
            sha = max(counts, key=lambda s: counts[s])
            self.stable_sha = sha
            self.stable_path = paths.get(sha, self.stable_path)

    def _stats(self, replicas: List[Dict[str, Any]], ids: List[int]
               ) -> Dict[str, Any]:
        """Windowed (since canary start) request/error totals + worst
        p95 across the given replica ids."""
        req = err = 0
        p95: Optional[float] = None
        for rep in replicas:
            if rep["id"] not in ids:
                continue
            base = self._baseline.get(rep["id"], {})
            req += max(0, int(rep.get("requests", 0))
                       - int(base.get("requests", 0)))
            err += max(0, int(rep.get("errors", 0))
                       - int(base.get("errors", 0)))
            if rep.get("p95_ms") is not None:
                p95 = max(p95 or 0.0, float(rep["p95_ms"]))
        return {"requests": req, "errors": err, "p95_ms": p95}

    def _reload_set(self, ids: List[int], path: str) -> List[int]:
        return [i for i in ids if self._reload(i, path)]

    # -- the tick ------------------------------------------------------

    def tick(self, t: float, replicas: List[Dict[str, Any]],
             head: Optional[Dict[str, Any]]) -> None:
        """One control cycle.  ``replicas``: the front door's snapshots
        (id, alive/ejected/draining flags, lineage block, cumulative
        requests/errors, windowed p95_ms).  ``head``: the newest ledger
        entry (``newest_lineage_entry``), or None."""
        if self.phase == "stable":
            self._learn_stable([r for r in replicas
                                if r.get("alive")
                                and not r.get("ejected")])
            self._maybe_start(t, replicas, head)
            return
        self._judge(t, replicas)

    def _maybe_start(self, t: float, replicas: List[Dict[str, Any]],
                     head: Optional[Dict[str, Any]]) -> None:
        if head is None or self.stable_sha is None:
            return
        sha = str(head["sha256"])
        if sha == self.stable_sha or sha in self.rejected:
            return
        if sha not in self._verified:
            if not verify_sha(head["path"], sha):
                self.rejected.add(sha)
                self._event("rollout/candidate_rejected", sha=sha[:12],
                            path=head["path"],
                            reason="lineage checksum mismatch")
                return
            self._verified.add(sha)
        routable = [r["id"] for r in replicas
                    if r.get("alive") and not r.get("ejected")
                    and not r.get("draining")]
        ids = choose_canaries(routable, self.cfg["fraction"])
        if not ids:
            return  # < 2 routable replicas: no stable side to compare
        loaded = self._reload_set(ids, head["path"])
        if not loaded:
            self.rejected.add(sha)
            self._event("rollout/candidate_rejected", sha=sha[:12],
                        path=head["path"],
                        reason="canary reload failed on every replica")
            return
        self.phase = "canary"
        self.candidate = dict(head)
        self.canary_ids = loaded
        self.since_t = t
        self._baseline = {r["id"]: {"requests": int(r.get("requests", 0)),
                                    "errors": int(r.get("errors", 0))}
                          for r in replicas}
        logging.info(f"rollout: canary {sha[:12]} started on replicas "
                     f"{loaded} (stable {self.stable_sha[:12]})")
        self._event("rollout/canary_start", sha=sha[:12],
                    stable=self.stable_sha[:12], replicas=loaded,
                    epoch=head.get("epoch"))

    def _judge(self, t: float, replicas: List[Dict[str, Any]]) -> None:
        live = {r["id"] for r in replicas
                if r.get("alive") and not r.get("ejected")}
        stable_ids = [r["id"] for r in replicas
                      if r["id"] not in self.canary_ids
                      and r["id"] in live]
        obs = {
            "t": t,
            "canary_alive": any(i in live for i in self.canary_ids),
            "canary": self._stats(replicas, self.canary_ids),
            "stable": self._stats(replicas, stable_ids),
        }
        verdict = decide_rollout(self.cfg, {"since_t": self.since_t},
                                 obs)
        if verdict["action"] == "continue":
            return
        sha = str(self.candidate["sha256"]) if self.candidate else "?"
        if verdict["action"] == "promote":
            promoted = self._reload_set(stable_ids,
                                        self.candidate["path"])
            self.stable_sha = sha
            self.stable_path = self.candidate["path"]
            self.promotions += 1
            logging.info(f"rollout: promoted {sha[:12]} "
                         f"({verdict['reason']})")
            self._event("rollout/promote", sha=sha[:12],
                        replicas=promoted, reason=verdict["reason"])
        else:
            rolled = (self._reload_set(self.canary_ids,
                                       self.stable_path)
                      if self.stable_path else [])
            self.rejected.add(sha)
            self.rollbacks += 1
            logging.warning(f"rollout: ROLLED BACK {sha[:12]} "
                            f"({verdict['reason']})")
            self._event("rollout/rollback", sha=sha[:12],
                        replicas=rolled, reason=verdict["reason"])
        self.phase = "stable"
        self.candidate = None
        self.canary_ids = []
        self._baseline = {}
