"""Bucket planner: the fixed menu of batch sizes the server compiles.

XLA programs are shape-specialized, so a server that accepted every
batch size would compile on the request path — unbounded tail latency
on exactly the requests that miss the menu.  Instead the tier AOT-
compiles a FIXED menu of bucket sizes up front (``--serve-buckets``,
against the persistent compilation cache) and every micro-batch is
padded to one of them.  The planning rule:

  * pending >= some bucket: take the LARGEST bucket that fills
    completely — maximum rows per dispatch, zero padding;
  * pending < the smallest bucket (a deadline flush): pad up to the
    smallest bucket — the padding rows are provably inert because the
    predict program runs eval-mode (BatchNorm uses running stats, no
    dropout), so every output row depends only on its own input row
    (pinned by tests/test_serve.py).

Pure functions over ints — no JAX, no threads.
"""

from __future__ import annotations

from typing import Sequence, Tuple


def parse_buckets(spec) -> Tuple[int, ...]:
    """``"1,4,16,64"`` (or an int sequence) -> sorted unique bucket
    tuple.  Rejects empty menus and non-positive sizes loudly — a typo
    here would otherwise surface as a compile at request time."""
    if isinstance(spec, str):
        parts = [p.strip() for p in spec.split(",") if p.strip()]
        try:
            sizes = [int(p) for p in parts]
        except ValueError as e:
            raise ValueError(
                f"--serve-buckets must be comma-separated ints, "
                f"got {spec!r}") from e
    else:
        sizes = [int(b) for b in spec]
    if not sizes:
        raise ValueError("--serve-buckets must name at least one bucket")
    if any(b < 1 for b in sizes):
        raise ValueError(
            f"--serve-buckets sizes must be >= 1, got {sorted(sizes)}")
    return tuple(sorted(set(sizes)))


def choose_bucket(pending: int, buckets: Sequence[int]) -> int:
    """The bucket for ``pending`` queued requests: largest fully-filled
    bucket, else the smallest one (padded)."""
    if pending < 1:
        raise ValueError(f"choose_bucket needs pending >= 1, got {pending}")
    fits = [b for b in buckets if b <= pending]
    return max(fits) if fits else min(buckets)


def plan_batch(pending: int, buckets: Sequence[int]) -> Tuple[int, int, int]:
    """(take, bucket, padding) for one micro-batch: dequeue ``take``
    requests, pad with ``padding`` inert rows to ``bucket``."""
    bucket = choose_bucket(pending, buckets)
    take = min(pending, bucket)
    return take, bucket, bucket - take
