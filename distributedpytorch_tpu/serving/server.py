"""The serving replica: HTTP front end + micro-batch driver loop.

Split of responsibilities (the thread model IS the design):

  handler threads (ThreadingHTTPServer)  parse + validate the request,
      ``admit()`` it into the bounded micro-batcher (503 on refusal —
      the backpressure answer), then BLOCK on the request's event until
      the driver answers.  Handlers never touch the device.
  driver thread (``run()``, the caller's thread)  the only thread that
      dispatches: coalesce pending requests into the largest ready
      bucket (batcher.py), pad to the bucket size, call the injected
      ``infer_fn``, fan results back out, and tick the elastic health
      boundary between batches.  One dispatcher means no device-side
      locking and a stable XLA dispatch cadence.

``infer_fn`` is injected (a closure over the jitted predict program,
built in cli.run_serve) so this module stays JAX-free: every queueing /
deadline / shed / requeue behavior is unit-testable with a stub.

Elastic contract: ``run()`` lets WorldChangedError (raised by the
injected ``health_fn``) propagate AFTER the current batch resolved, so
the caller can reconfigure the world, rebuild the predict program
against the new generation, ``set_infer()`` it, and call ``run()``
again — the HTTP listener and the queued requests (host-side numpy)
persist across the reconfigure.  Only the dying rank's in-flight
requests are lost, and they die with its sockets.

Fault sites (faults.py): ``serve.request`` fires per request in the
handler (an injected ioerror answers 500), ``serve.admit`` fires at
admission (shed-path testing), ``serve.infer`` fires per micro-batch in
the driver — ioerror fails that batch's requests and the loop carries
on; rank_loss vanishes the replica mid-dispatch, the chaos-gate shape
survivors must absorb.

Control plane (ISSUE 19): the front door (frontdoor.py) drives two
admin endpoints.  ``POST /admin/drain`` starts a graceful retirement —
new requests are shed with 503 + Retry-After, the queue flushes, and
``run()`` returns once empty (the caller exits; an elastic world
shrinks around it at the next boundary).  ``POST /admin/reload
{"checkpoint": PATH}`` is the zero-downtime hot-swap seam: the handler
parks a swap request, the DRIVER thread applies it between batches
through the injected ``swap_fn(path) -> (infer_fn, lineage_info)``
(built in cli.run_serve over ``restore_for_serving``), so the predict
program is replaced with no listener restart and no mid-batch tear.
``stats()`` (the ``/livez`` body and the exporter's ``/healthz`` serve
block) reports the served checkpoint's lineage (sha256 + epoch) and
the draining flag — what the front door's canary verdict keys on.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults, telemetry, tracing
from .batcher import MicroBatcher, Request

# Driver poll granularity: the upper bound on how stale a shutdown /
# health check can go while the queue is empty.
_TICK_S = 0.25


class ServingTier:
    """One replica: owns the listener, the batcher, and the driver loop."""

    def __init__(self, infer_fn: Callable[[np.ndarray], Tuple],
                 sample_shape: Sequence[int], sample_dtype,
                 buckets: Sequence[int], max_queue: int,
                 max_latency_s: float, port: int,
                 request_timeout_s: float = 30.0,
                 max_requests: int = 0):
        self._infer = infer_fn
        self.sample_shape = tuple(int(d) for d in sample_shape)
        self.sample_dtype = np.dtype(sample_dtype)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.port = int(port)
        self.request_timeout_s = float(request_timeout_s)
        self.max_requests = int(max_requests)
        self.batcher = MicroBatcher(self.buckets, max_queue, max_latency_s)
        self.answered = 0        # driver thread only
        self.checkpoint: Optional[dict] = None  # lineage of the served ckpt
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._swap_fn: Optional[Callable[[str], Tuple]] = None
        self._swap_lock = threading.Lock()
        self._pending_swap: Optional[dict] = None
        self.swap_timeout_s = 180.0
        self._server = None
        self._http_thread = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        """Bind the port and start answering.  The listener outlives
        elastic reconfigures — only close() takes it down."""
        import http.server

        tier = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 - http.server API
                path = self.path.rstrip("/")
                if path == "/admin/drain":
                    tier.drain()
                    tier._respond(self, 200, {"draining": True,
                                              "queue_depth":
                                                  tier.batcher.depth()})
                    return
                if path == "/admin/reload":
                    try:
                        tier._handle_reload(self)
                    # broad on purpose: a reload failure must become
                    # the caller's 500, never take the listener down
                    except Exception as e:
                        logging.error(f"serve: reload handler "
                                      f"failed: {e}")
                        try:
                            tier._respond(self, 500,
                                          {"error": repr(e)})
                        except Exception:
                            pass  # caller already gone mid-answer
                    return
                if path != "/predict":
                    self.send_error(404)
                    return
                try:
                    tier._handle_predict(self)
                except BrokenPipeError:
                    pass  # client gave up; its timeout, not our crash
                except Exception as e:
                    # A handler bug must answer THIS request and never
                    # take the listener thread down with it.
                    logging.error(f"serve: request handler failed: {e}")
                    try:
                        tier._respond(self, 500, {"error": repr(e)})
                    # broad on purpose: the 500 above is best-effort —
                    # if the socket is already gone there is nobody
                    # left to answer, and raising would kill the
                    # listener thread for everyone else
                    except Exception:
                        pass

            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.rstrip("/") == "/livez":
                    tier._respond(self, 200, tier.stats())
                else:
                    self.send_error(404)

            def log_message(self, fmt, *args):
                pass  # per-request lines would drown the run log

        self._server = http.server.ThreadingHTTPServer(
            ("0.0.0.0", self.port), _Handler)
        self.port = self._server.server_address[1]  # resolve port=0
        self._server.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="serve-listener", daemon=True)
        self._http_thread.start()
        logging.info(
            f"serve: listening on :{self.port} "
            f"(buckets {list(self.buckets)}, queue bound "
            f"{self.batcher.max_queue}, flush "
            f"{self.batcher.max_latency_s * 1000:.0f}ms)")

    def set_infer(self, infer_fn: Callable[[np.ndarray], Tuple]) -> None:
        """Swap the predict program (post-reconfigure rebuild)."""
        self._infer = infer_fn

    def set_checkpoint(self, info: Optional[dict]) -> None:
        """Record the served checkpoint's lineage (sha256/epoch/path) —
        surfaced on /livez and the exporter /healthz serve block, the
        identity the front door's canary verdict compares."""
        self.checkpoint = info

    def set_swap_fn(self, fn: Callable[[str], Tuple]) -> None:
        """Install the hot-swap builder: ``fn(path) -> (infer_fn,
        lineage_info)`` — rebuilds the predict closure for a new
        checkpoint (restore + warmup).  Without one, /admin/reload
        answers 501."""
        self._swap_fn = fn

    def drain(self) -> None:
        """Graceful retirement: stop admitting, flush in-flight, let
        run() return once the queue is empty.  Idempotent."""
        if not self._draining.is_set():
            logging.info("serve: draining — admissions closed, "
                         "flushing the queue")
            telemetry.get().event("serve/drain_start",
                                  queue_depth=self.batcher.depth())
        self._draining.set()

    def stop(self) -> None:
        """Ask the driver loop to exit at the next boundary."""
        self._stop.set()

    def close(self) -> None:
        """Stop the listener and answer every still-queued request with
        a shutdown error — a draining tier never leaves a client
        hanging on a request it silently dropped."""
        self._stop.set()
        for req in self.batcher.close():
            req.fail(RuntimeError("server shutting down"))
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
            self._http_thread.join(timeout=5.0)

    # -- handler side (HTTP threads) ----------------------------------

    def _respond(self, handler, code: int, payload: dict,
                 req_id: Optional[str] = None) -> None:
        body = json.dumps(payload).encode("utf-8")
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        if req_id is not None:
            handler.send_header("X-DPT-Request-Id", req_id)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _handle_reload(self, handler) -> None:
        """The /admin/reload endpoint: park a swap request for the
        driver thread and wait for it to apply between batches — the
        zero-downtime checkpoint hot-swap (rollout.py drives this)."""
        if self._swap_fn is None:
            self._respond(handler, 501,
                          {"error": "no swap_fn installed "
                                    "(stub tier or pre-ISSUE-19 "
                                    "driver)"})
            return
        try:
            n = int(handler.headers.get("Content-Length", 0))
            doc = json.loads(handler.rfile.read(n) or b"{}")
            path = doc["checkpoint"]
        except (KeyError, TypeError, ValueError) as e:
            self._respond(handler, 400,
                          {"error": f"bad reload request: {e}"})
            return
        swap = {"path": str(path), "done": threading.Event(),
                "error": None, "info": None}
        with self._swap_lock:
            if self._pending_swap is not None:
                self._respond(handler, 409,
                              {"error": "a swap is already in flight"})
                return
            self._pending_swap = swap
        if not swap["done"].wait(self.swap_timeout_s):
            self._respond(handler, 504,
                          {"error": f"swap did not apply within "
                                    f"{self.swap_timeout_s:g}s"})
            return
        if swap["error"] is not None:
            self._respond(handler, 500, {"error": swap["error"]})
            return
        self._respond(handler, 200, {"reloaded": True,
                                     "checkpoint": swap["info"]})

    def _handle_predict(self, handler) -> None:
        tel = telemetry.get()
        tel.counter("serve/requests").add()
        if self._draining.is_set():
            # retirement: shed loudly so the front door routes around
            # us while the queue flushes (same 503 contract as full)
            tel.counter("serve/shed").add()
            body = json.dumps({"error": "draining"}).encode("utf-8")
            handler.send_response(503)
            handler.send_header("Content-Type", "application/json")
            handler.send_header("Retry-After", "1")
            handler.send_header("Content-Length", str(len(body)))
            handler.end_headers()
            handler.wfile.write(body)
            return
        try:
            faults.fire("serve.request")
            n = int(handler.headers.get("Content-Length", 0))
            payload = json.loads(handler.rfile.read(n))
            arr = np.asarray(payload["image"], dtype=self.sample_dtype)
        except (KeyError, TypeError, ValueError) as e:
            tel.counter("serve/bad_request").add()
            self._respond(handler, 400, {"error": f"bad request: {e}"})
            return
        except OSError as e:  # injected serve.request ioerror included
            tel.counter("serve/failed").add()
            self._respond(handler, 500, {"error": repr(e)})
            return
        if arr.shape != self.sample_shape:
            tel.counter("serve/bad_request").add()
            self._respond(handler, 400, {
                "error": f"image shape {list(arr.shape)} != expected "
                         f"{list(self.sample_shape)}"})
            return
        # Every valid request gets its deterministic id here; every
        # answer below — 200, 503 shed, 504 timeout, 500 — carries it
        # back as X-DPT-Request-Id, and its terminal record lands in
        # trace-rank<N>.jsonl (tracing.py).
        trace = tracing.get().start()
        rid = trace.id if trace is not None else None
        req = Request(arr, trace=trace)
        try:
            faults.fire("serve.admit")
            admitted = self.batcher.admit(req)
        except OSError as e:
            tel.counter("serve/failed").add()
            self._respond(handler, 500, {"error": repr(e)}, req_id=rid)
            if trace is not None:
                trace.finish(500, "failed", error=repr(e))
            return
        if not admitted:
            # THE backpressure answer: shed now, while the client can
            # still retry elsewhere — a full queue must never grow.
            tel.counter("serve/shed").add()
            depth = self.batcher.depth()
            self._respond(handler, 503, {
                "error": "queue full",
                "queue_depth": depth}, req_id=rid)
            if trace is not None:
                trace.finish(503, "shed", queue_depth=depth)
            return
        if not req.wait(self.request_timeout_s):
            tel.counter("serve/timeout").add()
            self._respond(handler, 504, {"error": "request timed out"},
                          req_id=rid)
            if trace is not None:
                trace.finish(504, "timeout")
            return
        if req.error is not None:
            code = 503 if self._stop.is_set() else 500
            self._respond(handler, code, {"error": repr(req.error)},
                          req_id=rid)
            if trace is not None:
                trace.finish(code, "failed", error=repr(req.error))
            return
        self._respond(handler, 200, req.result, req_id=rid)
        if trace is not None:
            trace.finish(200, "answered")

    # -- driver side (run() caller's thread) --------------------------

    def run(self, health_fn: Optional[Callable[[], bool]] = None,
            health_tick_s: float = 0.5,
            shutdown: Optional[Any] = None) -> int:
        """The micro-batch loop.  Returns the number of requests
        answered when stopped (stop()/close(), a shutdown request, a
        health tick returning True, or --serve-max-requests reached).
        WorldChangedError from ``health_fn`` propagates to the caller's
        elastic loop with the queue intact."""
        tel = telemetry.get()
        next_health = time.monotonic() + health_tick_s
        while not self._stop.is_set():
            if shutdown is not None and getattr(shutdown, "requested",
                                                False) \
                    and health_fn is None:
                break  # single-replica SIGTERM: no agreement needed
            if self.max_requests and self.answered >= self.max_requests:
                break
            if self._draining.is_set() and self.batcher.depth() == 0:
                tel.event("serve/drain_done", answered=self.answered)
                logging.info(f"serve: drained after answering "
                             f"{self.answered} requests")
                break
            self._apply_swap(tel)
            batch = self.batcher.next_batch(_TICK_S)
            if batch is not None:
                self._run_batch(tel, *batch)
            if health_fn is not None \
                    and time.monotonic() >= next_health:
                # Between batches, never mid-dispatch: the boundary's
                # collective must not interleave with a device step.
                if health_fn():
                    break
                next_health = time.monotonic() + health_tick_s
        return self.answered

    def _apply_swap(self, tel) -> None:
        """Driver-thread-only: apply a parked /admin/reload between
        batches.  The swap builder runs on the one thread that owns
        dispatch, so the predict program is never replaced mid-batch;
        queued requests simply wait out the restore+warmup (persistent-
        cache hits make that seconds) and are answered by the NEW
        program."""
        with self._swap_lock:
            swap = self._pending_swap
        if swap is None:
            return
        try:
            infer_fn, info = self._swap_fn(swap["path"])
            self._infer = infer_fn
            self.checkpoint = info
            tracing.get().set_lineage(
                (info or {}).get("sha256"))
            swap["info"] = info
            tel.event("serve/swap",
                      checkpoint=(info or {}).get("file"),
                      sha=str((info or {}).get("sha256"))[:12],
                      epoch=(info or {}).get("epoch"))
            logging.info(f"serve: hot-swapped to "
                         f"{(info or {}).get('file')} "
                         f"(sha {str((info or {}).get('sha256'))[:12]})")
        except Exception as e:
            # a bad candidate (torn file, wrong model) must fail THIS
            # reload and leave the serving program untouched
            swap["error"] = repr(e)
            tel.event("serve/swap_failed", path=swap["path"],
                      error=repr(e))
            logging.error(f"serve: hot-swap to {swap['path']!r} "
                          f"failed: {e}")
        finally:
            with self._swap_lock:
                self._pending_swap = None
            swap["done"].set()

    def _run_batch(self, tel, reqs: List[Request], bucket: int) -> None:
        arr = np.zeros((bucket,) + self.sample_shape, self.sample_dtype)
        for i, r in enumerate(reqs):
            arr[i] = r.payload
        for r in reqs:
            if r.trace is not None:
                r.trace.mark_infer_start(bucket)
        t0 = time.perf_counter()
        try:
            faults.fire("serve.infer")
            labels, confs = self._infer(arr)
        except Exception as e:
            # One bad batch (an injected ioerror, a device hiccup) fails
            # ITS requests and the tier keeps serving — dying here would
            # turn a transient into an outage.
            tel.counter("serve/failed").add(len(reqs))
            tel.counter("serve/batches").add()
            self.answered += len(reqs)
            logging.error(f"serve: micro-batch of {len(reqs)} failed: {e}")
            for r in reqs:
                if r.trace is not None:
                    r.trace.mark_infer_end()
                r.fail(e)
            return
        infer_ms = (time.perf_counter() - t0) * 1000.0
        tel.counter("serve/batches").add()
        tel.counter("serve/batch_rows").add(bucket)
        tel.counter("serve/padded_rows").add(bucket - len(reqs))
        tel.histogram("serve/infer_ms").observe(infer_ms)
        tel.gauge("serve/queue_depth").set(self.batcher.depth())
        for i, r in enumerate(reqs):
            latency_ms = r.age_s() * 1000.0
            tel.histogram("serve/request_latency_ms").observe(latency_ms)
            if r.trace is not None:
                r.trace.mark_infer_end()
                r.trace.note_latency(latency_ms)
            r.complete({
                "label": int(labels[i]),
                "confidence": round(float(confs[i]), 6),
                "bucket": bucket,
                "latency_ms": round(latency_ms, 3),
            })
        tel.counter("serve/answered").add(len(reqs))
        self.answered += len(reqs)

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        """/livez body + the exporter's extra-health payload.  The
        ``checkpoint`` block (lineage sha256 + epoch + path) is the
        served-model identity the front door's rollout verdict keys
        on; ``draining`` tells it to stop routing here."""
        return {
            "ok": True,
            "queue_depth": self.batcher.depth(),
            "answered": self.answered,
            "buckets": list(self.buckets),
            "port": self.port,
            "draining": self._draining.is_set(),
            "checkpoint": self.checkpoint,
        }
