"""The fleet front door: one port, health-aware routing, admission
control, and the autoscale + rollout control loop (ISSUE 19 tentpole).

``main.py frontdoor`` turns N independent serve replicas (each
answering ``/predict`` on ``serve_port + slot``) into ONE resilient
service:

  routing      every client request is proxied to the least-pending
               routable replica (deterministic round-robin tie-break);
               a replica is ejected from rotation after ``eject_after``
               consecutive probe/transport failures or a stale
               ``last_step_age_s``, and readmitted on its first healthy
               probe.  Requests are idempotent (stateless predict), so
               a transport failure or 5xx retries ONCE on a different
               replica — the upstream's ``X-DPT-Request-Id`` is
               preserved end-to-end either way.
  admission    a fleet-level pending budget layered over the
               per-replica 503 backpressure: past ``pending_budget``
               in-flight proxied requests the front door sheds
               immediately with 503 + ``Retry-After`` instead of
               queueing unboundedly.  Every upstream call carries a
               hard deadline (deadline.py), so one hung replica costs
               at most ``upstream_timeout_s`` of one handler thread —
               never the accept loop.
  control      a once-per-``interval_s`` tick probes every replica's
               ``/healthz``, folds the results through the PURE
               deciders (``decide_health`` here, ``decide_scale`` in
               controller.py, ``decide_rollout`` in rollout.py), and
               executes: launch an ``--elastic-join`` replica, drain
               one for retirement, start/promote/rollback a canary.
               Every decision is emitted as a telemetry event
               (``frontdoor/*``, ``controller/*``, ``rollout/*``) so
               ``main.py timeline`` shows the control plane next to
               the data plane.

Thread model mirrors server.py: handler threads (ThreadingHTTPServer)
only proxy — pick upstream, forward with a deadline, relay; the single
control-loop thread owns probing and all policy execution.  Shared
state (the upstream table) is guarded by one lock, held only for
bookkeeping, never across a socket call.

The pure deciders at the top of this module are clock-free functions
of (config, snapshot) in the ``slo.evaluate`` style — the snapshots
carry the counters, the functions never read a clock — so the fleet
simulator direction in ROADMAP.md can drive the exact routing policy
at N=100 replicas.
"""

from __future__ import annotations

import collections
import http.client
import json
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .. import deadline as dl
from .. import telemetry
from . import controller as ctrl
from . import rollout as ro

#: telemetry rank for the front-door process: far above any plausible
#: world size, so its JSONL never collides with a replica's.
FRONTDOOR_RANK = 90

#: front-door shed counter name injected into fleet samples, so the
#: autoscale decider sees fleet-level sheds next to replica-level ones
#: (controller._shed_total folds both).
FD_SHED_COUNTER = ctrl.FD_SHED_COUNTER

ROUTE_DEFAULTS: Dict[str, Any] = {
    "eject_after": 3,       # consecutive failures before ejection
    "max_step_age_s": 0.0,  # stale-health ejection threshold (0 = off)
    "pending_budget": 64,   # fleet-level in-flight cap
    "retry_after_s": 1.0,   # Retry-After hint on shed
}


def _policy(cfg: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    out = dict(ROUTE_DEFAULTS)
    out.update(cfg or {})
    return out


# -- pure routing/admission policy -------------------------------------

def decide_health(cfg: Optional[Dict[str, Any]],
                  replicas: Sequence[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
    """Pure ejection/readmission decisions over replica snapshots
    (``{"id", "ejected", "consecutive_failures", "last_step_age_s"}``).
    Eject on ``eject_after`` consecutive failures or a stale health
    age; readmit an ejected replica whose failure streak reset (a
    healthy probe zeroes it) and whose age is fresh again."""
    c = _policy(cfg)
    eject_after = int(c["eject_after"])
    max_age = float(c["max_step_age_s"] or 0.0)
    out: List[Dict[str, Any]] = []
    for rep in replicas:
        fails = int(rep.get("consecutive_failures", 0))
        age = rep.get("last_step_age_s")
        stale = bool(max_age > 0.0 and age is not None
                     and float(age) > max_age)
        if not rep.get("ejected"):
            if fails >= eject_after:
                out.append({"id": rep["id"], "action": "eject",
                            "reason": f"{fails} consecutive failures"})
            elif stale:
                out.append({"id": rep["id"], "action": "eject",
                            "reason": f"stale health: last_step_age_s "
                                      f"{float(age):.1f} > "
                                      f"{max_age:.1f}"})
        elif fails == 0 and not stale:
            out.append({"id": rep["id"], "action": "readmit",
                        "reason": "healthy probe"})
    return out


def routable_ids(replicas: Sequence[Dict[str, Any]]) -> List[int]:
    """Replicas eligible for NEW requests: seen alive at least once,
    not ejected, not draining."""
    return sorted(r["id"] for r in replicas
                  if r.get("alive") and not r.get("ejected")
                  and not r.get("draining"))


def pick_upstream(ids: Sequence[int], pending: Dict[int, int],
                  rr: int, exclude: Sequence[int] = ()
                  ) -> Optional[int]:
    """Least-pending routable replica, deterministic round-robin among
    ties (``rr`` is the caller's monotonically increasing pick
    counter).  Pure; None when nothing is routable."""
    pool = [i for i in sorted(ids) if i not in set(exclude)]
    if not pool:
        return None
    low = min(int(pending.get(i, 0)) for i in pool)
    tied = [i for i in pool if int(pending.get(i, 0)) == low]
    return tied[rr % len(tied)]


def admission(cfg: Optional[Dict[str, Any]], pending_total: int
              ) -> Dict[str, Any]:
    """Fleet-level admission: admit while the in-flight count is under
    the pending budget, else shed with a Retry-After hint.  Pure."""
    c = _policy(cfg)
    if int(pending_total) >= int(c["pending_budget"]):
        return {"admit": False,
                "retry_after_s": float(c["retry_after_s"])}
    return {"admit": True, "retry_after_s": 0.0}


# -- upstream bookkeeping ----------------------------------------------

class Upstream:
    """One replica slot as the front door sees it.  Mutated only under
    the front door's lock; ``snapshot()`` is what the pure deciders and
    the rollout manager consume."""

    def __init__(self, uid: int, predict_port: int, health_port: int,
                 health_path: str = "/healthz"):
        self.id = int(uid)
        self.predict_port = int(predict_port)
        self.health_port = int(health_port)
        self.health_path = health_path
        self.alive = False            # answered a probe at least once
        self.ejected = False
        self.draining = False
        self.consecutive_failures = 0
        self.pending = 0
        self.last_step_age_s: Optional[float] = None
        self.lineage: Optional[Dict[str, Any]] = None
        self.requests = 0             # proxied attempts that answered
        self.errors = 0               # 5xx answers (shed 503 excluded)
        self.unreachable = 0          # transport failures / deadlines
        self.shed = 0                 # upstream's own 503 backpressure
        self.latencies: collections.deque = collections.deque(
            maxlen=1024)

    def p95_ms(self) -> Optional[float]:
        if not self.latencies:
            return None
        vals = sorted(self.latencies)
        return vals[int(0.95 * (len(vals) - 1))]

    def snapshot(self) -> Dict[str, Any]:
        return {"id": self.id, "alive": self.alive,
                "ejected": self.ejected, "draining": self.draining,
                "consecutive_failures": self.consecutive_failures,
                "pending": self.pending,
                "last_step_age_s": self.last_step_age_s,
                "lineage": self.lineage,
                "requests": self.requests,
                # rollout's error signal: application 5xx AND
                # unreachability both count against a canary
                "errors": self.errors + self.unreachable,
                "shed": self.shed, "p95_ms": self.p95_ms()}


class SubprocessLauncher:
    """Scale-up executor: spawn one ``--elastic-join`` replica per
    ``launch()`` from a shell command template.  The command is
    operator-supplied (config ``--launch-cmd``); stdout/stderr land in
    numbered logs under ``log_dir`` so a failed join is debuggable."""

    def __init__(self, cmd: str, cwd: Optional[str] = None,
                 log_dir: Optional[str] = None):
        self.cmd = cmd
        self.cwd = cwd
        self.log_dir = log_dir
        self.launched = 0
        self.procs: List[Any] = []

    def launch(self) -> bool:
        import shlex
        import subprocess

        self.launched += 1
        out = None
        if self.log_dir:
            import os

            os.makedirs(self.log_dir, exist_ok=True)
            out = open(f"{self.log_dir}/join-{self.launched}.log", "ab")
        try:
            self.procs.append(subprocess.Popen(
                shlex.split(self.cmd), cwd=self.cwd, stdout=out,
                stderr=out))
            return True
        except OSError as e:
            logging.error(f"frontdoor: launch command failed: {e}")
            return False


# -- the front door -----------------------------------------------------

class FrontDoor:
    """The impure shell: listener + proxy + control loop."""

    def __init__(self, port: int,
                 replicas: Dict[int, Dict[str, Any]],
                 *, host: str = "127.0.0.1",
                 policy: Optional[Dict[str, Any]] = None,
                 upstream_timeout_s: float = 10.0,
                 probe_timeout_s: float = 2.0,
                 interval_s: float = 0.5,
                 collector: Optional[Any] = None,
                 scale_cfg: Optional[Dict[str, Any]] = None,
                 launcher: Optional[Callable[[], bool]] = None,
                 rollout_cfg: Optional[Dict[str, Any]] = None,
                 watch_dir: Optional[str] = None,
                 reload_timeout_s: float = 180.0,
                 drain_timeout_s: float = 10.0):
        self.port = int(port)
        self.host = host
        self.policy = _policy(policy)
        self.upstream_timeout_s = float(upstream_timeout_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.interval_s = float(interval_s)
        self.reload_timeout_s = float(reload_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._ups: Dict[int, Upstream] = {
            int(uid): Upstream(
                uid, spec["predict_port"],
                spec.get("health_port") or spec["predict_port"],
                spec.get("health_path", "/healthz"))
            for uid, spec in replicas.items()}
        self._lock = threading.Lock()
        self._rr = 0
        self._pending_total = 0
        self._shed = 0            # fleet-level admission sheds
        self._no_upstream = 0     # 503s for "nothing routable"
        self._retries = 0
        self._answered = 0
        self._client_codes: Dict[int, int] = {}
        self._coll = collector
        self._scale_cfg = dict(scale_cfg) if scale_cfg else None
        self._scale_state: Dict[str, Any] = {}
        self._launcher = launcher
        self.scale_events: List[Dict[str, Any]] = []
        self.rollout: Optional[ro.RolloutManager] = None
        if watch_dir is not None:
            self.rollout = ro.RolloutManager(
                rollout_cfg, reload_fn=self._reload_replica,
                event_fn=self._event)
        self._watch_dir = watch_dir
        self.cycle = 0
        self._server: Optional[Any] = None
        self._http_thread: Optional[threading.Thread] = None

    # -- telemetry -----------------------------------------------------

    def _event(self, name: str, **attrs: Any) -> None:
        tel = telemetry.get()
        tel.event(name, **attrs)
        tel.flush()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        import http.server

        fd = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 - http.server API
                if self.path.rstrip("/") != "/predict":
                    self.send_error(404)
                    return
                try:
                    fd._proxy(self)
                except BrokenPipeError:
                    pass  # client gave up mid-relay
                # broad on purpose: the front door must answer every
                # request — a proxy bug becomes the client's 500, not
                # a dropped connection
                except Exception as e:
                    logging.error(f"frontdoor: handler failed: {e}")
                    try:
                        fd._respond(self, 500, {"error": repr(e)})
                    except Exception:
                        pass  # client already gone — nothing to answer

            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.rstrip("/") in ("/healthz", "/livez"):
                    fd._respond(self, 200, fd.status_doc())
                else:
                    self.send_error(404)

            def log_message(self, fmt, *args):
                pass

        self._server = http.server.ThreadingHTTPServer(
            ("0.0.0.0", self.port), _Handler)
        self.port = self._server.server_address[1]
        self._server.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="frontdoor-listener", daemon=True)
        self._http_thread.start()
        logging.info(f"frontdoor: listening on :{self.port} over "
                     f"{len(self._ups)} replica slots "
                     f"(pending budget {self.policy['pending_budget']})")

    def close(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
            if self._http_thread is not None:
                self._http_thread.join(timeout=5.0)

    # -- proxy path (handler threads) ----------------------------------

    def _respond(self, handler, code: int, payload: dict,
                 headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode("utf-8")
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        for k, v in (headers or {}).items():
            handler.send_header(k, v)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)
        with self._lock:
            self._client_codes[code] = \
                self._client_codes.get(code, 0) + 1

    def _relay(self, handler, status: int, raw: bytes,
               rid: Optional[str], uid: int) -> None:
        handler.send_response(status)
        handler.send_header("Content-Type", "application/json")
        if rid:
            handler.send_header("X-DPT-Request-Id", rid)
        handler.send_header("X-DPT-Upstream", str(uid))
        handler.send_header("Content-Length", str(len(raw)))
        handler.end_headers()
        handler.wfile.write(raw)
        with self._lock:
            self._client_codes[status] = \
                self._client_codes.get(status, 0) + 1

    def _forward(self, up: Upstream, body: bytes):
        """One deadline-bounded upstream attempt.  Raises OSError on
        any transport failure (timeout included); returns
        ``(status, raw_body, request_id)``."""
        conn = http.client.HTTPConnection(
            self.host, up.predict_port, timeout=self.upstream_timeout_s)
        try:
            conn.request("POST", "/predict", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            raw = resp.read()
            return (int(resp.status), raw,
                    resp.getheader("X-DPT-Request-Id"))
        except http.client.HTTPException as e:
            raise OSError(f"upstream protocol error: {e}") from e
        finally:
            conn.close()

    def _proxy(self, handler) -> None:
        tel = telemetry.get()
        tel.counter("frontdoor/requests").add()
        n = int(handler.headers.get("Content-Length", 0))
        body = handler.rfile.read(n)
        with self._lock:
            verdict = admission(self.policy, self._pending_total)
            if not verdict["admit"]:
                self._shed += 1
            else:
                self._pending_total += 1
        if not verdict["admit"]:
            tel.counter("frontdoor/shed").add()
            self._respond(
                handler, 503,
                {"error": "front door at capacity",
                 "pending": self._pending_total},
                headers={"Retry-After":
                         f"{verdict['retry_after_s']:g}"})
            return
        tried: List[int] = []
        last = None  # (status, raw, rid, uid) from an answering 5xx
        try:
            for attempt in range(2):
                with self._lock:
                    snaps = [u.snapshot() for u in self._ups.values()]
                    pending = {u.id: u.pending
                               for u in self._ups.values()}
                    uid = pick_upstream(routable_ids(snaps), pending,
                                        self._rr, exclude=tried)
                    self._rr += 1
                    if uid is None:
                        break
                    up = self._ups[uid]
                    up.pending += 1
                tried.append(uid)
                if attempt:
                    self._retries += 1
                    tel.counter("frontdoor/retries").add()
                t0 = time.monotonic()
                try:
                    status, raw, rid = self._forward(up, body)
                except OSError as e:
                    with self._lock:
                        up.pending -= 1
                        up.unreachable += 1
                        up.consecutive_failures += 1
                    logging.info(f"frontdoor: replica {uid} "
                                 f"unreachable ({e}); "
                                 f"{'retrying' if not attempt else 'giving up'}")
                    continue
                ms = (time.monotonic() - t0) * 1000.0
                with self._lock:
                    up.pending -= 1
                    up.requests += 1
                    up.consecutive_failures = 0
                    up.latencies.append(ms)
                    if status == 503:
                        up.shed += 1
                    elif status >= 500:
                        up.errors += 1
                if status < 500:
                    self._answered += 1
                    self._relay(handler, status, raw, rid, uid)
                    return
                last = (status, raw, rid, uid)
            if last is not None:
                # both attempts answered 5xx: relay the upstream's own
                # error — the id still names the real failing request
                self._relay(handler, *last)
            else:
                self._no_upstream += 1
                tel.counter("frontdoor/no_upstream").add()
                self._respond(
                    handler, 503, {"error": "no routable replica"},
                    headers={"Retry-After":
                             f"{self.policy['retry_after_s']:g}"})
        finally:
            with self._lock:
                self._pending_total -= 1

    # -- control loop (single thread) ----------------------------------

    def _probe(self, budget: dl.Deadline) -> None:
        for up in list(self._ups.values()):
            doc = dl.fetch_json(
                f"http://{self.host}:{up.health_port}"
                f"{up.health_path}",
                self.probe_timeout_s, deadline=budget)
            with self._lock:
                if doc is None:
                    if up.alive:
                        up.consecutive_failures += 1
                    continue
                serve = doc.get("serve")
                if not isinstance(serve, dict):
                    # probe hit a /livez (tier.stats() body) directly
                    serve = doc if "queue_depth" in doc else {}
                up.alive = True
                up.consecutive_failures = 0
                age = doc.get("last_step_age_s")
                up.last_step_age_s = (float(age) if age is not None
                                      else None)
                up.draining = bool(serve.get("draining"))
                lin = serve.get("checkpoint")
                if isinstance(lin, dict) and lin.get("sha256"):
                    up.lineage = lin

    def _apply_health(self) -> None:
        with self._lock:
            snaps = [u.snapshot() for u in self._ups.values()
                     if u.alive]
        for d in decide_health(self.policy, snaps):
            with self._lock:
                up = self._ups[d["id"]]
                up.ejected = d["action"] == "eject"
            logging.info(f"frontdoor: {d['action']} replica "
                         f"{d['id']} — {d['reason']}")
            self._event(f"frontdoor/{d['action']}", id=d["id"],
                        reason=d["reason"])

    def _reload_replica(self, uid: int, path: str) -> bool:
        up = self._ups.get(int(uid))
        if up is None:
            return False
        status, body = dl.post_json(
            f"http://{self.host}:{up.predict_port}/admin/reload",
            {"checkpoint": path}, timeout_s=self.reload_timeout_s)
        if status != 200:
            logging.warning(f"frontdoor: reload of replica {uid} -> "
                            f"{path} answered {status} {body}")
        return status == 200

    def _drain_replica(self, uid: int) -> bool:
        up = self._ups.get(int(uid))
        if up is None:
            return False
        status, _ = dl.post_json(
            f"http://{self.host}:{up.predict_port}/admin/drain", {},
            timeout_s=self.drain_timeout_s)
        if status == 200:
            with self._lock:
                up.draining = True
        return status == 200

    def _autoscale(self, samples: List[Dict[str, Any]]) -> None:
        if self._scale_cfg is None or not samples:
            return
        decision = ctrl.decide_scale(self._scale_cfg,
                                     self._scale_state, samples)
        if decision["action"] == "none":
            return
        t = float(samples[-1]["t"])
        if decision["action"] == "up":
            if self._launcher is None or not self._launcher():
                logging.warning(
                    f"frontdoor: scale-up wanted ({decision['reason']})"
                    f" but no launcher is configured")
                return
            logging.info(f"frontdoor: scale UP {decision['world']} -> "
                         f"{decision['target']} ({decision['reason']})")
            self._event("controller/scale_up",
                        world=decision["world"],
                        target=decision["target"],
                        reason=decision["reason"])
        else:
            protected = list(self.rollout.canary_ids) \
                if self.rollout else []
            with self._lock:
                snaps = [u.snapshot() for u in self._ups.values()]
            victim = ctrl.pick_retire(routable_ids(snaps), protected)
            if victim is None or not self._drain_replica(victim):
                return
            logging.info(f"frontdoor: scale DOWN {decision['world']} "
                         f"-> {decision['target']}: draining replica "
                         f"{victim} ({decision['reason']})")
            self._event("controller/scale_down",
                        world=decision["world"],
                        target=decision["target"], id=victim,
                        reason=decision["reason"])
        self._scale_state["last_action_t"] = t
        self.scale_events.append(decision)

    def tick(self) -> None:
        """One control cycle: probe -> eject/readmit -> collect ->
        autoscale -> rollout.  The probe pass shares one deadline
        budget, so N wedged replicas cannot stretch a cycle past
        ~max(interval, one probe timeout)."""
        self.cycle += 1
        budget = dl.Deadline(max(self.interval_s, self.probe_timeout_s))
        self._probe(budget)
        self._apply_health()
        samples: List[Dict[str, Any]] = []
        if self._coll is not None:
            sample = self._coll.scrape_once()
            # surface the fleet-level sheds to the scale decider
            with self._lock:
                sample["counters"][FD_SHED_COUNTER] = float(self._shed)
            samples = list(self._coll._samples)
        self._autoscale(samples)
        if self.rollout is not None and self._watch_dir:
            with self._lock:
                snaps = [u.snapshot() for u in self._ups.values()]
            head = ro.newest_lineage_entry(self._watch_dir)
            self.rollout.tick(
                samples[-1]["t"] if samples else float(self.cycle)
                * self.interval_s, snaps, head)

    def run(self, max_cycles: int = 0,
            shutdown: Optional[threading.Event] = None) -> int:
        """The control loop: tick every ``interval_s`` until shutdown
        (or ``max_cycles`` for gates).  Returns cycles run."""
        while not (shutdown is not None and shutdown.is_set()):
            t0 = time.monotonic()
            self.tick()
            if max_cycles and self.cycle >= max_cycles:
                break
            rest = self.interval_s - (time.monotonic() - t0)
            if rest > 0:
                if shutdown is not None:
                    shutdown.wait(rest)
                else:
                    time.sleep(rest)
        return self.cycle

    # -- introspection -------------------------------------------------

    def status_doc(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "ok": True, "port": self.port, "cycle": self.cycle,
                "pending": self._pending_total,
                "answered": self._answered, "shed": self._shed,
                "no_upstream": self._no_upstream,
                "retries": self._retries,
                "client_codes": {str(k): v for k, v
                                 in sorted(self._client_codes.items())},
                "rollout": ({"phase": self.rollout.phase,
                             "stable": (self.rollout.stable_sha
                                        or "")[:12],
                             "canary_ids": self.rollout.canary_ids,
                             "rollbacks": self.rollout.rollbacks,
                             "promotions": self.rollout.promotions}
                            if self.rollout else None),
                "scale_events": len(self.scale_events),
                "upstreams": {str(u.id): u.snapshot()
                              for u in self._ups.values()},
            }


# -- CLI entry (main.py frontdoor) --------------------------------------

def run_cli(cfg) -> int:
    """``main.py frontdoor``: stand up the front door over
    ``--ranks`` replica slots (predict on ``serve_port + slot``,
    health on ``metrics_port + slot``), with optional autoscale
    (``--autoscale`` + ``--launch-cmd``) and rollout (``--rollout``).
    A monitoring/control process, never a member of the world — no JAX
    backend is touched."""
    import signal

    from .. import fleet, slo

    telemetry.configure(cfg.rsl_path, True, rank=FRONTDOOR_RANK)
    tel = telemetry.get()
    slos = slo.load_spec(cfg.slo_spec) if cfg.slo_spec else None
    max_world = cfg.fd_max_world or cfg.fd_ranks
    nslots = max(cfg.fd_ranks, max_world)
    replicas = {
        i: {"predict_port": cfg.serve_port + i,
            "health_port": ((cfg.metrics_port + i)
                            if cfg.metrics_port
                            else (cfg.serve_port + i)),
            "health_path": ("/healthz" if cfg.metrics_port
                            else "/livez")}
        for i in range(nslots)}
    collector = None
    if cfg.metrics_port:
        collector = fleet.FleetCollector(
            cfg.rsl_path, ranks=nslots,
            metrics_port=cfg.metrics_port,
            interval_s=cfg.fd_interval,
            stale_after=cfg.fleet_stale_after, port=0, slos=slos)
    scale_cfg = None
    launcher = None
    if cfg.fd_autoscale:
        scale_cfg = {"min_world": cfg.fd_min_world,
                     "max_world": max_world,
                     "queue_high": cfg.fd_queue_high,
                     "queue_low": cfg.fd_queue_low,
                     "up_hold_s": cfg.fd_up_hold,
                     "down_hold_s": cfg.fd_down_hold,
                     "cooldown_s": cfg.fd_cooldown}
        if cfg.fd_launch_cmd:
            launcher = SubprocessLauncher(
                cfg.fd_launch_cmd, log_dir=cfg.rsl_path).launch
    rollout_cfg = None
    watch_dir = None
    if cfg.fd_rollout:
        watch_dir = cfg.fd_watch_dir or cfg.rsl_path
        rollout_cfg = {"fraction": cfg.fd_canary_fraction,
                       "hold_s": cfg.fd_canary_hold,
                       "min_requests": cfg.fd_canary_min_requests,
                       "max_error_ratio": cfg.fd_canary_max_error,
                       "p95_factor": cfg.fd_canary_p95_factor}
    fd = FrontDoor(
        cfg.fd_port, replicas,
        policy={"eject_after": cfg.fd_eject_after,
                "max_step_age_s": cfg.fd_max_step_age,
                "pending_budget": cfg.fd_pending_budget,
                "retry_after_s": cfg.fd_retry_after},
        upstream_timeout_s=cfg.fd_upstream_timeout,
        interval_s=cfg.fd_interval, collector=collector,
        scale_cfg=scale_cfg, launcher=launcher,
        rollout_cfg=rollout_cfg, watch_dir=watch_dir)
    shutdown = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: shutdown.set())
    fd.start()
    tel.event("frontdoor_start", port=fd.port, slots=nslots,
              autoscale=bool(scale_cfg), rollout=bool(watch_dir))
    tel.flush()
    try:
        cycles = fd.run(max_cycles=cfg.fd_max_cycles,
                        shutdown=shutdown)
        doc = fd.status_doc()
        logging.info(
            f"frontdoor: stopped after {cycles} cycles — "
            f"{doc['answered']} answered, {doc['shed']} shed, "
            f"{len(fd.scale_events)} scale events, "
            f"{doc['rollout']['rollbacks'] if doc['rollout'] else 0} "
            f"rollbacks")
    finally:
        fd.close()
        tel.close()
    return 0
