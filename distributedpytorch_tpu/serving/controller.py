"""Autoscale policy for the serving fleet — pure and clock-free
(ISSUE 19 tentpole 2).

``decide_scale`` is a pure function of (config, controller state, the
fleet sample window) in the ``slo.evaluate`` style: samples carry
their own ordering time ``t`` (the collector's monotonic stamp), the
module never imports ``time``, and identical inputs give identical
decisions — so the ROADMAP's fleet-simulator direction can drive it at
N=100+ replicas exactly as the live front door drives it at 2.

The decision ladder, in priority order:

  repair     world below ``min_world`` (a replica died and aged out of
             the fleet series) -> scale UP immediately; capacity floors
             outrank hysteresis.
  scale up   sustained pressure: EVERY sample in the trailing
             ``up_hold_s`` window shows fleet queue depth >=
             ``queue_high``, OR any shed was counted inside the window,
             OR an SLO burn-rate verdict is firing.  The queue trigger
             is deliberately ahead of the shed trigger: a load ramp
             fills queues before it sheds, so the tier grows BEFORE the
             shed rate crosses a floor — the shed/burn triggers are the
             backstop, not the plan.
  scale dn   sustained idleness: EVERY sample in the trailing
             ``down_hold_s`` window shows queue depth <= ``queue_low``,
             zero shed movement, and no firing verdicts.

Both holds require the window to be fully COVERED by samples (there is
a sample at or before ``t - hold``): a young series never triggers.  A
``cooldown_s`` refractory period after any action plus the two
asymmetric holds are the hysteresis that keeps diurnal traffic — load
oscillating between the two thresholds — from flapping the world size
(pinned by tests/test_controller.py on a synthetic diurnal series).

The impure half (launching ``--elastic-join`` replicas, POSTing
``/admin/drain``) lives in the front door's control loop
(frontdoor.py), which also emits every non-``none`` decision as a
``controller/scale_*`` telemetry event for ``main.py timeline``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

#: queue-depth gauge in the fleet merged series (summed across ranks).
QUEUE_GAUGE = "dpt_serve_queue_depth"
#: shed counter in the fleet merged series.
SHED_COUNTER = "dpt_serve_shed_total"
#: the front door's own admission sheds, injected into the samples by
#: frontdoor.tick() — fleet-level backpressure counts as pressure too.
FD_SHED_COUNTER = "dpt_frontdoor_shed_total"

SCALE_DEFAULTS: Dict[str, Any] = {
    "min_world": 1,      # repair floor: below this, scale up now
    "max_world": 4,      # clamp: never launch past this
    "queue_high": 8.0,   # sustained fleet queue depth that means "grow"
    "queue_low": 1.0,    # sustained fleet queue depth that means "idle"
    "up_hold_s": 2.0,    # pressure must hold this long before growing
    "down_hold_s": 10.0,  # idleness must hold this long before retiring
    "cooldown_s": 5.0,   # refractory period after any action
}


def _cfg(cfg: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    out = dict(SCALE_DEFAULTS)
    out.update(cfg or {})
    return out


def _queue_depth(sample: Dict[str, Any]) -> float:
    """Fleet-wide queue depth of one sample: the merged gauge (fleet.py
    sums gauges across alive ranks at merge time)."""
    g = sample.get("gauges", {}).get(QUEUE_GAUGE, 0.0)
    if isinstance(g, dict):  # per-rank form: sum it ourselves
        return float(sum(float(v) for v in g.values()))
    return float(g or 0.0)


def _counter(sample: Dict[str, Any], name: str) -> float:
    return float(sample.get("counters", {}).get(name, 0.0))


def _shed_total(sample: Dict[str, Any]) -> float:
    """Replica-level 503s plus the front door's own admission sheds."""
    return _counter(sample, SHED_COUNTER) \
        + _counter(sample, FD_SHED_COUNTER)


def _firing(sample: Dict[str, Any]) -> List[str]:
    return [v.get("name", "?") for v in sample.get("verdicts", [])
            if v.get("firing")]


def _window(samples: Sequence[Dict[str, Any]], hold_s: float
            ) -> Optional[List[Dict[str, Any]]]:
    """The trailing ``hold_s`` of the series, or None when the series
    does not yet span it (no sample at/before the window start)."""
    if not samples:
        return None
    t = float(samples[-1]["t"])
    start = t - float(hold_s)
    if not any(float(s["t"]) <= start for s in samples):
        return None
    return [s for s in samples if float(s["t"]) >= start]


def decide_scale(cfg: Optional[Dict[str, Any]],
                 state: Optional[Dict[str, Any]],
                 samples: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Pure scale decision over the fleet sample window.

    ``state`` carries only ``last_action_t`` (the sample-clock time of
    the previous up/down action; the caller stamps it).  Returns
    ``{"action": "none"|"up"|"down", "reason", "world", "target"}`` —
    ``target`` is the post-action world size, clamped to
    [min_world, max_world].
    """
    c = _cfg(cfg)
    if not samples:
        return {"action": "none", "reason": "no samples", "world": 0,
                "target": 0}
    latest = samples[-1]
    t = float(latest["t"])
    world = len(latest.get("alive") or [])
    minw, maxw = int(c["min_world"]), int(c["max_world"])

    def none(reason: str) -> Dict[str, Any]:
        return {"action": "none", "reason": reason, "world": world,
                "target": world}

    last = (state or {}).get("last_action_t")
    if last is not None and (t - float(last)) < float(c["cooldown_s"]):
        return none(f"cooldown ({t - float(last):.1f}s since last "
                    f"action < {c['cooldown_s']:.1f}s)")

    # Repair outranks hysteresis: a dead replica is a capacity hole NOW.
    if world < minw:
        return {"action": "up",
                "reason": f"world {world} below min_world {minw}",
                "world": world, "target": min(world + 1, maxw)}

    up_w = _window(samples, c["up_hold_s"])
    if up_w is not None and world < maxw:
        depths = [_queue_depth(s) for s in up_w]
        if all(d >= float(c["queue_high"]) for d in depths):
            return {"action": "up",
                    "reason": f"queue depth >= {c['queue_high']:g} for "
                              f"{c['up_hold_s']:g}s (min "
                              f"{min(depths):g})",
                    "world": world, "target": min(world + 1, maxw)}
        shed = _shed_total(up_w[-1]) - _shed_total(up_w[0])
        if shed > 0:
            return {"action": "up",
                    "reason": f"{shed:g} requests shed inside the "
                              f"{c['up_hold_s']:g}s window",
                    "world": world, "target": min(world + 1, maxw)}
        firing = _firing(latest)
        if firing:
            return {"action": "up",
                    "reason": f"slo burn firing: {', '.join(firing)}",
                    "world": world, "target": min(world + 1, maxw)}

    down_w = _window(samples, c["down_hold_s"])
    if down_w is not None and world > minw:
        depths = [_queue_depth(s) for s in down_w]
        shed = _shed_total(down_w[-1]) - _shed_total(down_w[0])
        if all(d <= float(c["queue_low"]) for d in depths) \
                and shed <= 0 and not _firing(latest):
            return {"action": "down",
                    "reason": f"queue depth <= {c['queue_low']:g} for "
                              f"{c['down_hold_s']:g}s, zero shed",
                    "world": world, "target": max(world - 1, minw)}

    return none("no sustained pressure or idleness")


def pick_retire(candidates: Sequence[int],
                protected: Sequence[int] = ()) -> Optional[int]:
    """Which replica a scale-down drains: the HIGHEST eligible slot —
    joiners land on high slots, so the tier retires newest-first and
    the stable low slots (and anything ``protected``, e.g. a live
    canary) keep serving.  Pure; None when nothing is eligible."""
    pool = sorted(set(int(c) for c in candidates)
                  - set(int(p) for p in protected))
    return pool[-1] if pool else None
