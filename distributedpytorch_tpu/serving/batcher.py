"""The dynamic micro-batcher: a bounded queue that coalesces requests.

Two latency regimes, one rule.  Under load the queue always holds at
least the largest bucket, so every dispatch is a full batch at maximum
throughput.  At low traffic a lone request must not wait for
neighbors that never come: the OLDEST queued request carries a flush
deadline (``max_latency_s`` after admission), and when it expires the
driver dispatches whatever is pending, padded to the smallest bucket.

Backpressure is explicit and load is SHED, never queued unboundedly:
``admit()`` refuses once ``max_queue`` requests are waiting, and the
HTTP front end turns that refusal into a 503 the client sees
immediately — a saturated tier answers "try elsewhere" in
milliseconds instead of timing everyone out seconds later
(graftlint's unbounded-queue-in-server rule pins this shape for any
future handler code).

Threading model: HTTP handler threads call ``admit()``; ONE driver
thread calls ``next_batch()``.  All queue state is guarded by a single
condition variable.  Requests are host-side numpy payloads plus a
``threading.Event`` the handler thread waits on — so queued requests
survive an elastic reconfigure (no device state), and ``requeue()``
can put a batch back at the FRONT when the world changes mid-dispatch.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, List, Optional, Sequence, Tuple

from .planner import plan_batch


class QueueFullError(RuntimeError):
    """Raised by admit(block=False) callers that prefer an exception to
    a bool — the 503 signal."""


class Request:
    """One in-flight request: payload in, result or error out.  ``trace``
    is the request's span chain (tracing.RequestTrace) when tracing is
    on — the batcher stamps the queue-side transitions, the server the
    infer/respond ones."""

    __slots__ = ("payload", "enqueued_mono", "result", "error", "_done",
                 "trace")

    def __init__(self, payload: Any, trace: Any = None):
        self.payload = payload
        self.enqueued_mono = time.monotonic()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self._done = threading.Event()
        self.trace = trace

    def complete(self, result: Any) -> None:
        self.result = result
        self._done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self._done.set()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        """Block the handler thread until the driver answers.  False =
        still pending at the timeout (the front end's 504)."""
        return self._done.wait(timeout_s)

    def age_s(self) -> float:
        return time.monotonic() - self.enqueued_mono


class MicroBatcher:
    """Bounded coalescing queue between handler threads and the driver."""

    def __init__(self, buckets: Sequence[int], max_queue: int,
                 max_latency_s: float):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if max_latency_s <= 0:
            raise ValueError(
                f"max_latency_s must be > 0, got {max_latency_s}")
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.max_queue = int(max_queue)
        self.max_latency_s = float(max_latency_s)
        # deque growth is bounded by the explicit admit() check below —
        # deque(maxlen=...) would silently DROP requests instead of
        # shedding them with an answer, the exact failure mode the
        # backpressure contract exists to prevent.
        self._queue: collections.deque = collections.deque()
        self._cond = threading.Condition()
        self._closed = False

    # -- handler side --------------------------------------------------

    def admit(self, req: Request) -> bool:
        """Enqueue, or refuse (False) when the bound is hit / closing.
        The refusal IS the backpressure: the caller answers 503 now."""
        with self._cond:
            if self._closed or len(self._queue) >= self.max_queue:
                return False
            req.enqueued_mono = time.monotonic()
            if req.trace is not None:
                req.trace.mark_admitted()  # queue_wait starts HERE
            self._queue.append(req)
            self._cond.notify()
            return True

    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    # -- driver side ---------------------------------------------------

    def next_batch(self, timeout_s: float
                   ) -> Optional[Tuple[List[Request], int]]:
        """Block until a batch is READY, at most ``timeout_s``.

        Ready means: a full largest bucket is pending, or the oldest
        request's flush deadline passed.  Returns (requests, bucket) —
        ``len(requests) <= bucket``, the difference is padding — or
        None on timeout (the driver's chance to tick health/shutdown
        checks; pending-but-not-due requests stay queued and flush on
        a later call, so polling never loses the deadline)."""
        end = time.monotonic() + timeout_s
        with self._cond:
            while True:
                now = time.monotonic()
                if self._queue:
                    if len(self._queue) >= self.buckets[-1]:
                        break  # a full largest bucket: dispatch now
                    flush_at = (self._queue[0].enqueued_mono
                                + self.max_latency_s)
                    if now >= flush_at:
                        break  # oldest request's deadline: flush
                    wake = min(end, flush_at)
                else:
                    if self._closed:
                        return None
                    wake = end
                if wake - now <= 0:
                    return None
                self._cond.wait(wake - now)
            take, bucket, _pad = plan_batch(len(self._queue), self.buckets)
            reqs = [self._queue.popleft() for _ in range(take)]
            for r in reqs:
                if r.trace is not None:
                    r.trace.mark_dequeued()  # queue_wait ends, batch_form starts
            return reqs, bucket

    def requeue(self, reqs: List[Request]) -> None:
        """Put a dispatched-but-unanswered batch back at the FRONT (in
        order) — the elastic reconfigure path: the batch outlives the
        world that was about to compute it.  Ignores the bound on
        purpose: these requests were already admitted once."""
        with self._cond:
            for r in reversed(reqs):
                self._queue.appendleft(r)
            self._cond.notify()

    def close(self) -> List[Request]:
        """Refuse new admissions and drain the queue; the caller fails
        the drained requests (shutdown answers, never silence)."""
        with self._cond:
            self._closed = True
            drained = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        return drained
