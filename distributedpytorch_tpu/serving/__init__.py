"""The serving tier: batched, elastic inference over lineage-verified
checkpoints (``main.py serve`` — ISSUE 15).

The training side of this repo already owns everything a production
inference tier needs — self-describing checkpoints that convert across
param layouts at load (checkpoint.py + models/scan.py), AOT compilation
against the persistent XLA cache (bounded restart-to-first-response),
an elastic world manager that survives rank loss (elastic.py), and a
live ``/metrics`` exporter (goodput.py).  This package adds the three
missing pieces, deliberately JAX-free so the batching logic is unit-
testable without a backend:

  planner.py   the bucket planner: a fixed menu of AOT-compiled batch
               sizes (``--serve-buckets``) and the pick-largest-ready /
               pad-to-smallest decision for a pending queue
  batcher.py   the dynamic micro-batcher: a BOUNDED request queue that
               coalesces pending requests into the largest ready bucket
               under a ``--serve-max-latency-ms`` flush deadline, with
               explicit backpressure (admit() refuses when full — the
               HTTP front end turns that into a 503, never unbounded
               growth)
  server.py    the replica: a ThreadingHTTPServer front end whose
               handler threads ONLY validate + enqueue, and a single
               driver thread that runs the micro-batch loop, calls the
               injected ``infer_fn`` (the jitted predict program lives
               in cli.py), and ticks the elastic health boundary
               between batches

Replica topology: each process is one replica serving its own HTTP
port (``--serve-port + initial_rank``) over a replica-LOCAL device
mesh (runtime.make_serve_mesh) — requests shard across replicas at the
request level, so the predict program contains no cross-host
collectives and a replica's dispatch cadence is its own.  The shared
elastic world exists for membership only: a replica dying costs its
in-flight requests (its clients see the connection drop), the
survivors reconfigure at the next health tick and keep answering, and
``--elastic-join`` grows the tier back.  Queued requests are host-side
numpy arrays, so they SURVIVE a reconfigure: only the batch in flight
when the world broke is at risk — and that batch lives on the rank
that died.

ISSUE 19 adds the fleet front door on top — a separate JAX-free
control-plane process (``main.py frontdoor``) clients talk to instead
of picking a replica themselves:

  frontdoor.py  one client port: health-aware routing (probe, eject,
                readmit), fleet-level admission with Retry-After
                shedding, deadline-bounded proxying with one retry on
                another replica, and the control loop that feeds the
                two policy modules below
  controller.py the autoscale policy: pure decisions over the fleet
                collector's merged samples (queue depth, shed
                counters, SLO verdicts) with hysteresis, cooldown and
                min/max-world clamps
  rollout.py    the canary rollout policy + manager: watch the
                checkpoint lineage ledger, canary a newer verified
                checkpoint on a fraction of replicas via
                /admin/reload, promote or auto-roll-back on the
                canary-vs-stable error-rate/p95 comparison
"""

from .planner import parse_buckets, choose_bucket, plan_batch  # noqa: F401
from .batcher import MicroBatcher, Request, QueueFullError  # noqa: F401
from .server import ServingTier  # noqa: F401
