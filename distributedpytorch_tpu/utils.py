"""L1: utilities — logging, timing, RNG discipline.

Counterpart of the reference's utils.py grab-bag, minus what moved to
dedicated modules (model zoo → models/, checkpoint → checkpoint.py,
losses/metrics → ops/).
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading
import time
from typing import Tuple

import jax
import numpy as np


def initialize_logging(rsl_path: str, log_file: str,
                       truncate: bool = True) -> None:
    """File + stdout logging (ref: initializeLogging, utils.py:196-202).

    The reference opens the file with mode 'w' in *every* process, so ranks
    truncate each other's log.  Here only one process should call this with
    ``truncate=True``; others append — combined with the global process-index
    gate in runtime.is_main() this fixes SURVEY defect #7.
    """
    os.makedirs(rsl_path, exist_ok=True)
    mode = "w" if truncate else "a"
    root = logging.getLogger()
    # Re-invocation safe (the reference re-inits in every driver,
    # classif.py:79,201): clear stale handlers rather than stacking them.
    for h in list(root.handlers):
        root.removeHandler(h)
        h.close()
    logging.basicConfig(
        level=logging.INFO,
        format="%(message)s",
        handlers=[
            logging.FileHandler(os.path.join(rsl_path, log_file), mode=mode),
            logging.StreamHandler(sys.stdout),
        ],
    )


class GracefulShutdown:
    """SIGTERM/SIGINT -> finish the current epoch, checkpoint, exit clean.

    SURVEY §5 failure/elastic recovery: the reference's only story is
    manual restart with ``-f`` (ref main.py:46-48, classif.py:141-147) and
    a bare signal kills it wherever it happens to be.  Preemptible TPU VMs
    get SIGTERM with a grace window — under this context manager the signal
    only sets a flag; the driver checks ``requested`` at each epoch (or,
    under --epochs-per-dispatch K, each K-epoch chunk — one XLA dispatch is
    not interruptible) boundary after the rolling checkpoint is written,
    and stops cleanly, so the next run resumes with ``-f`` losing at most
    the interrupted epoch/chunk.  Multi-host: the break decision must be
    taken through ``runtime.any_process`` so every host leaves the loop at
    the SAME boundary — a lone host breaking early would deadlock the rest
    in the next collective.

    A SECOND signal restores the previous handler and re-raises, so a
    repeated Ctrl-C still force-aborts a hung or long-running dispatch.

    No-op outside the main thread (Python restricts signal handlers to it);
    ``requested`` simply stays False there.
    """

    def __init__(self):
        self.requested = False
        self._prev = {}

    def _handle(self, signum, frame):
        del frame
        if self.requested:  # second signal: escalate to a real abort
            logging.warning(f"second signal {signum}: aborting now")
            signal.signal(signum, self._prev.get(signum, signal.SIG_DFL))
            signal.raise_signal(signum)
            return
        self.requested = True
        # Telemetry point event, buffered (no file I/O in the handler);
        # the epoch-boundary flush or close() writes it out, so even a
        # preempted run's JSONL records when the signal landed.  Both
        # sinks take REENTRANT locks (the handler runs on the main
        # thread and may have interrupted a frame inside them).
        from . import flightrec, telemetry

        try:
            telemetry.get().event("preempt_signal", signum=int(signum))
            # The flight recorder DOES dump here (one bounded JSON
            # write): the grace window may be cut short by the
            # platform, and the black box is only worth carrying if it
            # survives the preempt.
            rec = flightrec.get()
            rec.record_event("preempt_signal", signum=int(signum))
            rec.dump("preempt_signal")
        # broad on purpose: an exception escaping a signal handler is
        # raised INTO the interrupted frame — a failed audit write must
        # never crash the epoch the graceful path is trying to finish
        except Exception:
            logging.exception("preempt handler: audit write failed")
        logging.warning(
            f"received signal {signum}: finishing the current epoch, "
            "then checkpointing and exiting (repeat to abort immediately)")

    def __enter__(self):
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def __exit__(self, *exc):
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        return False


def get_duration(start_time: float, end_time: float) -> Tuple[int, int]:
    """(minutes, seconds) split (ref: getDuration, utils.py:182-186)."""
    elapsed = end_time - start_time
    mins = int(elapsed / 60)
    secs = int(elapsed - mins * 60)
    return mins, secs


def monotonic() -> float:
    return time.monotonic()


def root_key(seed: int) -> jax.Array:
    """The run's root PRNG key (ref: setRandomSeed, utils.py:188-194).

    The reference seeds four global generators with the same value on every
    rank.  JAX's functional PRNG replaces all of that with one key; derive
    per-purpose streams with ``fold_key`` so data order, augmentation and
    init never collide.  XLA is deterministic by construction — there is no
    cudnn.benchmark equivalent to switch off.
    """
    return jax.random.PRNGKey(seed)


def fold_key(key: jax.Array, *ids: int) -> jax.Array:
    """Derive a substream, e.g. fold_key(root, epoch, process_index)."""
    for i in ids:
        key = jax.random.fold_in(key, i)
    return key


def epoch_numpy_rng(seed: int, epoch: int) -> np.random.Generator:
    """Host-side generator for the sampler permutation.

    Seeded from (seed, epoch) exactly like DistributedSampler's
    ``g.manual_seed(self.seed + self.epoch)`` (torch semantics the reference
    relies on via ref dataloader.py:147 + classif.py:164-165) — identical on
    every process so all ranks agree on the global permutation.
    """
    return np.random.default_rng(np.uint64(seed) + np.uint64(epoch))


def tree_size_bytes(tree) -> int:
    return sum(x.size * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(tree))


def largest_divisor_leq(n: int, limit: int) -> int:
    """Largest divisor of ``n`` that is <= ``limit`` (at least 1).
    Static-shape chunk sizing: MoE dispatch groups (models/moe.py) and
    the conv-dW VMEM batch chunk (ops/conv.py)."""
    d = max(1, min(n, limit))
    while n % d:
        d -= 1
    return d


def print_network_info(params) -> None:
    """Param inventory (ref: printNetworkInfo, utils.py:164-166 — fixed:
    the reference passes multiple args to logging.info and crashes)."""
    leaves = jax.tree_util.tree_leaves_with_path(params)
    total = 0
    for path, leaf in leaves:
        total += leaf.size
        logging.info(f"{jax.tree_util.keystr(path)}: "
                     f"{tuple(leaf.shape)} {leaf.dtype}")
    logging.info(f"total parameters: {total:,}")
