"""Root pytest conftest: force an 8-device virtual CPU mesh.

Tests exercise real SPMD semantics (mesh sharding, psum/pmean collectives)
without TPU hardware via ``--xla_force_host_platform_device_count=8`` —
the JAX equivalent of the reference author's "single node, loopback master"
trick (ref config.py:19-20).

This must run before anything initializes a JAX backend: the environment's
sitecustomize registers a TPU tunnel backend at interpreter startup, and
``jax.config.update('jax_platforms', 'cpu')`` re-points selection at the
host platform, while XLA_FLAGS (read at first backend init) fans it out to
8 virtual devices.  The recipe lives in ``__graft_entry__._force_cpu_devices``
(shared with the driver's multi-chip dry-run so the two cannot drift).
Set DPT_TESTS_ON_TPU=1 to run the suite on real chips.
"""

import os

if os.environ.get("DPT_TESTS_ON_TPU") != "1":
    from __graft_entry__ import _force_cpu_devices

    _force_cpu_devices(8)
