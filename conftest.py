"""Root pytest conftest: force an 8-device virtual CPU mesh.

Tests exercise real SPMD semantics (mesh sharding, psum/pmean collectives)
without TPU hardware via ``--xla_force_host_platform_device_count=8`` —
the JAX equivalent of the reference author's "single node, loopback master"
trick (ref config.py:19-20).

This must run before anything initializes a JAX backend: the environment's
sitecustomize registers a TPU tunnel backend at interpreter startup, and
``jax.config.update('jax_platforms', 'cpu')`` re-points selection at the
host platform, while XLA_FLAGS (read at first backend init) fans it out to
8 virtual devices.  Set DPT_TESTS_ON_TPU=1 to run the suite on real chips.
"""

import os

if os.environ.get("DPT_TESTS_ON_TPU") != "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    # One synchronous dispatch at a time: with a single host core, queueing
    # several 8-participant collective programs can starve XLA:CPU's 40s
    # rendezvous (observed as SIGABRT in rendezvous.cc).
    jax.config.update("jax_cpu_enable_async_dispatch", False)
