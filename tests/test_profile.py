"""SURVEY §5 tracing: --profile writes a jax.profiler trace of the first
post-compile epoch; print_network_info logs the param inventory (the
reference defines printNetworkInfo but it is unused AND crashes —
ref utils.py:164-166)."""

import logging
import os

import jax
import numpy as np

from distributedpytorch_tpu import utils
from distributedpytorch_tpu.cli import run_train
from distributedpytorch_tpu.config import Config
from distributedpytorch_tpu.models import get_model


def test_profile_flag_writes_trace(tmp_path):
    cfg = Config(action="train", data_path="/tmp/nodata",
                 rsl_path=str(tmp_path), dataset="synthetic",
                 model_name="mlp", batch_size=8, nb_epochs=2, debug=True,
                 half_precision=False, profile=True)
    result = run_train(cfg)
    assert len(result["history"]) == 2
    trace_dir = tmp_path / "trace"
    assert trace_dir.is_dir()
    # at least one trace artifact landed under the directory
    found = [f for _, _, fs in os.walk(trace_dir) for f in fs]
    assert found, "profiler trace directory is empty"
    assert "profiler trace written" in (tmp_path / cfg.log_file).read_text()


def test_print_network_info_logs_inventory(caplog):
    model = get_model("mlp", 10, half_precision=False)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 28, 28, 3), np.float32),
                        train=False)["params"]
    with caplog.at_level(logging.INFO):
        utils.print_network_info(params)
    assert any("total parameters" in r.message for r in caplog.records)
