"""ShardedLoader prefetch-queue observability is keyed PER EPOCH
GENERATOR (ISSUE 3 satellite): two interleaved epoch() iterations must
expose distinct lookahead structures via queue_for(), instead of the
pre-fix behavior where self._queue reflected only the most recent
epoch() call and interleaved iterations clobbered each other's view.
"""

import numpy as np

from distributedpytorch_tpu import runtime, telemetry
from distributedpytorch_tpu.data.datasets import Split
from distributedpytorch_tpu.data.io import make_synthetic
from distributedpytorch_tpu.data.pipeline import ShardedLoader


def _loader(prefetch=2, producer_threads=0):
    tr_x, tr_y, _, _ = make_synthetic(num_train=64, num_test=8,
                                      image_size=28, channels=1, seed=0)
    mesh = runtime.make_mesh()
    return ShardedLoader(Split(tr_x, tr_y), mesh, batch_per_replica=2,
                         shuffle=False, seed=0, prefetch=prefetch,
                         producer_threads=producer_threads)


def test_queue_none_before_first_iteration():
    loader = _loader()
    assert loader._queue is None
    assert loader.queue_for(0) is None


def test_interleaved_epochs_keep_distinct_queues():
    loader = _loader(prefetch=2)
    it0 = loader.epoch(0)
    it1 = loader.epoch(1)
    a0 = next(it0)           # starts epoch 0's generator + queue
    b0 = next(it1)           # starts epoch 1's generator + queue
    q0, q1 = loader.queue_for(0), loader.queue_for(1)
    assert q0 is not None and q1 is not None
    assert q0 is not q1      # pre-fix: the second call clobbered this
    # _queue (compat handle) tracks the most recently STARTED epoch
    assert loader._queue is q1

    # draining one epoch leaves the other's queue untouched and usable
    rest0 = list(it0)
    assert loader.queue_for(0) is q0
    assert loader.queue_for(1) is q1 and len(q1) > 0
    rest1 = list(it1)

    n = len(loader)
    assert 1 + len(rest0) == n and 1 + len(rest1) == n
    # unshuffled loader: both epochs saw identical batch streams
    np.testing.assert_array_equal(np.asarray(a0[0]), np.asarray(b0[0]))


def test_interleaved_epochs_threaded_keyed():
    loader = _loader(prefetch=2, producer_threads=2)
    it0 = loader.epoch(0)
    it1 = loader.epoch(1)
    next(it0)
    next(it1)
    q0, q1 = loader.queue_for(0), loader.queue_for(1)
    assert isinstance(q0, list) and isinstance(q1, list)
    assert q0 is not q1
    it0.close()              # clean producer shutdown mid-epoch
    n1 = 1 + sum(1 for _ in it1)
    assert n1 == len(loader)


def test_rerunning_same_epoch_rebinds_its_key():
    loader = _loader(prefetch=2)
    list(loader.epoch(0))
    first = loader.queue_for(0)
    list(loader.epoch(0))
    assert loader.queue_for(0) is not first


def test_queue_history_bounded():
    loader = _loader(prefetch=2)
    for e in range(loader._QUEUE_HISTORY + 3):
        list(loader.epoch(e))
    assert len(loader._queues) == loader._QUEUE_HISTORY
    assert loader.queue_for(0) is None  # oldest pruned


def test_interleaved_wait_accounting_still_sums(tmp_path,
                                                monkeypatch):
    """data/wait_s stays a process-global cumulative counter; the keyed
    queues fix the INTROSPECTION clobbering.  Interleaving two epochs
    must still count every batch exactly once."""
    loader = _loader(prefetch=2)
    tel = telemetry.configure(str(tmp_path), enabled=True, rank=0)
    try:
        it0, it1 = loader.epoch(0), loader.epoch(1)
        done0 = done1 = False
        n = 0
        while not (done0 and done1):
            for it, attr in ((it0, "done0"), (it1, "done1")):
                try:
                    next(it)
                    n += 1
                except StopIteration:
                    if attr == "done0":
                        done0 = True
                    else:
                        done1 = True
        assert n == 2 * len(loader)
        assert tel.counter("data/batches").value == n
    finally:
        tel.close()
        telemetry.configure(str(tmp_path), enabled=False)
