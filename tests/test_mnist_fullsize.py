"""Real-MNIST readiness (VERDICT r3 missing #1): a FULL-SIZE fake corpus
(60k train / 10k test, 28x28 u8) in the torchvision on-disk layout
(MNIST/raw/*-ubyte.gz, ref dataloader.py:85-96), driven through the real
CLI — ``python main.py train -d .. `` / ``test -f ..`` as a user runs it
— covering argv parsing, the ``--dataset mnist`` IDX load, the mean/std
scan over all 60k pixels, the 90/10 split, one full training epoch,
checkpointing, and the eval pass.  After this, the only thing about real
MNIST this suite has not seen is the bytes themselves (no network egress
here; scripts/fetch_mnist.sh documents the fetch, BASELINE.md row 1b
holds the placeholder to fill when egress exists).

Runs as a SUBPROCESS on ONE virtual CPU device: at this scale the
8-virtual-device mesh hits XLA:CPU environment artifacts (a stochastic
collective-rendezvous deadlock on the single physical core, and
pathological GSPMD build times for the resident whole-epoch program —
see __graft_entry__._force_cpu_devices notes).  Multi-device SPMD
semantics are covered across the rest of the suite; THIS test's subject
is the real-data path at real size, which is mesh-width independent."""

import gzip
import os
import re
import struct
import sys

import numpy as np
import pytest

from tests._subproc import await_all, child_env

pytestmark = pytest.mark.slow


def _write_idx_gz(path, arr: np.ndarray) -> None:
    """MNIST wire format: >HBB magic (0, 0x08=u8, ndim) + >I dims + raw."""
    header = struct.pack(">HBB", 0, 0x08, arr.ndim)
    header += struct.pack(">" + "I" * arr.ndim, *arr.shape)
    with gzip.open(path, "wb", compresslevel=1) as f:
        f.write(header + arr.tobytes())


@pytest.fixture(scope="module")
def mnist_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("mnist_fullsize")
    raw = root / "MNIST" / "raw"
    os.makedirs(raw)
    rng = np.random.default_rng(1234)

    def corpus(n):
        # Learnable at full scale: the label is encoded in brightness,
        # surviving the train-time rotation/crop augmentation.
        labels = rng.integers(0, 10, size=(n,)).astype(np.uint8)
        base = (labels.astype(np.int32) * 24 + 12)[:, None, None]
        noise = rng.integers(-10, 11, size=(n, 28, 28))
        imgs = np.clip(base + noise, 0, 255).astype(np.uint8)
        return imgs, labels

    tr_x, tr_y = corpus(60000)
    te_x, te_y = corpus(10000)
    _write_idx_gz(raw / "train-images-idx3-ubyte.gz", tr_x)
    _write_idx_gz(raw / "train-labels-idx1-ubyte.gz", tr_y)
    _write_idx_gz(raw / "t10k-images-idx3-ubyte.gz", te_x)
    _write_idx_gz(raw / "t10k-labels-idx1-ubyte.gz", te_y)
    return str(root)


def _run_cli(args, log_path, timeout):
    env_extra = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
    }
    env = child_env()
    env.update(env_extra)
    out = open(log_path, "ab")
    import subprocess

    from tests._subproc import REPO
    p = subprocess.Popen([sys.executable, "main.py", *args], cwd=REPO,
                         env=env, stdout=out, stderr=out)
    await_all([p], [log_path], timeout=timeout)


def test_full_size_mnist_cli_train_and_test(mnist_dir, tmp_path):
    rsl = str(tmp_path / "rsl")
    train_log = str(tmp_path / "train_out.txt")
    _run_cli(["train", "-d", mnist_dir, "--rsl_path", rsl, "--model",
              "cnn", "-e", "1", "-b", "512", "--no-bf16"],
             train_log, timeout=1500)
    log = open(os.path.join(rsl, "test.log")).read()
    assert "Number of training examples: 54000" in log
    assert "Number of validation examples: 6000" in log
    assert re.search(r"Epoch: 0", log), log[-2000:]

    ckpt = os.path.join(rsl, "bestmodel-mnist-cnn.ckpt")
    assert os.path.exists(ckpt)
    test_log = str(tmp_path / "test_out.txt")
    _run_cli(["test", "-d", mnist_dir, "--rsl_path", rsl, "--no-bf16",
              "-b", "512", "-f", ckpt], test_log, timeout=900)
    log = open(os.path.join(rsl, "test.log")).read()
    m = re.search(r"Acc: ([0-9.]+)%", log)
    assert m, log[-2000:]
    # brightness encodes the label; one epoch at 54k samples must beat
    # chance by a wide margin if the full pipeline actually learned
    assert float(m.group(1)) > 50.0
