"""Pipeline parallelism (models/vit_pipeline.py): the GPipe schedule over
the 'model' mesh axis is EXACTLY a re-scheduling of the sequential block
chain — pinned forward and backward on the 8-device virtual mesh, then
end-to-end through the CLI."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu import runtime
from distributedpytorch_tpu.cli import run_train
from distributedpytorch_tpu.config import Config
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.models.vit_pipeline import (
    PipelinedViT, make_pipeline_fn, sequential_blocks)

DIM, DEPTH, HEADS = 64, 4, 4


def _stacked_params(key):
    d, dep = DIM, DEPTH
    ks = jax.random.split(key, 6)
    init = jax.nn.initializers.lecun_normal(batch_axis=0)
    return {
        "ln1_scale": jnp.ones((dep, d), jnp.float32),
        "ln1_bias": jnp.zeros((dep, d), jnp.float32),
        "qkv_kernel": init(ks[0], (dep, d, 3 * d), jnp.float32),
        "qkv_bias": jnp.zeros((dep, 3 * d), jnp.float32),
        "proj_kernel": init(ks[1], (dep, d, d), jnp.float32),
        "proj_bias": jnp.zeros((dep, d), jnp.float32),
        "ln2_scale": jnp.ones((dep, d), jnp.float32),
        "ln2_bias": jnp.zeros((dep, d), jnp.float32),
        "up_kernel": init(ks[2], (dep, d, 4 * d), jnp.float32),
        "up_bias": jnp.zeros((dep, 4 * d), jnp.float32),
        "down_kernel": init(ks[3], (dep, 4 * d, d), jnp.float32),
        "down_bias": jnp.zeros((dep, d), jnp.float32),
    }


@pytest.mark.parametrize("n_stages", [2, 4])
def test_pipeline_matches_sequential(n_stages):
    mesh = runtime.make_mesh(model_parallel=n_stages)
    params = _stacked_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, DIM), jnp.float32)

    want = sequential_blocks(params, x, HEADS, DEPTH)
    pipe = make_pipeline_fn(mesh, n_stages, DEPTH, HEADS)
    got = jax.jit(pipe)(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match_sequential():
    n_stages = 4
    mesh = runtime.make_mesh(model_parallel=n_stages)
    params = _stacked_params(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 16, DIM), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(4), (8, 16, DIM), jnp.float32)
    pipe = make_pipeline_fn(mesh, n_stages, DEPTH, HEADS)

    g_seq = jax.grad(lambda p: jnp.sum(
        sequential_blocks(p, x, HEADS, DEPTH) * w))(params)
    g_pipe = jax.jit(jax.grad(lambda p: jnp.sum(pipe(p, x) * w)))(params)
    for k in g_seq:
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]),
            rtol=5e-5, atol=5e-5, err_msg=f"grad {k} mismatch")


@pytest.mark.parametrize("n_stages,n_micro", [(2, 8), (4, 8)])
def test_pipeline_more_microbatches_matches_sequential(n_stages, n_micro):
    """n_micro > n_stages (the bubble-shrinking regime,
    --pipeline-microbatches): same numerics, forward and backward."""
    mesh = runtime.make_mesh(model_parallel=n_stages)
    params = _stacked_params(jax.random.PRNGKey(6))
    # 8 data shards x n_micro rows per shard
    dp = 8 // n_stages
    x = jax.random.normal(jax.random.PRNGKey(7),
                          (dp * n_micro, 16, DIM), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(8), x.shape, jnp.float32)
    pipe = make_pipeline_fn(mesh, n_stages, DEPTH, HEADS, n_micro=n_micro)

    want = sequential_blocks(params, x, HEADS, DEPTH)
    got = jax.jit(pipe)(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    g_seq = jax.grad(lambda p: jnp.sum(
        sequential_blocks(p, x, HEADS, DEPTH) * w))(params)
    g_pipe = jax.jit(jax.grad(lambda p: jnp.sum(pipe(p, x) * w)))(params)
    for k in g_seq:
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]),
            rtol=5e-5, atol=5e-5, err_msg=f"grad {k} mismatch")


def test_pipeline_schedule_tick_count():
    """The GPipe schedule runs EXACTLY n_stages + n_micro - 1 ticks: the
    scan length is visible in the traced jaxpr, so the schedule (not
    just its numerics) is pinned."""
    n_stages, n_micro = 4, 8
    mesh = runtime.make_mesh(model_parallel=n_stages)
    params = _stacked_params(jax.random.PRNGKey(0))
    x = jnp.zeros((2 * n_micro, 16, DIM), jnp.float32)
    pipe = make_pipeline_fn(mesh, n_stages, DEPTH, HEADS, n_micro=n_micro)
    jaxpr = str(jax.make_jaxpr(pipe)(params, x))
    assert f"length={n_stages + n_micro - 1}" in jaxpr, (
        "expected a GPipe tick scan of length P+M-1 in the program")


def test_pipelined_vit_model_matches_unpipelined():
    mesh = runtime.make_mesh(model_parallel=4)
    x = jax.random.normal(jax.random.PRNGKey(5), (8, 28, 28, 3))
    plain = PipelinedViT(num_classes=10, dim=DIM, depth=DEPTH,
                         heads=HEADS, dtype=jnp.float32)
    params = plain.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    want = plain.apply({"params": params}, x)
    piped = PipelinedViT(num_classes=10, dim=DIM, depth=DEPTH,
                         heads=HEADS, dtype=jnp.float32,
                         pipeline_fn=make_pipeline_fn(mesh, 4, DEPTH,
                                                      HEADS))
    got = jax.jit(lambda p, a: piped.apply({"params": p}, a))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
@pytest.mark.parametrize("n_micro,batch", [(0, 4), (4, 8)])
def test_pipeline_cli_trains(tmp_path, n_micro, batch):
    # batch (per-replica) sized so each data shard's batch
    # (batch x model_parallel) holds >= M microbatch rows and the
    # pipeline actually engages (run_train validates this)
    res = run_train(Config(
        action="train", data_path="/tmp/nodata",
        rsl_path=str(tmp_path / "pp"), dataset="synthetic",
        model_name="vit", batch_size=batch, nb_epochs=1, debug=True,
        half_precision=False, model_parallel=2, pipeline_parallel=True,
        pipeline_microbatches=n_micro))
    h = res["history"][0]
    assert np.isfinite(h["train_loss"]) and np.isfinite(h["valid_loss"])
    assert 0.0 <= h["train_acc"] <= 1.0


def test_pipeline_cli_batch_validation(tmp_path):
    """A per-data-shard batch that cannot hold the M microbatches must
    fail fast (NOT silently train the sequential schedule)."""
    with pytest.raises(ValueError, match="per-data-shard batch"):
        run_train(Config(
            action="train", data_path="/tmp/nodata",
            rsl_path=str(tmp_path / "bad"), dataset="synthetic",
            model_name="vit", batch_size=1, nb_epochs=1, debug=True,
            half_precision=False, model_parallel=2,
            pipeline_parallel=True, pipeline_microbatches=4))
    with pytest.raises(ValueError, match="requires --pipeline-parallel"):
        run_train(Config(
            action="train", data_path="/tmp/nodata",
            rsl_path=str(tmp_path / "bad2"), dataset="synthetic",
            model_name="vit", batch_size=8, nb_epochs=1, debug=True,
            half_precision=False, pipeline_microbatches=4))


def test_layout_conversion_roundtrip_and_cross_model():
    """convert_layout: stacked (PipelinedViT) <-> per-block (ViT) — the
    SAME weights produce the same logits through either model, and a
    stacked->blocks->stacked round trip is bitwise."""
    from distributedpytorch_tpu.models.vit import ViT
    from distributedpytorch_tpu.models.vit_pipeline import (
        convert_layout, params_layout)

    x = jax.random.normal(jax.random.PRNGKey(9), (4, 28, 28, 3))
    piped = PipelinedViT(num_classes=10, dtype=jnp.float32)
    p_params = piped.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    want = piped.apply({"params": p_params}, x)

    from flax import serialization
    sd = serialization.to_state_dict(p_params)
    assert params_layout(sd) == "stacked"
    blocks_sd = convert_layout(sd, "blocks")
    assert params_layout(blocks_sd) == "blocks"

    plain = ViT(num_classes=10, dtype=jnp.float32)
    v_init = plain.init({"params": jax.random.PRNGKey(1)}, x)["params"]
    v_params = serialization.from_state_dict(v_init, blocks_sd)
    got = plain.apply({"params": v_params}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    back = convert_layout(blocks_sd, "stacked")
    for k, v in sd.items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(back[k]),
                                      err_msg=f"round-trip {k}")


@pytest.mark.slow
def test_pipeline_checkpoint_tests_without_pipeline_mesh(tmp_path):
    """VERDICT r3 weak #6: a --pipeline-parallel-trained checkpoint must
    `test -f` on a plain (no pipeline mesh) config — load_checkpoint
    converts the stacked layout to per-block at restore time."""
    from distributedpytorch_tpu.cli import run_test

    rsl = str(tmp_path / "pp")
    run_train(Config(
        action="train", data_path="/tmp/nodata", rsl_path=rsl,
        dataset="synthetic", model_name="vit", batch_size=8, nb_epochs=1,
        debug=True, half_precision=False, model_parallel=2,
        pipeline_parallel=True))
    ckpt_file = f"{rsl}/bestmodel-synthetic-vit.ckpt"
    res = run_test(Config(
        action="test", data_path="/tmp/nodata", rsl_path=rsl,
        dataset="synthetic", debug=True, half_precision=False,
        checkpoint_file=ckpt_file))
    assert res["model_name"] == "vit"
    assert np.isfinite(res["test_loss"])
    assert 0.0 <= res["test_acc"] <= 1.0


def test_pipeline_orbax_checkpoint_tests_without_pipeline_mesh(tmp_path):
    """VERDICT r4 missing #4: the SAME cross-layout contract for the
    orbax format — a --pipeline-parallel-trained orbax DIRECTORY must
    `test -f` on a plain config.  _load_orbax reads meta.json's
    params_layout, restores into a stacked-shaped abstract tree, and
    converts to the per-block layout."""
    pytest.importorskip("orbax.checkpoint")
    from distributedpytorch_tpu.cli import run_test

    rsl = str(tmp_path / "pporb")
    run_train(Config(
        action="train", data_path="/tmp/nodata", rsl_path=rsl,
        dataset="synthetic", model_name="vit", batch_size=8, nb_epochs=1,
        debug=True, half_precision=False, model_parallel=2,
        pipeline_parallel=True, ckpt_format="orbax"))
    ckpt_dir = f"{rsl}/bestmodel-synthetic-vit.ckpt"
    assert os.path.isdir(ckpt_dir)
    res = run_test(Config(
        action="test", data_path="/tmp/nodata", rsl_path=rsl,
        dataset="synthetic", debug=True, half_precision=False,
        checkpoint_file=ckpt_dir))
    assert res["model_name"] == "vit"
    assert np.isfinite(res["test_loss"])
    assert 0.0 <= res["test_acc"] <= 1.0


def test_pipeline_validation():
    mesh2 = runtime.make_mesh(model_parallel=2)
    with pytest.raises(ValueError, match="attention model family"):
        get_model("cnn", 10, pipeline_parallel=True, mesh=mesh2)
    with pytest.raises(ValueError, match="exclusive"):
        get_model("vit", 10, pipeline_parallel=True, attention="flash",
                  mesh=mesh2)
    with pytest.raises(ValueError, match="model-parallel"):
        get_model("vit", 10, pipeline_parallel=True,
                  mesh=runtime.make_mesh())

def test_ring_pipeline_matches_sequential():
    """VERDICT r5 item 7 (the composition): GPipe stages over 'model'
    WITH ring attention over 'seq' on a 3-D (2 data, 2 stage, 2 seq)
    mesh — forward and gradients pinned to the plain sequential
    schedule, on a token count (18) that does NOT divide the ring
    (pads to 20, kv_valid masks the pad)."""
    mesh = runtime.make_mesh(model_parallel=2, seq_parallel=2)
    assert mesh.shape == {"data": 2, "model": 2, "seq": 2}
    params = _stacked_params(jax.random.PRNGKey(10))
    x = jax.random.normal(jax.random.PRNGKey(11), (8, 18, DIM),
                          jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(12), (8, 18, DIM),
                          jnp.float32)

    want = sequential_blocks(params, x, HEADS, DEPTH)
    pipe = make_pipeline_fn(mesh, 2, DEPTH, HEADS, ring=True)
    got = jax.jit(pipe)(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    g_seq = jax.grad(lambda p: jnp.sum(
        sequential_blocks(p, x, HEADS, DEPTH) * w))(params)
    g_pipe = jax.jit(jax.grad(lambda p: jnp.sum(pipe(p, x) * w)))(params)
    for k in g_seq:
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]),
            rtol=5e-5, atol=5e-5, err_msg=f"grad {k} mismatch")


def test_ring_pipeline_requires_seq_axis():
    mesh2 = runtime.make_mesh(model_parallel=2)
    with pytest.raises(ValueError, match="seq-parallel"):
        make_pipeline_fn(mesh2, 2, DEPTH, HEADS, ring=True)


@pytest.mark.slow
def test_ring_pipeline_cli_train_and_test(tmp_path):
    """Ring x pipeline end-to-end through the CLI on the 3-D mesh, then
    `test -f` BOTH with the matching flags (3-D mesh rebuild) and plain
    (stacked->blocks conversion) — both must produce the same loss."""
    from distributedpytorch_tpu.cli import run_test

    rsl = str(tmp_path / "ringpp")
    run_train(Config(
        action="train", data_path="/tmp/nodata", rsl_path=rsl,
        dataset="synthetic", model_name="vit", attention="ring",
        pipeline_parallel=True, model_parallel=2, seq_parallel=2,
        batch_size=2, nb_epochs=1, debug=True, half_precision=False))
    ck = f"{rsl}/bestmodel-synthetic-vit.ckpt"
    same = run_test(Config(
        action="test", data_path="/tmp/nodata", rsl_path=rsl,
        dataset="synthetic", debug=True, half_precision=False,
        checkpoint_file=ck, attention="ring", pipeline_parallel=True,
        model_parallel=2, seq_parallel=2, batch_size=2))
    plain = run_test(Config(
        action="test", data_path="/tmp/nodata", rsl_path=rsl,
        dataset="synthetic", debug=True, half_precision=False,
        checkpoint_file=ck))
    assert np.isfinite(same["test_loss"])
    np.testing.assert_allclose(same["test_loss"], plain["test_loss"],
                               rtol=1e-5)


def test_seq_parallel_validation(tmp_path):
    """--seq-parallel without the ring x pipeline combination must fail
    fast, not silently build a 2-D mesh."""
    with pytest.raises(ValueError, match="seq-parallel"):
        run_train(Config(
            action="train", data_path="/tmp/nodata",
            rsl_path=str(tmp_path / "sp"), dataset="synthetic",
            model_name="vit", seq_parallel=2, batch_size=4, nb_epochs=1,
            debug=True, half_precision=False))
