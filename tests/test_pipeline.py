"""Pipeline parallelism (models/vit_pipeline.py): the GPipe schedule over
the 'model' mesh axis is EXACTLY a re-scheduling of the sequential block
chain — pinned forward and backward on the 8-device virtual mesh, then
end-to-end through the CLI."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu import runtime
from distributedpytorch_tpu.cli import run_train
from distributedpytorch_tpu.config import Config
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.models.vit_pipeline import (
    PipelinedViT, make_pipeline_fn, sequential_blocks)

DIM, DEPTH, HEADS = 64, 4, 4


def _stacked_params(key):
    d, dep = DIM, DEPTH
    ks = jax.random.split(key, 6)
    init = jax.nn.initializers.lecun_normal(batch_axis=0)
    return {
        "ln1_scale": jnp.ones((dep, d), jnp.float32),
        "ln1_bias": jnp.zeros((dep, d), jnp.float32),
        "qkv_kernel": init(ks[0], (dep, d, 3 * d), jnp.float32),
        "qkv_bias": jnp.zeros((dep, 3 * d), jnp.float32),
        "proj_kernel": init(ks[1], (dep, d, d), jnp.float32),
        "proj_bias": jnp.zeros((dep, d), jnp.float32),
        "ln2_scale": jnp.ones((dep, d), jnp.float32),
        "ln2_bias": jnp.zeros((dep, d), jnp.float32),
        "up_kernel": init(ks[2], (dep, d, 4 * d), jnp.float32),
        "up_bias": jnp.zeros((dep, 4 * d), jnp.float32),
        "down_kernel": init(ks[3], (dep, 4 * d, d), jnp.float32),
        "down_bias": jnp.zeros((dep, d), jnp.float32),
    }


@pytest.mark.parametrize("n_stages", [2, 4])
def test_pipeline_matches_sequential(n_stages):
    mesh = runtime.make_mesh(model_parallel=n_stages)
    params = _stacked_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16, DIM), jnp.float32)

    want = sequential_blocks(params, x, HEADS, DEPTH)
    pipe = make_pipeline_fn(mesh, n_stages, DEPTH, HEADS)
    got = jax.jit(pipe)(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match_sequential():
    n_stages = 4
    mesh = runtime.make_mesh(model_parallel=n_stages)
    params = _stacked_params(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (8, 16, DIM), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(4), (8, 16, DIM), jnp.float32)
    pipe = make_pipeline_fn(mesh, n_stages, DEPTH, HEADS)

    g_seq = jax.grad(lambda p: jnp.sum(
        sequential_blocks(p, x, HEADS, DEPTH) * w))(params)
    g_pipe = jax.jit(jax.grad(lambda p: jnp.sum(pipe(p, x) * w)))(params)
    for k in g_seq:
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]),
            rtol=5e-5, atol=5e-5, err_msg=f"grad {k} mismatch")


def test_pipelined_vit_model_matches_unpipelined():
    mesh = runtime.make_mesh(model_parallel=4)
    x = jax.random.normal(jax.random.PRNGKey(5), (8, 28, 28, 3))
    plain = PipelinedViT(num_classes=10, dim=DIM, depth=DEPTH,
                         heads=HEADS, dtype=jnp.float32)
    params = plain.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    want = plain.apply({"params": params}, x)
    piped = PipelinedViT(num_classes=10, dim=DIM, depth=DEPTH,
                         heads=HEADS, dtype=jnp.float32,
                         pipeline_fn=make_pipeline_fn(mesh, 4, DEPTH,
                                                      HEADS))
    got = jax.jit(lambda p, a: piped.apply({"params": p}, a))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_cli_trains(tmp_path):
    res = run_train(Config(
        action="train", data_path="/tmp/nodata",
        rsl_path=str(tmp_path / "pp"), dataset="synthetic",
        model_name="vit", batch_size=4, nb_epochs=1, debug=True,
        half_precision=False, model_parallel=2, pipeline_parallel=True))
    h = res["history"][0]
    assert np.isfinite(h["train_loss"]) and np.isfinite(h["valid_loss"])
    assert 0.0 <= h["train_acc"] <= 1.0


def test_pipeline_validation():
    mesh2 = runtime.make_mesh(model_parallel=2)
    with pytest.raises(ValueError, match="attention model family"):
        get_model("cnn", 10, pipeline_parallel=True, mesh=mesh2)
    with pytest.raises(ValueError, match="exclusive"):
        get_model("vit", 10, pipeline_parallel=True, attention="flash",
                  mesh=mesh2)
    with pytest.raises(ValueError, match="model-parallel"):
        get_model("vit", 10, pipeline_parallel=True,
                  mesh=runtime.make_mesh())