"""runtime._multihost_env: rendezvous must trigger on Cloud TPU pod
markers, not only on our own coordinator vars (VERDICT r1 weak #7)."""

from distributedpytorch_tpu import runtime


def test_no_markers_means_single_host(monkeypatch):
    for v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
              "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(v, raising=False)
    assert not runtime._multihost_env()


def test_explicit_coordinator_vars(monkeypatch):
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    assert runtime._multihost_env()


def test_pod_hostname_list(monkeypatch):
    for v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
              "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(v, raising=False)
    # single-host TPU VM: one entry -> NOT multi-host
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t1v-n-abc-w-0")
    assert not runtime._multihost_env()
    # pod slice: several workers -> multi-host
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w-0,w-1,w-2,w-3")
    assert runtime._multihost_env()


def test_env_only_rendezvous_two_processes(tmp_path):
    """The env:// contract for REAL (ref classif.py:86-87 reads its
    rendezvous from env vars; our launcher parity is JAX_COORDINATOR_
    ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID): two subprocesses export
    ONLY env vars, call initialize_distributed() with no arguments, and
    must complete an actual cross-process allgather.  This upgrades the
    multi-host discovery path from env-var unit tests to a real
    rendezvous (VERDICT r3 missing #2, as far as one host allows).

    Uses the shared _subproc scaffolding: log FILES (a full PIPE would
    block a chatty child mid-collective and deadlock the world) and
    await_all's shared deadline + straggler kill."""
    import subprocess
    import sys

    from tests._subproc import await_all, child_env, free_port

    port = free_port()
    child = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from distributedpytorch_tpu import runtime\n"
        "runtime.initialize_distributed()\n"  # argless: env only
        "import jax.numpy as jnp\n"
        "from jax.experimental.multihost_utils import process_allgather\n"
        "got = process_allgather(jnp.asarray([jax.process_index()]))\n"
        "assert got.reshape(-1).tolist() == [0, 1], got\n"
        "print('RANK', jax.process_index(), 'OK', flush=True)\n")

    procs, logs = [], []
    for r in range(2):
        env = child_env()
        env.update({
            "JAX_COORDINATOR_ADDRESS": f"localhost:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(r),
        })
        log = str(tmp_path / f"rank{r}.txt")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", child], env=env,
            stdout=open(log, "ab"), stderr=subprocess.STDOUT))
    await_all(procs, logs, timeout=240)
    for log in logs:
        assert "OK" in open(log).read()
