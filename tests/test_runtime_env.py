"""runtime._multihost_env: rendezvous must trigger on Cloud TPU pod
markers, not only on our own coordinator vars (VERDICT r1 weak #7)."""

from distributedpytorch_tpu import runtime


def test_no_markers_means_single_host(monkeypatch):
    for v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
              "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(v, raising=False)
    assert not runtime._multihost_env()


def test_explicit_coordinator_vars(monkeypatch):
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:1234")
    assert runtime._multihost_env()


def test_pod_hostname_list(monkeypatch):
    for v in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
              "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(v, raising=False)
    # single-host TPU VM: one entry -> NOT multi-host
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "t1v-n-abc-w-0")
    assert not runtime._multihost_env()
    # pod slice: several workers -> multi-host
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w-0,w-1,w-2,w-3")
    assert runtime._multihost_env()
