"""Fault-injection harness + retry policy (faults.py): plan parsing
(inline DSL and JSON file), deterministic site firing and hit windows,
retry schedule determinism, transient-vs-fatal classification, the
retry telemetry trail, and the zero-cost-when-disabled contract."""

import json
import os
import time

import pytest

from distributedpytorch_tpu import faults, flightrec, telemetry


@pytest.fixture(autouse=True)
def clean_plan():
    """Every test starts and ends with no installed plan — the module
    global must never leak between tests (or into the rest of the
    suite, where it would fire faults inside unrelated runs)."""
    faults.install(None)
    yield
    faults.install(None)
    telemetry._active = telemetry.Telemetry(enabled=False)
    flightrec._active = flightrec.FlightRecorder(enabled=False)


# -- plan parsing ------------------------------------------------------


def test_dsl_parses_sites_kinds_and_windows():
    plan = faults.parse_plan(
        "data.read:ioerror:0:2; ckpt.save:preempt:2", seed=7)
    assert plan.seed == 7
    assert [s.site for s in plan.specs] == ["data.read", "ckpt.save"]
    assert plan.specs[0].kind == "ioerror"
    assert (plan.specs[0].after_n, plan.specs[0].count) == (0, 2)
    assert (plan.specs[1].after_n, plan.specs[1].count) == (2, 1)
    assert plan.targets("data.read") and plan.targets("ckpt.save")
    assert not plan.targets("ckpt.restore")


def test_json_plan_roundtrips_with_filters(tmp_path):
    doc = {"seed": 3, "faults": [
        {"site": "ckpt.finalize", "kind": "torn", "after_n": 1,
         "count": 1, "rank": 0, "path_match": "checkpoint-"}]}
    path = tmp_path / "plan.json"
    path.write_text(json.dumps(doc))
    plan = faults.parse_plan(str(path))
    assert plan.seed == 3
    spec = plan.specs[0]
    assert (spec.rank, spec.path_match) == (0, "checkpoint-")


@pytest.mark.parametrize("bad, match", [
    ("nosuch.site:ioerror:0", "unknown fault site"),
    ("data.read:explode:0", "unknown fault kind"),
    ("data.read:ioerror", "expected 'site:kind:after_n"),
    ("data.read:ioerror:x", "must be integers"),
    ("", "empty fault plan"),
])
def test_bad_dsl_is_actionable(bad, match):
    with pytest.raises(ValueError, match=match):
        faults.parse_plan(bad)


def test_bad_json_plan_is_actionable(tmp_path):
    garbage = tmp_path / "plan.json"
    garbage.write_text("not json {")
    with pytest.raises(ValueError, match="cannot read fault plan"):
        faults.parse_plan(str(garbage))
    wrong_shape = tmp_path / "shape.json"
    wrong_shape.write_text(json.dumps({"faults": "nope"}))
    with pytest.raises(ValueError, match="'faults' list"):
        faults.parse_plan(str(wrong_shape))
    unknown_key = tmp_path / "key.json"
    unknown_key.write_text(json.dumps(
        {"faults": [{"site": "data.read", "kind": "ioerror",
                     "when": "later"}]}))
    with pytest.raises(ValueError, match="unknown key"):
        faults.parse_plan(str(unknown_key))


# -- site firing -------------------------------------------------------


def test_fire_hits_exact_window():
    faults.install(faults.parse_plan("data.read:ioerror:2:2"))
    faults.fire("data.read")  # hit 1: before the window
    faults.fire("data.read")  # hit 2: still before
    for _ in range(2):        # hits 3-4: the (after_n, after_n+count]
        with pytest.raises(faults.InjectedIOError):
            faults.fire("data.read")
    faults.fire("data.read")  # hit 5: past the window


def test_fatal_kind_raises_fatal():
    faults.install(faults.parse_plan("ckpt.save:fatal:0"))
    with pytest.raises(faults.FatalFaultError):
        faults.fire("ckpt.save")


def test_injected_ioerror_is_oserror_and_transient():
    assert issubclass(faults.InjectedIOError, OSError)
    assert any(issubclass(faults.InjectedIOError, t)
               for t in faults.TRANSIENT)


def test_torn_kind_truncates_file_and_continues(tmp_path):
    victim = tmp_path / "checkpoint-000.ckpt"
    victim.write_bytes(b"x" * 1000)
    faults.install(faults.parse_plan("ckpt.finalize:torn:0"))
    faults.fire("ckpt.finalize", path=str(victim))  # must NOT raise
    assert victim.stat().st_size == 500


def test_stall_kind_sleeps_and_continues():
    faults.install(faults.parse_plan("data.host_batch:stall:0:1:0.2"))
    t0 = time.perf_counter()
    faults.fire("data.host_batch")  # must NOT raise — it's a straggler
    assert time.perf_counter() - t0 >= 0.2
    t0 = time.perf_counter()
    faults.fire("data.host_batch")  # past the window: instant
    assert time.perf_counter() - t0 < 0.1


def test_stall_dsl_default_duration():
    plan = faults.parse_plan("data.host_batch:stall:3")
    assert plan.specs[0].kind == "stall"
    assert plan.specs[0].stall_s == pytest.approx(0.25)
    with pytest.raises(ValueError, match="stall_s"):
        faults.FaultSpec(site="data.read", kind="stall", stall_s=0.0)


def test_fault_firing_lands_in_flight_recorder(tmp_path):
    rec = flightrec.configure(str(tmp_path), True)
    faults.install(faults.parse_plan("data.read:ioerror:0:1"))
    with pytest.raises(faults.InjectedIOError):
        faults.fire("data.read")
    events = [r for r in rec._ring if r.get("kind") == "event"]
    assert [e["name"] for e in events] == ["fault_injected"]
    assert events[0]["site"] == "data.read"
    # the injected kind rides along as "fault_kind" — it must not
    # clobber the record schema's reserved "kind" field
    assert events[0]["fault_kind"] == "ioerror"


def test_path_match_filters_hits(tmp_path):
    # The hit counter advances on EVERY targeted fire — path_match only
    # filters which hits act — so the window must span both hits.
    plan = faults.FaultPlan([faults.FaultSpec(
        site="ckpt.finalize", kind="ioerror", path_match="best",
        count=2)])
    faults.install(plan)
    faults.fire("ckpt.finalize", path=str(tmp_path / "checkpoint-0"))
    with pytest.raises(faults.InjectedIOError):
        faults.fire("ckpt.finalize", path=str(tmp_path / "bestmodel"))


# -- zero-cost when disabled ------------------------------------------


def test_no_plan_is_a_noop():
    assert faults.installed() is None
    assert not faults.targets("data.read")
    for site in faults.SITES:  # one None check per call, nothing else
        faults.fire(site)


# -- retry policy ------------------------------------------------------


def test_retry_schedule_is_deterministic():
    p = faults.RetryPolicy(seed=5)
    a = [p._delay("data.read", k) for k in (1, 2, 3)]
    b = [p._delay("data.read", k) for k in (1, 2, 3)]
    assert a == b
    # exponential envelope with jitter in [0.5, 1.0] of the backoff
    for k, d in enumerate(a, start=1):
        backoff = min(p.max_delay_s, p.base_delay_s * 2.0 ** (k - 1))
        assert 0.5 * backoff <= d <= backoff
    # different sites / seeds jitter differently
    assert p._delay("ckpt.save", 1) != a[0]
    assert faults.RetryPolicy(seed=6)._delay("data.read", 1) != a[0]


def test_retry_recovers_after_transients(tmp_path):
    telemetry._active = telemetry.Telemetry(
        enabled=True, rsl_path=str(tmp_path), rank=0)
    faults.install(faults.parse_plan("data.read:ioerror:0:2"))
    calls = []

    def read():
        faults.fire("data.read")
        calls.append(1)
        return "payload"

    p = faults.RetryPolicy(base_delay_s=0.001, seed=0)
    assert p.call(read, "data.read") == "payload"
    assert len(calls) == 1  # two injected failures, third attempt wins
    tel = telemetry.get()
    assert tel.counter("retry/attempts").value == 2
    assert tel.counter("retry/giveups").value == 0


def test_retry_gives_up_after_max_attempts(tmp_path):
    telemetry._active = telemetry.Telemetry(
        enabled=True, rsl_path=str(tmp_path), rank=0)

    def always_fails():
        raise TimeoutError("unreachable")

    p = faults.RetryPolicy(max_attempts=3, base_delay_s=0.001)
    with pytest.raises(TimeoutError):
        p.call(always_fails, "runtime.init")
    tel = telemetry.get()
    assert tel.counter("retry/attempts").value == 2  # retries, not tries
    assert tel.counter("retry/giveups").value == 1


def test_fatal_and_nontransient_never_retried():
    attempts = []

    def fatal():
        attempts.append(1)
        raise faults.FatalFaultError("injected")

    p = faults.RetryPolicy(base_delay_s=0.001)
    with pytest.raises(faults.FatalFaultError):
        p.call(fatal, "ckpt.save")
    assert len(attempts) == 1  # attempt 1 included: no retry on fatal

    def missing():
        attempts.append(1)
        raise FileNotFoundError("no such checkpoint")

    with pytest.raises(FileNotFoundError):
        # narrowed transient tuple: FileNotFoundError is a plain OSError
        # but the caller classifies it fatal (retrying cannot help)
        p.call(missing, "ckpt.restore",
               transient=(PermissionError, TimeoutError))
    assert len(attempts) == 2


def test_retry_deadline_stops_further_attempts():
    p = faults.RetryPolicy(max_attempts=100, base_delay_s=0.001,
                           timeout_s=0.0)
    attempts = []

    def fails():
        attempts.append(1)
        raise TimeoutError("slow")

    with pytest.raises(TimeoutError):
        p.call(fails, "data.read")
    assert len(attempts) == 1  # deadline already passed after attempt 1


def test_configure_installs_plan_and_policy(tmp_path):
    faults.configure("data.read:ioerror:0", fault_seed=9,
                     retry_max_attempts=5, retry_base_delay_s=0.01,
                     retry_timeout_s=1.5)
    assert faults.targets("data.read")
    p = faults.policy()
    assert (p.max_attempts, p.base_delay_s, p.timeout_s, p.seed) \
        == (5, 0.01, 1.5, 9)
    faults.configure(None)  # re-invocation clears the plan
    assert faults.installed() is None


# -- handler reentrancy ------------------------------------------------


def test_plan_lock_is_reentrant_for_signal_handler_path():
    """FaultPlan.fire runs under telemetry's write path, which the
    GracefulShutdown signal handler re-enters ON THE SAME THREAD that
    may already be inside fire() — with a plain Lock the second acquire
    blocks forever (the PR 12 preempt-handler deadlock class, now
    caught statically by graftlint's lock-order-cycle rule)."""
    plan = faults.FaultPlan([], seed=0)
    assert plan._lock.acquire(blocking=False)
    try:
        # same-thread re-acquire must succeed immediately (RLock);
        # blocking=False keeps a regression a failure, not a hang
        assert plan._lock.acquire(blocking=False), \
            "FaultPlan._lock must be reentrant: the signal handler " \
            "re-enters fire() on the interrupted thread"
        plan._lock.release()
    finally:
        plan._lock.release()


def test_fire_reachable_while_plan_lock_held_same_thread():
    """End-to-end form: firing a site while the plan lock is already
    held by this thread (as a mid-fire signal handler would) completes
    instead of deadlocking."""
    faults.install(faults.parse_plan("data.read:ioerror:99"))
    plan = faults.installed()
    with plan._lock:
        assert faults.fire("data.read", path=None) is None  # hit 0 != 99
