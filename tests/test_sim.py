"""Deterministic fleet simulator (sim/, ISSUE 20): schema compat with
the live pipelines, byte-identical same-seed replay, the autoscaler's
oscillation bound in closed loop, and the joiner give-up telemetry.

The heavy fleet-scale proofs (N=100 chaos floors, the exact-incident
pin) live in ``scripts/sim_gate.py``; these tests pin the CONTRACTS a
refactor is most likely to tear: the simulator's artifacts must parse
through telemetry.aggregate / tracing.reconcile / goodput.report /
timeline.build_timeline with zero skips, and replaying a seed must
reproduce the event log byte for byte.  Everything runs the control
scenario at reduced duration — pure CPU, virtual clock, a few seconds.
"""

import json
import math
import os

import pytest

from distributedpytorch_tpu import (elastic, goodput, telemetry,
                                    timeline, tracing)
from distributedpytorch_tpu.config import config_from_argv
from distributedpytorch_tpu.serving.controller import (QUEUE_GAUGE,
                                                       decide_scale)
from distributedpytorch_tpu.sim import runner as sim_runner
from distributedpytorch_tpu.sim import scenario as scmod


@pytest.fixture
def restore_global():
    yield
    telemetry._active = telemetry.Telemetry(enabled=False)


# -- determinism -------------------------------------------------------

def test_same_seed_replays_byte_identical():
    """The tentpole contract: seed in, event log out — twice.  The
    sha256 is computed over the full canonical event JSONL, so any
    nondeterminism anywhere in the loop (set iteration, unseeded rng,
    wall-clock leakage) tears this."""
    a = sim_runner.run_scenario("control", seed=11, duration_s=45.0)
    b = sim_runner.run_scenario("control", seed=11, duration_s=45.0)
    assert a["event_log_sha256"] == b["event_log_sha256"]
    assert a["requests"] == b["requests"]
    c = sim_runner.run_scenario("control", seed=12, duration_s=45.0)
    assert a["event_log_sha256"] != c["event_log_sha256"]


def test_control_is_the_null_hypothesis():
    """Over-provisioned + flat light traffic: nothing moves."""
    r = sim_runner.run_scenario("control", seed=3, duration_s=45.0)
    assert r["scale"]["actions"] == 0
    assert r["incidents"] == []
    assert r["requests"]["dropped_forever"] == 0
    assert r["requests"]["fd_shed"] == 0
    assert r["requests"]["answered"] + r["in_flight_at_end"] \
        == r["requests"]["admitted"]


def test_unknown_scenario_raises():
    with pytest.raises(ValueError, match="unknown scenario"):
        sim_runner.run_scenario("nope", seed=0)


def test_scenario_file_and_size_overrides(tmp_path):
    path = tmp_path / "tiny.json"
    path.write_text(json.dumps({"replicas": 3, "duration_s": 20.0,
                                "traffic": {"kind": "constant",
                                            "rps": 2.0}}))
    r = sim_runner.run_scenario(str(path), seed=1)
    assert r["scenario"] == "tiny" and r["replicas_start"] == 3
    r2 = sim_runner.run_scenario("control", seed=1, replicas=4,
                                 duration_s=20.0)
    assert r2["replicas_start"] == 4


# -- artifact schema compat with the live pipelines --------------------

@pytest.fixture(scope="module")
def control_run(tmp_path_factory):
    rsl = str(tmp_path_factory.mktemp("simrun"))
    report = sim_runner.run_scenario("control", seed=5, duration_s=60.0,
                                     rsl_path=rsl)
    return rsl, report


def test_sim_telemetry_aggregates_with_zero_skips(control_run):
    rsl, report = control_run
    events = telemetry.load_events(os.path.join(rsl, "telemetry"))
    agg = telemetry.aggregate(events)
    assert agg["skipped_events"] == 0
    assert len(agg["ranks"]) >= report["replicas_start"]
    names = {e.get("name") for e in agg["events"]}
    assert {"sim/replica_start", "sim/frontdoor_start"} <= names


def test_sim_traces_reconcile_clean(control_run):
    rsl, report = control_run
    records = tracing.load_records(rsl)
    assert len(records) == report["trace_records"] > 0
    assert tracing.reconcile(records) == []


def test_sim_goodput_and_timeline_render(control_run):
    rsl, report = control_run
    assert "wall-clock attribution" in goodput.report(rsl)
    tl = timeline.build_timeline(rsl)
    assert len(tl["ranks"]) >= report["replicas_start"]


def test_sim_report_pins_model_provenance(control_run):
    _, report = control_run
    assert report["latency_model_provenance"]["source"]
    assert report["event_log_sha256"]


# -- autoscaler oscillation bound in closed loop -----------------------

def _sample(t, world, depth):
    return {"t": float(t), "alive": list(range(world)),
            "gauges": {QUEUE_GAUGE: float(depth)}, "counters": {}}


def test_decide_scale_diurnal_closed_loop_never_reverses():
    """Property pin for the sim's autoscale floors: drive decide_scale
    in closed loop (decisions change the world, the world changes the
    queue depth) under five full diurnal periods.  The controller may
    GROW to the settling size, but once settled the hysteresis must
    hold — zero direction changes, world stable over the tail."""
    cfg = {"min_world": 4, "max_world": 10, "queue_high": 8.0,
           "queue_low": 1.0, "up_hold_s": 2.0, "down_hold_s": 40.0,
           "cooldown_s": 5.0}
    world, state, samples, actions = 4, {}, [], []
    worlds = []
    for t in range(300):  # 5 x 60s periods, 1s scrape cadence
        load = 30.0 + 15.0 * math.sin(2 * math.pi * t / 60.0)
        samples.append(_sample(t, world, depth=load / world))
        samples = samples[-90:]
        d = decide_scale(cfg, state, samples)
        if d["action"] != "none":
            actions.append((t, d["action"]))
            state["last_action_t"] = float(t)
            world = d["target"]
        worlds.append(world)
    kinds = [a for _, a in actions]
    changes = sum(1 for x, y in zip(kinds, kinds[1:]) if x != y)
    assert changes == 0, f"flapped: {actions}"
    assert kinds and set(kinds) == {"up"}  # it did settle by growing
    assert all(t < 120 for t, _ in actions), f"late action: {actions}"
    assert len(set(worlds[120:])) == 1  # stable over the last 3 periods


# -- scenario catalog sanity ------------------------------------------

def test_every_builtin_scenario_loads_and_validates():
    for name in scmod.SCENARIOS:
        sc = scmod.load_scenario(name)
        assert sc["name"] == name
        scmod.timed_faults(sc, seed=0)


def test_fault_plan_rejects_live_sites_and_fatal_kinds(tmp_path):
    bad_site = dict(scmod.SCENARIOS["control"], name="x",
                    fault_plan="data.read:ioerror:1:1")
    p = tmp_path / "x.json"
    p.write_text(json.dumps(bad_site))
    with pytest.raises(ValueError, match="sim.step"):
        sim_runner.run_scenario(str(p), seed=0)
    bad_kind = dict(scmod.SCENARIOS["control"], name="y",
                    fault_plan="sim.step:fatal:1:1")
    p2 = tmp_path / "y.json"
    p2.write_text(json.dumps(bad_kind))
    with pytest.raises(ValueError, match="no fleet-level reading"):
        sim_runner.run_scenario(str(p2), seed=0)


# -- satellite: the joiner's bounded wait ------------------------------

def test_join_wait_flag_parses():
    cfg = config_from_argv(["train", "-d", "/x",
                            "--elastic-join-wait", "45"])
    assert cfg.elastic_join_wait == 45.0
    assert config_from_argv(["train", "-d", "/x"]) \
        .elastic_join_wait == 600.0


def test_join_wait_timeout_emits_telemetry_event(tmp_path,
                                                 restore_global):
    """A joiner that gives up is a capacity event: the TimeoutError
    must be preceded by an elastic/join_wait_timeout JSONL event
    naming the claim and the wait bound."""
    tel_dir = tmp_path / "tel"
    telemetry.configure(str(tel_dir), enabled=True, rank=0)
    with pytest.raises(TimeoutError, match="no admit/decline"):
        elastic.wait_for_admission(str(tmp_path / "elastic"), "h-9",
                                   timeout_s=0.3)
    telemetry.get().close()
    events = telemetry.load_events(os.path.join(str(tel_dir),
                                                "telemetry"))
    hits = [e for e in events
            if e.get("name") == "elastic/join_wait_timeout"]
    assert len(hits) == 1
    assert hits[0]["attrs"]["jid"] == "h-9"
    assert hits[0]["attrs"]["wait_s"] == 0.3
