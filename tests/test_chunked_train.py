"""--epochs-per-dispatch: fused-epoch training equals per-epoch training."""

import pytest

from distributedpytorch_tpu.cli import run_train
from distributedpytorch_tpu.config import Config

# subprocess worlds / full CLI chains: the slow tier (scripts/gate.sh runs -m 'not slow')
pytestmark = pytest.mark.slow


@pytest.mark.parametrize("k", [2])
def test_chunked_metrics_match_per_epoch(tmp_path, k):
    base = dict(action="train", data_path="/tmp/nodata",
                dataset="synthetic", model_name="mlp", batch_size=8,
                nb_epochs=2, debug=True, half_precision=False)
    r1 = run_train(Config(rsl_path=str(tmp_path / "a"), **base))
    r2 = run_train(Config(rsl_path=str(tmp_path / "b"),
                          epochs_per_dispatch=k, **base))
    assert len(r1["history"]) == len(r2["history"]) == 2
    for h1, h2 in zip(r1["history"], r2["history"]):
        assert h1["epoch"] == h2["epoch"]
        # same sampler plans + same keys -> same training up to compiler
        # reassociation between the fused and per-epoch programs
        assert h1["train_loss"] == pytest.approx(h2["train_loss"], abs=2e-3)
        assert h1["valid_loss"] == pytest.approx(h2["valid_loss"], abs=2e-3)
    # chunk-final checkpoint exists
    files = [f.name for f in (tmp_path / "b").iterdir()]
    assert "checkpoint-synthetic-mlp-001.ckpt" in files
