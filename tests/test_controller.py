"""Autoscale policy (serving/controller.py, ISSUE 19 tentpole 2).

Every test drives ``decide_scale`` with synthetic fleet sample windows
— the pure/clock-free contract means no sockets, no sleeps, no clock:
the samples carry their own ``t``.  The two behavioral pins the issue
names live here: a load RAMP grows the tier on queue depth before the
shed counter moves, and a DIURNAL series oscillating between the two
thresholds never flaps the world size.
"""

from distributedpytorch_tpu.serving.controller import (FD_SHED_COUNTER,
                                                       QUEUE_GAUGE,
                                                       SHED_COUNTER,
                                                       decide_scale,
                                                       pick_retire)


def _s(t, world=2, depth=0.0, shed=0.0, fd_shed=0.0, firing=False):
    """One fleet sample in the collector's merged-series shape."""
    return {
        "t": float(t),
        "alive": list(range(world)),
        "gauges": {QUEUE_GAUGE: float(depth)},
        "counters": {SHED_COUNTER: float(shed),
                     FD_SHED_COUNTER: float(fd_shed)},
        "verdicts": ([{"name": "availability", "firing": True}]
                     if firing else []),
    }


CFG = {"min_world": 1, "max_world": 4, "queue_high": 8.0,
       "queue_low": 1.0, "up_hold_s": 2.0, "down_hold_s": 10.0,
       "cooldown_s": 5.0}


def _series(points, **kw):
    return [_s(t, depth=d, **kw) for t, d in points]


# -- scale up ----------------------------------------------------------

def test_ramp_scales_up_on_queue_depth_before_any_shed():
    """The issue's ramp scenario: queues fill, nothing sheds yet — the
    tier must grow on the queue trigger, not wait for a shed floor."""
    ramp = _series([(0, 2), (1, 9), (2, 10), (3, 12)])
    d = decide_scale(CFG, {}, ramp)
    # at t=3 the trailing 2s window is [1..3] — not all >= 8 yet? it is:
    # depths 9,10,12.  The t=0 sample provides window coverage.
    assert d["action"] == "up"
    assert "queue depth" in d["reason"]
    assert "shed" not in d["reason"]
    assert d["target"] == 3


def test_shed_movement_inside_window_is_the_backstop_trigger():
    samples = [_s(0, depth=2.0, shed=5.0), _s(1, depth=2.0, shed=5.0),
               _s(3, depth=2.0, shed=7.0)]
    d = decide_scale(CFG, {}, samples)
    assert d["action"] == "up" and "shed" in d["reason"]


def test_frontdoor_admission_sheds_count_as_pressure():
    samples = [_s(0), _s(1), _s(3, fd_shed=4.0)]
    assert decide_scale(CFG, {}, samples)["action"] == "up"


def test_firing_slo_verdict_scales_up():
    samples = [_s(0), _s(1), _s(3, firing=True)]
    d = decide_scale(CFG, {}, samples)
    assert d["action"] == "up" and "burn" in d["reason"]


def test_uncovered_window_never_triggers():
    """A young series (no sample at/before t - hold) must not act —
    two hot samples 0.5s apart are not 2s of sustained pressure."""
    samples = [_s(10.0, depth=50.0), _s(10.5, depth=50.0)]
    assert decide_scale(CFG, {}, samples)["action"] == "none"


def test_max_world_clamps_scale_up():
    samples = _series([(0, 9), (1, 9), (3, 9)], world=4)
    assert decide_scale(CFG, {}, samples)["action"] == "none"


# -- scale down --------------------------------------------------------

def test_sustained_idleness_scales_down():
    samples = _series([(t, 0.5) for t in range(0, 12)])
    d = decide_scale(CFG, {}, samples)
    assert d["action"] == "down" and d["target"] == 1


def test_min_world_clamps_scale_down():
    samples = _series([(t, 0.0) for t in range(0, 12)], world=1)
    assert decide_scale(CFG, {}, samples)["action"] == "none"


def test_shed_movement_blocks_scale_down():
    """Fresh sheds during an otherwise idle window must not retire a
    replica — they are pressure (the up backstop wins)."""
    samples = [_s(t, depth=0.0, shed=(2.0 if t >= 11 else 0.0))
               for t in range(0, 12)]
    assert decide_scale(CFG, {}, samples)["action"] != "down"


# -- hysteresis --------------------------------------------------------

def test_cooldown_blocks_back_to_back_actions():
    ramp = _series([(0, 9), (1, 9), (3, 9)])
    assert decide_scale(CFG, {}, ramp)["action"] == "up"
    held = decide_scale(CFG, {"last_action_t": 3.0}, ramp)
    assert held["action"] == "none" and "cooldown" in held["reason"]


def test_repair_outranks_hysteresis_but_not_cooldown():
    """A dead replica (world below the floor) is repaired immediately —
    no hold window needed — but still spaced by the cooldown so a
    slow-to-join replacement is not double-launched."""
    samples = [_s(5.0, world=0)]
    d = decide_scale(CFG, {}, samples)
    assert d["action"] == "up" and "min_world" in d["reason"]
    assert decide_scale(CFG, {"last_action_t": 4.0},
                        samples)["action"] == "none"


def test_diurnal_oscillation_never_flaps():
    """Load swinging between the two thresholds (above queue_low,
    below queue_high) is the no-man's-land hysteresis exists for: no
    suffix of the series may trigger either action."""
    diurnal = [_s(t, depth=4.0 + 3.0 * ((t // 5) % 2))
               for t in range(0, 40)]   # 4.0 <-> 7.0, 5s half-period
    state = {}
    for end in range(2, len(diurnal) + 1):
        d = decide_scale(CFG, state, diurnal[:end])
        assert d["action"] == "none", \
            f"flapped at t={end - 1}: {d['reason']}"


def test_per_rank_gauge_dict_is_summed():
    s = _s(0)
    s["gauges"][QUEUE_GAUGE] = {"0": 5.0, "1": 6.0}
    samples = [s, _s(1, depth=11.0), _s(3, depth=11.0)]
    assert decide_scale(CFG, {}, samples)["action"] == "up"


# -- retirement pick ---------------------------------------------------

def test_pick_retire_highest_slot_first():
    assert pick_retire([0, 2, 1]) == 2


def test_pick_retire_respects_protected_canaries():
    assert pick_retire([0, 1, 2], protected=[2]) == 1
    assert pick_retire([1], protected=[1]) is None
    assert pick_retire([]) is None
