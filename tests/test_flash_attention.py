"""Pallas flash attention (ops/flash_attention.py) pinned against the
reference full_attention: outputs AND gradients, causal and bidirectional,
block-aligned and ragged sequence lengths.  On the CPU test mesh the
kernels run in Pallas interpret mode — the same kernel logic the TPU
lowers through Mosaic."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu.ops import attention
from distributedpytorch_tpu.ops.flash_attention import flash_attention

B, H, D = 2, 2, 32


def _qkv(s, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, s, H, D), jnp.float32)
                 for k in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [128, 256])
def test_forward_matches_full(s, causal):
    q, k, v = _qkv(s)
    want = attention.full_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ragged_forward_matches_full(causal):
    # S=49 (the vit token count): pads to one 128 block, masked keys
    q, k, v = _qkv(49)
    want = attention.full_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [128, 200])
def test_gradients_match_full(s, causal):
    q, k, v = _qkv(s, seed=3)
    w = jax.random.normal(jax.random.PRNGKey(9), (B, s, H, D))

    def loss_full(q, k, v):
        return jnp.sum(attention.full_attention(q, k, v, causal=causal) * w)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) * w)

    want = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for g, wv, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wv),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{name} mismatch (S={s})")


def test_bfloat16_io():
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(128, seed=5))
    want = attention.full_attention(q, k, v)
    got = flash_attention(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_vit_with_flash_attention_matches_default():
    from distributedpytorch_tpu.models.vit import ViT

    x = jax.random.normal(jax.random.PRNGKey(3), (2, 28, 28, 3))
    base = ViT(num_classes=10, dtype=jnp.float32)
    params = base.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    want = base.apply({"params": params}, x)
    flash = ViT(num_classes=10, dtype=jnp.float32,
                attention_fn=lambda q, k, v: flash_attention(q, k, v))
    got = flash.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_flash_cli_trains_and_matches_full(tmp_path):
    """--attention flash end-to-end through run_train (interpret mode on
    the CPU mesh): pins to the identical full-attention run."""
    import jax as _jax

    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    def cfg(name, attention):
        return Config(action="train", data_path="/tmp/nodata",
                      rsl_path=str(tmp_path / name), dataset="synthetic",
                      model_name="vit", batch_size=4, nb_epochs=1,
                      debug=True, half_precision=False,
                      attention=attention)

    full = run_train(cfg("full", "full"))
    flash = run_train(cfg("flash", "flash"))
    f = _jax.tree_util.tree_leaves(_jax.device_get(full["state"].params))
    g = _jax.tree_util.tree_leaves(_jax.device_get(flash["state"].params))
    for i, (a, b) in enumerate(zip(f, g)):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=1e-2, atol=1.5e-3,
            err_msg=f"param leaf {i}: flash-trained != full-trained")


def test_flash_requires_vit():
    from distributedpytorch_tpu.models import get_model

    with pytest.raises(ValueError, match="attention model family"):
        get_model("cnn", 10, attention="flash")


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="Mosaic lowering needs a real TPU backend "
                           "(run with DPT_TESTS_ON_TPU=1)")
def test_partial_positional_kernel_mosaic_lowering():
    """Round-4 advisor: the position-carrying kernel variants
    (flash_attention_partial and its (1,8,s)/(1,8,block) position
    layouts) must compile through Mosaic on real hardware, not just the
    interpreter — fwd AND bwd including the lse cotangent.  One call
    spanning all keys equals the normalized full-attention result.
    The bench attention suite times the same path every round."""
    from distributedpytorch_tpu.ops import flash_attention as fa

    bh, s, d = 4, 256, 32
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q, k, v = (jax.random.normal(kk, (bh, s, d), jnp.float32)
               for kk in ks)
    pos = jnp.arange(s, dtype=jnp.int32)

    o, lse = jax.jit(lambda a, x, y: fa.flash_attention_partial(
        a, x, y, pos, pos, True, None))(q, k, v)
    want = attention.full_attention(
        q[:, :, None, :], k[:, :, None, :], v[:, :, None, :],
        causal=True)[:, :, 0, :]
    np.testing.assert_allclose(np.asarray(o), np.asarray(want),
                               rtol=2e-2, atol=2e-2)
    assert np.all(np.isfinite(np.asarray(lse)))

    def loss(a, x, y):
        oo, ll = fa.flash_attention_partial(a, x, y, pos, pos, True, None)
        return jnp.sum(oo ** 2) + 1e-3 * jnp.sum(ll)

    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for g in grads:
        assert np.all(np.isfinite(np.asarray(g)))
