"""The fleet collector (distributedpytorch_tpu/fleet.py, ISSUE 16).

Prometheus text round trip (the per-rank exposition parses back into
the exact sketch that produced it), merge semantics (counters sum,
sketches fold, dpt_up is the collector's verdict), then the collector
against fake rank exporters on ephemeral ports: scrape cycles, the
/fleet + /metrics re-export, elastic age-out of a silent rank, and the
SLO alerting path writing exactly one incident bundle per episode with
the suspect rank and the offending request ids from trace records.
"""

import http.server
import json
import threading
import time
import urllib.request

import pytest

from distributedpytorch_tpu import fleet, slo, telemetry

# -- parsing + merging -------------------------------------------------


def _sketch(values):
    h = telemetry.Histogram("dpt_lat_ms")
    for v in values:
        h.observe(v)
    return h


def _rank_text(requests, failed, latencies, rank):
    """One rank's /metrics body, in the exporter's exposition shape."""
    merged = {
        "counters": {"dpt_serve_requests_total": float(requests),
                     "dpt_serve_failed_total": float(failed),
                     'dpt_goodput_seconds_total{category="compute"}': 2.0},
        "gauges": {"dpt_serve_queue_depth": 1.0},
        "histograms": {"dpt_serve_request_latency_ms":
                       _sketch(latencies)},
    }
    return fleet.render_fleet_metrics(merged, 1)


def test_parse_metrics_roundtrips_the_sketch():
    values = [1.5, 2.0, 10.0, 250.0, 0.0, -1.0]
    text = _rank_text(10, 1, values, rank=0)
    parsed = fleet.parse_metrics(text)
    assert parsed["counters"]["dpt_serve_requests_total"] == 10.0
    assert parsed["counters"][
        'dpt_goodput_seconds_total{category="compute"}'] == 2.0
    assert parsed["gauges"]["dpt_serve_queue_depth"] == 1.0
    st = parsed["histograms"]["dpt_serve_request_latency_ms"]
    src = _sketch(values)
    assert st["count"] == src.count and st["nonpos"] == src._nonpos
    assert {int(k): v for k, v in st["buckets"].items()} == src._buckets
    assert st["min"] == src.min and st["max"] == src.max


def test_merge_targets_sums_counters_and_folds_sketches():
    import random
    rng = random.Random(3)
    va = [rng.lognormvariate(3.0, 1.0) for _ in range(2000)]
    vb = [rng.lognormvariate(4.0, 0.5) for _ in range(1000)]
    pa = fleet.parse_metrics(_rank_text(100, 5, va, 0))
    pb = fleet.parse_metrics(_rank_text(50, 0, vb, 1))
    merged = fleet.merge_targets([pa, pb])
    assert merged["counters"]["dpt_serve_requests_total"] == 150.0
    assert merged["counters"]["dpt_serve_failed_total"] == 5.0
    h = merged["histograms"]["dpt_serve_request_latency_ms"]
    pooled = _sketch(va + vb)
    assert h.count == pooled.count
    for q in (0.5, 0.95, 0.99):
        assert h.quantile(q) == pytest.approx(pooled.quantile(q),
                                              rel=1e-9)


def test_fleet_render_reports_alive_count_not_self_reports():
    text = fleet.render_fleet_metrics(
        {"counters": {}, "gauges": {"dpt_up": 1.0}, "histograms": {}}, 3)
    assert text.endswith("dpt_up 3\n")
    # per-rank dpt_up self-reports never leak into the merged gauges
    merged = fleet.merge_targets([{"gauges": {"dpt_up": 1.0}}])
    assert "dpt_up" not in merged["gauges"]


# -- fake rank exporters ------------------------------------------------

class _FakeExporter:
    """A stand-in rank: serves a mutable /metrics body + /healthz."""

    def __init__(self, rank):
        self.rank = rank
        self.requests = 0.0
        self.failed = 0.0
        self.latencies = [5.0]
        outer = self

        class _H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - http.server API
                if self.path.startswith("/metrics"):
                    body = _rank_text(outer.requests, outer.failed,
                                      outer.latencies,
                                      outer.rank).encode()
                elif self.path.startswith("/healthz"):
                    body = json.dumps({"status": "ok",
                                       "rank": outer.rank}).encode()
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                      _H)
        self.port = self.server.server_address[1]
        self.server.daemon_threads = True
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture
def two_ranks():
    exps = [_FakeExporter(0), _FakeExporter(1)]
    yield exps
    for e in exps:
        e.close()


def _collector(tmp_path, exps, **kw):
    """A collector aimed at fake exporters.  The fakes sit on arbitrary
    ephemeral ports, so the base+rank port convention is patched per
    target after construction."""
    args = dict(rsl_path=str(tmp_path), ranks=len(exps), metrics_port=0,
                interval_s=0.05, stale_after=2, port=0, max_cycles=0)
    args.update(kw)
    coll = fleet.FleetCollector(**args)
    for t, e in zip(coll._targets, exps):
        t.port = e.port
    return coll


def test_collector_scrapes_merges_persists_and_reexports(tmp_path,
                                                         two_ranks):
    two_ranks[0].requests = 30.0
    two_ranks[1].requests = 12.0
    coll = _collector(tmp_path, two_ranks, max_cycles=2)
    coll.start()
    try:
        coll.run()
        # merged == sum of per-rank scrapes, same cycle
        with urllib.request.urlopen(
                f"http://127.0.0.1:{coll.port}/fleet", timeout=5) as r:
            doc = json.loads(r.read())
        assert doc["alive"] == [0, 1]
        assert doc["counters"]["dpt_serve_requests_total"] == 42.0
        per_rank = sum(
            t["counters"]["dpt_serve_requests_total"]
            for t in doc["targets"].values())
        assert per_rank == doc["counters"]["dpt_serve_requests_total"]
        assert doc["targets"]["0"]["health"]["status"] == "ok"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{coll.port}/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "dpt_serve_requests_total 42" in text
        assert text.endswith("dpt_up 2\n")
    finally:
        coll.close()
    lines = [json.loads(ln) for ln in
             (tmp_path / "fleet-metrics.jsonl").read_text().splitlines()]
    assert [s["cycle"] for s in lines] == [1, 2]
    assert all(s["kind"] == "fleet_sample" for s in lines)


def test_collector_ages_out_dead_rank_and_sees_joiner(tmp_path,
                                                      two_ranks):
    coll = _collector(tmp_path, two_ranks, stale_after=2)
    try:
        coll.scrape_once()
        assert coll._samples[-1]["alive"] == [0, 1]
        two_ranks[1].close()  # the rank dies
        coll.scrape_once()    # failure 1: still within grace
        assert coll._samples[-1]["alive"] == [0, 1]
        coll.scrape_once()    # failure 2 == stale_after: aged out
        sample = coll._samples[-1]
        assert sample["alive"] == [0]
        assert "1" not in sample["targets"]
        # no stale dpt_up: the re-export counts ONE alive rank
        assert fleet.render_fleet_metrics(
            {"counters": sample["counters"], "gauges": sample["gauges"],
             "histograms": {}},
            len(sample["alive"])).endswith("dpt_up 1\n")
        # a joiner on the same port re-appears within one cycle
        joiner = _FakeExporter(1)
        coll._targets[1].port = joiner.port
        try:
            coll.scrape_once()
            assert coll._samples[-1]["alive"] == [0, 1]
        finally:
            joiner.close()
    finally:
        coll.close()


ERROR_SLO = {"name": "serve-errors", "kind": "ratio",
             "bad": "dpt_serve_failed_total",
             "total": "dpt_serve_requests_total",
             "target": 0.99,
             "windows": [{"seconds": 0.2, "burn": 2.0},
                         {"seconds": 0.6, "burn": 1.0}]}


def test_collector_fires_exactly_one_incident_per_episode(tmp_path,
                                                          two_ranks):
    # offending trace records: rank 1 failed two requests "now"
    now = time.time()
    with open(tmp_path / "trace-rank1.jsonl", "w") as f:
        for seq, outcome in ((4, "failed"), (5, "failed"),
                             (6, "answered")):
            f.write(json.dumps({
                "kind": "request", "id": "r1-%06d" % seq, "seq": seq,
                "rank": 1, "status": 500 if outcome == "failed" else 200,
                "outcome": outcome, "spans": {}, "total_s": 0.0,
                "ts": now, "mono": 0.0, "ts_admit": now,
                "mono_admit": 0.0}) + "\n")
    slos = slo.validate_spec({"slos": [ERROR_SLO]})
    coll = _collector(tmp_path, two_ranks, slos=slos, interval_s=0.1)
    try:
        coll.scrape_once()
        time.sleep(0.1)
        coll.scrape_once()  # clean baseline: nothing fires
        assert coll.incidents_written == 0
        # rank 1 starts failing hard
        two_ranks[0].requests = 100.0
        two_ranks[1].requests = 100.0
        two_ranks[1].failed = 50.0
        for _ in range(8):
            time.sleep(0.1)
            coll.scrape_once()
        assert coll.incidents_written == 1  # one bundle per episode
        bundles = slo.load_incidents(str(tmp_path))
        assert len(bundles) == 1
        b = bundles[0]
        assert b["slo"] == "serve-errors"
        assert b["suspect_ranks"] == [1]
        assert "r1-000004" in b["offending_requests"]
        assert "r1-000006" not in b["offending_requests"]  # answered
        assert b["healthz"]["1"]["status"] == "ok"
        # recovery clears, a second burst is a NEW episode
        two_ranks[1].failed = 50.0  # frozen: error rate decays to 0
        for _ in range(10):
            time.sleep(0.1)
            two_ranks[0].requests += 30
            two_ranks[1].requests += 30
            coll.scrape_once()
        assert "serve-errors" not in coll._firing
        two_ranks[1].failed = 200.0
        two_ranks[1].requests += 100
        time.sleep(0.1)
        coll.scrape_once()
        assert coll.incidents_written == 2
    finally:
        coll.close()


def test_run_cli_validation_error_is_a_clean_exit(tmp_path, capsys):
    from distributedpytorch_tpu.config import Config

    bad = tmp_path / "slo.json"
    bad.write_text(json.dumps({"slos": [{"name": "x"}]}))
    cfg = Config(action="fleet", rsl_path=str(tmp_path),
                 metrics_port=1, fleet_ranks=1, fleet_port=0,
                 fleet_interval=0.05, fleet_stale_after=1,
                 fleet_max_cycles=1, slo_spec=str(bad))
    assert fleet.run_cli(cfg) == 2
    out = capsys.readouterr().out
    assert "kind" in out and "slo.json" in out
