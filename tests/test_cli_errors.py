"""CLI error surface: user mistakes must log-and-exit (rc 1), never
traceback — the reference's error style (ref classif.py:119-120,130-131,
utils.py:102-103).
"""

from distributedpytorch_tpu.cli import main
from distributedpytorch_tpu.config import config_from_argv


def _argv(tmp_path, *extra):
    return ["train", "-d", str(tmp_path / "nodata"),
            "--rsl_path", str(tmp_path / "rsl"), "--debug", *extra]


def test_missing_checkpoint_file_exits_cleanly(tmp_path):
    rc = main(["test", "-d", str(tmp_path), "--rsl_path", str(tmp_path),
               "--dataset", "synthetic", "--debug",
               "-f", str(tmp_path / "does-not-exist.ckpt")])
    assert rc == 1


def test_corrupt_checkpoint_file_exits_cleanly(tmp_path):
    bad = tmp_path / "corrupt.ckpt"
    bad.write_bytes(b"\x00\x01not a msgpack checkpoint\xff")
    rc = main(["test", "-d", str(tmp_path), "--rsl_path", str(tmp_path),
               "--dataset", "synthetic", "--debug", "-f", str(bad)])
    assert rc == 1


def test_missing_real_dataset_exits_cleanly(tmp_path):
    """--dataset cifar10 with no raw files is an error, not a silent
    synthetic fallback."""
    rc = main(_argv(tmp_path, "--dataset", "cifar10", "--model", "mlp",
                    "-e", "1"))
    assert rc == 1


def test_synthetic_fallback_flag_opts_in(tmp_path):
    """The old always-fallback behavior survives behind an explicit flag."""
    rc = main(_argv(tmp_path, "--dataset", "mnist", "--model", "mlp",
                    "-e", "1", "-b", "8", "--synthetic-fallback",
                    "--no-bf16"))
    assert rc == 0


def test_epochs_per_dispatch_stream_conflict_exits_cleanly(tmp_path):
    rc = main(_argv(tmp_path, "--dataset", "synthetic", "--model", "mlp",
                    "-e", "2", "--data-mode", "stream",
                    "--epochs-per-dispatch", "2"))
    assert rc == 1


def test_epochs_per_dispatch_below_one_exits_cleanly(tmp_path):
    rc = main(_argv(tmp_path, "--dataset", "synthetic", "--model", "mlp",
                    "-e", "1", "--epochs-per-dispatch", "0"))
    assert rc == 1


def test_use_pretrained_without_path_exits_cleanly(tmp_path):
    rc = main(_argv(tmp_path, "--dataset", "synthetic", "--model", "resnet",
                    "-e", "1", "--use-pretrained"))
    assert rc == 1


def test_use_pretrained_unsupported_arch_exits_cleanly(tmp_path):
    w = tmp_path / "w.pth"
    w.write_bytes(b"whatever")  # arch check fires before the file is read
    rc = main(_argv(tmp_path, "--dataset", "synthetic", "--model", "cnn",
                    "-e", "1", "--use-pretrained",
                    "--pretrained-path", str(w)))
    assert rc == 1


def test_config_carries_fallback_flag():
    cfg = config_from_argv(["train", "-d", "/x", "--synthetic-fallback"])
    assert cfg.synthetic_fallback
    assert not config_from_argv(["train", "-d", "/x"]).synthetic_fallback

def test_use_pretrained_with_resume_exits_cleanly(tmp_path):
    """--use-pretrained + -f is a contradiction (all weights come from the
    checkpoint); it must error, never silently ignore the flag — and the
    guard must fire before the checkpoint file is ever read (pinned via
    the message: a missing -f file would raise 'cannot read checkpoint')."""
    import pytest as _pytest

    from distributedpytorch_tpu.cli import run_train

    cfg = config_from_argv(_argv(tmp_path, "--dataset", "synthetic",
                                 "--model", "resnet", "-e", "1",
                                 "--use-pretrained",
                                 "--pretrained-path", str(tmp_path / "w.pth"),
                                 "-f", str(tmp_path / "some.ckpt")))
    with _pytest.raises(ValueError, match="cannot be combined"):
        run_train(cfg)
    assert main(_argv(tmp_path, "--dataset", "synthetic", "--model",
                      "resnet", "-e", "1", "--use-pretrained",
                      "--pretrained-path", str(tmp_path / "w.pth"),
                      "-f", str(tmp_path / "some.ckpt"))) == 1


def test_use_pretrained_on_test_subcommand_exits_cleanly(tmp_path):
    rc = main(["test", "-d", str(tmp_path), "--rsl_path", str(tmp_path),
               "--dataset", "synthetic", "--debug", "--use-pretrained",
               "-f", str(tmp_path / "some.ckpt")])
    assert rc == 1


def test_pretrained_file_without_state_dict_exits_cleanly(tmp_path):
    """A .pth holding a bare tensor (not a state_dict) must surface as the
    CLI's log-and-exit, not an AttributeError traceback."""
    import torch

    w = tmp_path / "bare.pth"
    torch.save(torch.zeros(3), str(w))
    rc = main(_argv(tmp_path, "--dataset", "synthetic", "--model", "resnet",
                    "-e", "1", "--use-pretrained",
                    "--pretrained-path", str(w)))
    assert rc == 1


def test_seq_parallel_argv_roundtrip():
    cfg = config_from_argv(["train", "-d", "/x", "--model", "vit",
                            "--attention", "ring", "--pipeline-parallel",
                            "--model-parallel", "2",
                            "--seq-parallel", "2"])
    assert cfg.seq_parallel == 2 and cfg.pipeline_parallel
    assert config_from_argv(["train", "-d", "/x"]).seq_parallel == 1
