"""Child for the multi-process preemption test: long run (100 epochs) so a
mid-run SIGTERM to ONE host must stop BOTH via runtime.any_process
agreement at the same epoch boundary."""

import argparse
import json
import os
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coord", required=True)
    ap.add_argument("--nproc", type=int, required=True)
    ap.add_argument("--pid", type=int, required=True)
    ap.add_argument("--rsl", required=True)
    ap.add_argument("--out", required=True)
    a = ap.parse_args()

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from distributedpytorch_tpu import runtime

    runtime.initialize_distributed(coordinator_address=a.coord,
                                   num_processes=a.nproc, process_id=a.pid)

    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    cfg = Config(action="train", data_path="/nodata",
                 rsl_path=os.path.join(a.rsl, f"rank{a.pid}"),
                 dataset="synthetic", model_name="mlp", batch_size=8,
                 nb_epochs=100, debug=True, half_precision=False)
    result = run_train(cfg)
    with open(a.out, "w") as f:
        json.dump({"epochs": len(result["history"]),
                   "preempted": bool(result.get("preempted"))}, f)
    print(f"rank {a.pid} done after {len(result['history'])} epochs",
          file=sys.stderr)


if __name__ == "__main__":
    main()
