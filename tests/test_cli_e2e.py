"""End-to-end: train -> checkpoint -> resume -> test via the driver API.

Covers the north-star command contract (BASELINE.json): the same flow as
``python main.py train -d PATH`` / ``test -d PATH -f FILE``, exercised
in-process on the 8-device CPU mesh with the synthetic corpus + --debug
subset (the reference's own smoke mode, ref dataloader.py:139-144).
"""

import os

import numpy as np
import pytest

from distributedpytorch_tpu import checkpoint as ckpt
from distributedpytorch_tpu.cli import run_test, run_train
from distributedpytorch_tpu.config import Config, config_from_argv

# subprocess worlds / full CLI chains: the slow tier (scripts/gate.sh runs -m 'not slow')
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    rsl = str(tmp_path_factory.mktemp("rsl"))
    cfg = Config(action="train", data_path="/tmp/nodata", rsl_path=rsl,
                 dataset="synthetic", model_name="cnn", batch_size=8,
                 nb_epochs=1, debug=True, half_precision=False)
    result = run_train(cfg)
    return cfg, result


def test_train_produces_history_and_checkpoints(trained):
    cfg, result = trained
    assert len(result["history"]) == 1
    h = result["history"][0]
    assert 0 <= h["train_acc"] <= 1 and 0 <= h["valid_acc"] <= 1
    files = os.listdir(cfg.rsl_path)
    assert "checkpoint-synthetic-cnn-000.ckpt" in files
    assert "bestmodel-synthetic-cnn.ckpt" in files
    assert cfg.log_file in files  # rsl/test.log (ref config.py:34,36)


def test_resume_continues_from_next_epoch(trained):
    cfg, _ = trained
    path = ckpt.checkpoint_path(cfg.rsl_path, "synthetic", "cnn", 0)
    cfg2 = cfg.replace(nb_epochs=2, checkpoint_file=path)
    result = run_train(cfg2)
    # resumed at epoch 1 (ref utils.py:133: saved epoch + 1)
    assert [h["epoch"] for h in result["history"]] == [1]
    # model name came from the checkpoint, not config (fixes defect #3)
    assert result["model_name"] == "cnn"


def test_test_subcommand_loads_best_model(trained):
    cfg, _ = trained
    best = ckpt.best_model_path(cfg.rsl_path, "synthetic", "cnn")
    cfg_t = Config(action="test", data_path="/tmp/nodata",
                   rsl_path=cfg.rsl_path, dataset="synthetic", debug=True,
                   batch_size=8, checkpoint_file=best, half_precision=False)
    result = run_test(cfg_t)
    assert result["model_name"] == "cnn"
    assert 0.0 <= result["test_acc"] <= 1.0


def test_streaming_mode_e2e(tmp_path):
    """Force the streamed (host-batched, prefetching) pipeline through the
    driver — the path larger-than-HBM corpora take."""
    cfg = Config(action="train", data_path="/tmp/nodata",
                 rsl_path=str(tmp_path), dataset="synthetic",
                 model_name="mlp", batch_size=8, nb_epochs=1, debug=True,
                 half_precision=False, data_mode="stream")
    result = run_train(cfg)
    assert len(result["history"]) == 1
    assert np.isfinite(result["history"][0]["train_loss"])


def test_focal_loss_cli_e2e(tmp_path):
    """--loss focal_loss works end-to-end (reference crashes: defect #4)."""
    cfg = Config(action="train", data_path="/tmp/nodata",
                 rsl_path=str(tmp_path), dataset="synthetic",
                 model_name="mlp", batch_size=8, nb_epochs=1, debug=True,
                 half_precision=False, loss="focal_loss")
    result = run_train(cfg)
    assert np.isfinite(result["history"][0]["train_loss"])


def test_cli_parser_matches_reference_surface():
    cfg = config_from_argv(["train", "-d", "/x", "-b", "32", "-e", "5",
                            "--debug"])
    assert cfg.action == "train" and cfg.data_path == "/x"
    assert cfg.batch_size == 32 and cfg.nb_epochs == 5 and cfg.debug
    cfg = config_from_argv(["test", "-d", "/x", "-f", "m.ckpt"])
    assert cfg.action == "test" and cfg.checkpoint_file == "m.ckpt"
    with pytest.raises(SystemExit):  # -f required for test (ref main.py:53)
        config_from_argv(["test", "-d", "/x"])
    with pytest.raises(SystemExit):  # -d required (ref main.py:28-30)
        config_from_argv(["train"])
