"""ops/pooling.py: max_pool_2x2 must be bit-identical to flax nn.max_pool
in forward AND backward (first-max gradient routing), ties included."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu.ops.pooling import max_pool_2x2


def _ref_pool(x):
    return nn.max_pool(x, (2, 2), strides=(2, 2))


def test_forward_matches_flax():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 12, 16))
    np.testing.assert_array_equal(np.asarray(max_pool_2x2(x)),
                                  np.asarray(_ref_pool(x)))


def test_backward_matches_flax_random():
    # random values: no ties, gradients must agree exactly
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 6, 8))
    w = jax.random.normal(jax.random.PRNGKey(2), (2, 3, 3, 8))

    g_fast = jax.grad(lambda y: jnp.sum(max_pool_2x2(y) * w))(x)
    g_ref = jax.grad(lambda y: jnp.sum(_ref_pool(y) * w))(x)
    np.testing.assert_array_equal(np.asarray(g_fast), np.asarray(g_ref))


def test_backward_tie_first_max_wins():
    # all-equal window: the FIRST element in row-major order takes the
    # whole gradient (torch MaxPool2d / XLA select-and-scatter semantics)
    x = jnp.ones((1, 2, 2, 1), jnp.float32)
    g = jax.grad(lambda y: jnp.sum(max_pool_2x2(y)) * 3.0)(x)
    np.testing.assert_allclose(np.asarray(g)[0, :, :, 0],
                               [[3.0, 0.0], [0.0, 0.0]])
    # and it matches the flax op's routing on the same tie
    g_ref = jax.grad(lambda y: jnp.sum(_ref_pool(y)) * 3.0)(x)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(g_ref))


def test_backward_partial_tie():
    # tie between positions (0,1) and (1,0); (0,1) is first in row-major
    x = jnp.array([[[0.0], [5.0]],
                   [[5.0], [1.0]]], jnp.float32)[None]
    g = jax.grad(lambda y: jnp.sum(max_pool_2x2(y)))(x)
    np.testing.assert_allclose(np.asarray(g)[0, :, :, 0],
                               [[0.0, 1.0], [0.0, 0.0]])
    g_ref = jax.grad(lambda y: jnp.sum(_ref_pool(y)))(x)
    np.testing.assert_array_equal(np.asarray(g), np.asarray(g_ref))


def test_bfloat16_dtype_preserved():
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 4, 4, 4), jnp.bfloat16)
    out = max_pool_2x2(x)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(out, np.float32), np.asarray(_ref_pool(x), np.float32))


def test_odd_spatial_raises():
    with pytest.raises(ValueError, match="even"):
        max_pool_2x2(jnp.zeros((1, 5, 4, 1)))
