"""The serving tier (distributedpytorch_tpu/serving, ISSUE 15).

Fast layers first: the bucket planner and micro-batcher are pure
stdlib+numpy (no JAX) and are tested as units — coalescing, the flush
deadline, explicit backpressure, requeue order, close-drains.  The
ServingTier HTTP round trip runs in-process against a stub infer_fn on
an ephemeral port.  The JAX-backed contracts — padded rows provably
inert in predict_step, cross-layout restore_for_serving — use the
cheap zoo models on the synthetic dataset.  The full `main.py serve`
CLI path (AOT-warmed buckets answering real requests, /metrics live,
rank loss mid-serve) is the serve_gate's and chaos stage G's job.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributedpytorch_tpu.serving import (MicroBatcher, Request,
                                            ServingTier, choose_bucket,
                                            parse_buckets, plan_batch)

# -- bucket planner ----------------------------------------------------


def test_parse_buckets_string_and_sequence():
    assert parse_buckets("1,4,16,64") == (1, 4, 16, 64)
    assert parse_buckets("16, 4,1") == (1, 4, 16)
    assert parse_buckets([8, 2, 8]) == (2, 8)


def test_parse_buckets_rejects_garbage():
    with pytest.raises(ValueError, match="comma-separated"):
        parse_buckets("1,two")
    with pytest.raises(ValueError, match="at least one"):
        parse_buckets("")
    with pytest.raises(ValueError, match=">= 1"):
        parse_buckets("4,0")


def test_choose_bucket_largest_filled_else_smallest():
    buckets = (1, 4, 16)
    assert choose_bucket(1, buckets) == 1
    assert choose_bucket(3, buckets) == 1
    assert choose_bucket(4, buckets) == 4
    assert choose_bucket(15, buckets) == 4
    assert choose_bucket(16, buckets) == 16
    assert choose_bucket(100, buckets) == 16
    # pending below every bucket pads up to the smallest
    assert choose_bucket(1, (4, 16)) == 4


def test_plan_batch_take_and_padding():
    assert plan_batch(3, (1, 4, 16)) == (1, 1, 0)
    assert plan_batch(5, (4, 16)) == (4, 4, 0)
    assert plan_batch(2, (4, 16)) == (2, 4, 2)   # deadline flush pads
    assert plan_batch(40, (1, 4, 16)) == (16, 16, 0)


# -- micro-batcher -----------------------------------------------------

FAST = 0.02  # flush deadline used across batcher tests, seconds


def _reqs(n):
    return [Request(np.full((2,), i, np.uint8)) for i in range(n)]


def test_batcher_coalesces_full_largest_bucket():
    b = MicroBatcher((1, 4), max_queue=16, max_latency_s=10.0)
    for r in _reqs(5):
        assert b.admit(r)
    # 5 pending >= largest bucket: dispatch is immediate, no deadline
    reqs, bucket = b.next_batch(timeout_s=0.5)
    assert bucket == 4 and len(reqs) == 4
    assert b.depth() == 1


def test_batcher_flush_deadline_releases_partial_batch():
    b = MicroBatcher((4, 16), max_queue=16, max_latency_s=FAST)
    t0 = time.monotonic()
    assert b.admit(Request(np.zeros(2, np.uint8)))
    reqs, bucket = b.next_batch(timeout_s=2.0)
    waited = time.monotonic() - t0
    # released by the deadline, not the timeout: padded to the
    # smallest bucket
    assert bucket == 4 and len(reqs) == 1
    assert FAST * 0.5 <= waited < 1.0


def test_batcher_timeout_returns_none_and_keeps_pending():
    b = MicroBatcher((4,), max_queue=16, max_latency_s=0.5)
    assert b.next_batch(timeout_s=0.01) is None      # empty queue
    assert b.admit(Request(np.zeros(2, np.uint8)))
    # pending but not yet due: the driver gets its health-tick chance
    # and the request stays queued for a later call
    assert b.next_batch(timeout_s=0.01) is None
    assert b.depth() == 1
    reqs, bucket = b.next_batch(timeout_s=2.0)       # deadline flush
    assert len(reqs) == 1 and bucket == 4


def test_batcher_backpressure_refuses_at_bound():
    b = MicroBatcher((1,), max_queue=2, max_latency_s=FAST)
    assert b.admit(Request(np.zeros(2, np.uint8)))
    assert b.admit(Request(np.zeros(2, np.uint8)))
    assert not b.admit(Request(np.zeros(2, np.uint8)))  # shed, not grown
    assert b.depth() == 2


def test_batcher_requeue_puts_batch_back_in_order():
    b = MicroBatcher((4,), max_queue=8, max_latency_s=FAST)
    first = _reqs(4)
    for r in first:
        b.admit(r)
    straggler = Request(np.full((2,), 9, np.uint8))
    b.admit(straggler)
    reqs, _ = b.next_batch(timeout_s=1.0)
    assert reqs == first
    # the world changed mid-dispatch: the batch goes back to the FRONT
    b.requeue(reqs)
    again, _ = b.next_batch(timeout_s=1.0)
    assert again == first
    assert b.depth() == 1  # the straggler kept its place behind them


def test_batcher_close_drains_and_refuses():
    b = MicroBatcher((4,), max_queue=8, max_latency_s=FAST)
    queued = _reqs(3)
    for r in queued:
        b.admit(r)
    assert b.close() == queued
    assert not b.admit(Request(np.zeros(2, np.uint8)))
    assert b.next_batch(timeout_s=0.01) is None


def test_request_wait_complete_fail():
    r = Request(np.zeros(2, np.uint8))
    assert not r.wait(timeout_s=0.01)
    r.complete({"label": 3})
    assert r.wait(timeout_s=0.01) and r.result == {"label": 3}
    r2 = Request(np.zeros(2, np.uint8))
    r2.fail(RuntimeError("boom"))
    assert r2.wait(timeout_s=0.01) and isinstance(r2.error, RuntimeError)


# -- ServingTier HTTP round trip (stub infer, no JAX) -------------------

SHAPE = (4, 4)


def _stub_infer(arr):
    # label = the row's max pixel; proves per-row payloads arrive intact
    return (arr.reshape(arr.shape[0], -1).max(axis=1).astype(np.int32),
            np.full((arr.shape[0],), 0.5, np.float64))


def _post(port, image, timeout=5.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"image": image}).encode())
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _make_tier(**kw):
    args = dict(infer_fn=_stub_infer, sample_shape=SHAPE,
                sample_dtype=np.uint8, buckets=(1, 4), max_queue=8,
                max_latency_s=0.01, port=0, request_timeout_s=5.0)
    args.update(kw)
    return ServingTier(**args)


def _serve_in_thread(tier):
    t = threading.Thread(target=tier.run, daemon=True)
    t.start()
    return t


def test_tier_e2e_round_trip_and_livez():
    tier = _make_tier()
    tier.start()
    driver = _serve_in_thread(tier)
    try:
        img = np.full(SHAPE, 7, np.uint8).tolist()
        status, body = _post(tier.port, img)
        assert status == 200
        assert body["label"] == 7 and body["bucket"] in (1, 4)
        assert body["latency_ms"] >= 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{tier.port}/livez", timeout=5) as r:
            live = json.loads(r.read())
        assert live["ok"] and live["answered"] >= 1
    finally:
        tier.close()
        driver.join(timeout=5)
        assert not driver.is_alive()


def test_tier_rejects_bad_shape_and_bad_json():
    tier = _make_tier()
    tier.start()
    driver = _serve_in_thread(tier)
    try:
        status, body = _post(tier.port, [[1, 2], [3, 4]])
        assert status == 400 and "shape" in body["error"]
        req = urllib.request.Request(
            f"http://127.0.0.1:{tier.port}/predict", data=b"not json")
        try:
            urllib.request.urlopen(req, timeout=5)
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        tier.close()
        driver.join(timeout=5)


def test_tier_sheds_with_503_when_queue_full():
    """Backpressure end to end: with the driver NOT running, the
    bounded queue fills and every further request is answered 503
    immediately — shed and counted, never hung."""
    tier = _make_tier(max_queue=2)
    tier.start()  # listener up, driver deliberately not started
    try:
        img = np.zeros(SHAPE, np.uint8).tolist()
        results = []
        threads = [threading.Thread(
            target=lambda: results.append(_post(tier.port, img, 5.0)))
            for _ in range(4)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        # the two overflow requests must answer promptly; the two
        # queued ones are still waiting on the (absent) driver
        deadline = time.monotonic() + 5.0
        while len(results) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(results) >= 2, "overflow requests hung instead of shed"
        assert all(code == 503 for code, _ in results)
        assert all("queue full" in body["error"] for _, body in results)
        assert time.monotonic() - t0 < 5.0
    finally:
        tier.close()  # fails the two queued requests with shutdown
        for t in threads:
            t.join(timeout=5)


def test_tier_infer_failure_fails_batch_but_keeps_serving():
    calls = {"n": 0}

    def flaky(arr):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("injected")
        return _stub_infer(arr)

    tier = _make_tier(infer_fn=flaky)
    tier.start()
    driver = _serve_in_thread(tier)
    try:
        img = np.full(SHAPE, 3, np.uint8).tolist()
        status, body = _post(tier.port, img)
        assert status == 500 and "injected" in body["error"]
        status, body = _post(tier.port, img)   # the tier survived
        assert status == 200 and body["label"] == 3
    finally:
        tier.close()
        driver.join(timeout=5)


def test_tier_set_infer_swap_answers_queued_requests():
    """The elastic shrink-while-serving shape, simulated: requests
    queued while the replica is down (infer swapped to a failing stub =
    the reconfigure window) are answered by the REBUILT replica after
    set_infer — queued work survives the world change."""
    tier = _make_tier(max_latency_s=0.005)
    tier.start()  # no driver yet: this is the reconfigure window
    img = np.full(SHAPE, 5, np.uint8).tolist()
    results = []
    threads = [threading.Thread(
        target=lambda: results.append(_post(tier.port, img, 10.0)))
        for _ in range(3)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 5.0
    while tier.batcher.depth() < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert tier.batcher.depth() == 3
    # the rebuilt replica comes up and the driver resumes
    tier.set_infer(_stub_infer)
    driver = _serve_in_thread(tier)
    try:
        for t in threads:
            t.join(timeout=10)
        assert len(results) == 3
        assert all(code == 200 and body["label"] == 5
                   for code, body in results)
    finally:
        tier.close()
        driver.join(timeout=5)


def test_tier_max_requests_stops_driver():
    tier = _make_tier(max_requests=2)
    tier.start()
    driver = _serve_in_thread(tier)
    try:
        img = np.zeros(SHAPE, np.uint8).tolist()
        assert _post(tier.port, img)[0] == 200
        assert _post(tier.port, img)[0] == 200
        driver.join(timeout=5)
        assert not driver.is_alive()   # answered its quota and stopped
        assert tier.answered == 2
    finally:
        tier.close()


# -- control plane: drain + hot-swap (ISSUE 19) -------------------------

def _admin(port, path, doc=None, timeout=5.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(doc if doc is not None else {}).encode())
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_tier_drain_closes_admissions_and_driver_exits():
    tier = _make_tier()
    tier.start()
    driver = _serve_in_thread(tier)
    try:
        img = np.full(SHAPE, 5, np.uint8).tolist()
        assert _post(tier.port, img)[0] == 200
        status, body = _admin(tier.port, "/admin/drain")
        assert status == 200 and body["draining"]
        # draining sheds new work with an answer, never a hang
        status, body = _post(tier.port, img)
        assert status == 503 and "draining" in body["error"]
        # ...and the driver exits once the queue is flushed
        driver.join(timeout=5)
        assert not driver.is_alive()
        assert tier.stats()["draining"]
    finally:
        tier.close()


def test_tier_reload_answers_501_without_swap_fn():
    tier = _make_tier()
    tier.start()
    try:
        status, body = _admin(tier.port, "/admin/reload",
                              {"checkpoint": "/tmp/x.ckpt"})
        assert status == 501 and "swap_fn" in body["error"]
    finally:
        tier.close()


def test_tier_reload_rejects_bad_body():
    tier = _make_tier()
    tier.set_swap_fn(lambda path: (_stub_infer, None))
    tier.start()
    try:
        status, body = _admin(tier.port, "/admin/reload",
                              {"not_checkpoint": True})
        assert status == 400 and "bad reload request" in body["error"]
    finally:
        tier.close()


def test_tier_hot_swap_switches_infer_and_lineage():
    """The zero-downtime contract: /admin/reload swaps the predict
    program between batches — the listener never closes, the answer
    changes, and the served lineage (stats + /livez) follows."""
    def swapped_infer(arr):
        return (np.full((arr.shape[0],), 42, np.int32),
                np.full((arr.shape[0],), 0.9, np.float64))

    info = {"file": "v2.ckpt", "sha256": "c0ffee" * 10 + "beef",
            "epoch": 2, "path": "/tmp/v2.ckpt"}
    tier = _make_tier()
    tier.set_checkpoint({"file": "v1.ckpt", "sha256": "a" * 64,
                         "epoch": 1})
    tier.set_swap_fn(lambda path: (swapped_infer, dict(info,
                                                       path=path)))
    tier.start()
    driver = _serve_in_thread(tier)
    try:
        img = np.full(SHAPE, 7, np.uint8).tolist()
        assert _post(tier.port, img)[1]["label"] == 7   # old program
        status, body = _admin(tier.port, "/admin/reload",
                              {"checkpoint": "/tmp/v2.ckpt"})
        assert status == 200 and body["reloaded"]
        assert body["checkpoint"]["epoch"] == 2
        assert _post(tier.port, img)[1]["label"] == 42  # new program
        assert tier.stats()["checkpoint"]["file"] == "v2.ckpt"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{tier.port}/livez", timeout=5) as r:
            live = json.loads(r.read())
        assert live["checkpoint"]["sha256"].startswith("c0ffee")
    finally:
        tier.close()
        driver.join(timeout=5)


def test_tier_failed_swap_answers_500_and_keeps_old_program():
    def bad_swap(path):
        raise ValueError(f"lineage verification failed for {path}")

    tier = _make_tier()
    tier.set_checkpoint({"file": "v1.ckpt", "sha256": "a" * 64})
    tier.set_swap_fn(bad_swap)
    tier.start()
    driver = _serve_in_thread(tier)
    try:
        img = np.full(SHAPE, 3, np.uint8).tolist()
        status, body = _admin(tier.port, "/admin/reload",
                              {"checkpoint": "/tmp/torn.ckpt"})
        assert status == 500
        assert "lineage verification failed" in body["error"]
        # the old program is untouched and still answering
        assert _post(tier.port, img)[1]["label"] == 3
        assert tier.stats()["checkpoint"]["file"] == "v1.ckpt"
    finally:
        tier.close()
        driver.join(timeout=5)


# -- JAX-backed contracts ----------------------------------------------

@pytest.fixture(scope="module")
def mlp_serving():
    """A tiny trained-for-zero-epochs mlp engine + replicated state on
    the synthetic dataset: enough to pin predict_step semantics."""
    from distributedpytorch_tpu import runtime, utils
    from distributedpytorch_tpu.cli import (_build_engine, _place_state)
    from distributedpytorch_tpu.config import Config
    from distributedpytorch_tpu.data.datasets import load_dataset

    cfg = Config(action="serve", data_path="/tmp/nodata",
                 rsl_path="/tmp/serve_unit", dataset="synthetic",
                 model_name="mlp", batch_size=8, debug=True,
                 half_precision=False)
    dataset = load_dataset("synthetic", cfg.data_path, cfg.seed,
                           debug=True)
    mesh = runtime.make_serve_mesh()
    engine = _build_engine(cfg, "mlp", dataset, steps_per_epoch=1,
                           mesh=mesh)
    state = _place_state(engine.init_state(utils.root_key(cfg.seed)),
                         mesh, cfg)
    return cfg, dataset, engine, state


def test_predict_step_padded_rows_are_inert(mlp_serving):
    """The planner's correctness claim: a short batch padded with zero
    rows answers the real rows EXACTLY as the unpadded batch would —
    eval-mode apply makes every output row a function of its own input
    row only."""
    _cfg, dataset, engine, state = mlp_serving
    images = dataset.splits["test"].images[:3]
    labels_exact, confs_exact = engine.predict_step(state, images)
    padded = np.zeros((8,) + images.shape[1:], images.dtype)
    padded[:3] = images
    labels_pad, confs_pad = engine.predict_step(state, padded)
    np.testing.assert_array_equal(np.asarray(labels_pad)[:3],
                                  np.asarray(labels_exact))
    np.testing.assert_allclose(np.asarray(confs_pad)[:3],
                               np.asarray(confs_exact), rtol=1e-6)


def test_predict_step_confidence_is_max_softmax(mlp_serving):
    _cfg, dataset, engine, state = mlp_serving
    images = dataset.splits["test"].images[:4]
    labels, confs = engine.predict_step(state, images)
    labels, confs = np.asarray(labels), np.asarray(confs)
    assert labels.shape == (4,) and labels.dtype == np.int32
    assert np.all((0 < confs) & (confs <= 1.0))
    assert np.all((0 <= labels) & (labels < dataset.nb_classes))


def test_restore_for_serving_cross_layout(tmp_path, mlp_serving):
    """A scan-layout vit checkpoint restores into a PLAIN vit serving
    template (layout converted at load) and predicts identically to
    the scan engine that wrote it — the any-checkpoint contract."""
    from distributedpytorch_tpu import checkpoint as ckpt
    from distributedpytorch_tpu import runtime, utils
    from distributedpytorch_tpu.cli import (_build_engine, _place_state)
    from distributedpytorch_tpu.config import Config
    from distributedpytorch_tpu.data.datasets import load_dataset

    dataset = load_dataset("synthetic", "/tmp/nodata", 42, debug=True)
    mesh = runtime.make_serve_mesh()

    def build(scan_layers):
        cfg = Config(action="serve", data_path="/tmp/nodata",
                     rsl_path=str(tmp_path), dataset="synthetic",
                     model_name="vit", batch_size=8, debug=True,
                     half_precision=False, scan_layers=scan_layers)
        engine = _build_engine(cfg, "vit", dataset, steps_per_epoch=1,
                               mesh=mesh)
        state = _place_state(engine.init_state(utils.root_key(42)),
                             mesh, cfg)
        return cfg, engine, state

    _, scan_engine, scan_state = build(scan_layers=True)
    path = str(tmp_path / "bestmodel-synthetic-vit.ckpt")
    ckpt.save_checkpoint(path, "vit", scan_state, epoch=0,
                         best_valid_loss=1.0)

    cfg_plain, plain_engine, template = build(scan_layers=False)
    restored, epoch = ckpt.restore_for_serving(path, template)
    assert epoch == 0
    restored = _place_state(restored, mesh, cfg_plain)

    images = dataset.splits["test"].images[:4]
    labels_scan, confs_scan = scan_engine.predict_step(scan_state,
                                                       images)
    labels_plain, confs_plain = plain_engine.predict_step(restored,
                                                          images)
    np.testing.assert_array_equal(np.asarray(labels_plain),
                                  np.asarray(labels_scan))
    np.testing.assert_allclose(np.asarray(confs_plain),
                               np.asarray(confs_scan), atol=1e-5)


def test_tier_with_real_engine_round_trip(mlp_serving):
    """In-process e2e with the REAL predict program behind the HTTP
    front end: the cli.run_serve infer-closure shape, minus the CLI."""
    import jax

    from distributedpytorch_tpu import runtime

    _cfg, dataset, engine, state = mlp_serving
    mesh = runtime.make_serve_mesh()
    n_dev = int(mesh.devices.size)

    def infer(arr):
        sh = (runtime.data_sharding(mesh) if arr.shape[0] % n_dev == 0
              else runtime.replicated_sharding(mesh))
        labels, confs = engine.predict_step(state,
                                            jax.device_put(arr, sh))
        with runtime.sanctioned_host_transfer():
            return np.asarray(labels), np.asarray(confs)

    images = dataset.splits["test"].images
    tier = ServingTier(infer, images.shape[1:], images.dtype,
                       buckets=(1, 4), max_queue=8, max_latency_s=0.01,
                       port=0, request_timeout_s=30.0)
    tier.start()
    driver = _serve_in_thread(tier)
    try:
        status, body = _post(tier.port, images[0].tolist(), timeout=30.0)
        assert status == 200
        assert 0 <= body["label"] < dataset.nb_classes
        assert 0 < body["confidence"] <= 1.0
    finally:
        tier.close()
        driver.join(timeout=5)
