"""utils.GracefulShutdown unit semantics (ISSUE 5 satellite): the first
signal only sets the flag, a SECOND signal escalates to the previous
handler (a hung dispatch stays abortable), and construction off the
main thread is a clean no-op (Python restricts signal handlers to the
main thread)."""

import signal
import threading

from distributedpytorch_tpu import utils


def test_first_signal_sets_flag_and_run_continues():
    with utils.GracefulShutdown() as gs:
        assert not gs.requested
        signal.raise_signal(signal.SIGTERM)
        assert gs.requested  # flag only — no exception, no exit
    # context exit restored the previous handler
    assert signal.getsignal(signal.SIGTERM) is signal.SIG_DFL


def test_second_signal_escalates_to_previous_handler():
    hits = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
    try:
        with utils.GracefulShutdown() as gs:
            signal.raise_signal(signal.SIGTERM)
            assert gs.requested and hits == []
            # second signal: restore the pre-context handler and
            # re-raise through it — a force-abort, not another flag set
            signal.raise_signal(signal.SIGTERM)
            assert hits == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_non_main_thread_is_noop():
    before = signal.getsignal(signal.SIGTERM)
    result = {}

    def enter():
        with utils.GracefulShutdown() as gs:
            result["requested"] = gs.requested
            result["handler"] = signal.getsignal(signal.SIGTERM)

    t = threading.Thread(target=enter)
    t.start()
    t.join(timeout=30)
    assert not t.is_alive()
    assert result["requested"] is False
    assert result["handler"] is before  # never touched the handlers
    assert signal.getsignal(signal.SIGTERM) is before
