"""Device-side double-buffered prefetch (--device-prefetch,
pipeline.ShardedLoader): a dedicated transfer thread issues the sharded
``device_put`` for batches t+1..t+N while step t computes.  Like the
threaded producers, it must be invisible except for speed — identical
batch stream (values AND order) to the synchronous path under every
(device_prefetch x producer_threads) combination, clean exception
propagation, no thread leaks — and it must compose with the elastic
loader lifecycle: ``release()`` stops/drains/joins in-flight transfer
machinery before the mesh is dropped, and a ``reshard()``-derived
loader keeps the knob and still covers the dataset exactly once."""

import threading
import time

import numpy as np
import pytest

from distributedpytorch_tpu import runtime, telemetry
from distributedpytorch_tpu.data.datasets import Split
from distributedpytorch_tpu.data.io import make_synthetic
from distributedpytorch_tpu.data.pipeline import ShardedLoader


@pytest.fixture
def restore_global():
    yield
    telemetry._active = telemetry.Telemetry(enabled=False)


def _split(num_train=128):
    tr_x, tr_y, _, _ = make_synthetic(num_train=num_train, num_test=8,
                                      image_size=28, channels=1, seed=0)
    return Split(tr_x, tr_y)


def _loader(device_prefetch, producer_threads=0, num_train=128,
            mesh=None, split=None):
    return ShardedLoader(split or _split(num_train),
                         mesh or runtime.make_mesh(),
                         batch_per_replica=2, shuffle=True, seed=7,
                         prefetch=2, producer_threads=producer_threads,
                         device_prefetch=device_prefetch)


def _materialize(loader, epoch):
    return [tuple(np.asarray(a) for a in batch)
            for batch in loader.epoch(epoch)]


@pytest.mark.parametrize("nthreads", [0, 2])
@pytest.mark.parametrize("depth", [1, 3])
def test_device_prefetch_stream_identical_to_sync(depth, nthreads):
    """Byte-identical values and order for any prefetch depth, with and
    without the host-side producer pool underneath, across epochs
    (distinct shuffles).  The single ordered transfer thread is what
    makes this hold by construction."""
    sync = _loader(0)
    prefetching = _loader(depth, producer_threads=nthreads)
    for epoch in (0, 1):
        got = _materialize(prefetching, epoch)
        want = _materialize(sync, epoch)
        assert len(got) == len(want) == len(sync)
        for g, w in zip(got, want):
            for ga, wa in zip(g, w):
                np.testing.assert_array_equal(ga, wa)


@pytest.mark.parametrize("nthreads", [0, 2])
def test_gather_failure_propagates_to_consumer(nthreads):
    loader = _loader(2, producer_threads=nthreads)
    orig = loader._host_batch

    def failing(per_rank, step):
        if step == 5:
            raise RuntimeError("corrupt shard")
        return orig(per_rank, step)

    loader._host_batch = failing
    got = []
    with pytest.raises(RuntimeError, match="corrupt shard"):
        for batch in loader.epoch(0):
            got.append(batch)
    # every batch before the failure was delivered in order
    assert len(got) == 5


def test_no_thread_leaks_across_epochs():
    loader = _loader(2, producer_threads=2)
    before = set(threading.enumerate())
    for epoch in range(3):
        for _ in loader.epoch(epoch):
            pass
    # partially-consumed epoch: generator close() must also reap the
    # transfer thread and any gather producers under it
    it = loader.epoch(3)
    next(it)
    it.close()
    deadline = time.monotonic() + 10
    while set(threading.enumerate()) - before \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert set(threading.enumerate()) == before


def test_release_drains_inflight_transfers():
    """Elastic pre-teardown: release() on a loader with an epoch mid-
    flight must stop, drain and JOIN the transfer machinery — no
    in-flight device_put may outlive the mesh it targets."""
    loader = _loader(3, producer_threads=2)
    before = set(threading.enumerate())
    it = loader.epoch(0)
    next(it)  # transfer thread live, queue filling
    assert loader._active_runs
    loader.release()
    assert loader._active_runs == []
    assert loader.mesh is None and loader.sharding is None
    deadline = time.monotonic() + 10
    while set(threading.enumerate()) - before \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert set(threading.enumerate()) == before
    it.close()


def test_reshard_keeps_knob_and_covers_exactly_once():
    """The reshard-derived loader inherits device_prefetch and, like any
    fresh loader, enumerates the dataset exactly once (valid-mask
    dedup) — the elastic resume contract."""
    from jax.sharding import Mesh
    import jax

    split = Split(
        images=np.arange(50 * 4, dtype=np.uint8).reshape(50, 2, 2),
        labels=np.arange(50, dtype=np.int32) % 10)
    n = len(jax.devices())
    old = ShardedLoader(split, Mesh(np.array(jax.devices()),
                                    (runtime.DATA_AXIS,)),
                        batch_per_replica=4, shuffle=True, seed=1,
                        device_prefetch=2, producer_threads=1)
    old.release()
    new_mesh = Mesh(np.array(jax.devices()[:max(1, n // 2)]),
                    (runtime.DATA_AXIS,))
    loader = old.reshard(new_mesh)
    assert loader.device_prefetch == 2
    assert loader.producer_threads == 1
    seen = []
    for images, labels, valid in loader.epoch(0):
        img = np.asarray(images)
        v = np.asarray(valid)
        # row i of the split is filled with i*4..i*4+3, so the [0,0]
        # pixel // 4 recovers the sample index
        seen.extend((img[v][:, 0, 0] // 4).tolist())
    assert sorted(seen) == list(range(50))


def test_device_wait_telemetry_counters(restore_global, tmp_path):
    """The prefetch consumer charges its blocking to a DEDICATED
    data/device_wait_s counter (goodput's data_wait attribution stays
    with the cli step loop), and the shared stream counters keep
    working."""
    loader = _loader(2, producer_threads=1)
    tel = telemetry.configure(str(tmp_path), enabled=True, rank=0)
    n = sum(1 for _ in loader.epoch(0))
    assert n == len(loader)
    assert tel.counter("data/batches").value == n
    assert tel.counter("data/device_wait_s").value >= 0.0
    assert 0 <= tel.counter("data/starved_steps").value <= n
    assert tel.counter("data/queue_depth_sum").value >= 0
    tel.close()


def test_device_wait_drops_vs_prefetch_off(restore_global, tmp_path):
    """The point of the knob: with a slow host gather and a busy
    consumer, the transfer thread hides the gather+H2D under compute
    and the consumer's blocking time drops vs prefetch-off (which pays
    the whole chain inline every step).  Same canned-stall shape as the
    CI overlap gate, kept coarse (2x) for loaded CI machines."""
    delay = 0.004

    def measure(depth):
        loader = _loader(depth, num_train=256)
        orig = loader._host_batch

        def slow(per_rank, step):
            time.sleep(delay)  # artificially slow host gather
            return orig(per_rank, step)

        loader._host_batch = slow
        tel = telemetry.configure(str(tmp_path / f"d{depth}"),
                                  enabled=True, rank=0)
        n = 0
        for _ in loader.epoch(0):
            time.sleep(delay)  # consumer busy: the compute to hide under
            n += 1
        assert n == len(loader)
        name = "data/device_wait_s" if depth else "data/wait_s"
        wait = tel.counter(name).value
        tel.close()
        return wait

    off = measure(0)
    on = measure(2)
    assert on < off / 2, (on, off)
