"""Best-model bookkeeping: the recorded best_valid_loss and the best-model
file must stay in sync across rolling checkpoints, chunked dispatch, and
resume (ref classif.py:176-192 semantics, minus its defects).
"""

import os

import pytest
from flax import serialization

from distributedpytorch_tpu import checkpoint as ckpt
from distributedpytorch_tpu.cli import run_train
from distributedpytorch_tpu.config import Config

_BASE = dict(action="train", data_path="/tmp/nodata", dataset="synthetic",
             model_name="mlp", batch_size=8, nb_epochs=2, debug=True,
             half_precision=False)


def _stored_loss(path: str) -> float:
    with open(path, "rb") as f:
        return float(serialization.msgpack_restore(f.read())["loss"])


def test_rolling_checkpoint_carries_updated_best(tmp_path):
    """An improving epoch's rolling file must store the NEW best, so a
    resume from it restores the same best the run logged."""
    result = run_train(Config(rsl_path=str(tmp_path), **_BASE))
    final = ckpt.checkpoint_path(str(tmp_path), "synthetic", "mlp", 1)
    assert _stored_loss(final) == pytest.approx(result["best_valid_loss"])


def test_resume_restores_logged_best(tmp_path):
    """Resume-after-improvement: restored best_valid_loss equals the one
    the first run recorded (VERDICT round-1 weak #3)."""
    r1 = run_train(Config(rsl_path=str(tmp_path), **_BASE))
    path = ckpt.checkpoint_path(str(tmp_path), "synthetic", "mlp", 1)
    r2 = run_train(Config(rsl_path=str(tmp_path), checkpoint_file=path,
                          **dict(_BASE, nb_epochs=3)))
    # epoch 2's valid loss can only lower the restored best, never raise it
    assert r2["best_valid_loss"] <= r1["best_valid_loss"] + 1e-12


def test_chunked_best_file_tracks_mid_chunk_improvement(tmp_path):
    """With epochs_per_dispatch covering all epochs, the first chunk always
    contains the first improvement (from inf), so bestmodel-* must exist and
    store the same best_valid_loss the run returned — even when the best
    epoch is not chunk-final."""
    result = run_train(Config(rsl_path=str(tmp_path), epochs_per_dispatch=2,
                              **_BASE))
    best = ckpt.best_model_path(str(tmp_path), "synthetic", "mlp")
    assert os.path.exists(best)
    assert _stored_loss(best) == pytest.approx(result["best_valid_loss"])
    # the rolling chunk-final file carries the same (updated) best
    final = ckpt.checkpoint_path(str(tmp_path), "synthetic", "mlp", 1)
    assert _stored_loss(final) == pytest.approx(result["best_valid_loss"])
