"""SPMD correctness: the sharded step equals the single-device step.

This is the DDP-equivalence proof (SURVEY §7 test plan: "8-way grad-mean ==
1-way big-batch grad"): one optimization step on a batch sharded over the
8-device 'data' mesh must produce the same parameters as the identical
global batch on a single device — i.e. XLA's inserted gradient reduction
is exactly DDP's allreduce-mean.
"""

import jax
import numpy as np
import pytest

from distributedpytorch_tpu import runtime
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.train.engine import Engine, make_optimizer


def _engine(model_name="cnn"):
    model = get_model(model_name, 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 10, False)
    return Engine(model, model_name, get_loss_fn("cross_entropy"), tx,
                  mean=0.5, std=0.25, input_size=28, half_precision=False)


def _global_batch(b=64, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 256, size=(b, 28, 28), dtype=np.uint8),
            rng.integers(0, 10, size=(b,)).astype(np.int32),
            np.ones(b, dtype=bool))


@pytest.mark.parametrize("model_name", ["cnn", "mlp"])
def test_sharded_step_equals_single_device_step(model_name):
    devices = jax.devices()
    assert len(devices) == 8
    mesh8 = runtime.make_mesh()
    eng = _engine(model_name)
    key = jax.random.PRNGKey(3)
    images, labels, valid = _global_batch(64)

    # 8-way: batch sharded over 'data', params replicated over the mesh.
    state8 = jax.device_put(eng.init_state(jax.random.PRNGKey(0)),
                            runtime.replicated_sharding(mesh8))
    shard = runtime.data_sharding(mesh8)
    s8, m8 = eng.train_step(state8,
                            jax.device_put(images, shard),
                            jax.device_put(labels, shard),
                            jax.device_put(valid, shard), key)

    # single device: same global batch, same init, same key.
    dev0 = devices[0]
    state1 = jax.device_put(eng.init_state(jax.random.PRNGKey(0)), dev0)
    s1, m1 = eng.train_step(state1,
                            jax.device_put(images, dev0),
                            jax.device_put(labels, dev0),
                            jax.device_put(valid, dev0), key)

    assert float(m8["loss"]) == pytest.approx(float(m1["loss"]), abs=1e-5)
    assert float(m8["correct"]) == float(m1["correct"])
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(s8.params)),
                    jax.tree_util.tree_leaves(jax.device_get(s1.params))):
        np.testing.assert_allclose(a, b, atol=5e-5)  # compiler reassociation


def test_uneven_world_metrics_are_global():
    """Masked metrics sum over all shards: accuracy counts every valid
    example exactly once (fixes SURVEY defect #9's shard-local metrics)."""
    mesh8 = runtime.make_mesh()
    eng = _engine()
    state = jax.device_put(eng.init_state(jax.random.PRNGKey(0)),
                           runtime.replicated_sharding(mesh8))
    images, labels, valid = _global_batch(64)
    valid[60:] = False  # simulate wraparound padding on the last shard
    shard = runtime.data_sharding(mesh8)
    out = eng.eval_step(state,
                        jax.device_put(images, shard),
                        jax.device_put(labels, shard),
                        jax.device_put(valid, shard))
    assert float(out["valid"]) == 60.0
    assert 0.0 <= float(out["correct"]) <= 60.0


def test_mesh_shapes_and_shardings():
    mesh = runtime.make_mesh()
    assert mesh.shape == {"data": 8, "model": 1}
    mesh2 = runtime.make_mesh(model_parallel=2)
    assert mesh2.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        runtime.make_mesh(model_parallel=3)
    with pytest.raises(ValueError):
        runtime.make_mesh(data_parallel=3, model_parallel=2)
