"""DistributedSampler-parity semantics (ref dataloader.py:147-152)."""

import numpy as np
import pytest

from distributedpytorch_tpu.data.sampler import ShardedSampler


def _all_ranks(n, world, batch, shuffle=True, seed=1234):
    return [ShardedSampler(num_samples=n, world_size=world, rank=r,
                           batch_size=batch, shuffle=shuffle, seed=seed)
            for r in range(world)]


def test_valid_positions_cover_dataset_exactly_once():
    samplers = _all_ranks(1000, 8, 16)
    idx = np.concatenate([s.epoch_indices(3)[0].ravel() for s in samplers])
    valid = np.concatenate([s.epoch_indices(3)[1].ravel() for s in samplers])
    assert sorted(idx[valid].tolist()) == list(range(1000))


def test_equal_shard_sizes_and_static_shapes():
    samplers = _all_ranks(1003, 8, 16)  # not divisible: wraparound pad
    shapes = {s.epoch_indices(0)[0].shape for s in samplers}
    assert shapes == {(samplers[0].batches_per_epoch, 16)}


def test_epoch_keyed_reshuffle_and_determinism():
    s = ShardedSampler(num_samples=512, world_size=4, rank=1, batch_size=8,
                       shuffle=True, seed=1234)
    e0a, _ = s.epoch_indices(0)
    e0b, _ = s.epoch_indices(0)
    e1, _ = s.epoch_indices(1)
    np.testing.assert_array_equal(e0a, e0b)
    assert e0a.tolist() != e1.tolist()


def test_all_ranks_agree_on_global_permutation():
    samplers = _all_ranks(256, 8, 4)
    perms = [s.global_permutation(7) for s in samplers]
    for p in perms[1:]:
        np.testing.assert_array_equal(perms[0], p)


def test_no_shuffle_is_identity_order():
    s = ShardedSampler(num_samples=64, world_size=1, rank=0, batch_size=8,
                       shuffle=False, seed=0)
    idx, valid = s.epoch_indices(0)
    np.testing.assert_array_equal(idx.ravel(), np.arange(64))
    assert valid.all()


def test_rank_out_of_range_rejected():
    with pytest.raises(ValueError):
        ShardedSampler(num_samples=10, world_size=2, rank=2, batch_size=2)
