"""The driver entry points must keep working: compile-check + dry-run."""

import jax
import numpy as np

import __graft_entry__ as graft


def test_entry_forward_is_jittable():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)
    assert bool(np.isfinite(np.asarray(out)).all())


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_2():
    graft.dryrun_multichip(2)
