"""The driver entry points must keep working: compile-check + dry-run."""

import os

import jax
import numpy as np
import pytest

import __graft_entry__ as graft

# dryrun_multichip provisions its own virtual-CPU platform; on a real-TPU
# suite run (DPT_TESTS_ON_TPU=1) that would re-point the whole process at
# CPU, silently degrading every later test — run it only on the CPU mesh.
_on_tpu = os.environ.get("DPT_TESTS_ON_TPU") == "1"


def test_entry_forward_is_jittable():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)
    assert bool(np.isfinite(np.asarray(out)).all())


@pytest.mark.skipif(_on_tpu, reason="would force the process onto CPU")
@pytest.mark.slow
def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


@pytest.mark.skipif(_on_tpu, reason="would force the process onto CPU")
def test_dryrun_multichip_2():
    graft.dryrun_multichip(2)
