"""Device-resident epoch mode == streaming mode, batch for batch.

The resident path (whole split in HBM, lax.scan over the epoch, one XLA
dispatch) must train *identically* to the streamed per-step path: same
sampler plan, same augmentation keys, same updates.
"""

import jax
import numpy as np
import pytest

from distributedpytorch_tpu import runtime
from distributedpytorch_tpu.data.datasets import Split
from distributedpytorch_tpu.data.pipeline import ResidentLoader, ShardedLoader
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.train.engine import Engine, make_optimizer


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(0)
    split = Split(
        images=rng.integers(0, 256, size=(200, 28, 28), dtype=np.uint8),
        labels=rng.integers(0, 10, size=(200,)).astype(np.int32))
    mesh = runtime.make_mesh()
    model = get_model("cnn", 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 10, False)
    engine = Engine(model, "cnn", get_loss_fn("cross_entropy"), tx,
                    mean=0.5, std=0.25, input_size=28, half_precision=False)

    def make_state():  # fresh each call: train_epoch donates its input
        return jax.device_put(engine.init_state(jax.random.PRNGKey(0)),
                              runtime.replicated_sharding(mesh))

    return split, mesh, engine, make_state


def test_resident_plan_matches_streaming_batches(setup):
    split, mesh, _, _make_state = setup
    res = ResidentLoader(split, mesh, 4, shuffle=True, seed=1234)
    stream = ShardedLoader(split, mesh, 4, shuffle=True, seed=1234)
    assert len(res) == len(stream)
    idx, valid = jax.device_get(res.epoch_plan(epoch=2))
    for step, (imgs, labels, v) in enumerate(stream.epoch(2)):
        np.testing.assert_array_equal(split.images[idx[step]],
                                      np.asarray(imgs))
        np.testing.assert_array_equal(split.labels[idx[step]],
                                      np.asarray(labels))
        np.testing.assert_array_equal(valid[step], np.asarray(v))


def test_resident_epoch_trains_identically_to_streaming(setup):
    split, mesh, engine, make_state = setup
    key = jax.random.PRNGKey(7)

    res = ResidentLoader(split, mesh, 4, shuffle=True, seed=1234)
    idx, valid = res.epoch_plan(epoch=0)
    state_res, metrics = engine.train_epoch(make_state(), res.images,
                                            res.labels, idx, valid, key)
    assert metrics["loss"].shape == (len(res),)

    stream = ShardedLoader(split, mesh, 4, shuffle=True, seed=1234)
    state_str = make_state()
    stream_losses = []
    for imgs, labels, v in stream.epoch(0):
        state_str, m = engine.train_step(state_str, imgs, labels, v, key)
        stream_losses.append(float(m["loss"]))

    # scan vs per-step programs differ only by compiler reassociation
    np.testing.assert_allclose(np.asarray(metrics["loss"]),
                               np.asarray(stream_losses), atol=1e-4)
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(state_res.params)),
            jax.tree_util.tree_leaves(jax.device_get(state_str.params))):
        np.testing.assert_allclose(a, b, atol=2e-3)
    assert int(state_res.step) == int(state_str.step) == len(res)


def test_resident_eval_epoch_matches_streaming(setup):
    split, mesh, engine, make_state = setup
    state0 = make_state()
    res = ResidentLoader(split, mesh, 4, shuffle=False, seed=1234)
    idx, valid = res.epoch_plan(epoch=0)
    tot_res = jax.device_get(
        engine.eval_epoch(state0, res.images, res.labels, idx, valid))

    stream = ShardedLoader(split, mesh, 4, shuffle=False, seed=1234)
    totals = {k: 0.0 for k in tot_res}
    for imgs, labels, v in stream.epoch(0):
        m = jax.device_get(engine.eval_step(state0, imgs, labels, v))
        for k in totals:
            totals[k] += float(m[k])

    for k in totals:
        assert float(tot_res[k]) == pytest.approx(totals[k], rel=1e-5)


def test_prefetch_queue_overlaps(setup):
    """VERDICT r5 item 6 (timing structure): with prefetch=N, the loader
    keeps the next batch(es) device_put — H2D in flight — while the
    consumer holds the previous one.  The queue must be (a) primed to
    depth N before the first yield and (b) non-empty through steady
    state, draining only for the final batches."""
    split, mesh, _, _make_state = setup
    loader = ShardedLoader(split, mesh, 4, shuffle=True, seed=7,
                           prefetch=2)
    n = len(loader)
    depths = []
    for i, (imgs, labels, valid) in enumerate(loader.epoch(0)):
        depths.append(len(loader._queue))
        assert imgs.shape[0] == loader.global_batch
    assert len(depths) == n
    # At yield time one slot was just popped and refills only after
    # control returns to the generator, so steady-state depth observed
    # by the consumer is prefetch-1 — i.e. one full batch is already on
    # device (H2D in flight) while this one is being consumed.
    assert all(d == 1 for d in depths[:-1]), depths
    # the tail drains: the last yield has nothing queued behind it
    assert depths[-1] == 0, depths
