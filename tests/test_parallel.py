"""Model-parallel parameter sharding: same math, different layout.

A train step with params/optimizer state sharded over the 'model' axis of
a 2-D (data=4, model=2) mesh must produce the same parameters and metrics
as the replicated 1-D run — XLA inserts the gathers; the math is unchanged.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributedpytorch_tpu import parallel, runtime
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.train.engine import Engine, make_optimizer


def _engine(optimizer="adam"):
    model = get_model("mlp", 10, half_precision=False)
    tx = make_optimizer(optimizer, 1e-3, 0.9, 0.1, steps_per_epoch=4,
                        feature_extract=False)
    return Engine(model, "mlp", get_loss_fn("cross_entropy"), tx,
                  mean=0.45, std=0.2, input_size=28, half_precision=False)


def _batch(n=16):
    rng = np.random.default_rng(0)
    return (rng.integers(0, 256, size=(n, 28, 28), dtype=np.uint8),
            rng.integers(0, 10, size=(n,)).astype(np.int32),
            np.ones(n, dtype=bool))


def test_leaf_spec_rules():
    # largest divisible axis is sharded
    assert parallel.leaf_spec((784, 512), 2) == P(parallel.MODEL_AXIS, None)
    assert parallel.leaf_spec((512, 784), 2) == P(None, parallel.MODEL_AXIS)
    assert parallel.leaf_spec((64,), 2) == P()          # below size floor
    assert parallel.leaf_spec((784, 512), 1) == P()     # no model axis
    # large but indivisible -> replicated, never an error
    assert parallel.leaf_spec((257, 263), 2, min_elements=1) == P()


def test_sharded_step_equals_replicated():
    # SGD for the param-equality check: its update is linear in the
    # gradient, so float-level grad equality shows through.  Adam's
    # first-step g/(sqrt(v)+eps) normalization turns fp-reassociation
    # noise on near-zero gradients (the two layouts decompose the
    # collectives differently) into +-lr sign flips — a property of Adam,
    # not of the sharding (same situation as tests/test_grad_accum.py).
    engine = _engine("SGD")
    images, labels, valid = _batch()
    key = jax.random.PRNGKey(1)

    # replicated baseline on the 1-D data mesh
    mesh1 = runtime.make_mesh()
    s_rep = jax.device_put(engine.init_state(jax.random.PRNGKey(0)),
                           runtime.replicated_sharding(mesh1))
    img1 = jax.device_put(images, runtime.data_sharding(mesh1))
    lab1 = jax.device_put(labels, runtime.data_sharding(mesh1))
    val1 = jax.device_put(valid, runtime.data_sharding(mesh1))
    s_rep, m_rep = engine.train_step(s_rep, img1, lab1, val1, key)

    # model-parallel layout on the 2-D (4, 2) mesh
    mesh2 = runtime.make_mesh(model_parallel=2)
    state = engine.init_state(jax.random.PRNGKey(0))
    sharding = parallel.state_sharding(state, mesh2)
    s_mp = jax.device_put(state, sharding)
    # at least one param tensor actually lives sharded over 'model'
    specs = {s.spec for s in jax.tree_util.tree_leaves(
        parallel.tree_sharding(state.params, mesh2))}
    assert any(parallel.MODEL_AXIS in (ax for ax in spec if ax)
               for spec in specs if spec), specs
    img2 = jax.device_put(images, runtime.data_sharding(mesh2))
    lab2 = jax.device_put(labels, runtime.data_sharding(mesh2))
    val2 = jax.device_put(valid, runtime.data_sharding(mesh2))
    s_mp, m_mp = engine.train_step(s_mp, img2, lab2, val2, key)

    assert float(m_rep["loss"]) == pytest.approx(float(m_mp["loss"]),
                                                 abs=1e-5)
    # Collective decomposition differs (reduce-scatter+gather vs
    # all-reduce), so fp reassociation noise gets amplified by Adam's
    # rescaling; bound the divergence far below one update step (lr=1e-3).
    for a, b in zip(jax.tree_util.tree_leaves(s_rep.params),
                    jax.tree_util.tree_leaves(s_mp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=0,
                                   atol=1e-4)


def test_model_parallel_cli_e2e(tmp_path):
    """--model-parallel 2 through the real driver: trains, checkpoints,
    and produces finite metrics on the (4, 2) mesh."""
    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    cfg = Config(action="train", data_path="/tmp/nodata", dataset="synthetic",
                 rsl_path=str(tmp_path), model_name="mlp", batch_size=8,
                 nb_epochs=1, debug=True, half_precision=False,
                 model_parallel=2)
    result = run_train(cfg)
    assert np.isfinite(result["history"][0]["train_loss"])
    assert (tmp_path / "bestmodel-synthetic-mlp.ckpt").exists()


def test_eval_step_with_sharded_params():
    engine = _engine()
    images, labels, valid = _batch()
    mesh2 = runtime.make_mesh(model_parallel=2)
    state = engine.init_state(jax.random.PRNGKey(0))
    s_mp = jax.device_put(state, parallel.state_sharding(state, mesh2))
    m = engine.eval_step(s_mp,
                         jax.device_put(images, runtime.data_sharding(mesh2)),
                         jax.device_put(labels, runtime.data_sharding(mesh2)),
                         jax.device_put(valid, runtime.data_sharding(mesh2)))
    assert np.isfinite(float(m["loss_numer"]))
    assert float(m["valid"]) == len(labels)
