"""Orbax directory checkpoints (--ckpt-format orbax): the TPU-native
sharded-save path.  Same five logical fields and the same train -> resume
-> test contract as the msgpack default; model-parallel state is saved
AS-LAID-OUT with no all-gather."""

import os

import jax
import numpy as np
import pytest

from distributedpytorch_tpu import checkpoint as ckpt
from distributedpytorch_tpu import parallel, runtime
from distributedpytorch_tpu.cli import run_test, run_train
from distributedpytorch_tpu.config import Config
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.train.engine import Engine, make_optimizer


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    rsl = str(tmp_path_factory.mktemp("orbax_rsl"))
    cfg = Config(action="train", data_path="/tmp/nodata", rsl_path=rsl,
                 dataset="synthetic", model_name="cnn", batch_size=8,
                 nb_epochs=1, debug=True, half_precision=False,
                 ckpt_format="orbax")
    result = run_train(cfg)
    return cfg, result


@pytest.mark.slow
def test_orbax_checkpoints_are_directories(trained):
    cfg, _ = trained
    rolling = ckpt.checkpoint_path(cfg.rsl_path, "synthetic", "cnn", 0)
    best = ckpt.best_model_path(cfg.rsl_path, "synthetic", "cnn")
    assert os.path.isdir(rolling) and os.path.isdir(best)
    assert os.path.exists(os.path.join(best, "meta.json"))
    assert ckpt.get_checkpoint_model_name(best) == "cnn"


@pytest.mark.slow
def test_orbax_resume_and_test_subcommand(trained):
    cfg, first = trained
    rolling = ckpt.checkpoint_path(cfg.rsl_path, "synthetic", "cnn", 0)
    result = run_train(cfg.replace(nb_epochs=2, checkpoint_file=rolling))
    assert [h["epoch"] for h in result["history"]] == [1]

    best = ckpt.best_model_path(cfg.rsl_path, "synthetic", "cnn")
    out = run_test(Config(action="test", data_path="/tmp/nodata",
                          rsl_path=cfg.rsl_path, dataset="synthetic",
                          debug=True, batch_size=8, checkpoint_file=best,
                          half_precision=False))
    assert out["model_name"] == "cnn"
    assert 0.0 <= out["test_acc"] <= 1.0


def test_orbax_roundtrip_bitwise(tmp_path):
    """save -> load restores every leaf exactly (both formats promise
    this; orbax goes through its own serialization)."""
    model = get_model("mlp", 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 4, False)
    engine = Engine(model, "mlp", get_loss_fn("cross_entropy"), tx,
                    mean=0.45, std=0.2, input_size=28,
                    half_precision=False)
    state = engine.init_state(jax.random.PRNGKey(7))
    rng = np.random.default_rng(0)
    state, _ = engine.train_step(
        state, rng.integers(0, 256, (8, 28, 28), np.uint8),
        rng.integers(0, 10, (8,)).astype(np.int32), np.ones(8, bool),
        jax.random.PRNGKey(1))

    path = str(tmp_path / "ck")
    ckpt.save_checkpoint(path, "mlp", state, 3, 0.25, fmt="orbax")
    template = engine.init_state(jax.random.PRNGKey(0))
    restored, next_epoch, best = ckpt.load_checkpoint(path, template)
    assert next_epoch == 4 and best == 0.25
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(state)),
                    jax.tree_util.tree_leaves(jax.device_get(restored))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_orbax_saves_sharded_state_without_gather(tmp_path):
    """Model-parallel state saves as-laid-out: no gather_replicated call,
    and the restore round-trips exactly."""
    model = get_model("mlp", 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 4, False)
    engine = Engine(model, "mlp", get_loss_fn("cross_entropy"), tx,
                    mean=0.45, std=0.2, input_size=28,
                    half_precision=False)
    mesh = runtime.make_mesh(model_parallel=2)
    state = engine.init_state(jax.random.PRNGKey(0))
    s_mp = jax.device_put(state, parallel.state_sharding(state, mesh))

    path = str(tmp_path / "ck")
    ckpt.save_checkpoint(path, "mlp", s_mp, 0, 1.0, fmt="orbax")
    template = engine.init_state(jax.random.PRNGKey(1))
    restored, _, _ = ckpt.load_checkpoint(path, template)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(state)),
                    jax.tree_util.tree_leaves(jax.device_get(restored))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_corrupt_orbax_dir_is_value_error(tmp_path):
    bad = tmp_path / "bad_ckpt"
    bad.mkdir()
    with pytest.raises(ValueError, match="orbax"):
        ckpt.get_checkpoint_model_name(str(bad))


def test_bad_ckpt_format_rejected(tmp_path):
    cfg = Config(action="train", data_path="/x", rsl_path=str(tmp_path),
                 ckpt_format="Orbax")
    with pytest.raises(ValueError, match="ckpt_format"):
        run_train(cfg)


def test_orbax_restore_without_optimizer_across_optimizers(tmp_path):
    """ADVICE r2: restore_optimizer=False must work even when the saved
    opt_state (adam: two moment trees) does not structurally match the
    current optimizer's (SGD+momentum: one trace tree) — the abstract
    restore template takes opt_state from the DISK metadata and discards
    it, grafting the fresh template opt_state back."""
    model = get_model("mlp", 10, half_precision=False)
    tx_adam = make_optimizer("adam", 1e-3, 0.9, 0.1, 4, False)
    eng_adam = Engine(model, "mlp", get_loss_fn("cross_entropy"), tx_adam,
                      mean=0.45, std=0.2, input_size=28,
                      half_precision=False)
    state = eng_adam.init_state(jax.random.PRNGKey(0))
    path = str(tmp_path / "ck_adam")
    ckpt.save_checkpoint(path, "mlp", state, 2, 0.5, fmt="orbax")

    tx_sgd = make_optimizer("SGD", 1e-3, 0.9, 0.1, 4, False)
    eng_sgd = Engine(model, "mlp", get_loss_fn("cross_entropy"), tx_sgd,
                     mean=0.45, std=0.2, input_size=28,
                     half_precision=False)
    template = eng_sgd.init_state(jax.random.PRNGKey(1))
    restored, next_epoch, best = ckpt.load_checkpoint(
        path, template, restore_optimizer=False)
    assert next_epoch == 3 and best == 0.5
    # params came from the checkpoint; opt_state stayed the SGD template's
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(state.params)),
            jax.tree_util.tree_leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (jax.tree_util.tree_structure(restored.opt_state)
            == jax.tree_util.tree_structure(template.opt_state))
