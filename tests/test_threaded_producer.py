"""Threaded host pipeline (--producer-threads, pipeline.ShardedLoader):
the background producers must be invisible except for speed — identical
batch stream (values AND order) to the synchronous path, clean exception
propagation, no thread leaks across epochs — and the telemetry split
must show the overlap: consumer wait_s drops when production overlaps
consumption, and the prefetch initial fill lands in data/warmup_s, not
wait_s."""

import threading
import time

import numpy as np
import pytest

from distributedpytorch_tpu import runtime, telemetry
from distributedpytorch_tpu.data.datasets import Split
from distributedpytorch_tpu.data.io import make_synthetic
from distributedpytorch_tpu.data.pipeline import ShardedLoader


@pytest.fixture
def restore_global():
    yield
    telemetry._active = telemetry.Telemetry(enabled=False)


def _split(num_train=128):
    tr_x, tr_y, _, _ = make_synthetic(num_train=num_train, num_test=8,
                                      image_size=28, channels=1, seed=0)
    return Split(tr_x, tr_y)


def _loader(producer_threads, prefetch=2, shuffle=True, num_train=128):
    return ShardedLoader(_split(num_train), runtime.make_mesh(),
                         batch_per_replica=2, shuffle=shuffle, seed=7,
                         prefetch=prefetch,
                         producer_threads=producer_threads)


def _materialize(loader, epoch):
    return [tuple(np.asarray(a) for a in batch)
            for batch in loader.epoch(epoch)]


@pytest.mark.parametrize("prefetch", [0, 2])
@pytest.mark.parametrize("nthreads", [1, 3])
def test_threaded_stream_identical_to_sync(prefetch, nthreads):
    """Byte-identical values and order for any thread count, under both
    prefetch depths, across epochs (distinct shuffles)."""
    sync = _loader(0, prefetch=prefetch)
    threaded = _loader(nthreads, prefetch=prefetch)
    for epoch in (0, 1):
        got = _materialize(threaded, epoch)
        want = _materialize(sync, epoch)
        assert len(got) == len(want) == len(sync)
        for g, w in zip(got, want):
            for ga, wa in zip(g, w):
                np.testing.assert_array_equal(ga, wa)


def test_producer_exception_propagates_to_consumer():
    loader = _loader(2)
    orig = loader._host_batch

    def failing(per_rank, step):
        if step == 5:
            raise RuntimeError("corrupt shard")
        return orig(per_rank, step)

    loader._host_batch = failing
    got = []
    with pytest.raises(RuntimeError, match="corrupt shard"):
        for batch in loader.epoch(0):
            got.append(batch)
    # every batch before the failure was delivered in order
    assert len(got) == 5


def test_no_thread_leaks_across_epochs():
    loader = _loader(2)
    before = set(threading.enumerate())
    for epoch in range(3):
        for _ in loader.epoch(epoch):
            pass
    # partially-consumed epoch: generator close() must also reap threads
    it = loader.epoch(3)
    next(it)
    it.close()
    deadline = time.monotonic() + 10
    while set(threading.enumerate()) - before \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    assert set(threading.enumerate()) == before


def test_threaded_wait_drops_vs_sync(restore_global, tmp_path):
    """The acceptance criterion: with a slow host gather and a busy
    consumer, the threaded producer overlaps production with consumption
    and data/wait_s (true consumer blocking) drops measurably vs the
    synchronous path, which pays the gather inline every step."""
    delay = 0.004

    def measure(nthreads):
        loader = _loader(nthreads, prefetch=2, num_train=256)
        orig = loader._host_batch

        def slow(per_rank, step):
            time.sleep(delay)  # artificially slow host gather
            return orig(per_rank, step)

        loader._host_batch = slow
        tel = telemetry.configure(str(tmp_path / f"t{nthreads}"),
                                  enabled=True, rank=0)
        n = 0
        for _ in loader.epoch(0):
            time.sleep(delay)  # consumer busy: the compute to hide under
            n += 1
        wait = tel.counter("data/wait_s").value
        batches = tel.counter("data/batches").value
        tel.close()
        assert batches == n == len(loader)
        return wait

    sync_wait = measure(0)
    threaded_wait = measure(1)
    # sync pays ~every gather inline; threaded hides it under the
    # consumer's own work.  Require at least a 2x drop (the observed
    # drop is far larger; 2x keeps the assert robust on loaded CI).
    assert threaded_wait < sync_wait / 2, (threaded_wait, sync_wait)


def test_prefetch_initial_fill_counts_as_warmup_not_wait(restore_global,
                                                         tmp_path):
    """Satellite fix: the sync prefetch>0 loop's initial fill happens
    before the consumer asked for anything — it must land in
    data/warmup_s, leaving data/wait_s as steady-state blocking only.
    Only the fill's two gathers are slowed, so before the fix wait_s
    would absorb ~2*delay and the discrimination is unambiguous."""
    delay = 0.05
    loader = _loader(0, prefetch=2, num_train=256)
    orig = loader._host_batch

    def slow_first_two(per_rank, step):
        if step < 2:  # exactly the prefetch=2 initial fill
            time.sleep(delay)
        return orig(per_rank, step)

    loader._host_batch = slow_first_two
    tel = telemetry.configure(str(tmp_path), enabled=True, rank=0)
    n = sum(1 for _ in loader.epoch(0))
    warmup = tel.counter("data/warmup_s").value
    wait = tel.counter("data/wait_s").value
    tel.close()
    assert n == len(loader)
    # the fill paid both slow gathers ...
    assert warmup >= 2 * delay * 0.9
    # ... and none of that time leaked into the steady-state counter
    assert wait < delay


def test_queue_introspection_and_counters_threaded(restore_global,
                                                   tmp_path):
    loader = _loader(2, prefetch=2)
    tel = telemetry.configure(str(tmp_path), enabled=True, rank=0)
    n = sum(1 for _ in loader.epoch(0))
    assert n == len(loader)
    assert tel.counter("data/batches").value == n
    assert tel.counter("data/queue_depth_sum").value >= 0
    assert 0 <= tel.counter("data/starved_steps").value <= n
    # the bounded queues are exposed for tests/bench introspection
    assert isinstance(loader._queue, list) and len(loader._queue) == 2
    tel.close()


def test_threaded_disabled_telemetry_counts_nothing(restore_global):
    loader = _loader(1)
    tel = telemetry.get()
    assert not tel.enabled
    n = sum(1 for _ in loader.epoch(0))
    assert n == len(loader)
    assert tel.counter("data/batches").value == 0
