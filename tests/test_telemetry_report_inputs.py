"""Telemetry report robustness (ISSUE 3 satellite): the aggregator must
degrade gracefully on the inputs real runs produce — an empty run dir, a
rank file missing (killed host), a torn last line (killed mid-write),
and hand-mangled event fields — through both CLI entries.
"""

import json
import os
import subprocess
import sys

import pytest

from distributedpytorch_tpu import telemetry
from distributedpytorch_tpu.telemetry import (aggregate, load_events,
                                              render_report, report)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_rank(tmp_path, rank, events, torn_tail=None):
    d = tmp_path / "telemetry"
    d.mkdir(exist_ok=True)
    lines = [json.dumps(e) for e in events]
    if torn_tail is not None:
        lines.append(torn_tail)
    (d / f"rank{rank}.jsonl").write_text("\n".join(lines) + "\n")
    return str(d)


def _span(rank, name, dur, **attrs):
    ev = {"kind": "span", "name": name, "dur_s": dur, "parent": None,
          "ts": 1.0, "rank": rank}
    if attrs:
        ev["attrs"] = attrs
    return ev


def test_missing_directory_is_value_error(tmp_path):
    with pytest.raises(ValueError, match="no telemetry directory"):
        load_events(str(tmp_path / "telemetry"))


def test_empty_directory_is_value_error(tmp_path):
    (tmp_path / "telemetry").mkdir()
    with pytest.raises(ValueError, match="no telemetry events"):
        load_events(str(tmp_path / "telemetry"))


def test_partial_one_rank_missing(tmp_path):
    """2-of-3 ranks present (one host died before flushing): the report
    still renders, scoped to the ranks that wrote files."""
    _write_rank(tmp_path, 0, [_span(0, "epoch", 1.0, epoch=0),
                              _span(0, "train_pass", 0.7, epoch=0)])
    d = _write_rank(tmp_path, 2, [_span(2, "epoch", 3.0, epoch=0)])
    agg = aggregate(load_events(d))
    assert agg["ranks"] == [0, 2]
    text = render_report(agg)
    assert "2 rank(s)" in text
    assert "slowest" in text  # straggler view over the present ranks
    assert agg["epoch_s_per_rank"][2] == pytest.approx(3.0)


def test_truncated_last_line_skipped(tmp_path):
    """A run killed mid-write leaves a torn final line; it must be
    skipped, not crash the whole report."""
    d = _write_rank(tmp_path, 0,
                    [_span(0, "epoch", 1.0),
                     {"kind": "counter", "name": "data/batches",
                      "value": 8, "ts": 1.0, "rank": 0}],
                    torn_tail='{"kind": "span", "name": "tr')
    events = load_events(d)
    assert len(events) == 2
    agg = aggregate(events)
    assert agg["counters"]["data/batches"] == 8


def test_malformed_events_skipped_not_fatal(tmp_path):
    """Events with wrong-typed fields (hand-edited files, version skew)
    are counted as skipped, and the rest still aggregate."""
    d = _write_rank(tmp_path, 0, [
        _span(0, "epoch", 1.0),
        {"kind": "counter", "name": "data/batches", "value": "NaNope",
         "ts": 1.0, "rank": 0},                       # bad value type
        {"kind": "gauge", "name": "throughput/mfu", "value": [1],
         "ts": 1.0, "rank": 0},                       # bad value type
        {"kind": "span", "name": 7, "dur_s": 1.0,
         "ts": 1.0, "rank": 0},                       # bad name type
        {"kind": "mystery", "name": "x", "ts": 1.0, "rank": 0},
        {"kind": "counter", "name": "data/batches", "value": 3,
         "ts": 1.0, "rank": "zero"},                  # bad rank type
    ])
    agg = aggregate(load_events(d))
    assert agg["skipped_events"] == 5
    # none of the counter rows were well-formed, so the counter is absent
    assert "data/batches" not in agg["counters"]
    assert agg["spans"]["epoch"]["count"] == 1
    assert "malformed event(s) skipped" in render_report(agg)


def test_report_entry_points_empty_dir(tmp_path):
    """Both CLI entries surface the empty-input error as exit 1 with a
    message, not a traceback."""
    from distributedpytorch_tpu.cli import main

    assert main(["telemetry", "--rsl_path", str(tmp_path)]) == 1

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "telemetry_report.py"),
         "--rsl_path", str(tmp_path)],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert "error:" in proc.stderr
    assert "Traceback" not in proc.stderr


def test_report_function_on_partial_run(tmp_path):
    _write_rank(tmp_path, 0, [_span(0, "epoch", 1.0)])
    text = report(str(tmp_path))
    assert "telemetry report" in text


def test_close_is_idempotent_after_partial_configure(tmp_path):
    """configure() then immediate close() leaves no file when nothing
    was emitted — and a second close is a no-op."""
    tel = telemetry.configure(str(tmp_path), enabled=True, rank=5)
    tel.event("run_start")
    tel.close()
    tel.close()
    path = tmp_path / "telemetry" / "rank5.jsonl"
    lines = path.read_text().strip().splitlines()
    assert len(lines) == 1
    # restore the module singleton for other tests
    telemetry.configure(str(tmp_path), enabled=False)
