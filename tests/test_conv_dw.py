"""Patch-reuse Pallas conv-dW (ops/conv.py) pinned against XLA autodiff
of the identical conv: forward, dx, dW — plus the SmallCNN flag path's
param-tree compatibility.  On the CPU mesh the kernel runs in Pallas
interpret mode; bench.py / scripts measure the Mosaic lowering on chip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu.ops import conv as conv_mod
from distributedpytorch_tpu.ops.conv import conv3x3_dw, conv3x3_same


def _ref_conv(x, w):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


@pytest.mark.parametrize("shape", [(4, 28, 28, 32, 32), (2, 14, 14, 32, 64),
                                   (8, 14, 14, 64, 64), (3, 8, 8, 32, 32)])
def test_grads_match_xla_autodiff(shape):
    b, h, w, ci, co = shape
    kx, kw, kg = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (b, h, w, ci), jnp.float32)
    wgt = jax.random.normal(kw, (3, 3, ci, co), jnp.float32) * 0.1
    dy = jax.random.normal(kg, (b, h, w, co), jnp.float32)

    np.testing.assert_allclose(
        np.asarray(conv3x3_same(x, wgt)), np.asarray(_ref_conv(x, wgt)),
        rtol=1e-5, atol=1e-5)

    def loss(f):
        return lambda a, k: jnp.sum(f(a, k) * dy)

    dx_ref, dw_ref = jax.grad(loss(_ref_conv), argnums=(0, 1))(x, wgt)
    dx_got, dw_got = jax.grad(loss(conv3x3_same), argnums=(0, 1))(x, wgt)
    np.testing.assert_allclose(np.asarray(dx_got), np.asarray(dx_ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dw_got), np.asarray(dw_ref),
                               rtol=2e-4, atol=2e-3)


def test_dw_kernel_direct():
    """conv3x3_dw alone vs an einsum reference over the padded input."""
    kx, kg = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.normal(kx, (4, 10, 10, 32), jnp.float32)
    dy = jax.random.normal(kg, (4, 10, 10, 32), jnp.float32)
    got = conv3x3_dw(x, dy)
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    want = np.stack([np.stack([
        np.einsum("bhwc,bhwd->cd", np.asarray(xp[:, kh:kh + 10,
                                                 kw:kw + 10, :]),
                  np.asarray(dy))
        for kw in range(3)], 0) for kh in range(3)], 0)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-3)


def test_chunking_respects_budget_and_divides():
    # budget is honored for the ACTUAL element width (ADVICE #2): bf16
    # and f32 chunks both fit, and f32 chunks are no larger than bf16's
    for itemsize in (2, 4):
        for b in (1, 6, 64, 512):
            bc = conv_mod._chunk(b, 28, 28, 32, itemsize)
            assert b % bc == 0
            assert (bc * 28 * 28 * 9 * 32 * itemsize
                    <= conv_mod._PATCH_VMEM_BUDGET) or bc == 1
            assert bc <= conv_mod._chunk(b, 28, 28, 32, 2)
    # big batch on the small feature map still fits
    assert conv_mod._chunk(512, 14, 14, 64, 4) >= 1


def test_smallcnn_flag_same_tree_and_close_grads():
    """pallas_dw=True: identical param tree (checkpoint-interchangeable)
    and matching loss gradients on the same init."""
    from distributedpytorch_tpu.models.simple import SmallCNN

    x = jax.random.normal(jax.random.PRNGKey(2), (4, 28, 28, 3),
                          jnp.float32)
    plain = SmallCNN(num_classes=10, dtype=jnp.float32)
    fast = SmallCNN(num_classes=10, dtype=jnp.float32, pallas_dw=True)
    p0 = plain.init({"params": jax.random.PRNGKey(3)}, x)["params"]
    p1 = fast.init({"params": jax.random.PRNGKey(3)}, x)["params"]
    assert jax.tree_util.tree_structure(p0) == \
        jax.tree_util.tree_structure(p1)
    for a, b in zip(jax.tree_util.tree_leaves(p0),
                    jax.tree_util.tree_leaves(p1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def loss(model, p):
        return jnp.sum(model.apply({"params": p}, x) ** 2)

    g0 = jax.grad(lambda p: loss(plain, p))(p0)
    g1 = jax.grad(lambda p: loss(fast, p))(p0)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-3)


def test_pallas_dw_registry_validation():
    from distributedpytorch_tpu.models import get_model

    with pytest.raises(ValueError, match="cnn model only"):
        get_model("vit", 10, pallas_dw=True)
    model = get_model("cnn", 10, pallas_dw=True, half_precision=False)
    assert getattr(model, "pallas_dw") is True
