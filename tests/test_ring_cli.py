"""Ring attention as a PRODUCT path (VERDICT round-2 item #3): the CLI's
``--attention ring`` trains a ViT end-to-end through ``run_train`` on the
(data, model) mesh, and the result pins to the identical run with fused
full attention — same seed, same data, same sharded-parameter layout, the
ONLY difference being the attention implementation."""

import jax
import numpy as np
import pytest

from distributedpytorch_tpu.cli import run_test, run_train
from distributedpytorch_tpu.config import Config
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu import runtime

# subprocess worlds / full CLI chains: the slow tier (scripts/gate.sh runs -m 'not slow')
pytestmark = pytest.mark.slow


def _cfg(tmp_path, name, **kw):
    kw.setdefault("model_parallel", 2)
    return Config(action="train", data_path="/tmp/nodata",
                  rsl_path=str(tmp_path / name), dataset="synthetic",
                  model_name="vit", batch_size=4, nb_epochs=1, debug=True,
                  half_precision=False, **kw)


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("ring_cli")
    full = run_train(_cfg(tmp_path, "full", attention="full"))
    ring = run_train(_cfg(tmp_path, "ring", attention="ring"))
    return tmp_path, full, ring


def test_ring_cli_trains_to_same_params_as_full(trained):
    _, full, ring = trained
    fleaves = jax.tree_util.tree_leaves(
        jax.device_get(full["state"].params))
    rleaves = jax.tree_util.tree_leaves(
        jax.device_get(ring["state"].params))
    assert len(fleaves) == len(rleaves) > 0
    for i, (f, r) in enumerate(zip(fleaves, rleaves)):
        # flash-merge summation order differs from the fused softmax, so
        # a trained epoch accumulates small drift (measured max ~5e-4 on
        # ~1e-3-magnitude params); the tight per-step equivalence lives
        # in test_attention.py, and the loss-history pin below stays 1e-3
        np.testing.assert_allclose(
            np.asarray(r), np.asarray(f), rtol=1e-2, atol=1.5e-3,
            err_msg=f"param leaf {i}: ring-trained != full-trained")


def test_ring_cli_history_matches_full(trained):
    _, full, ring = trained
    f, r = full["history"][0], ring["history"][0]
    assert abs(f["train_loss"] - r["train_loss"]) < 1e-3
    assert abs(f["valid_loss"] - r["valid_loss"]) < 1e-3


def test_ring_checkpoint_tests_through_cli(trained):
    tmp_path, full, ring = trained
    import os

    best = os.path.join(str(tmp_path / "ring"),
                        "bestmodel-synthetic-vit.ckpt")
    assert os.path.exists(best)
    res = run_test(Config(
        action="test", data_path="/tmp/nodata", rsl_path=str(tmp_path / "t"),
        dataset="synthetic", checkpoint_file=best, debug=True,
        half_precision=False, model_parallel=2, attention="ring"))
    assert res["model_name"] == "vit"
    assert 0.0 <= res["test_acc"] <= 1.0


def test_ring_requires_vit():
    with pytest.raises(ValueError, match="attention model family"):
        get_model("cnn", 10, attention="ring",
                  mesh=runtime.make_mesh(model_parallel=2))


def test_ring_requires_model_axis(tmp_path):
    with pytest.raises(ValueError, match="model-parallel"):
        run_train(_cfg(tmp_path, "bad", attention="ring",
                       model_parallel=1))
