"""Transfer-guard sanitizer (analysis/transfer_guard.py): the smoke
passes on the real training loop and FAILS when a per-step host sync —
the paper's own bug class (ref classif.py:61-62) — is injected.

Also pins the fact that motivates the sanitizer's patched-primitive
layer: on the CPU backend jax's native transfer guard records no
device->host transfer at all (a CPU buffer is already host memory), so
without the shim a CPU smoke would be vacuous.
"""

import jax
import jax.numpy as jnp
import pytest

from distributedpytorch_tpu import runtime
from distributedpytorch_tpu.analysis import transfer_guard as tg


def test_native_guard_is_vacuous_on_cpu():
    """The design premise: if this ever starts raising, the patched
    primitives could be retired in favor of the native guard alone."""
    x = jnp.ones(4) + 1
    with jax.transfer_guard_device_to_host("disallow_explicit"):
        jax.device_get(x)  # does NOT raise on the CPU backend


def test_patched_primitives_block_unsanctioned_syncs():
    x = jnp.ones(4) + 1
    with tg._patched_sync_primitives():
        with pytest.raises(tg.HostTransferViolation):
            jax.device_get(x)
        with pytest.raises(tg.HostTransferViolation):
            float(x[0])
        with pytest.raises(tg.HostTransferViolation):
            x[0].item()
        # the sanctioned context re-allows, and nests
        with runtime.sanctioned_host_transfer():
            assert float(jax.device_get(x)[0]) == 2.0
    # patches restored: unguarded sync works again
    assert float(x[0]) == 2.0


def test_patched_primitives_restore_on_error():
    orig = jax.device_get
    with pytest.raises(RuntimeError, match="boom"):
        with tg._patched_sync_primitives():
            raise RuntimeError("boom")
    assert jax.device_get is orig


def test_smoke_passes_on_clean_loop(tmp_path):
    assert tg.run_smoke(rsl_path=str(tmp_path)) is True


def test_smoke_fails_on_injected_per_step_device_get(tmp_path):
    """Acceptance criterion: a deliberate per-step jax.device_get in
    the train loop turns the smoke red."""
    assert tg.run_smoke(rsl_path=str(tmp_path),
                        inject_host_sync=True) is False


def test_injection_does_not_leak(tmp_path):
    from distributedpytorch_tpu import cli

    before = cli._build_engine
    tg.run_smoke(rsl_path=str(tmp_path), inject_host_sync=True)
    assert cli._build_engine is before
