"""Tensor parallelism for the vit family (VERDICT round-2 item #4):
sharded-ACTIVATION Megatron-style TP via parallel.make_tp_constrain, as
distinct from the ZeRO-style parameter sharding --model-parallel alone
provides.  Pinned three ways on the 8-device virtual mesh:

  1. identical params -> identical logits (constraints change layout,
     never math);
  2. e2e: run_train with --tensor-parallel equals the same run without;
  3. the compiled train step's per-device temp (activation) memory is
     measurably smaller with TP — the property ZeRO cannot provide.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu import parallel, runtime
from distributedpytorch_tpu.cli import run_train
from distributedpytorch_tpu.config import Config
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.models.vit import ViT


def test_tp_logits_equal_plain():
    mesh = runtime.make_mesh(model_parallel=4)  # (data=2, model=4)
    x = jax.random.normal(jax.random.PRNGKey(0), (8, 28, 28, 3))
    plain = ViT(num_classes=10, dtype=jnp.float32)
    tp = ViT(num_classes=10, dtype=jnp.float32,
             tp_constrain=parallel.make_tp_constrain(mesh))
    params = plain.init({"params": jax.random.PRNGKey(1)}, x)["params"]
    want = plain.apply({"params": params}, x)
    got = jax.jit(lambda p, a: tp.apply({"params": p}, a))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def _cfg(tmp_path, name, **kw):
    return Config(action="train", data_path="/tmp/nodata",
                  rsl_path=str(tmp_path / name), dataset="synthetic",
                  model_name="vit", batch_size=4, nb_epochs=1, debug=True,
                  half_precision=False, model_parallel=2, **kw)


@pytest.mark.slow
def test_tp_cli_trains_to_same_params(tmp_path):
    base = run_train(_cfg(tmp_path, "base"))
    tp = run_train(_cfg(tmp_path, "tp", tensor_parallel=True))
    b = jax.tree_util.tree_leaves(jax.device_get(base["state"].params))
    t = jax.tree_util.tree_leaves(jax.device_get(tp["state"].params))
    assert len(b) == len(t) > 0
    for i, (x, y) in enumerate(zip(b, t)):
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(x), rtol=2e-4, atol=2e-4,
            err_msg=f"param leaf {i}: TP-trained != replicated-trained")


def test_tp_requires_vit_and_model_axis():
    mesh2 = runtime.make_mesh(model_parallel=2)
    with pytest.raises(ValueError, match="attention model family"):
        get_model("resnet", 10, tensor_parallel=True, mesh=mesh2)
    with pytest.raises(ValueError, match="model-parallel"):
        get_model("vit", 10, tensor_parallel=True,
                  mesh=runtime.make_mesh())
    with pytest.raises(ValueError, match="pick one"):
        get_model("vit", 10, tensor_parallel=True, attention="ring",
                  mesh=mesh2)


def _compiled_train_memory(tp: bool) -> float:
    """Per-device temp (activation/workspace) bytes of a compiled ViT
    fwd+bwd step on the (data=2, model=4) mesh, sized so activations
    dominate (dim 256, 196 tokens, batch 16)."""
    mesh = runtime.make_mesh(model_parallel=4)
    model = ViT(num_classes=10, patch=4, dim=256, depth=2, heads=8,
                dtype=jnp.float32,
                tp_constrain=parallel.make_tp_constrain(mesh) if tp
                else None)
    x = jnp.zeros((16, 56, 56, 3), jnp.float32)
    params = jax.jit(model.init)({"params": jax.random.PRNGKey(0)},
                                 x)["params"]
    params = jax.device_put(params, runtime.replicated_sharding(mesh))
    xs = jax.device_put(x, runtime.data_sharding(mesh))

    def loss(p, a):
        return jnp.sum(model.apply({"params": p}, a, train=True) ** 2)

    compiled = jax.jit(jax.grad(loss)).lower(params, xs).compile()
    mem = compiled.memory_analysis()
    if mem is None:
        pytest.skip("backend reports no memory analysis")
    temp = getattr(mem, "temp_size_in_bytes", None)
    if not temp:
        pytest.skip("backend reports no temp size")
    return float(temp)


def test_tp_shrinks_activation_memory():
    full = _compiled_train_memory(tp=False)
    tp = _compiled_train_memory(tp=True)
    # Megatron TP over 4-way 'model': head/hidden activations drop ~4x;
    # require a conservative >=25% whole-step drop so the test stays
    # robust to XLA workspace noise.
    assert tp < 0.75 * full, \
        f"TP temp {tp / 1e6:.1f} MB not < 75% of full {full / 1e6:.1f} MB"
