"""Dataset loading, splits, stats, augmentation, sharded loader."""

import gzip
import os
import struct

import jax
import numpy as np

from distributedpytorch_tpu import runtime
from distributedpytorch_tpu.data import augment, datasets, io, pipeline


def test_devices_are_virtual_cpu_mesh():
    assert jax.devices()[0].platform == "cpu"
    assert jax.device_count() == 8


def test_synthetic_dataset_shapes_and_stats():
    ds = datasets.load_dataset("synthetic", "/tmp/none", seed=1234)
    assert len(ds.splits["train"]) == 54000      # 90% of 60000
    assert len(ds.splits["valid"]) == 6000
    assert len(ds.splits["test"]) == 10000
    assert ds.splits["train"].images.dtype == np.uint8
    assert 0.0 < ds.mean < 1.0 and 0.0 < ds.std < 1.0
    assert ds.nb_classes == 10
    w = ds.class_weights()
    assert w.shape == (10,) and np.all(w > 0)


def test_debug_subset_is_200(tmp_path):
    ds = datasets.load_dataset("synthetic", str(tmp_path), seed=1234,
                               debug=True)
    assert len(ds.splits["train"]) == 200       # ref dataloader.py:141


def test_idx_roundtrip(tmp_path):
    """Write the MNIST wire format (gzipped) and read it back."""
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, size=(7, 28, 28), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(7,), dtype=np.uint8)
    raw = tmp_path / "MNIST" / "raw"
    os.makedirs(raw)

    def _write(name, arr):
        header = struct.pack(">HBB", 0, 0x08, arr.ndim)
        header += struct.pack(">" + "I" * arr.ndim, *arr.shape)
        with gzip.open(raw / (name + ".gz"), "wb") as f:
            f.write(header + arr.tobytes())

    _write("train-images-idx3-ubyte", imgs)
    _write("train-labels-idx1-ubyte", labels)
    _write("t10k-images-idx3-ubyte", imgs)
    _write("t10k-labels-idx1-ubyte", labels)

    tr_x, tr_y, te_x, te_y = io.load_mnist_like(str(tmp_path), "MNIST")
    np.testing.assert_array_equal(tr_x, imgs)
    np.testing.assert_array_equal(tr_y, labels)
    np.testing.assert_array_equal(te_x, imgs)


def test_train_transform_shapes_channels_determinism():
    imgs = np.random.default_rng(0).integers(
        0, 256, size=(8, 28, 28), dtype=np.uint8)
    key = jax.random.PRNGKey(42)
    out = augment.train_transform(key, imgs, 0.5, 0.25, 28)
    assert out.shape == (8, 28, 28, 3)
    # grayscale -> 3 identical channels (ref TensorRepeat)
    np.testing.assert_allclose(out[..., 0], out[..., 1])
    # same key -> identical; different key -> different
    out2 = augment.train_transform(key, imgs, 0.5, 0.25, 28)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2))
    out3 = augment.train_transform(jax.random.PRNGKey(43), imgs, 0.5, 0.25, 28)
    assert not np.allclose(np.asarray(out), np.asarray(out3))


def test_train_transform_rgb():
    imgs = np.random.default_rng(0).integers(
        0, 256, size=(4, 32, 32, 3), dtype=np.uint8)
    out = augment.train_transform(jax.random.PRNGKey(0), imgs, 0.5, 0.25, 32)
    assert out.shape == (4, 32, 32, 3)


def test_eval_transform_is_deterministic_resize_normalize():
    imgs = np.full((2, 28, 28), 128, dtype=np.uint8)
    out = augment.eval_transform(imgs, 0.5, 0.25, 56)
    assert out.shape == (2, 56, 56, 3)
    # constant image: resize exact, normalize = (128/255 - .5)/.25
    expected = np.full_like(np.asarray(out), (128 / 255 - 0.5) / 0.25)
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-4)


def test_sharded_loader_batches():
    ds = datasets.load_dataset("synthetic", "/tmp/none", seed=1234)
    mesh = runtime.make_mesh()
    ld = pipeline.ShardedLoader(ds.splits["valid"], mesh, 16,
                                shuffle=True, seed=1234)
    assert ld.global_batch == 16 * 8
    steps = 0
    for imgs, labels, valid in ld.epoch(0):
        assert imgs.shape == (128, 28, 28)
        assert labels.shape == (128,)
        assert imgs.sharding.spec == jax.sharding.PartitionSpec("data")
        assert len(imgs.addressable_shards) == 8
        steps += 1
    assert steps == len(ld)
    # epoch coverage: all valid labels across ranks match dataset exactly
    total_valid = sum(int(np.asarray(v).sum()) for _, _, v in ld.epoch(1))
    assert total_valid == len(ds.splits["valid"])


def test_auto_residency_bounded_by_device_memory(monkeypatch):
    """'auto' residency accounts for real HBM (VERDICT r1 weak #8): the
    per-split budget is min(resident_max_bytes, 30% of device memory)."""
    from distributedpytorch_tpu import cli
    from distributedpytorch_tpu.config import Config

    ds = datasets.load_dataset("synthetic", "/tmp/none", seed=1234)
    mesh = runtime.make_mesh()
    split = ds.splits["valid"]  # ~4.7 MB raw
    cfg = Config(action="train", data_path="/x", data_mode="auto")

    # Plenty of memory (or unknown, the CPU case): resident.
    monkeypatch.setattr(runtime, "device_memory_limit", lambda: None)
    assert isinstance(cli._make_loader(cfg, split, mesh, False),
                      pipeline.ResidentLoader)
    monkeypatch.setattr(runtime, "device_memory_limit",
                        lambda: 16 * 1024**3)
    assert isinstance(cli._make_loader(cfg, split, mesh, False),
                      pipeline.ResidentLoader)

    # Tiny device memory: 30% of it is below the split size -> stream,
    # even though resident_max_bytes alone would have allowed residency.
    monkeypatch.setattr(runtime, "device_memory_limit",
                        lambda: split.images.nbytes)
    assert isinstance(cli._make_loader(cfg, split, mesh, False),
                      pipeline.ShardedLoader)

    # Explicit resident mode bypasses the budget (user asserted it fits).
    cfg_r = Config(action="train", data_path="/x", data_mode="resident")
    assert isinstance(cli._make_loader(cfg_r, split, mesh, False),
                      pipeline.ResidentLoader)
