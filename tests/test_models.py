"""Model zoo: registry, shapes, param-count parity, freeze masks.

Heavy architectures are validated with jax.eval_shape (topology and
parameter counts, no FLOPs) so the suite stays fast on one CPU core;
small models run real forwards.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu import models
from distributedpytorch_tpu.models.registry import (AUX_LOGIT_MODELS,
                                                    DROPOUT_MODELS)

RNGS = {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)}


def _shape_init(name, batch=2, num_classes=10):
    m = models.get_model(name, num_classes, half_precision=False)
    size = models.get_model_input_size(name)
    x = jnp.zeros((batch, size, size, 3), jnp.float32)
    variables = jax.eval_shape(
        functools.partial(m.init, train=True), RNGS, x)
    out = jax.eval_shape(
        lambda v, x: m.apply(v, x, train=False), variables, x)
    return m, variables, out


# Param counts with 10 classes; every torchvision-derived architecture is
# pinned to torchvision's corresponding model head-swapped to 10 classes
# (ref utils.py:38-105).  vgg11_bn: 132,868,840 total − 4,097,000 (1000-way
# classifier[6]) + 40,970 (10-way) = 128,812,810 — torchvision keeps conv
# bias on even with BN, so ours does too.  inception_v3 (aux_logits=True):
# 27,161,264 − 2,049,000 (fc) − 769,000 (AuxLogits.fc) + 20,490 + 7,690
# = 24,371,444 (both heads replaced, ref utils.py:93-98).
_EXPECTED_PARAMS = {
    "resnet": 11_181_642,
    "alexnet": 57_044_810,
    "vgg": 128_812_810,
    "squeezenet": 740_554,
    "densenet": 6_964_106,
    "inception": 24_371_444,
}


@pytest.mark.parametrize("name", sorted(models.MODEL_REGISTRY))
def test_zoo_shapes_and_counts(name):
    _, variables, out = _shape_init(name)
    assert out.shape == (2, 10)
    n = sum(int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(variables["params"]))
    if name in _EXPECTED_PARAMS:
        assert n == _EXPECTED_PARAMS[name], name
    assert n > 1000


def test_inception_returns_aux_logits_in_train_mode():
    m = models.get_model("inception", 10, half_precision=False)
    x = jnp.zeros((2, 299, 299, 3), jnp.float32)
    variables = jax.eval_shape(functools.partial(m.init, train=True), RNGS, x)
    out = jax.eval_shape(
        lambda v, x: m.apply(v, x, train=True,
                             rngs={"dropout": jax.random.PRNGKey(0)},
                             mutable=["batch_stats"])[0],
        variables, x)
    assert isinstance(out, tuple) and len(out) == 2  # (logits, aux_logits)
    assert out[0].shape == (2, 10) and out[1].shape == (2, 10)
    assert "inception" in AUX_LOGIT_MODELS and "inception" in DROPOUT_MODELS


def test_small_models_forward_real():
    x = jnp.ones((4, 28, 28, 3), jnp.float32)
    for name in ("cnn", "mlp"):
        m = models.get_model(name, 10, half_precision=False)
        v = m.init(RNGS, x, train=True)
        out = m.apply(v, x, train=False)
        assert out.shape == (4, 10)
        assert out.dtype == jnp.float32
        assert bool(jnp.isfinite(out).all())


def test_bfloat16_compute_float32_params():
    m = models.get_model("cnn", 10, half_precision=True)
    x = jnp.ones((2, 28, 28, 3), jnp.float32)
    v = m.init(RNGS, x, train=True)
    for p in jax.tree_util.tree_leaves(v["params"]):
        assert p.dtype == jnp.float32  # master weights stay f32
    assert m.apply(v, x, train=False).dtype == jnp.float32  # logits f32


def test_inception_small_input_trains_error_not_nan():
    """Below the aux head's 17x17 feature-map floor, train mode raises a
    clear error instead of silently producing NaN logits."""
    m = models.get_model("inception", 10, half_precision=False)
    x = jnp.zeros((2, 128, 128, 3), jnp.float32)
    with pytest.raises(ValueError, match="aux head"):
        jax.eval_shape(functools.partial(m.init, train=True), RNGS, x)


def test_invalid_model_name_raises():
    with pytest.raises(ValueError, match="Invalid model name"):
        models.get_model("nope", 10)
    with pytest.raises(ValueError):
        models.get_model_input_size("nope")


def test_input_size_registry_matches_reference():
    # ref utils.py:24-36 — 224 for all torchvision models, 299 inception
    for name in ("resnet", "alexnet", "vgg", "squeezenet", "densenet"):
        assert models.get_model_input_size(name) == 224
    assert models.get_model_input_size("inception") == 299
    assert models.get_model_input_size("cnn") == 28


def test_trainable_mask_labels_head_vs_backbone():
    m = models.get_model("resnet", 10, half_precision=False)
    x = jnp.zeros((1, 224, 224, 3), jnp.float32)
    variables = jax.eval_shape(functools.partial(m.init, train=True), RNGS, x)
    mask = models.trainable_mask(variables["params"])
    labels = set(jax.tree_util.tree_leaves(mask))
    assert labels == {"head", "backbone"}
    assert set(jax.tree_util.tree_leaves(mask["head"])) == {"head"}
