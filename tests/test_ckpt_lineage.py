"""Checkpoint lineage (ISSUE 5): content checksums in the rolling
ledger, verify-on-load, loud fallback past a torn head to the newest
valid snapshot, keep-K rotation, AsyncSaver degrade-to-sync, and the
one-line actionable errors for missing/garbage orbax meta.json."""

import json
import os

import jax
import numpy as np
import pytest

from distributedpytorch_tpu import checkpoint as ckpt
from distributedpytorch_tpu import telemetry
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.train.engine import Engine, make_optimizer


@pytest.fixture
def restore_global():
    yield
    telemetry._active = telemetry.Telemetry(enabled=False)


@pytest.fixture(scope="module")
def trained_state():
    model = get_model("mlp", 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 4, False)
    engine = Engine(model, "mlp", get_loss_fn("cross_entropy"), tx,
                    mean=0.45, std=0.2, input_size=28,
                    half_precision=False)
    return engine, engine.init_state(jax.random.PRNGKey(7))


def _save_epochs(rsl, state, epochs):
    paths = []
    for e in epochs:
        p = ckpt.checkpoint_path(rsl, "synthetic", "mlp", e)
        ckpt.save_checkpoint(p, "mlp", state, e, 0.5 - 0.1 * e)
        paths.append(p)
    return paths


# -- lineage ledger + verify-on-load -----------------------------------


def test_save_records_lineage_and_verifies(tmp_path, trained_state):
    _, state = trained_state
    (path,) = _save_epochs(str(tmp_path), state, [0])
    doc = json.load(open(ckpt.lineage_path(str(tmp_path))))
    (rec,) = [r for r in doc["records"]
              if r["file"] == os.path.basename(path)]
    assert rec["epoch"] == 0 and rec["bytes"] == os.path.getsize(path)
    assert len(rec["sha256"]) == 64
    assert ckpt.verify_checkpoint(path) is None


def test_verify_detects_torn_file(tmp_path, trained_state):
    _, state = trained_state
    (path,) = _save_epochs(str(tmp_path), state, [0])
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    reason = ckpt.verify_checkpoint(path)
    assert reason is not None and "checksum mismatch" in reason


def test_unrecorded_file_stays_loadable(tmp_path, trained_state):
    # pre-lineage checkpoints (no ledger entry) must not be rejected
    _, state = trained_state
    (path,) = _save_epochs(str(tmp_path), state, [0])
    os.remove(ckpt.lineage_path(str(tmp_path)))
    assert ckpt.verify_checkpoint(path) is None
    ckpt.load_checkpoint(path, state)


# -- fallback past a torn head -----------------------------------------


def test_fallback_skips_torn_head_loudly(tmp_path, trained_state,
                                         restore_global):
    telemetry._active = telemetry.Telemetry(
        enabled=True, rsl_path=str(tmp_path), rank=0)
    _, state = trained_state
    p0, p1 = _save_epochs(str(tmp_path), state, [0, 1])
    with open(p1, "r+b") as f:  # tear the newest (head) snapshot
        f.truncate(os.path.getsize(p1) // 2)
    _, start_epoch, _ = ckpt.load_checkpoint_with_fallback(
        p1, state, str(tmp_path), "synthetic", "mlp")
    assert start_epoch == 1  # resumed from epoch 0 -> next epoch is 1
    telemetry.get().close()
    events = [json.loads(l) for l in
              open(tmp_path / "telemetry" / "rank0.jsonl")]
    fb = [e for e in events if e.get("kind") == "event"
          and e.get("name") == "ckpt_fallback"]
    assert len(fb) == 1
    assert fb[0]["attrs"]["skipped"] == os.path.basename(p1)


def test_fallback_exhausted_is_actionable(tmp_path, trained_state):
    _, state = trained_state
    (p0,) = _save_epochs(str(tmp_path), state, [0])
    with open(p0, "r+b") as f:
        f.truncate(os.path.getsize(p0) // 2)
    with pytest.raises(ValueError, match="no valid checkpoint"):
        ckpt.load_checkpoint_with_fallback(
            p0, state, str(tmp_path), "synthetic", "mlp")


# -- keep-K rotation ---------------------------------------------------


def test_rotation_keeps_k_newest(tmp_path, trained_state):
    _, state = trained_state
    rsl = str(tmp_path)
    for e in range(4):
        _save_epochs(rsl, state, [e])
        ckpt.rotate_checkpoint(rsl, "synthetic", "mlp", e, keep=2)
    kept = ckpt.list_checkpoints(rsl, "synthetic", "mlp")
    assert [os.path.basename(p) for p in kept] == [
        "checkpoint-synthetic-mlp-003.ckpt",
        "checkpoint-synthetic-mlp-002.ckpt"]
    # rotated-away files are pruned from the ledger too
    doc = json.load(open(ckpt.lineage_path(rsl)))
    assert {r["file"] for r in doc["records"]} == {
        os.path.basename(p) for p in kept}


# -- AsyncSaver degrade-to-sync ----------------------------------------


def test_saver_degrade_switches_to_sync(restore_global):
    saver = ckpt.AsyncSaver(on_error="degrade")
    ran = []

    def boom():
        raise OSError("disk full")

    saver.submit(boom)
    saver.wait()  # with on_error='raise' this would re-raise
    assert saver.degraded
    saver.submit(lambda: ran.append("sync"))  # runs on THIS thread
    assert ran == ["sync"]
    saver.close()


def test_saver_default_still_raises():
    saver = ckpt.AsyncSaver()

    def boom():
        raise OSError("disk full")

    saver.submit(boom)
    with pytest.raises(OSError, match="disk full"):
        saver.wait()
    saver.close()


# -- orbax meta.json actionable errors (ISSUE 5 satellite) -------------


def test_missing_meta_is_one_line_actionable(tmp_path):
    d = tmp_path / "notackpt"
    d.mkdir()
    with pytest.raises(ValueError) as ei:
        ckpt.load_checkpoint(str(d), None)
    msg = str(ei.value)
    assert "missing meta.json" in msg and "--ckpt-format orbax" in msg
    assert "\n" not in msg  # ONE line, not a traceback dump


def test_garbage_meta_is_one_line_actionable(tmp_path):
    d = tmp_path / "corrupt"
    d.mkdir()
    (d / "meta.json").write_text("not json {")
    with pytest.raises(ValueError) as ei:
        ckpt.load_checkpoint(str(d), None)
    msg = str(ei.value)
    assert "garbage meta.json" in msg
    assert "restore from" in msg  # says what to DO about it
    assert "\n" not in msg


# -- lineage_info: the served-model identity (ISSUE 19) -----------------


def test_lineage_info_reads_ledger_and_hashes_loose_files(tmp_path,
                                                          trained_state):
    import hashlib

    _, state = trained_state
    (path,) = _save_epochs(str(tmp_path), state, [2])
    info = ckpt.lineage_info(path)
    assert info["epoch"] == 2 and len(info["sha256"]) == 64
    assert info["file"] == os.path.basename(path)
    assert ckpt.verify_checkpoint(path) is None
    # pre-lineage loose file: identity computed from content
    loose = tmp_path / "loose.ckpt"
    loose.write_bytes(b"payload")
    info2 = ckpt.lineage_info(str(loose))
    assert info2["sha256"] == hashlib.sha256(b"payload").hexdigest()
    assert info2["epoch"] is None
    # unreadable path: no identity, no exception
    assert ckpt.lineage_info(str(tmp_path / "missing.ckpt")) is None
