"""Engine: optimizer parity, train-step mechanics, checkpoint round-trip."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu import checkpoint as ckpt
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.train.engine import Engine, make_optimizer


def _make_engine(model_name="cnn", optimizer="adam", feature_extract=False,
                 loss="cross_entropy", class_weights=None):
    model = get_model(model_name, 10, half_precision=False)
    loss_fn = get_loss_fn(loss, class_weights)
    tx = make_optimizer(optimizer, 1e-3, 0.9, 0.1, steps_per_epoch=10,
                        feature_extract=feature_extract)
    return Engine(model, model_name, loss_fn, tx, mean=0.5, std=0.25,
                  input_size=28, half_precision=False)


def _batch(b=16, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(b, 28, 28), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(b,)).astype(np.int32)
    valid = np.ones(b, dtype=bool)
    return images, labels, valid


def test_train_step_reduces_loss_and_increments_step():
    eng = _make_engine()
    state = eng.init_state(jax.random.PRNGKey(0))
    # Learnable batch: brightness encodes the label, surviving the random
    # crop/rotation the train step applies on device.
    labels = np.tile(np.arange(10), 7)[:64].astype(np.int32)
    images = np.broadcast_to(
        (labels * 25 + 15)[:, None, None], (64, 28, 28)).astype(np.uint8)
    valid = np.ones(64, dtype=bool)
    key = jax.random.PRNGKey(1)
    losses = []
    for _ in range(30):
        state, metrics = eng.train_step(state, images, labels, valid, key)
        losses.append(float(metrics["loss"]))
    assert int(state.step) == 30
    assert losses[-1] < losses[0] * 0.75  # fits the signal


def test_valid_mask_excludes_padding_from_loss_and_metrics():
    eng = _make_engine()
    state = eng.init_state(jax.random.PRNGKey(0))
    images, labels, _ = _batch(8)
    full = eng.eval_step(state, images, labels, np.ones(8, dtype=bool))
    half_mask = np.array([True] * 4 + [False] * 4)
    half = eng.eval_step(state, images, labels, half_mask)
    assert float(half["valid"]) == 4.0
    assert float(full["valid"]) == 8.0
    # masked-out examples contribute nothing
    first4 = eng.eval_step(state, images[:4], labels[:4],
                           np.ones(4, dtype=bool))
    assert float(half["loss_numer"]) == pytest.approx(
        float(first4["loss_numer"]), rel=1e-5)
    assert float(half["correct"]) == float(first4["correct"])


def test_sgd_step_lr_schedule_decays_per_epoch():
    tx = make_optimizer("SGD", 1e-3, 0.9, 0.1, steps_per_epoch=5,
                        feature_extract=False)
    params = {"w": jnp.ones((4,))}
    opt_state = tx.init(params)
    grads = {"w": jnp.ones((4,))}
    lrs = []
    for _ in range(12):
        updates, opt_state = tx.update(grads, opt_state, params)
        lrs.append(float(-updates["w"][0]))
    # momentum warms up within an epoch; ratio across epoch boundary = 0.1
    assert lrs[5] / lrs[4] < 0.2     # decayed after 5 steps
    assert lrs[10] / lrs[9] < 0.2    # and again after 10


def test_invalid_optimizer_raises():
    with pytest.raises(ValueError, match="Invalid optimizer"):
        make_optimizer("nope", 1e-3, 0.9, 0.1, 1, False)


def test_feature_extract_freezes_backbone():
    eng = _make_engine(feature_extract=True)
    state = eng.init_state(jax.random.PRNGKey(0))
    images, labels, valid = _batch(16)
    before = jax.device_get(state.params)
    state2, _ = eng.train_step(state, images, labels, valid,
                               jax.random.PRNGKey(1))
    after = jax.device_get(state2.params)
    # head moved
    assert not np.allclose(before["head"]["kernel"],
                           after["head"]["kernel"])
    # backbone frozen (ref utils.py:107-110 semantics)
    for name in before:
        if name == "head":
            continue
        np.testing.assert_array_equal(before[name]["kernel"],
                                      after[name]["kernel"])


def test_checkpoint_roundtrip_restores_bitwise(tmp_path):
    eng = _make_engine()
    state = eng.init_state(jax.random.PRNGKey(0))
    images, labels, valid = _batch(32)
    state, _ = eng.train_step(state, images, labels, valid,
                              jax.random.PRNGKey(1))
    path = str(tmp_path / "ck.ckpt")
    ckpt.save_checkpoint(path, "cnn", state, epoch=3, best_valid_loss=0.25)

    fresh = eng.init_state(jax.random.PRNGKey(7))
    restored, next_epoch, best = ckpt.load_checkpoint(path, fresh)
    assert next_epoch == 4 and best == 0.25     # ref utils.py:133-134
    assert ckpt.get_checkpoint_model_name(path) == "cnn"
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(state)),
                    jax.tree_util.tree_leaves(jax.device_get(restored))):
        np.testing.assert_array_equal(a, b)
    # training continues identically from the restored state
    s1, m1 = eng.train_step(state, images, labels, valid,
                            jax.random.PRNGKey(2))
    s2, m2 = eng.train_step(restored, images, labels, valid,
                            jax.random.PRNGKey(2))
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), abs=1e-6)


def test_checkpoint_rotation_deletes_previous_epoch(tmp_path):
    eng = _make_engine()
    state = eng.init_state(jax.random.PRNGKey(0))
    rsl = str(tmp_path)
    for epoch in range(3):
        ckpt.rotate_checkpoint(rsl, "mnist", "cnn", epoch)
        ckpt.save_checkpoint(
            ckpt.checkpoint_path(rsl, "mnist", "cnn", epoch),
            "cnn", state, epoch, 1.0)
    files = sorted(f for f in os.listdir(rsl) if f.startswith("checkpoint"))
    # only the newest rolling file survives (fixes SURVEY defect #5)
    assert files == ["checkpoint-mnist-cnn-002.ckpt"]


def test_epoch_keys_match_streaming_derivation():
    """_epoch_keys hoists per-step PRNG derivation out of the epoch scan
    assuming state.step advances by exactly 1 per iteration; pin the
    hoisted keys to the streaming path's per-step fold_in+split at the
    key level (first, middle and last step) so a change to the step
    increment breaks loudly here, not as a silent resident!=streaming
    numerics drift."""
    eng = _make_engine()
    state = eng.init_state(jax.random.PRNGKey(0))
    # advance a few steps so state.step != 0
    images, labels, valid = _batch(8)
    for _ in range(3):
        state, _ = eng.train_step(state, images, labels, valid,
                                  jax.random.PRNGKey(9))
    key = jax.random.PRNGKey(5)
    n = 7
    aug_keys, dropout_keys = jax.device_get(eng._epoch_keys(state, key, n))
    for i in (0, n // 2, n - 1):
        step_key = jax.random.fold_in(key, int(state.step) + i)
        want_aug, want_drop = jax.device_get(jax.random.split(step_key))
        np.testing.assert_array_equal(aug_keys[i], want_aug)
        np.testing.assert_array_equal(dropout_keys[i], want_drop)


def test_weighted_loss_engine_path():
    w = np.linspace(0.5, 2.0, 10).astype(np.float32)
    eng = _make_engine(loss="weighted_cross_entropy", class_weights=w)
    state = eng.init_state(jax.random.PRNGKey(0))
    images, labels, valid = _batch(16)
    state, metrics = eng.train_step(state, images, labels, valid,
                                    jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))
