"""graftlint (distributedpytorch_tpu/analysis): every rule has a
positive (bad) and negative (good) fixture, suppressions need a
rationale, and the repo itself lints clean through both CLI entries.

Fixtures are written to tmp files with the basenames the file-targeted
rules key on (cli.py, engine.py, config.py) — the linter is
project-path driven, so a tmp project is a first-class subject.
"""

import json
import os
import textwrap

from distributedpytorch_tpu.analysis.core import (DEFAULT_SCOPE,
                                                  lint_paths,
                                                  render_findings,
                                                  run_cli)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(tmp_path, sources, rule=None):
    """Write {basename: source} into tmp_path, lint, return findings
    (optionally filtered to one rule)."""
    for name, src in sources.items():
        (tmp_path / name).write_text(textwrap.dedent(src))
    findings, _ = lint_paths([str(tmp_path)], root=str(tmp_path))
    if rule is not None:
        findings = [f for f in findings if f.rule == rule]
    return findings


# -- rule 1: host-sync-in-step-loop -----------------------------------

_STEP_LOOP_BAD = """
    import jax

    def drive(loader, engine, state, key):
        for images, labels, valid in loader.epoch(0):
            state, metrics = engine.train_step(state, images, labels,
                                               valid, key)
            loss = float(metrics["loss"])      # per-batch sync: BAD
        return state
"""

_STEP_LOOP_GOOD = """
    import jax

    def drive(loader, engine, state, key):
        losses = []
        for images, labels, valid in loader.epoch(0):
            state, metrics = engine.train_step(state, images, labels,
                                               valid, key)
            losses.append(metrics["loss"])     # stays on device
        return state, jax.device_get(losses)   # ONE per-epoch sync
"""


def test_host_sync_positive(tmp_path):
    found = _lint(tmp_path, {"cli.py": _STEP_LOOP_BAD},
                  rule="host-sync-in-step-loop")
    assert len(found) == 1 and "float()" in found[0].message


def test_host_sync_negative(tmp_path):
    assert _lint(tmp_path, {"cli.py": _STEP_LOOP_GOOD},
                 rule="host-sync-in-step-loop") == []


def test_host_sync_item_and_device_get(tmp_path):
    src = """
        import jax

        def drive(loader, engine, state):
            for step in range(loader.batches_per_epoch):
                m = engine.train_step(state)
                a = m["loss"].item()
                b = jax.device_get(m)
            return state
    """
    found = _lint(tmp_path, {"engine.py": src},
                  rule="host-sync-in-step-loop")
    assert len(found) == 2


def test_host_sync_only_in_targeted_files(tmp_path):
    # the same loop in a non-step-driving module is out of scope
    assert _lint(tmp_path, {"other.py": _STEP_LOOP_BAD},
                 rule="host-sync-in-step-loop") == []


# -- rule 2: trace-impurity -------------------------------------------

def test_trace_impurity_positive(tmp_path):
    src = """
        import time
        import jax

        @jax.jit
        def step(x):
            t0 = time.perf_counter()    # trace-time only: BAD
            print("step", x)            # trace-time only: BAD
            return x * 2
    """
    found = _lint(tmp_path, {"mod.py": src}, rule="trace-impurity")
    assert len(found) == 2
    assert any("print" in f.message for f in found)
    assert any("time.perf_counter" in f.message for f in found)


def test_trace_impurity_transitive_and_method(tmp_path):
    src = """
        import jax

        class Engine:
            def __init__(self):
                self.train_step = jax.jit(self._train_step)

            def _train_step(self, state, x):
                return self._helper(state, x)

            def _helper(self, state, x):
                self.cached = x        # trace-time mutation: BAD
                return x + 1
    """
    found = _lint(tmp_path, {"mod.py": src}, rule="trace-impurity")
    assert len(found) == 1 and "self.cached" in found[0].message


def test_trace_impurity_negative(tmp_path):
    src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.sum(x * 2)

        def host_logging(x):
            print("not traced:", x)    # fine outside traced functions
    """
    assert _lint(tmp_path, {"mod.py": src}, rule="trace-impurity") == []


# -- rule 3: collective-axis-consistency ------------------------------

def test_collective_axis_positive(tmp_path):
    src = """
        import jax
        import numpy as np
        from jax.sharding import Mesh

        def make_mesh(devs):
            return Mesh(np.array(devs), ("data", "model"))

        def reduce_ok(x):
            return jax.lax.psum(x, "data")

        def reduce_typo(x):
            return jax.lax.psum(x, "dta")   # undeclared axis: BAD
    """
    found = _lint(tmp_path, {"mod.py": src},
                  rule="collective-axis-consistency")
    assert len(found) == 1 and "'dta'" in found[0].message


def test_collective_axis_constant_and_default(tmp_path):
    src = """
        import jax
        import numpy as np
        from jax.sharding import Mesh

        DATA_AXIS = "data"

        def make_mesh(devs):
            return Mesh(np.array(devs), (DATA_AXIS,))

        def by_constant(x):
            return jax.lax.pmean(x, DATA_AXIS)          # ok

        def by_default(x, axis_name="data"):
            return jax.lax.all_gather(x, axis_name)     # ok (default)

        def bad_default(x, axis_name="modell"):
            return jax.lax.ppermute(x, axis_name, [(0, 1)])   # BAD
    """
    found = _lint(tmp_path, {"mod.py": src},
                  rule="collective-axis-consistency")
    assert len(found) == 1 and "'modell'" in found[0].message


# -- rule 4: prng-reuse ------------------------------------------------

def test_prng_reuse_positive(tmp_path):
    src = """
        import jax

        def sample(shape):
            key = jax.random.PRNGKey(0)
            a = jax.random.normal(key, shape)
            b = jax.random.uniform(key, shape)   # same key again: BAD
            return a + b
    """
    found = _lint(tmp_path, {"mod.py": src}, rule="prng-reuse")
    assert len(found) == 1 and "'key'" in found[0].message


def test_prng_reuse_negative_split(tmp_path):
    src = """
        import jax

        def sample(shape):
            key = jax.random.PRNGKey(0)
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, shape)
            b = jax.random.uniform(k2, shape)
            return a + b

        def derive_many(root):
            # fold_in/split are derivations, not consumptions
            keys = [jax.random.fold_in(root, i) for i in range(4)]
            return keys
    """
    assert _lint(tmp_path, {"mod.py": src}, rule="prng-reuse") == []


def test_prng_reuse_in_loop(tmp_path):
    src = """
        import jax

        def sample(shape):
            key = jax.random.PRNGKey(0)
            out = []
            for i in range(4):
                out.append(jax.random.normal(key, shape))  # reuse: BAD
            return out
    """
    found = _lint(tmp_path, {"mod.py": src}, rule="prng-reuse")
    assert len(found) == 1


def test_prng_reuse_branches_not_double_counted(tmp_path):
    src = """
        import jax

        def sample(flag, shape):
            key = jax.random.PRNGKey(0)
            if flag:
                return jax.random.normal(key, shape)
            else:
                return jax.random.uniform(key, shape)
    """
    assert _lint(tmp_path, {"mod.py": src}, rule="prng-reuse") == []


# -- rule 5: missing-donation -----------------------------------------

def test_missing_donation_positive(tmp_path):
    src = """
        import jax

        class Engine:
            def __init__(self):
                self.train_step = jax.jit(self._train_step)  # BAD

            def _train_step(self, state, batch):
                return state
    """
    found = _lint(tmp_path, {"mod.py": src}, rule="missing-donation")
    assert len(found) == 1 and "donate_argnums" in found[0].message


def test_missing_donation_negative(tmp_path):
    src = """
        import jax

        class Engine:
            def __init__(self):
                self.train_step = jax.jit(self._train_step,
                                          donate_argnums=0)
                self.eval_step = jax.jit(self._eval_step)  # eval: fine

            def _train_step(self, state, batch):
                return state

            def _eval_step(self, state, batch):
                return {"loss": 0.0}
    """
    assert _lint(tmp_path, {"mod.py": src},
                 rule="missing-donation") == []


# -- rule 6: thread-shared-state --------------------------------------

_THREAD_BAD = """
    import threading

    class Worker:
        def __init__(self):
            self._done = False

        def start(self):
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def _run(self):
            self._done = True

        def poll(self):
            return self._done        # unguarded cross-thread read: BAD
"""


def test_thread_shared_state_positive(tmp_path):
    found = _lint(tmp_path, {"mod.py": _THREAD_BAD},
                  rule="thread-shared-state")
    assert any(f.line and "_done" in f.message and "poll" in f.message
               for f in found)


def test_thread_shared_state_lock_negative(tmp_path):
    src = """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                with self._lock:
                    self._done = False

            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                with self._lock:
                    self._done = True

            def poll(self):
                with self._lock:
                    return self._done
    """
    assert _lint(tmp_path, {"mod.py": src},
                 rule="thread-shared-state") == []


def test_thread_shared_state_guarded_by_negative(tmp_path):
    src = """
        import threading

        class Worker:
            def __init__(self):
                # graftlint: guarded-by=join -- set before the thread
                # exits; poll() only runs after join()
                self._done = False

            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                self._done = True

            def poll(self):
                return self._done
    """
    assert _lint(tmp_path, {"mod.py": src},
                 rule="thread-shared-state") == []


def test_thread_shared_state_queue_exempt(tmp_path):
    src = """
        import queue
        import threading

        class Worker:
            def __init__(self):
                self._q = queue.Queue()

            def start(self):
                self._t = threading.Thread(target=self._run)
                self._t.start()

            def _run(self):
                self._q = self._q   # rebind of a thread-safe type
                self._q.put(1)

            def poll(self):
                return self._q.get()
    """
    assert _lint(tmp_path, {"mod.py": src},
                 rule="thread-shared-state") == []


# -- rule 7: config-drift ----------------------------------------------

def test_config_drift_positive_and_negative(tmp_path):
    config = """
        import dataclasses

        USED_CONST = 5
        DEAD_CONST = 7

        @dataclasses.dataclass
        class Config:
            used_field: int = 1
            dead_field: int = 2

        def build(p):
            p.add_argument("--live", dest="liveDest")
            p.add_argument("--dead", dest="deadDest")

        def from_argv(args):
            return Config(used_field=args.liveDest)
    """
    other = """
        from config import USED_CONST

        def f(cfg):
            return cfg.used_field + USED_CONST
    """
    found = _lint(tmp_path, {"config.py": config, "other.py": other},
                  rule="config-drift")
    msgs = "\n".join(f.message for f in found)
    assert "DEAD_CONST" in msgs
    assert "dead_field" in msgs
    assert "deadDest" in msgs
    assert "USED_CONST" not in msgs
    assert "'used_field'" not in msgs
    assert "liveDest" not in msgs


# -- rule 8: bare-except ----------------------------------------------

def test_bare_except_positive(tmp_path):
    src = """
        def f():
            try:
                return 1
            except Exception:
                return None
    """
    found = _lint(tmp_path, {"mod.py": src}, rule="bare-except")
    assert len(found) == 1


def test_bare_except_rationale_comment_negative(tmp_path):
    src = """
        def f():
            try:
                return 1
            except Exception:  # probing an optional backend API
                return None

        def g():
            try:
                return 1
            except Exception:
                # narrow types vary per jax version; None is the
                # documented fallback either way
                return None

        def h():
            try:
                return 1
            except ValueError:   # narrow: no rationale required
                return None
    """
    assert _lint(tmp_path, {"mod.py": src}, rule="bare-except") == []


# -- rule 9: retry-without-backoff ------------------------------------

def test_retry_without_backoff_positive(tmp_path):
    src = """
        def fetch(read):
            while True:           # hot-spin: hammers the failing read
                try:
                    return read()
                except OSError:
                    continue

        def fetch_counted(read, max_attempts):
            for attempt in range(max_attempts):
                try:
                    return read()
                except OSError:
                    pass
    """
    found = _lint(tmp_path, {"mod.py": src},
                  rule="retry-without-backoff")
    assert len(found) == 2
    assert all("backoff" in f.message for f in found)


def test_retry_without_backoff_negative(tmp_path):
    src = """
        import time
        from distributedpytorch_tpu import faults

        def paced(read):          # sleeps between attempts: fine
            while True:
                try:
                    return read()
                except OSError:
                    time.sleep(0.1)

        def policied(read):       # delegated pacing: fine
            return faults.retry(read, site="data.read")

        def bounded(q, item, stop):
            while not stop():     # the timeout IS the pacing: fine
                try:
                    q.put(item, timeout=0.1)
                    return True
                except Exception:  # queue.Full in real code
                    pass
            return False

        def drain(queue, host_iter):
            while queue:          # iterator control flow, not a retry
                yield queue.popleft()
                try:
                    queue.append(next(host_iter))
                except StopIteration:
                    pass

        def per_item(paths):      # skip-bad-item for loop: not a retry
            out = []
            for p in paths:
                try:
                    out.append(open(p).read())
                except OSError:
                    continue
            return out
    """
    assert _lint(tmp_path, {"mod.py": src},
                 rule="retry-without-backoff") == []


# -- rule 10: profiler-trace-leak --------------------------------------

def test_profiler_trace_leak_positive(tmp_path):
    src = """
        import jax

        def profile_epoch(run, path):
            jax.profiler.start_trace(path)
            run()                          # raising run() leaks: BAD
            jax.profiler.stop_trace()

        def profile_early_return(run, path, skip):
            jax.profiler.start_trace(path)
            if skip:
                return None                # leaks on this path: BAD
            run()
            jax.profiler.stop_trace()
    """
    found = _lint(tmp_path, {"mod.py": src}, rule="profiler-trace-leak")
    assert len(found) == 2
    assert all("finally" in f.message for f in found)


def test_profiler_trace_leak_finally_negative(tmp_path):
    src = """
        import jax

        def profile_epoch(run, path):
            jax.profiler.start_trace(path)
            try:
                run()
            finally:
                jax.profiler.stop_trace()  # every path stops: fine
    """
    assert _lint(tmp_path, {"mod.py": src},
                 rule="profiler-trace-leak") == []


def test_profiler_trace_leak_class_close_negative(tmp_path):
    # The split start/stop state machine (flightrec.AnomalyDetector):
    # one method starts, another stops K steps later, and close() owns
    # the finally that guarantees no capture outlives the object.
    src = """
        import jax

        class Capturer:
            def start(self, path):
                jax.profiler.start_trace(path)
                self.live = True

            def step(self):
                if self.live:
                    self.live = False
                    jax.profiler.stop_trace()

            def close(self):
                try:
                    self.live = False
                finally:
                    jax.profiler.stop_trace()
    """
    assert _lint(tmp_path, {"mod.py": src},
                 rule="profiler-trace-leak") == []


# -- rule 11: mixed-precision-accum ------------------------------------

def test_mixed_precision_accum_reduction_positive(tmp_path):
    src = """
        import jax.numpy as jnp

        def epoch_loss(losses):
            return jnp.sum(losses, dtype=jnp.bfloat16)

        def epoch_mean(losses):
            return jnp.mean(losses, dtype="float16")
    """
    found = _lint(tmp_path, {"mod.py": src},
                  rule="mixed-precision-accum")
    assert len(found) == 2
    assert all("half dtype" in f.message for f in found)


def test_mixed_precision_accum_buffer_positive(tmp_path):
    src = """
        import jax
        import jax.numpy as jnp

        def running_sum(xs):
            acc = jnp.zeros((), jnp.bfloat16)
            for x in xs:
                acc = acc + x
            return acc

        def scanned_sum(xs):
            acc = jnp.zeros((4,), dtype=jnp.float16)
            def body(c, x):
                return c + x, None
            out, _ = jax.lax.scan(body, acc, xs)
            return out
    """
    found = _lint(tmp_path, {"mod.py": src},
                  rule="mixed-precision-accum")
    hows = " | ".join(f.message for f in found)
    assert len(found) == 2, hows
    assert "rebound to an expression of itself" in hows
    assert "lax.scan" in hows


def test_mixed_precision_accum_negative(tmp_path):
    # f32 accumulation with a final downcast is the sanctioned pattern;
    # half-dtype buffers that are never accumulated into are fine too.
    src = """
        import jax
        import jax.numpy as jnp

        def running_sum(xs):
            acc = jnp.zeros((), jnp.float32)
            for x in xs:
                acc = acc + x
            return acc.astype(jnp.bfloat16)

        def activations(x):
            pad = jnp.zeros((4,), jnp.bfloat16)   # not an accumulator
            return jnp.concatenate([x, pad])

        def f32_reduce(losses):
            return jnp.sum(losses, dtype=jnp.float32)
    """
    assert _lint(tmp_path, {"mod.py": src},
                 rule="mixed-precision-accum") == []


def test_mixed_precision_accum_suppression_needs_rationale(tmp_path):
    src = """
        import jax.numpy as jnp

        def checksum(xs):
            # graftlint: disable=mixed-precision-accum -- parity checksum
            # reproduces the device's own bf16 summation order on purpose
            return jnp.sum(xs, dtype=jnp.bfloat16)
    """
    assert _lint(tmp_path, {"mod.py": src},
                 rule="mixed-precision-accum") == []
    bad = """
        import jax.numpy as jnp

        def checksum(xs):
            return jnp.sum(xs, dtype=jnp.bfloat16)  # graftlint: disable=mixed-precision-accum
    """
    findings = _lint(tmp_path, {"mod2.py": bad})
    assert sorted({f.rule for f in findings}) == [
        "bad-suppression", "mixed-precision-accum"]


# -- suppressions ------------------------------------------------------

def test_suppression_with_rationale_silences(tmp_path):
    src = """
        def f():
            try:
                return 1
            # graftlint: disable=bare-except -- probing an API that
            # raises implementation-defined types
            except Exception:
                return None
    """
    findings = _lint(tmp_path, {"mod.py": src})
    assert findings == []


def test_suppression_without_rationale_is_finding(tmp_path):
    src = """
        def f():
            try:
                return 1
            except Exception:  # graftlint: disable=bare-except
                return None
    """
    findings = _lint(tmp_path, {"mod.py": src})
    assert [f.rule for f in findings] == ["bad-suppression"]


def test_suppression_unknown_rule_is_finding(tmp_path):
    src = """
        X = 1  # graftlint: disable=no-such-rule -- because reasons
    """
    findings = _lint(tmp_path, {"mod.py": src})
    assert [f.rule for f in findings] == ["bad-suppression"]
    assert "no-such-rule" in findings[0].message


def test_parse_error_is_finding_not_crash(tmp_path):
    findings = _lint(tmp_path, {"mod.py": "def broken(:\n"})
    assert [f.rule for f in findings] == ["parse-error"]


# -- rule 12: collective-in-cleanup -----------------------------------

_CLEANUP_BAD_EXCEPT = """
    from distributedpytorch_tpu import runtime

    def boundary(err):
        try:
            step()
        except Exception:
            runtime.agree_health(True, False)
            raise
"""

_CLEANUP_BAD_FINALLY = """
    import jax

    def teardown(x):
        try:
            return step(x)
        finally:
            jax.experimental.multihost_utils.sync_global_devices("bye")
"""

_CLEANUP_RATIONALE = """
    from distributedpytorch_tpu import runtime

    def boundary(err):
        try:
            step()
        except Exception:
            # every rank takes this path: the epoch loop funnels ALL
            # exits (success included) through this agreement point
            runtime.agree_health(True, False)
            raise
"""

_CLEANUP_GOOD = """
    from distributedpytorch_tpu import runtime

    def boundary(err):
        failed = err is not None
        runtime.agree_health(failed, False)
        try:
            cleanup()
        finally:
            close_files()
"""


def test_collective_in_except_positive(tmp_path):
    found = _lint(tmp_path, {"mod.py": _CLEANUP_BAD_EXCEPT},
                  rule="collective-in-cleanup")
    assert len(found) == 1
    assert "except" in found[0].message


def test_collective_in_finally_positive(tmp_path):
    found = _lint(tmp_path, {"mod.py": _CLEANUP_BAD_FINALLY},
                  rule="collective-in-cleanup")
    assert len(found) == 1
    assert "finally" in found[0].message


def test_collective_in_cleanup_rationale_comment_silences(tmp_path):
    assert _lint(tmp_path, {"mod.py": _CLEANUP_RATIONALE},
                 rule="collective-in-cleanup") == []


def test_collective_outside_cleanup_negative(tmp_path):
    assert _lint(tmp_path, {"mod.py": _CLEANUP_GOOD},
                 rule="collective-in-cleanup") == []


# -- rule 13: wall-clock-in-measurement -------------------------------

_WALL_BAD = """
    import time

    def measure(fn):
        t0 = time.time()
        fn()
        return time.time() - t0
"""

_WALL_GOOD = """
    import time

    def measure(fn, rec):
        t0 = time.perf_counter()
        fn()
        rec["ts"] = time.time()        # stamp only: the blessed use
        rec["mono"] = time.monotonic()
        return time.perf_counter() - t0
"""


def test_wall_clock_direct_and_tainted_positive(tmp_path):
    found = _lint(tmp_path, {"meter.py": _WALL_BAD},
                  rule="wall-clock-in-measurement")
    # one finding for the subtraction line (direct call + tainted t0
    # collapse to one finding per expression, not two)
    assert len(found) == 1
    assert "perf_counter" in found[0].message


def test_wall_clock_stamp_only_negative(tmp_path):
    assert _lint(tmp_path, {"meter.py": _WALL_GOOD},
                 rule="wall-clock-in-measurement") == []


def test_wall_clock_augassign_tainted_positive(tmp_path):
    src = """
        import time

        def measure(fn):
            start = time.time()
            fn()
            elapsed = 0.0
            elapsed -= start
            return elapsed
    """
    found = _lint(tmp_path, {"meter.py": src},
                  rule="wall-clock-in-measurement")
    assert len(found) == 1
    assert "'start'" in found[0].message


def test_wall_clock_scope_isolation_negative(tmp_path):
    # a name tainted in one function is a different binding in another
    src = """
        import time

        def stamp(rec):
            t0 = time.time()
            rec["ts"] = t0

        def measure(fn, t0):
            fn()
            return time.perf_counter() - t0
    """
    assert _lint(tmp_path, {"meter.py": src},
                 rule="wall-clock-in-measurement") == []


def test_wall_clock_rationale_comment_silences(tmp_path):
    src = """
        import time

        def skew(peer_wall):
            # cross-host wall skew: wall clock IS the measurand here
            return time.time() - peer_wall
    """
    assert _lint(tmp_path, {"meter.py": src},
                 rule="wall-clock-in-measurement") == []


# -- rule 14: blocking-h2d-in-step-loop --------------------------------

_H2D_BAD = """
    import jax

    def drive(loader, engine, state, sharding):
        for images, labels, valid in loader.epoch(0):
            images = jax.device_put(images, sharding)
            state, metrics = engine.train_step(state, images, labels,
                                               valid)
        return state
"""

_H2D_GOOD = """
    import jax

    def drive(loader, engine, state, sharding):
        # per-epoch transfer outside the step loop is fine
        table = jax.device_put(loader.split.images, sharding)
        for step in range(loader.batches_per_epoch):
            state, metrics = engine.train_step(state, table, step)
        return state
"""


def test_h2d_device_put_in_step_loop_positive(tmp_path):
    found = _lint(tmp_path, {"engine.py": _H2D_BAD},
                  rule="blocking-h2d-in-step-loop")
    assert len(found) == 1
    assert "device-prefetch" in found[0].message


def test_h2d_block_until_ready_in_step_loop_positive(tmp_path):
    src = """
        import jax

        def drive(loader, engine, state):
            for batch in loader.epoch(0):
                state, m = engine.train_step(state, *batch)
                jax.block_until_ready(m)
            return state
    """
    found = _lint(tmp_path, {"cli.py": src},
                  rule="blocking-h2d-in-step-loop")
    assert len(found) == 1
    assert "stalls the step loop" in found[0].message


def test_h2d_per_epoch_transfer_negative(tmp_path):
    assert _lint(tmp_path, {"engine.py": _H2D_GOOD},
                 rule="blocking-h2d-in-step-loop") == []


def test_h2d_rationale_comment_silences(tmp_path):
    src = """
        import jax

        def drive(loader, engine, state, sharding):
            for images, labels, valid in loader.epoch(0):
                # warm-start probe: ONE inline put, measured on purpose
                images = jax.device_put(images, sharding)
                state, _ = engine.train_step(state, images, labels, valid)
            return state
    """
    assert _lint(tmp_path, {"engine.py": src},
                 rule="blocking-h2d-in-step-loop") == []


def test_h2d_non_step_module_negative(tmp_path):
    # the data pipeline is the transfer OWNER — its device_puts are the
    # fix, not the finding; only step-driving modules are in scope
    assert _lint(tmp_path, {"pipeline.py": _H2D_BAD},
                 rule="blocking-h2d-in-step-loop") == []


# -- rule 15: unbounded-queue-in-server --------------------------------

_SERVER_QUEUE_BAD = """
    import queue

    class Handler:
        def __init__(self):
            self.requests = queue.Queue()

        def handle(self, req):
            self.requests.put(req)
"""

_SERVER_QUEUE_GOOD = """
    import queue

    class Handler:
        def __init__(self, max_queue):
            self.requests = queue.Queue(maxsize=max_queue)

        def handle(self, req):
            try:
                self.requests.put_nowait(req)
            except queue.Full:
                return 503
            return 200
"""

_SERVER_LOOP_BAD = """
    class Server:
        def __init__(self):
            self.pending = []

        def accept_loop(self, sock):
            while True:
                req = sock.recv()
                self.pending.append(req)
"""

_SERVER_LOOP_GOOD = """
    class Server:
        def __init__(self, max_queue):
            self.pending = []
            self.max_queue = max_queue

        def accept_loop(self, sock):
            while True:
                req = sock.recv()
                if len(self.pending) >= self.max_queue:
                    req.answer(503)      # shed with an answer
                    continue
                self.pending.append(req)
"""


def test_unbounded_queue_ctor_positive(tmp_path):
    found = _lint(tmp_path, {"server.py": _SERVER_QUEUE_BAD},
                  rule="unbounded-queue-in-server")
    assert len(found) == 1
    assert "maxsize" in found[0].message


def test_bounded_queue_ctor_negative(tmp_path):
    assert _lint(tmp_path, {"server.py": _SERVER_QUEUE_GOOD},
                 rule="unbounded-queue-in-server") == []


def test_queue_maxsize_zero_is_unbounded_positive(tmp_path):
    src = """
        import queue

        class Handler:
            def __init__(self):
                self.requests = queue.Queue(maxsize=0)
    """
    found = _lint(tmp_path, {"handler.py": src},
                  rule="unbounded-queue-in-server")
    assert len(found) == 1


def test_producer_loop_append_positive(tmp_path):
    found = _lint(tmp_path, {"server.py": _SERVER_LOOP_BAD},
                  rule="unbounded-queue-in-server")
    assert len(found) == 1
    assert "while True" in found[0].message


def test_producer_loop_with_shed_guard_negative(tmp_path):
    assert _lint(tmp_path, {"server.py": _SERVER_LOOP_GOOD},
                 rule="unbounded-queue-in-server") == []


def test_unbounded_queue_rationale_comment_silences(tmp_path):
    src = """
        import queue

        class Handler:
            def __init__(self):
                # bounded by the admit() check in accept(): overflow is
                # answered with 503 before anything reaches this queue
                self.requests = queue.Queue()
    """
    assert _lint(tmp_path, {"server.py": src},
                 rule="unbounded-queue-in-server") == []


def test_unbounded_queue_non_server_module_negative(tmp_path):
    # only serving/request-handler modules are in scope: a pipeline's
    # internal queue has its own bounding story (rule 6 territory)
    assert _lint(tmp_path, {"pipeline.py": _SERVER_QUEUE_BAD},
                 rule="unbounded-queue-in-server") == []


def test_serving_package_path_is_in_scope(tmp_path):
    pkg = tmp_path / "serving"
    pkg.mkdir()
    (pkg / "dispatch.py").write_text(textwrap.dedent(_SERVER_QUEUE_BAD))
    findings, _ = lint_paths([str(tmp_path)], root=str(tmp_path))
    assert [f for f in findings
            if f.rule == "unbounded-queue-in-server"]


# -- rule 16: unbounded-metric-cardinality -----------------------------

_METRIC_FSTRING_BAD = """
    def record(tel, request_id, latency_ms):
        tel.counter(f"serve/errors/{request_id}").add()
        tel.histogram(f"latency/{request_id}").observe(latency_ms)
"""

_METRIC_PERCENT_BAD = """
    def record(tel, rank):
        tel.gauge("fleet/up_rank_%d" % rank).set(1.0)
"""

_METRIC_FORMAT_BAD = """
    def record(tel, path):
        tel.counter("io/{}".format(path)).add()
"""

_METRIC_CONCAT_BAD = """
    def record(tel, host):
        tel.counter("scrape/" + host).add()
"""

_METRIC_GOOD = """
    def record(tel, request_id, latency_ms):
        # identity goes in attrs / labels, the series name stays fixed
        tel.counter("serve/errors").add()
        tel.histogram("serve/request_latency_ms").observe(latency_ms)
        tel.gauge("fleet/alive").set(2.0)
        tel.counter("serve/" + "shed").add()     # literal concat: fine
        name = "serve/requests"
        tel.counter(name).add()                  # resolved elsewhere
"""


def test_metric_cardinality_fstring_positive(tmp_path):
    found = _lint(tmp_path, {"telemetry.py": _METRIC_FSTRING_BAD},
                  rule="unbounded-metric-cardinality")
    assert len(found) == 2
    assert "series" in found[0].message


def test_metric_cardinality_percent_and_format_positive(tmp_path):
    assert _lint(tmp_path, {"fleet.py": _METRIC_PERCENT_BAD},
                 rule="unbounded-metric-cardinality")
    assert _lint(tmp_path, {"goodput.py": _METRIC_FORMAT_BAD},
                 rule="unbounded-metric-cardinality")
    assert _lint(tmp_path, {"slo.py": _METRIC_CONCAT_BAD},
                 rule="unbounded-metric-cardinality")


def test_metric_cardinality_static_names_negative(tmp_path):
    assert _lint(tmp_path, {"telemetry.py": _METRIC_GOOD},
                 rule="unbounded-metric-cardinality") == []


def test_metric_cardinality_serving_package_in_scope(tmp_path):
    pkg = tmp_path / "serving"
    pkg.mkdir()
    (pkg / "server.py").write_text(textwrap.dedent(_METRIC_FSTRING_BAD))
    findings, _ = lint_paths([str(tmp_path)], root=str(tmp_path))
    assert [f for f in findings
            if f.rule == "unbounded-metric-cardinality"]


def test_metric_cardinality_non_telemetry_module_negative(tmp_path):
    assert _lint(tmp_path, {"engine.py": _METRIC_FSTRING_BAD},
                 rule="unbounded-metric-cardinality") == []


def test_metric_cardinality_rationale_comment_silences(tmp_path):
    src = """
        _PHASES = ("train", "eval")

        def record(tel, phase):
            # phase is drawn from the fixed _PHASES enum above: the
            # series set is bounded by construction
            tel.counter(f"step/{phase}").add()
    """
    assert _lint(tmp_path, {"telemetry.py": src},
                 rule="unbounded-metric-cardinality") == []


# -- CLI contract ------------------------------------------------------

def test_repo_lints_clean_via_run_cli(capsys):
    rc = run_cli(root=REPO)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "clean" in out


def test_repo_lints_clean_via_main_lint(capsys):
    from distributedpytorch_tpu.cli import main

    # cwd-independence is part of the contract only for the scripts/
    # entry; main.py lint runs from the repo root like main.py train
    cwd = os.getcwd()
    try:
        os.chdir(REPO)
        assert main(["lint"]) == 0
    finally:
        os.chdir(cwd)


def test_cli_nonzero_and_json_on_findings(tmp_path, capsys):
    (tmp_path / "cli.py").write_text(textwrap.dedent(_STEP_LOOP_BAD))
    rc = run_cli(json_output=True, paths=[str(tmp_path)],
                 root=str(tmp_path))
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["findings"] \
        and payload["findings"][0]["rule"] == "host-sync-in-step-loop"


def test_render_human_output(tmp_path):
    (tmp_path / "cli.py").write_text(textwrap.dedent(_STEP_LOOP_BAD))
    findings, files = lint_paths([str(tmp_path)], root=str(tmp_path))
    text = render_findings(findings, files)
    assert "cli.py:" in text and "[host-sync-in-step-loop]" in text


def test_default_scope_covers_package_and_scripts():
    assert "distributedpytorch_tpu" in DEFAULT_SCOPE
    assert "scripts" in DEFAULT_SCOPE
    assert "bench.py" in DEFAULT_SCOPE


# -- rule 17: collective-divergence (whole-program) --------------------

_DIVERGENT_DIRECT = """
    import jax

    def reduce(x):
        if jax.process_index() == 0:
            return jax.lax.psum(x, "data")     # main-only: BAD
        return x
"""

_DIVERGENT_LIB = """
    import jax

    def sync(x):
        return jax.lax.psum(x, "data")
"""

_DIVERGENT_CALLER = """
    from lib import sync

    def run(x, rank):
        if rank == 0:
            sync(x)                            # reaches psum: BAD
        return x
"""

_DIVERGENT_EARLY_EXIT = """
    import jax

    def save(x, is_main):
        if not is_main():
            return None
        return jax.lax.psum(x, "data")         # only main gets here
"""

_UNIFORM_OK = """
    import jax

    def reduce(x):
        if jax.process_count() > 1:            # same on every rank
            return jax.lax.psum(x, "data")
        return x
"""

_DIVERGENT_SUPPRESSED = """
    import jax

    def publish(x):
        if jax.process_index() == 0:
            # graftlint: disable=collective-divergence -- followers are parked polling a file, never in this collective
            return jax.lax.psum(x, "data")
        return x
"""


def test_collective_divergence_direct_positive(tmp_path):
    found = _lint(tmp_path, {"engine.py": _DIVERGENT_DIRECT},
                  rule="collective-divergence")
    assert len(found) == 1
    assert "process_index" in found[0].message
    assert "hang" in found[0].message


def test_collective_divergence_transitive_cross_file(tmp_path):
    found = _lint(tmp_path, {"lib.py": _DIVERGENT_LIB,
                             "caller.py": _DIVERGENT_CALLER},
                  rule="collective-divergence")
    assert [f for f in found if f.path.endswith("caller.py")]
    assert "psum" in found[0].message  # names the reached collective


def test_collective_divergence_early_exit_positive(tmp_path):
    found = _lint(tmp_path, {"engine.py": _DIVERGENT_EARLY_EXIT},
                  rule="collective-divergence")
    assert len(found) == 1
    assert "early exit" in found[0].message


def test_collective_divergence_uniform_condition_negative(tmp_path):
    assert _lint(tmp_path, {"engine.py": _UNIFORM_OK},
                 rule="collective-divergence") == []


def test_collective_divergence_suppression_with_rationale(tmp_path):
    assert _lint(tmp_path, {"engine.py": _DIVERGENT_SUPPRESSED},
                 rule="collective-divergence") == []


# -- rule 18: lock-order-cycle (whole-program) -------------------------

_TWO_LOCK_CYCLE = """
    import threading

    _a = threading.Lock()
    _b = threading.Lock()

    def one():
        with _a:
            with _b:
                pass

    def two():
        with _b:
            with _a:
                pass
"""

_THREE_LOCK_A = """
    import threading
    from libc import grab_c

    _a = threading.Lock()
    _b = threading.Lock()

    def ab(x):
        with _a:
            with _b:
                return x

    def bc(x):
        with _b:
            return grab_c(x)               # edge b -> c through a call
"""

_THREE_LOCK_C = """
    import threading
    from liba import ab

    _c = threading.Lock()

    def grab_c(x):
        with _c:
            return x

    def ca(x):
        with _c:
            return ab(x)                   # edge c -> a: closes cycle
"""

_HANDLER_LOCK_BAD = """
    import signal
    import threading

    _log_lock = threading.Lock()

    def log(msg):
        with _log_lock:
            pass

    def _handle(signum, frame):
        log("preempted")                   # handler -> Lock: BAD

    def install():
        signal.signal(signal.SIGTERM, _handle)
"""

_HANDLER_RLOCK_OK = _HANDLER_LOCK_BAD.replace("threading.Lock()",
                                              "threading.RLock()")

_SELF_DEADLOCK = """
    import threading

    _lock = threading.Lock()

    def log(msg):
        with _lock:
            pass

    def flush():
        with _lock:
            log("flush")                   # re-acquires _lock: BAD
"""

_NESTED_ORDERED_OK = """
    import threading

    _a = threading.Lock()
    _b = threading.Lock()

    def one():
        with _a:
            with _b:
                pass

    def two():
        with _a:                           # same global order: fine
            with _b:
                pass
"""


def test_lock_order_two_lock_cycle_positive(tmp_path):
    found = _lint(tmp_path, {"locks.py": _TWO_LOCK_CYCLE},
                  rule="lock-order-cycle")
    assert found
    assert "cycle" in found[0].message
    assert "_a" in found[0].message and "_b" in found[0].message


def test_lock_order_three_lock_cycle_through_call(tmp_path):
    found = _lint(tmp_path, {"liba.py": _THREE_LOCK_A,
                             "libc.py": _THREE_LOCK_C},
                  rule="lock-order-cycle")
    assert any("cycle" in f.message for f in found)


def test_lock_order_consistent_order_negative(tmp_path):
    assert _lint(tmp_path, {"locks.py": _NESTED_ORDERED_OK},
                 rule="lock-order-cycle") == []


def test_handler_acquires_plain_lock_positive(tmp_path):
    """The PR 12 preempt-handler deadlock, reconstructed: a signal
    handler whose call chain takes a non-reentrant Lock."""
    found = _lint(tmp_path, {"shutdown.py": _HANDLER_LOCK_BAD},
                  rule="lock-order-cycle")
    assert len(found) == 1
    assert "signal handler" in found[0].message
    assert "_handle" in found[0].message
    assert "log" in found[0].message       # names the chain
    assert "RLock" in found[0].message     # and the fix


def test_handler_acquires_rlock_negative(tmp_path):
    assert _lint(tmp_path, {"shutdown.py": _HANDLER_RLOCK_OK},
                 rule="lock-order-cycle") == []


def test_lock_reacquired_through_call_positive(tmp_path):
    found = _lint(tmp_path, {"locks.py": _SELF_DEADLOCK},
                  rule="lock-order-cycle")
    assert len(found) == 1
    assert "re-acquired" in found[0].message


# -- rule 19: mesh-axis-propagation (whole-program) --------------------

_AXIS_LIB = """
    import jax

    DATA_AXIS = "data"

    def reduce_mean(x, axis_name="data"):
        return jax.lax.pmean(x, axis_name)
"""

_AXIS_CALLER_BAD = """
    from lib import reduce_mean

    def run(x):
        return reduce_mean(x, axis_name="dtaa")   # typo: BAD
"""

_AXIS_CALLER_OK = """
    from lib import reduce_mean, DATA_AXIS

    def run(x):
        a = reduce_mean(x, axis_name="data")
        b = reduce_mean(x, axis_name=DATA_AXIS)
        c = reduce_mean(x)                        # default: rule 3's job
        return a, b, c
"""


def test_mesh_axis_cross_file_mismatch_positive(tmp_path):
    found = _lint(tmp_path, {"lib.py": _AXIS_LIB,
                             "caller.py": _AXIS_CALLER_BAD},
                  rule="mesh-axis-propagation")
    assert len(found) == 1
    assert found[0].path.endswith("caller.py")    # flagged at the SITE
    assert "'dtaa'" in found[0].message
    assert "pmean" in found[0].message


def test_mesh_axis_cross_file_clean_negative(tmp_path):
    assert _lint(tmp_path, {"lib.py": _AXIS_LIB,
                            "caller.py": _AXIS_CALLER_OK},
                 rule="mesh-axis-propagation") == []


# -- rule 20: outbound-call-without-timeout ----------------------------

_OUTBOUND_BAD = """
    import socket
    import urllib.request
    from http.client import HTTPConnection

    def probe(url, host, port):
        raw = urllib.request.urlopen(url).read()
        conn = HTTPConnection(host, port)
        sock = socket.create_connection((host, port))
        return raw, conn, sock
"""

_OUTBOUND_GOOD = """
    import socket
    import urllib.request
    from http.client import HTTPConnection

    def probe(url, host, port):
        raw = urllib.request.urlopen(url, timeout=2.0).read()
        conn = HTTPConnection(host, port, timeout=5.0)
        sock = socket.create_connection((host, port), 1.5)
        return raw, conn, sock
"""


def test_outbound_timeout_positive(tmp_path):
    found = _lint(tmp_path, {"fleet.py": _OUTBOUND_BAD},
                  rule="outbound-call-without-timeout")
    assert len(found) == 3
    assert "blocks forever" in found[0].message


def test_outbound_timeout_negative(tmp_path):
    assert _lint(tmp_path, {"fleet.py": _OUTBOUND_GOOD},
                 rule="outbound-call-without-timeout") == []


def test_outbound_timeout_none_literal_counts(tmp_path):
    src = """
        import urllib.request

        def probe(url):
            return urllib.request.urlopen(url, timeout=None).read()
    """
    found = _lint(tmp_path, {"frontdoor.py": src},
                  rule="outbound-call-without-timeout")
    assert len(found) == 1  # timeout=None is the block-forever spelling


def test_outbound_timeout_scoped_to_control_plane(tmp_path):
    # a training-side module may legitimately block (e.g. a dataset
    # download) — the rule only owns serving/fleet/controller code
    assert _lint(tmp_path, {"datasets.py": _OUTBOUND_BAD},
                 rule="outbound-call-without-timeout") == []


def test_outbound_timeout_serving_dir_targeted(tmp_path):
    os.makedirs(tmp_path / "serving", exist_ok=True)
    found = _lint(tmp_path,
                  {os.path.join("serving", "proxy.py"): _OUTBOUND_BAD},
                  rule="outbound-call-without-timeout")
    assert len(found) == 3


def test_outbound_timeout_rationale_escape(tmp_path):
    src = """
        import urllib.request

        def probe(url):
            # bounded by the caller's socket.setdefaulttimeout at init
            return urllib.request.urlopen(url).read()
    """
    assert _lint(tmp_path, {"rollout.py": src},
                 rule="outbound-call-without-timeout") == []


# -- rule 21: nondeterminism-in-policy ---------------------------------

_POLICY_BAD = """
    import time
    import random

    def decide_scale(cfg, state, samples):
        now = time.time()
        jitter = random.random()
        rng = random.Random()
        return {"action": "none", "t": now + jitter + rng.random()}
"""

_POLICY_GOOD = """
    import random

    def decide_scale(cfg, state, samples):
        t = samples[-1]["t"]          # time comes from the sample
        rng = random.Random(cfg["seed"])  # seeded stream: deterministic
        return {"action": "none", "t": t + rng.random()}
"""


def test_nondeterminism_positive(tmp_path):
    found = _lint(tmp_path, {"controller.py": _POLICY_BAD},
                  rule="nondeterminism-in-policy")
    # import time, time.time(), random.random(), zero-arg Random()
    assert len(found) == 4
    assert any("import" in f.message for f in found)
    assert any("virtual clock" in f.message for f in found)


def test_nondeterminism_negative_seeded_rng(tmp_path):
    assert _lint(tmp_path, {"slo.py": _POLICY_GOOD},
                 rule="nondeterminism-in-policy") == []


def test_nondeterminism_sim_dir_targeted(tmp_path):
    os.makedirs(tmp_path / "sim", exist_ok=True)
    found = _lint(tmp_path,
                  {os.path.join("sim", "engine.py"): _POLICY_BAD},
                  rule="nondeterminism-in-policy")
    assert len(found) == 4


def test_nondeterminism_scoped_to_policy_modules(tmp_path):
    # a live process module may hold clocks and entropy freely
    assert _lint(tmp_path, {"runtime.py": _POLICY_BAD},
                 rule="nondeterminism-in-policy") == []


def test_nondeterminism_frontdoor_function_granular(tmp_path):
    # frontdoor.py is a live process: only the pure decision helpers
    # the simulator composes are held to purity.
    src = """
        import time

        def serve_loop(cfg):
            return time.time()

        def decide_health(cfg, snapshots):
            return [{"t": time.monotonic()}]
    """
    found = _lint(tmp_path, {"frontdoor.py": src},
                  rule="nondeterminism-in-policy")
    assert len(found) == 1
    assert found[0].line == 8


def test_nondeterminism_entropy_calls(tmp_path):
    src = """
        import os
        import uuid
        import secrets

        def evaluate(slos, samples):
            a = os.urandom(8)
            b = uuid.uuid4()
            c = secrets.token_hex(4)
            return a, b, c
    """
    found = _lint(tmp_path, {"slo.py": src},
                  rule="nondeterminism-in-policy")
    assert len(found) == 3


def test_nondeterminism_rationale_escape(tmp_path):
    src = """
        def decide_rollout(cfg, state, obs):
            import time
            # wall stamp for the human-facing audit line only -- the
            # verdict below never reads it
            stamp = time.time()
            return {"action": "continue", "stamp": stamp}
    """
    found = _lint(tmp_path, {"rollout.py": src},
                  rule="nondeterminism-in-policy")
    # the rationale covers the call line; the function-local import of
    # time inside a decider is still its own finding
    assert len(found) == 1
    assert "import" in found[0].message


def test_nondeterminism_repo_policy_modules_clean():
    # The real deciders + the whole simulator must hold the purity
    # contract the simulator's replay rests on.
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(repo, "distributedpytorch_tpu")
    paths = [os.path.join(pkg, "slo.py"),
             os.path.join(pkg, "serving"),
             os.path.join(pkg, "sim")]
    findings, _ = lint_paths(paths, root=repo)
    assert [f for f in findings
            if f.rule == "nondeterminism-in-policy"] == []


# -- whole-program CLI contract ----------------------------------------

def test_json_output_lists_active_rules(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    rc = run_cli(json_output=True, paths=[str(tmp_path)],
                 root=str(tmp_path))
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    for name in ("collective-divergence", "lock-order-cycle",
                 "mesh-axis-propagation", "host-sync-in-step-loop",
                 "outbound-call-without-timeout",
                 "nondeterminism-in-policy",
                 "bad-suppression"):
        assert name in payload["rules"]


def test_changed_only_filters_to_git_changed_files(tmp_path, capsys):
    import subprocess

    def git(*argv):
        subprocess.run(["git", "-c", "user.name=t",
                        "-c", "user.email=t@t"] + list(argv),
                       cwd=str(tmp_path), check=True,
                       capture_output=True)

    # committed bad file (unchanged) + freshly added bad file
    (tmp_path / "cli.py").write_text(textwrap.dedent(_STEP_LOOP_BAD))
    git("init")
    git("add", "cli.py")
    git("commit", "-m", "seed")
    (tmp_path / "engine.py").write_text(
        textwrap.dedent(_DIVERGENT_DIRECT))

    rc = run_cli(json_output=True, paths=[str(tmp_path)],
                 root=str(tmp_path), changed_only=True)
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["changed_only"] is True
    flagged = {f["path"] for f in payload["findings"]}
    assert any(p.endswith("engine.py") for p in flagged)
    assert not any(p.endswith("cli.py") for p in flagged), \
        "unchanged files must be filtered from --changed-only output"


def test_changed_only_outside_git_is_usage_error(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    rc = run_cli(paths=[str(tmp_path)], root=str(tmp_path),
                 changed_only=True)
    assert rc == 2  # fail loudly, never silently lint nothing


def test_full_repo_lint_runtime_budget(capsys):
    """The whole-program build is paid ONCE per invocation (memoized on
    Project) and every per-file rule shares one cached AST index per
    module — the full ~80-file repo pass stays interactive.  Budgeted
    in CPU time (the pass is single-threaded) so a loaded CI box can't
    flake the test: typical is ~2.5s; the ceiling is generous, while a
    regression to per-rule re-traversal (~9s measured before the
    shared index) still fails."""
    import time

    t0 = time.process_time()
    rc = run_cli(root=REPO)
    dt = time.process_time() - t0
    capsys.readouterr()
    assert rc == 0
    assert dt < 6.0, f"full-repo lint took {dt:.2f}s CPU (budget 6.0s)"
