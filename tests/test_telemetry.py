"""Telemetry subsystem (telemetry.py): registry semantics, span nesting
and JSONL schema, pipeline data-wait counters, multi-rank report
aggregation, and the driver-level --telemetry contract — all CPU-only on
the 8-device virtual mesh (tier-1)."""

import json
import os

import numpy as np
import pytest

from distributedpytorch_tpu import telemetry


@pytest.fixture
def restore_global():
    """Tests that install a global instance must not leak an enabled one
    into the rest of the suite."""
    yield
    telemetry._active = telemetry.Telemetry(enabled=False)


def _read_events(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


# -- registry semantics ------------------------------------------------


def test_counter_gauge_histogram_semantics(tmp_path):
    tel = telemetry.Telemetry(enabled=True, rsl_path=str(tmp_path), rank=3)
    c = tel.counter("c")
    c.add()
    c.add(2.5)
    assert tel.counter("c") is c  # registry returns the same instance
    assert c.value == 3.5

    h = tel.histogram("h")
    for v in range(100):
        h.observe(float(v))
    s = h.summary()
    assert s["count"] == 100 and s["min"] == 0.0 and s["max"] == 99.0
    assert s["mean"] == pytest.approx(49.5)
    assert s["p50"] == pytest.approx(50.0, abs=2)
    assert s["p95"] == pytest.approx(95.0, abs=2)
    assert s["p99"] == pytest.approx(99.0, abs=2)

    tel.gauge("g").set(1.25, epoch=7)
    tel.close()
    events = _read_events(tmp_path / "telemetry" / "rank3.jsonl")
    by_kind = {(e["kind"], e["name"]): e for e in events}
    assert by_kind[("counter", "c")]["value"] == 3.5
    assert by_kind[("gauge", "g")]["value"] == 1.25
    assert by_kind[("gauge", "g")]["attrs"] == {"epoch": 7}
    assert by_kind[("histogram", "h")]["count"] == 100
    # every line carries the rank and the paired-stamp contract: wall
    # time for humans, monotonic for cross-record arithmetic
    assert all(e["rank"] == 3 and e["ts"] > 0 for e in events)
    assert all(isinstance(e["mono"], float) and e["mono"] > 0
               for e in events)


def test_histogram_merge_matches_pooled_within_sketch_error():
    """The fleet collector's core primitive (ISSUE 16): folding
    per-rank sketches must agree with one sketch that observed every
    value directly, and both must sit within the sketch's ~2% bound of
    the TRUE pooled quantiles — across disjoint distributions (ranks
    rarely see identical traffic), empty ranks, and non-positive
    observations."""
    import math
    import random

    rng = random.Random(1234)
    shards = [
        [rng.lognormvariate(2.0, 0.8) for _ in range(4000)],   # fast rank
        [rng.lognormvariate(4.5, 0.4) for _ in range(2500)],   # slow rank
        [rng.uniform(0.5, 900.0) for _ in range(1500)],        # noisy rank
        [],                                                    # idle rank
        [0.0, -3.0] + [rng.expovariate(0.01) for _ in range(500)],
    ]
    merged = telemetry.Histogram("lat")
    pooled = telemetry.Histogram("lat")
    for shard in shards:
        h = telemetry.Histogram("lat")
        for v in shard:
            h.observe(v)
            pooled.observe(v)
        merged.merge(h)
    values = sorted(v for shard in shards for v in shard)
    assert merged.count == pooled.count == len(values)
    assert merged.sum == pytest.approx(pooled.sum)
    assert (merged.min, merged.max) == (pooled.min, pooled.max)
    for q in (0.5, 0.9, 0.95, 0.99):
        exact = values[min(len(values) - 1, int(q * len(values)))]
        # bucket-wise merge is exact: merged == pooled to fp precision
        assert merged.quantile(q) == pytest.approx(pooled.quantile(q),
                                                   rel=1e-12)
        # and the sketch itself stays inside its ±2% representative
        # error of the true pooled quantile
        assert merged.quantile(q) == pytest.approx(exact, rel=0.02)
    # merging into an empty sketch is identity
    fresh = telemetry.Histogram("lat").merge(pooled)
    assert fresh.quantile(0.95) == pooled.quantile(0.95)
    # from_parts round trip (the fleet's reconstruct-then-merge path)
    rebuilt = telemetry.Histogram.from_parts(
        "lat", pooled.count, pooled.sum, pooled.min, pooled.max,
        dict(pooled._buckets), nonpos=pooled._nonpos)
    assert rebuilt.quantile(0.99) == pooled.quantile(0.99)
    assert math.isinf(telemetry.Histogram.from_parts(
        "lat", 0, 0.0, 0.0, 0.0, {}).min)


def test_disabled_instance_does_no_file_io(tmp_path):
    tel = telemetry.Telemetry(enabled=False, rsl_path=str(tmp_path))
    tel.counter("c").add()
    tel.gauge("g").set(1.0)
    tel.histogram("h").observe(0.1)
    with tel.span("s"):
        pass
    tel.event("e")
    tel.flush()
    tel.close()
    assert not os.path.exists(tmp_path / "telemetry")
    # and the span is the shared no-op (no per-call allocation)
    assert tel.span("a") is tel.span("b")


def test_close_is_idempotent(tmp_path):
    tel = telemetry.Telemetry(enabled=True, rsl_path=str(tmp_path), rank=0)
    tel.counter("c").add(1)
    tel.close()
    tel.close()  # second close: no duplicate summary block
    events = _read_events(tmp_path / "telemetry" / "rank0.jsonl")
    assert sum(1 for e in events if e["kind"] == "counter") == 1


def test_gauge_null_is_recorded_and_skipped_by_aggregate(tmp_path):
    tel = telemetry.Telemetry(enabled=True, rsl_path=str(tmp_path), rank=0)
    tel.gauge("throughput/mfu").set(None, reason="unknown_peak")
    tel.close()
    events = _read_events(tmp_path / "telemetry" / "rank0.jsonl")
    assert events[0]["value"] is None
    agg = telemetry.aggregate(events)
    assert "throughput/mfu" not in agg["gauges"]


# -- span nesting + JSONL schema round-trip ----------------------------


def test_span_nesting_and_schema_roundtrip(tmp_path):
    tel = telemetry.Telemetry(enabled=True, rsl_path=str(tmp_path), rank=1)
    with tel.span("outer", epoch=0):
        with tel.span("inner", step=4):
            pass
    tel.close()
    events = _read_events(tmp_path / "telemetry" / "rank1.jsonl")
    spans = {e["name"]: e for e in events if e["kind"] == "span"}
    assert spans["inner"]["parent"] == "outer"
    assert spans["outer"]["parent"] is None
    # inner closed first, and durations nest
    assert spans["inner"]["dur_s"] <= spans["outer"]["dur_s"]
    assert spans["inner"]["attrs"] == {"step": 4}
    # span stamps are END stamps: start = mono - dur_s, so inner's
    # reconstructed start can't precede outer's
    assert (spans["inner"]["mono"] - spans["inner"]["dur_s"]
            >= spans["outer"]["mono"] - spans["outer"]["dur_s"])
    # the aggregate of a round-tripped file sees both spans
    agg = telemetry.aggregate(events)
    assert agg["spans"]["outer"]["count"] == 1
    assert agg["spans"]["inner"]["count"] == 1


def test_configure_swaps_the_global(tmp_path, restore_global):
    tel = telemetry.configure(str(tmp_path), enabled=True, rank=0)
    assert telemetry.get() is tel and tel.enabled
    tel2 = telemetry.configure(str(tmp_path), enabled=False)
    assert telemetry.get() is tel2 and not tel2.enabled
    # the first instance was closed by the swap
    assert not tel.enabled


# -- pipeline data-wait counters on a synthetic loader -----------------


def _small_loader(prefetch):
    from distributedpytorch_tpu import runtime
    from distributedpytorch_tpu.data.datasets import Split
    from distributedpytorch_tpu.data.io import make_synthetic
    from distributedpytorch_tpu.data.pipeline import ShardedLoader

    tr_x, tr_y, _, _ = make_synthetic(num_train=64, num_test=8,
                                      image_size=28, channels=1, seed=0)
    mesh = runtime.make_mesh()
    return ShardedLoader(Split(tr_x, tr_y), mesh, batch_per_replica=2,
                         shuffle=False, seed=0, prefetch=prefetch)


@pytest.mark.parametrize("prefetch", [0, 2])
def test_pipeline_data_wait_counters(tmp_path, restore_global, prefetch):
    loader = _small_loader(prefetch)
    assert loader._queue is None  # exists before the first iteration
    tel = telemetry.configure(str(tmp_path), enabled=True, rank=0)
    n = sum(1 for _ in loader.epoch(0))
    assert n == len(loader)
    assert tel.counter("data/batches").value == n
    assert tel.counter("data/wait_s").value > 0
    if prefetch > 0:
        assert loader._queue is not None  # latest epoch iterator's queue
        # depth was sampled once per yielded batch
        assert tel.counter("data/queue_depth_sum").value >= n
        assert 0 <= tel.counter("data/starved_steps").value <= n


def test_pipeline_disabled_keeps_counters_at_zero(restore_global):
    loader = _small_loader(2)
    tel = telemetry.get()
    assert not tel.enabled
    n = sum(1 for _ in loader.epoch(0))
    assert n == len(loader)
    assert tel.counter("data/batches").value == 0  # nothing was counted


# -- report aggregation over multi-rank fixture files ------------------


def _write_rank_fixture(d, rank, epoch_s, wait_s):
    lines = []
    for epoch, dur in enumerate(epoch_s):
        lines.append({"kind": "span", "name": "epoch", "dur_s": dur,
                      "parent": None, "attrs": {"epoch": epoch},
                      "ts": 1000.0 + epoch, "rank": rank})
        lines.append({"kind": "span", "name": "train_pass",
                      "dur_s": dur * 0.8, "parent": "epoch",
                      "ts": 1000.0 + epoch, "rank": rank})
    lines.append({"kind": "counter", "name": "data/wait_s",
                  "value": wait_s, "ts": 1010.0, "rank": rank})
    lines.append({"kind": "counter", "name": "data/batches",
                  "value": 8, "ts": 1010.0, "rank": rank})
    lines.append({"kind": "counter", "name": "data/starved_steps",
                  "value": 2, "ts": 1010.0, "rank": rank})
    lines.append({"kind": "gauge",
                  "name": "throughput/samples_per_sec_per_chip",
                  "value": 1000.0 + rank, "ts": 1010.0, "rank": rank})
    lines.append({"kind": "gauge", "name": "throughput/mfu",
                  "value": 0.4 + 0.1 * rank, "ts": 1010.0, "rank": rank})
    with open(os.path.join(d, f"rank{rank}.jsonl"), "w") as f:
        f.write("\n".join(json.dumps(x) for x in lines) + "\n")


def test_report_aggregates_multi_rank_files(tmp_path):
    d = str(tmp_path / "telemetry")
    os.makedirs(d)
    _write_rank_fixture(d, 0, epoch_s=[1.0, 1.2], wait_s=0.2)
    _write_rank_fixture(d, 1, epoch_s=[2.0, 2.2], wait_s=0.9)
    agg = telemetry.aggregate(telemetry.load_events(d))
    assert agg["ranks"] == [0, 1]
    assert agg["spans"]["epoch"]["count"] == 4
    assert agg["spans"]["epoch"]["max_s"] == pytest.approx(2.2)
    # straggler view: rank 1 is ~2x slower
    assert agg["epoch_s_per_rank"][1] > agg["epoch_s_per_rank"][0]
    # starvation fraction = total wait / total train_pass time
    total_train = (1.0 + 1.2 + 2.0 + 2.2) * 0.8
    assert agg["data_starvation_fraction"] == pytest.approx(
        1.1 / total_train)
    assert agg["gauges"]["throughput/mfu"]["mean"] == pytest.approx(0.45)

    report = telemetry.render_report(agg)
    assert "slowest spans" in report
    assert "rank 1" in report and "slowest" in report
    assert "data starvation" in report
    assert "MFU: 45.0%" in report
    # torn last line (killed mid-write) is skipped, not fatal
    with open(os.path.join(d, "rank0.jsonl"), "a") as f:
        f.write('{"kind": "span", "na')
    telemetry.aggregate(telemetry.load_events(d))


def test_report_errors_without_telemetry_dir(tmp_path):
    with pytest.raises(ValueError, match="telemetry"):
        telemetry.report(str(tmp_path / "nope"))


# -- driver-level contract (acceptance criterion) ----------------------


def test_train_with_telemetry_writes_rank0_jsonl(tmp_path, restore_global):
    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    rsl = str(tmp_path / "rsl")
    cfg = Config(action="train", data_path="/tmp/nodata", rsl_path=rsl,
                 dataset="synthetic", model_name="mlp", batch_size=8,
                 nb_epochs=1, debug=True, half_precision=False,
                 telemetry=True, data_mode="stream")
    run_train(cfg)
    path = os.path.join(rsl, "telemetry", "rank0.jsonl")
    assert os.path.exists(path)
    events = _read_events(path)
    names = {(e["kind"], e["name"]) for e in events}
    assert ("span", "epoch") in names
    assert ("span", "train_pass") in names
    assert ("span", "eval_pass") in names
    assert ("span", "ckpt_save") in names
    assert ("counter", "data/wait_s") in names
    assert ("histogram", "step/dispatch_s") in names
    assert ("gauge", "throughput/samples_per_sec_per_chip") in names
    assert ("gauge", "throughput/mfu") in names  # recorded null on CPU
    assert ("event", "run_start") in names
    # the report renders from the real run's files
    report = telemetry.report(rsl)
    assert "slowest spans" in report and "epoch" in report
    sps = [e for e in events
           if e["name"] == "throughput/samples_per_sec_per_chip"]
    assert all(np.isfinite(e["value"]) and e["value"] > 0 for e in sps)


def test_train_without_telemetry_writes_nothing(tmp_path, restore_global):
    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    rsl = str(tmp_path / "rsl")
    run_train(Config(action="train", data_path="/tmp/nodata", rsl_path=rsl,
                     dataset="synthetic", model_name="mlp", batch_size=8,
                     nb_epochs=1, debug=True, half_precision=False))
    assert not os.path.exists(os.path.join(rsl, "telemetry"))


def test_telemetry_cli_flag_and_subcommand_roundtrip():
    from distributedpytorch_tpu.config import config_from_argv

    cfg = config_from_argv(["train", "-d", "/x", "--telemetry"])
    assert cfg.telemetry
    assert not config_from_argv(["train", "-d", "/x"]).telemetry
    rep = config_from_argv(["telemetry", "--rsl_path", "/some/dir"])
    assert rep.action == "telemetry" and rep.rsl_path == "/some/dir"


# -- writer I/O failure never kills training (ISSUE 5 satellite) -------


def test_write_error_disables_sink_and_counts(tmp_path, restore_global):
    from distributedpytorch_tpu import faults

    tel = telemetry.Telemetry(enabled=True, rsl_path=str(tmp_path),
                              rank=0)
    # One injected I/O error at the first flush: the write must be
    # swallowed (training would continue), counted, and the sink killed.
    faults.install(faults.parse_plan("telemetry.write:ioerror:0"))
    try:
        tel.event("before_failure")
        tel.flush()  # fails — must NOT raise
        assert tel.write_errors == 1 and tel._sink_dead
        tel.event("after_failure")
        tel.flush()  # dead sink: drops silently, still no raise
        assert tel.write_errors == 1
    finally:
        faults.install(None)
    # close() retries once (the condition may have cleared) so the
    # write_errors counter reaches the file for the report to see.
    tel.close()
    events = _read_events(tmp_path / "telemetry" / "rank0.jsonl")
    by_name = {e["name"]: e for e in events if e["kind"] == "counter"}
    assert by_name["telemetry/write_errors"]["value"] == 1.0


def test_report_warns_on_write_errors_and_skipped_ranks():
    agg = telemetry.aggregate([
        {"kind": "event", "name": "run_start", "rank": 0, "ts": 1.0,
         "attrs": {"processes": 2}},
        {"kind": "counter", "name": "telemetry/write_errors", "rank": 0,
         "ts": 2.0, "value": 3.0},
    ])
    report = telemetry.render_report(agg)
    assert "WARNING: 3 telemetry write error(s)" in report
    # 2 processes ran, only rank 0's file was readable
    assert "rank(s) [1] skipped" in report


# -- aggregation across an elastic reconfigure -------------------------


def test_aggregate_across_elastic_reconfigure_counts_once():
    """Two generations with different rank sets: counters sum exactly
    once per event, every rank that ever wrote is listed, and the
    shrunken world produces no spurious missing-rank WARNING."""
    events = [{"kind": "event", "name": "run_start", "rank": r,
               "ts": 1.0, "attrs": {"processes": 3}} for r in range(3)]
    # generation 0: three ranks count batches
    events += [{"kind": "counter", "name": "data/batches", "rank": r,
                "ts": 2.0, "value": 10.0} for r in range(3)]
    # rank 2 dies; the survivors re-rendezvous as a 2-world and say so
    events += [{"kind": "event", "name": "elastic/reconfigure",
                "rank": r, "ts": 3.0,
                "attrs": {"generation": 1, "old_world": 3,
                          "new_world": 2, "old_rank": r, "new_rank": r}}
               for r in range(2)]
    # generation 1: the survivors keep counting
    events += [{"kind": "counter", "name": "data/batches", "rank": r,
                "ts": 4.0, "value": 5.0} for r in range(2)]
    agg = telemetry.aggregate(events)
    assert agg["ranks"] == [0, 1, 2]
    assert agg["counters"]["data/batches"] == pytest.approx(40.0)
    report = telemetry.render_report(agg)
    # every rank's file is readable here — nothing to warn about
    assert "skipped (telemetry writer" not in report


def test_report_notes_departed_rank_instead_of_warning():
    """The departed rank's file never landed: with a reconfigure event
    in evidence that is expected elastic behavior (a note), while a
    missing rank INSIDE the surviving world stays a real WARNING."""
    base = [{"kind": "event", "name": "run_start", "rank": 0, "ts": 1.0,
             "attrs": {"processes": 3}},
            {"kind": "event", "name": "elastic/reconfigure", "rank": 0,
             "ts": 2.0, "attrs": {"generation": 1, "old_world": 3,
                                  "new_world": 2, "old_rank": 0,
                                  "new_rank": 0}}]
    # rank 1 present, rank 2 (departed) absent: note, no WARNING
    report = telemetry.render_report(telemetry.aggregate(
        base + [{"kind": "counter", "name": "data/batches", "rank": 1,
                 "ts": 2.5, "value": 1.0}]))
    assert "rank(s) [2] departed in an elastic reconfigure" in report
    assert "skipped (telemetry writer" not in report
    # rank 1 (a survivor slot) ALSO missing: that one is a lost writer
    report = telemetry.render_report(telemetry.aggregate(base))
    assert "rank(s) [2] departed in an elastic reconfigure" in report
    assert "rank(s) [1] skipped" in report


def test_report_across_shrink_then_grow_history():
    """Shrink to 2 then grow back to 3: the rejoined rank appearing
    mid-run must trip NEITHER the missing-rank WARNING nor the departed
    note (the current world is the NEWEST generation's size, not the
    minimum over the run), and counters still sum exactly once."""
    events = [{"kind": "event", "name": "run_start", "rank": r,
               "ts": 1.0, "attrs": {"processes": 3}} for r in range(3)]
    events += [{"kind": "counter", "name": "data/batches", "rank": r,
                "ts": 2.0, "value": 10.0} for r in range(3)]
    # rank 2 dies; survivors shrink to a 2-world...
    events += [{"kind": "event", "name": "elastic/reconfigure",
                "rank": r, "ts": 3.0,
                "attrs": {"generation": 1, "old_world": 3,
                          "new_world": 2, "old_rank": r, "new_rank": r}}
               for r in range(2)]
    # ...then it rejoins: survivors reconfigure to 3, the joiner
    # announces itself (appending to the departed incarnation's file).
    events += [{"kind": "event", "name": "elastic/reconfigure",
                "rank": r, "ts": 4.0,
                "attrs": {"generation": 2, "old_world": 2,
                          "new_world": 3, "old_rank": r, "new_rank": r,
                          "grow": True}} for r in range(2)]
    events += [{"kind": "event", "name": "elastic/join", "rank": 2,
                "ts": 4.0, "attrs": {"generation": 2, "new_world": 3,
                                     "new_rank": 2}}]
    events += [{"kind": "counter", "name": "data/batches", "rank": r,
                "ts": 5.0, "value": 5.0} for r in range(3)]
    agg = telemetry.aggregate(events)
    assert agg["ranks"] == [0, 1, 2]
    assert agg["counters"]["data/batches"] == pytest.approx(45.0)
    report = telemetry.render_report(agg)
    assert "rank(s) [2] joined mid-run in an elastic grow" in report
    assert "departed in an elastic reconfigure" not in report
    assert "skipped (telemetry writer" not in report


def test_report_grown_world_still_warns_on_lost_writer():
    """After a grow to world 3, a missing rank BELOW the final world is
    still a real lost-writer WARNING — the grow must not blanket-excuse
    missing files."""
    events = [{"kind": "event", "name": "run_start", "rank": 0,
               "ts": 1.0, "attrs": {"processes": 3}},
              {"kind": "event", "name": "elastic/reconfigure", "rank": 0,
               "ts": 2.0, "attrs": {"generation": 1, "old_world": 3,
                                    "new_world": 2, "old_rank": 0,
                                    "new_rank": 0}},
              {"kind": "event", "name": "elastic/reconfigure", "rank": 0,
               "ts": 3.0, "attrs": {"generation": 2, "old_world": 2,
                                    "new_world": 3, "old_rank": 0,
                                    "new_rank": 0, "grow": True}}]
    report = telemetry.render_report(telemetry.aggregate(events))
    # ranks 1 and 2 live inside the final 3-world yet left no files
    assert "rank(s) [1, 2] skipped" in report
