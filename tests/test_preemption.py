"""Graceful-shutdown (preemption) handling: SIGTERM mid-training finishes
the current epoch, writes the rolling checkpoint, and exits 0 — the
elastic-recovery story preemptible TPU VMs need (SURVEY §5: the reference
has none; a bare signal kills it wherever it is)."""

import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
from distributedpytorch_tpu.cli import main
import sys
sys.exit(main(["train", "-d", "/nodata", "--rsl_path", sys.argv[1],
               "--dataset", "synthetic", "--synthetic-fallback",
               "--model", "mlp", "-b", "8", "-e", "500", "--debug",
               "--no-bf16"]))
"""


def test_sigterm_checkpoints_and_exits_clean(tmp_path):
    rsl = str(tmp_path / "rsl")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen([sys.executable, "-c", CHILD, rsl],
                            cwd=REPO, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    try:
        # wait until at least one epoch has completed (log line appears)
        log = os.path.join(rsl, "test.log")
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if os.path.exists(log) and "Epoch: 0" in open(log).read():
                break
            if proc.poll() is not None:
                raise AssertionError(
                    proc.communicate()[0].decode()[-3000:])
            time.sleep(1)
        else:
            raise AssertionError("no epoch completed within 300s")

        proc.send_signal(signal.SIGTERM)
        out = proc.communicate(timeout=120)[0].decode()
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out[-3000:]
    text = open(log).read()
    assert "preempted after epoch" in text, text[-2000:]
    # the rolling checkpoint for the last finished epoch exists
    assert any(f.startswith("checkpoint-synthetic-mlp-")
               for f in os.listdir(rsl)), os.listdir(rsl)
    # training stopped early: far fewer than 500 epochs ran
    assert text.count("| Duration:") < 400
