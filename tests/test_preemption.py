"""Graceful-shutdown (preemption) handling: SIGTERM mid-training finishes
the current epoch, writes the rolling checkpoint, and exits 0 — the
elastic-recovery story preemptible TPU VMs need (SURVEY §5: the reference
has none; a bare signal kills it wherever it is)."""

import os
import signal
import sys

from tests._subproc import launch_logged, wait_for_epoch_line

CHILD = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
from distributedpytorch_tpu.cli import main
import sys
import pytest

# subprocess worlds / full CLI chains: the slow tier (scripts/gate.sh runs -m 'not slow')
pytestmark = pytest.mark.slow
sys.exit(main(["train", "-d", "/nodata", "--rsl_path", sys.argv[1],
               "--dataset", "synthetic", "--synthetic-fallback",
               "--model", "mlp", "-b", "8", "-e", "500", "--debug",
               "--no-bf16"]))
"""


def test_sigterm_checkpoints_and_exits_clean(tmp_path):
    rsl = str(tmp_path / "rsl")
    child_log = str(tmp_path / "child.txt")
    proc = launch_logged([sys.executable, "-c", CHILD, rsl], child_log)
    try:
        # wait until at least one epoch has completed (log line appears)
        log = os.path.join(rsl, "test.log")
        wait_for_epoch_line(log, [proc], proc_logs=[child_log])

        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    out = open(child_log).read()
    assert proc.returncode == 0, out[-3000:]
    text = open(log).read()
    assert "preempted after epoch" in text, text[-2000:]
    # the rolling checkpoint for the last finished epoch exists
    assert any(f.startswith("checkpoint-synthetic-mlp-")
               for f in os.listdir(rsl)), os.listdir(rsl)
    # training stopped early: far fewer than 500 epochs ran
    assert text.count("| Duration:") < 400
