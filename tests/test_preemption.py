"""Graceful-shutdown (preemption) handling: SIGTERM mid-training finishes
the current epoch, writes the rolling checkpoint, and exits 0 — the
elastic-recovery story preemptible TPU VMs need (SURVEY §5: the reference
has none; a bare signal kills it wherever it is)."""

import os
import signal
import subprocess
import sys

from tests._subproc import REPO, child_env, wait_for_epoch_line

CHILD = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
from distributedpytorch_tpu.cli import main
import sys
sys.exit(main(["train", "-d", "/nodata", "--rsl_path", sys.argv[1],
               "--dataset", "synthetic", "--synthetic-fallback",
               "--model", "mlp", "-b", "8", "-e", "500", "--debug",
               "--no-bf16"]))
"""


def test_sigterm_checkpoints_and_exits_clean(tmp_path):
    rsl = str(tmp_path / "rsl")
    proc = subprocess.Popen([sys.executable, "-c", CHILD, rsl],
                            cwd=REPO, env=child_env(),
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    try:
        # wait until at least one epoch has completed (log line appears)
        log = os.path.join(rsl, "test.log")
        wait_for_epoch_line(log, [proc])

        proc.send_signal(signal.SIGTERM)
        out = proc.communicate(timeout=120)[0].decode()
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out[-3000:]
    text = open(log).read()
    assert "preempted after epoch" in text, text[-2000:]
    # the rolling checkpoint for the last finished epoch exists
    assert any(f.startswith("checkpoint-synthetic-mlp-")
               for f in os.listdir(rsl)), os.listdir(rsl)
    # training stopped early: far fewer than 500 epochs ran
    assert text.count("| Duration:") < 400
