"""Graceful-shutdown (preemption) handling: SIGTERM mid-training finishes
the current epoch, writes the rolling checkpoint, and exits 0 — the
elastic-recovery story preemptible TPU VMs need (SURVEY §5: the reference
has none; a bare signal kills it wherever it is).

The subprocess e2e is timing-sensitive by nature (a real signal against
a real run); the deadlock class that used to make it FLAKY — the
handler re-entering a telemetry/flightrec lock the interrupted frame
already held — is pinned by the fast, deterministic reentrancy tests
below instead.
"""

import os
import signal
import sys
import threading

import pytest

from distributedpytorch_tpu import flightrec, telemetry
from distributedpytorch_tpu.utils import GracefulShutdown
from tests._subproc import launch_logged, wait_for_epoch_line

CHILD = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
from distributedpytorch_tpu.cli import main
import sys
sys.exit(main(["train", "-d", "/nodata", "--rsl_path", sys.argv[1],
               "--dataset", "synthetic", "--synthetic-fallback",
               "--model", "mlp", "-b", "8", "-e", "500", "--debug",
               "--no-bf16"]))
"""


def test_telemetry_lock_reentrant_under_signal_handler(tmp_path):
    """The preempt handler fires telemetry.event() on the MAIN thread,
    possibly interrupting a frame that already holds the telemetry
    lock: re-acquisition on the same thread must succeed immediately
    (a plain Lock here self-deadlocked the child the SIGTERM e2e kills
    at its timeout — the historical flake)."""
    tel = telemetry.configure(str(tmp_path), True)
    try:
        with tel._lock:
            # same-thread nonblocking re-acquire: True iff reentrant
            assert tel._lock.acquire(blocking=False), \
                "telemetry lock is not reentrant — the preempt " \
                "handler can deadlock mid-event"
            tel._lock.release()
            tel.event("nested", ok=True)  # the handler's actual call
    finally:
        tel.close()


def test_flightrec_lock_reentrant_under_signal_handler(tmp_path):
    rec = flightrec.configure(str(tmp_path), True, rank=0)
    with rec._lock:
        assert rec._lock.acquire(blocking=False), \
            "flight recorder lock is not reentrant — the preempt " \
            "handler's dump can deadlock mid-step"
        rec._lock.release()
        rec.record_event("nested", ok=True)
        rec.dump("nested")


def test_preempt_handler_inside_locked_sinks(tmp_path):
    """End to end on this thread: raise SIGTERM while BOTH sink locks
    are held, exactly the worst-case interrupt point.  The handler must
    set the flag and return without deadlocking or raising into the
    interrupted frame."""
    if threading.current_thread() is not threading.main_thread():
        pytest.skip("signal delivery requires the main thread")
    tel = telemetry.configure(str(tmp_path), True)
    rec = flightrec.configure(str(tmp_path), True, rank=0)
    try:
        with GracefulShutdown() as shutdown:
            with tel._lock, rec._lock:
                signal.raise_signal(signal.SIGTERM)
            assert shutdown.requested
        # the buffered audit trail survived the locked-section interrupt
        tel.flush()
        path = os.path.join(str(tmp_path), "telemetry", "rank0.jsonl")
        assert "preempt_signal" in open(path).read()
    finally:
        tel.close()


# subprocess worlds / full CLI chains: the slow tier (scripts/gate.sh
# runs -m 'not slow').  This marker used to sit INSIDE the CHILD source
# string above, silently leaving the e2e in the fast tier.
@pytest.mark.slow
def test_sigterm_checkpoints_and_exits_clean(tmp_path):
    rsl = str(tmp_path / "rsl")
    child_log = str(tmp_path / "child.txt")
    proc = launch_logged([sys.executable, "-c", CHILD, rsl], child_log)
    try:
        # wait until at least one epoch has completed (log line appears)
        log = os.path.join(rsl, "test.log")
        wait_for_epoch_line(log, [proc], proc_logs=[child_log])

        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    out = open(child_log).read()
    assert proc.returncode == 0, out[-3000:]
    text = open(log).read()
    assert "preempted after epoch" in text, text[-2000:]
    # the rolling checkpoint for the last finished epoch exists
    assert any(f.startswith("checkpoint-synthetic-mlp-")
               for f in os.listdir(rsl)), os.listdir(rsl)
    # training stopped early: far fewer than 500 epochs ran
    assert text.count("| Duration:") < 400
