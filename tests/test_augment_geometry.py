"""Pin the matmul-formulated warp to classical bilinear sampling.

The gather-free hat-matrix formulation in augment._warp_one must produce
exactly the same image as a straightforward numpy bilinear sampler for the
same affine parameters (rotation about center + crop-box resize with
half-pixel convention, zero fill outside) — i.e. the MXU-friendly rewrite
changed the execution strategy, not the math.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distributedpytorch_tpu.data import augment


def _numpy_bilinear_warp(img, theta, y0, x0, crop_h, crop_w, out_dim):
    h, w = img.shape
    ii = np.arange(out_dim, dtype=np.float64)
    ys = y0 + (ii[:, None] + 0.5) * crop_h / out_dim - 0.5
    xs = x0 + (ii[None, :] + 0.5) * crop_w / out_dim - 0.5
    ys = np.broadcast_to(ys, (out_dim, out_dim))
    xs = np.broadcast_to(xs, (out_dim, out_dim))
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    cos_t, sin_t = np.cos(-theta), np.sin(-theta)
    sy = cos_t * (ys - cy) - sin_t * (xs - cx) + cy
    sx = sin_t * (ys - cy) + cos_t * (xs - cx) + cx

    out = np.zeros((out_dim, out_dim))
    for i in range(out_dim):
        for j in range(out_dim):
            y, x = sy[i, j], sx[i, j]
            acc = 0.0
            fy, fx = int(np.floor(y)), int(np.floor(x))
            for yy in (fy, fy + 1):
                for xx in (fx, fx + 1):
                    if 0 <= yy < h and 0 <= xx < w:
                        wgt = max(0.0, 1 - abs(y - yy)) * \
                            max(0.0, 1 - abs(x - xx))
                        acc += wgt * img[yy, xx]
            out[i, j] = acc  # zero fill outside (RandomRotation fill=0)
    return out


def test_warp_matches_numpy_bilinear_reference():
    rng = np.random.default_rng(0)
    img = rng.uniform(0, 1, size=(28, 28)).astype(np.float32)
    key = jax.random.PRNGKey(11)
    params = jax.device_get(augment._sample_affine_batch(key, 1, 28, 28))
    theta, y0, x0, crop_h, crop_w = (float(p[0]) for p in params)

    ours = np.asarray(augment._warp_one(
        jnp.asarray(img), *(jnp.float32(p[0]) for p in params), 28))
    ref = _numpy_bilinear_warp(img, theta, y0, x0, crop_h, crop_w, 28)
    np.testing.assert_allclose(ours, ref, atol=1e-4)


def test_identity_affine_is_identity():
    """crop == full image, theta == 0 -> output equals input exactly."""
    rng = np.random.default_rng(1)
    img = rng.uniform(0, 1, size=(28, 28)).astype(np.float32)
    ref = _numpy_bilinear_warp(img, 0.0, 0.0, 0.0, 28.0, 28.0, 28)
    np.testing.assert_allclose(ref, img, atol=1e-12)


def test_sampled_params_within_torchvision_ranges():
    theta_b, y0_b, x0_b, ch_b, cw_b = (
        np.asarray(p) for p in jax.device_get(
            augment._sample_affine_batch(jax.random.PRNGKey(0), 256, 28, 28)))
    for theta, y0, x0, ch, cw in zip(theta_b, y0_b, x0_b, ch_b, cw_b):
        assert abs(theta) <= np.deg2rad(5.0) + 1e-6  # ref dataloader.py:102
        assert 1.0 <= ch <= 28.0 and 1.0 <= cw <= 28.0
        assert 0.0 <= y0 <= 28.0 - ch + 1e-5
        assert 0.0 <= x0 <= 28.0 - cw + 1e-5
        # torchvision RandomResizedCrop scale bounds: area in [0.08, 1]*HW
        area = ch * cw / (28.0 * 28.0)
        assert 0.05 <= area <= 1.0 + 1e-6
