"""Loss zoo parity against torch (CPU torch is available in the image).

The reference's losses are torch.nn.CrossEntropyLoss (ref classif.py:110),
CrossEntropyLoss(weight) (:112) and FocalLossN (ref utils.py:142-156);
these tests pin our pure-JAX implementations to torch's numerics.
"""

import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from distributedpytorch_tpu.ops import losses  # noqa: E402


@pytest.fixture
def batch():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, size=(16,)).astype(np.int64)
    weights = rng.uniform(0.5, 2.0, size=(10,)).astype(np.float32)
    return logits, labels, weights


def _scalar(numer, denom):
    return float(jnp.sum(numer) / jnp.sum(denom))


def test_cross_entropy_matches_torch(batch):
    logits, labels, _ = batch
    ours = _scalar(*losses.cross_entropy(jnp.asarray(logits),
                                         jnp.asarray(labels)))
    ref = torch.nn.CrossEntropyLoss()(torch.tensor(logits),
                                      torch.tensor(labels)).item()
    assert ours == pytest.approx(ref, rel=1e-5)


def test_weighted_cross_entropy_matches_torch(batch):
    logits, labels, weights = batch
    ours = _scalar(*losses.weighted_cross_entropy(
        jnp.asarray(logits), jnp.asarray(labels), jnp.asarray(weights)))
    ref = torch.nn.CrossEntropyLoss(weight=torch.tensor(weights))(
        torch.tensor(logits), torch.tensor(labels)).item()
    assert ours == pytest.approx(ref, rel=1e-5)


def test_focal_loss_matches_reference_formula(batch):
    """Pin to FocalLossN's exact computation (ref utils.py:150-156):
    nll_loss((1-p)^gamma * log_softmax, weight, reduction='none').mean()."""
    logits, labels, weights = batch
    t_logits, t_labels = torch.tensor(logits), torch.tensor(labels)
    log_prob = torch.nn.functional.log_softmax(t_logits, dim=-1)
    prob = torch.exp(log_prob)
    ref = torch.nn.functional.nll_loss(
        ((1 - prob) ** 2.0) * log_prob, t_labels,
        weight=torch.tensor(weights), reduction="none").mean().item()
    ours = _scalar(*losses.focal_loss(jnp.asarray(logits),
                                      jnp.asarray(labels),
                                      jnp.asarray(weights), gamma=2.0))
    assert ours == pytest.approx(ref, rel=1e-5)


def test_focal_loss_unweighted(batch):
    logits, labels, _ = batch
    t_logits, t_labels = torch.tensor(logits), torch.tensor(labels)
    log_prob = torch.nn.functional.log_softmax(t_logits, dim=-1)
    prob = torch.exp(log_prob)
    ref = torch.nn.functional.nll_loss(
        ((1 - prob) ** 2.0) * log_prob, t_labels,
        reduction="none").mean().item()
    ours = _scalar(*losses.focal_loss(jnp.asarray(logits),
                                      jnp.asarray(labels), None, 2.0))
    assert ours == pytest.approx(ref, rel=1e-5)


def test_dispatch_and_invalid_name():
    fn = losses.get_loss_fn("cross_entropy")
    n, d = fn(jnp.zeros((2, 3)), jnp.array([0, 1]))
    assert n.shape == (2,) and d.shape == (2,)
    with pytest.raises(ValueError, match="Invalid loss"):
        losses.get_loss_fn("nope")
    with pytest.raises(ValueError, match="requires class weights"):
        losses.get_loss_fn("weighted_cross_entropy")
