"""Canary rollout policy + manager (serving/rollout.py, ISSUE 19
tentpole 3).

The pure verdict (``decide_rollout``) and the canary pick are driven
with synthetic observations; the ledger readers get real tmp files;
``RolloutManager`` runs its whole stable -> canary -> promote/rollback
state machine against stub reload/event functions — no sockets, no
replicas, no JAX.
"""

import hashlib
import json

from distributedpytorch_tpu.serving.rollout import (LINEAGE_FILE,
                                                    RolloutManager,
                                                    choose_canaries,
                                                    decide_rollout,
                                                    newest_lineage_entry,
                                                    verify_sha)

# -- canary pick -------------------------------------------------------


def test_choose_canaries_fraction_at_least_one_never_all():
    assert choose_canaries([0, 1], 0.34) == [0]
    assert choose_canaries([0, 1, 2], 0.34) == [0]     # a third is ONE
    assert choose_canaries(range(6), 0.34) == [0, 1]
    assert choose_canaries([0, 1, 2], 1.0) == [0, 1]   # never the fleet
    assert choose_canaries([0], 0.5) == []             # no stable side
    assert choose_canaries([], 0.5) == []


def test_choose_canaries_deterministic_over_unsorted_ids():
    assert choose_canaries([2, 0, 1], 0.34) == [0]


# -- pure verdict ------------------------------------------------------

CFG = {"hold_s": 5.0, "min_requests": 20, "max_error_ratio": 0.05,
       "error_ratio_factor": 3.0, "p95_factor": 3.0,
       "p95_floor_ms": 50.0, "timeout_s": 120.0}


def _obs(t, creq=0, cerr=0, sreq=100, serr=0, cp95=None, sp95=None,
         alive=True):
    return {"t": t, "canary_alive": alive,
            "canary": {"requests": creq, "errors": cerr, "p95_ms": cp95},
            "stable": {"requests": sreq, "errors": serr, "p95_ms": sp95}}


def test_verdict_dead_canary_rolls_back():
    v = decide_rollout(CFG, {"since_t": 0.0}, _obs(1.0, alive=False))
    assert v["action"] == "rollback" and "died" in v["reason"]


def test_verdict_error_ratio_rolls_back():
    v = decide_rollout(CFG, {"since_t": 0.0},
                       _obs(2.0, creq=40, cerr=10, sreq=100, serr=1))
    assert v["action"] == "rollback" and "error ratio" in v["reason"]


def test_verdict_tolerates_fleetwide_errors():
    """Canary errors that merely MATCH stable's are not the canary's
    fault — no rollback when stable is equally unhealthy."""
    v = decide_rollout(CFG, {"since_t": 0.0},
                       _obs(2.0, creq=40, cerr=4, sreq=100, serr=10))
    assert v["action"] != "rollback"


def test_verdict_p95_regression_rolls_back():
    v = decide_rollout(CFG, {"since_t": 0.0},
                       _obs(2.0, creq=40, cp95=400.0, sp95=50.0))
    assert v["action"] == "rollback" and "p95" in v["reason"]


def test_verdict_p95_noise_floor_ignored():
    v = decide_rollout(CFG, {"since_t": 0.0},
                       _obs(2.0, creq=40, cp95=40.0, sp95=5.0))
    assert v["action"] != "rollback"  # 40ms is under the 50ms floor


def test_verdict_promotes_after_healthy_hold():
    assert decide_rollout(CFG, {"since_t": 0.0},
                          _obs(3.0, creq=40))["action"] == "continue"
    v = decide_rollout(CFG, {"since_t": 0.0}, _obs(6.0, creq=40))
    assert v["action"] == "promote"


def test_verdict_starved_canary_times_out():
    assert decide_rollout(CFG, {"since_t": 0.0},
                          _obs(60.0, creq=3))["action"] == "continue"
    v = decide_rollout(CFG, {"since_t": 0.0}, _obs(121.0, creq=3))
    assert v["action"] == "rollback" and "min_requests" in v["reason"]


# -- ledger readers ----------------------------------------------------

def _write_ledger(tmp_path, entries):
    recs = []
    for name, epoch, content in entries:
        p = tmp_path / name
        p.write_bytes(content)
        recs.append({"file": name, "epoch": epoch,
                     "sha256": hashlib.sha256(content).hexdigest(),
                     "bytes": len(content)})
    (tmp_path / LINEAGE_FILE).write_text(json.dumps({"records": recs}))
    return recs


def test_newest_lineage_entry_highest_epoch_wins(tmp_path):
    _write_ledger(tmp_path, [("a.ckpt", 1, b"old"),
                             ("b.ckpt", 3, b"new"),
                             ("c.ckpt", 2, b"mid")])
    head = newest_lineage_entry(str(tmp_path))
    assert head["file"] == "b.ckpt" and head["epoch"] == 3
    assert head["path"].endswith("b.ckpt")


def test_newest_lineage_entry_skips_missing_files(tmp_path):
    _write_ledger(tmp_path, [("a.ckpt", 1, b"old"),
                             ("gone.ckpt", 9, b"x")])
    (tmp_path / "gone.ckpt").unlink()
    assert newest_lineage_entry(str(tmp_path))["file"] == "a.ckpt"


def test_newest_lineage_entry_none_without_ledger(tmp_path):
    assert newest_lineage_entry(str(tmp_path)) is None
    (tmp_path / LINEAGE_FILE).write_text("not json {")
    assert newest_lineage_entry(str(tmp_path)) is None


def test_verify_sha_content_check(tmp_path):
    p = tmp_path / "m.ckpt"
    p.write_bytes(b"payload")
    good = hashlib.sha256(b"payload").hexdigest()
    assert verify_sha(str(p), good)
    assert not verify_sha(str(p), "0" * 64)
    assert not verify_sha(str(tmp_path / "missing"), good)


# -- the manager's state machine ---------------------------------------

class _Fleet:
    """Stub fleet: replica snapshots + a reload log."""

    def __init__(self, tmp_path, n=3):
        self.stable = _write_ledger(tmp_path, [("v1.ckpt", 1, b"v1")])[0]
        self.stable["path"] = str(tmp_path / "v1.ckpt")
        self.reps = [
            {"id": i, "alive": True, "ejected": False, "draining": False,
             "lineage": {"sha256": self.stable["sha256"],
                         "path": self.stable["path"]},
             "requests": 0, "errors": 0, "p95_ms": 10.0}
            for i in range(n)]
        self.reloads = []
        self.reload_ok = True
        self.events = []

    def reload_fn(self, uid, path):
        self.reloads.append((uid, path))
        return self.reload_ok

    def event_fn(self, name, **attrs):
        self.events.append((name, attrs))

    def head(self, tmp_path, name="v2.ckpt", epoch=2, content=b"v2"):
        rec = _write_ledger(tmp_path, [(name, epoch, content)])[0]
        return dict(rec, path=str(tmp_path / name))


def _mk(tmp_path, n=3, **cfg):
    fleet = _Fleet(tmp_path, n=n)
    base = {"fraction": 0.34, "hold_s": 5.0, "min_requests": 20,
            "timeout_s": 120.0}
    base.update(cfg)
    mgr = RolloutManager(base, fleet.reload_fn, fleet.event_fn)
    return fleet, mgr


def test_manager_learns_stable_and_ignores_current_head(tmp_path):
    fleet, mgr = _mk(tmp_path)
    head = dict(fleet.stable)
    mgr.tick(0.0, fleet.reps, head)
    assert mgr.stable_sha == fleet.stable["sha256"]
    assert mgr.phase == "stable" and fleet.reloads == []


def test_manager_canary_then_promote(tmp_path):
    fleet, mgr = _mk(tmp_path)
    mgr.tick(0.0, fleet.reps, dict(fleet.stable))
    head = fleet.head(tmp_path)
    mgr.tick(1.0, fleet.reps, head)
    assert mgr.phase == "canary" and mgr.canary_ids == [0]
    assert fleet.reloads == [(0, head["path"])]
    assert fleet.events[0][0] == "rollout/canary_start"
    # healthy canary traffic accumulates...
    for rep in fleet.reps:
        rep["requests"] = 50
    mgr.tick(3.0, fleet.reps, head)
    assert mgr.phase == "canary"  # hold_s not yet served
    mgr.tick(7.0, fleet.reps, head)
    assert mgr.phase == "stable" and mgr.promotions == 1
    assert mgr.stable_sha == head["sha256"]
    # the stable side was reloaded onto the candidate
    assert sorted(u for u, _ in fleet.reloads[1:]) == [1, 2]
    assert fleet.events[-1][0] == "rollout/promote"


def test_manager_bad_canary_rolls_back_and_never_retries(tmp_path):
    fleet, mgr = _mk(tmp_path)
    mgr.tick(0.0, fleet.reps, dict(fleet.stable))
    head = fleet.head(tmp_path)
    mgr.tick(1.0, fleet.reps, head)
    assert mgr.phase == "canary"
    fleet.reloads.clear()
    # the canary replica starts erroring hard
    fleet.reps[0]["requests"] = 40
    fleet.reps[0]["errors"] = 20
    for rep in fleet.reps[1:]:
        rep["requests"] = 40
    mgr.tick(2.0, fleet.reps, head)
    assert mgr.phase == "stable" and mgr.rollbacks == 1
    assert fleet.reloads == [(0, fleet.stable["path"])]  # restored
    assert mgr.stable_sha == fleet.stable["sha256"]
    assert fleet.events[-1][0] == "rollout/rollback"
    # the rejected sha must not canary-loop
    mgr.tick(3.0, fleet.reps, head)
    assert mgr.phase == "stable" and fleet.reloads == \
        [(0, fleet.stable["path"])]


def test_manager_rejects_checksum_mismatch(tmp_path):
    fleet, mgr = _mk(tmp_path)
    mgr.tick(0.0, fleet.reps, dict(fleet.stable))
    head = fleet.head(tmp_path)
    (tmp_path / "v2.ckpt").write_bytes(b"torn")   # rotate under it
    mgr.tick(1.0, fleet.reps, head)
    assert mgr.phase == "stable" and fleet.reloads == []
    assert fleet.events[-1][0] == "rollout/candidate_rejected"
    assert head["sha256"] in mgr.rejected


def test_manager_failed_reload_rejects_candidate(tmp_path):
    fleet, mgr = _mk(tmp_path)
    fleet.reload_ok = False
    mgr.tick(0.0, fleet.reps, dict(fleet.stable))
    head = fleet.head(tmp_path)
    mgr.tick(1.0, fleet.reps, head)
    assert mgr.phase == "stable"
    assert fleet.events[-1][0] == "rollout/candidate_rejected"


def test_manager_single_replica_fleet_never_canaries(tmp_path):
    fleet, mgr = _mk(tmp_path, n=1)
    mgr.tick(0.0, fleet.reps, dict(fleet.stable))
    mgr.tick(1.0, fleet.reps, fleet.head(tmp_path))
    assert mgr.phase == "stable" and fleet.reloads == []
