"""Bench-trend regression ledger (distributedpytorch_tpu/benchtrend.py,
ISSUE 12 satellite): deltas are computed ONLY between provenance-clean
(``fresh``) rows, replayed rounds are shown but never become a delta
endpoint, the verdict gates the latest fresh-vs-fresh delta against the
threshold, and both CLI surfaces exit 1 on a regression.
"""

import json
import os
import subprocess
import sys

import pytest

from distributedpytorch_tpu import benchtrend

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIX = os.path.join(REPO, "tests", "fixtures", "benchtrend")


def _rows(trend):
    return {r["round"]: r for r in trend["rounds"]}


def test_ok_history_gates_green():
    trend = benchtrend.build_trend(os.path.join(FIX, "ok"))
    rows = _rows(trend)
    # r01 is a legacy row (no fresh flag, no error) -> eligible.
    assert rows[1]["eligible"] and rows[1]["fresh"] is None
    assert rows[2]["delta"] == pytest.approx(0.10)
    # r03 is a replay: shown, excluded, and NEVER a delta endpoint.
    assert rows[3]["fresh"] is False and not rows[3]["eligible"]
    assert rows[3]["delta"] is None
    # r04's delta skips the replay and compares against r02.
    assert rows[4]["delta"] == pytest.approx(1200.0 / 1100.0 - 1.0)
    assert trend["latest_delta"] == pytest.approx(1200.0 / 1100.0 - 1.0)
    assert trend["n_eligible"] == 3
    assert trend["ok"] and not trend["regression"]


def test_replay_never_used_as_delta_endpoint_even_at_tail():
    # The history ends on a wildly-off replay (value 1 vs 1000): if the
    # ledger ever differenced it, this would read as a -99.9% crash.
    trend = benchtrend.build_trend(os.path.join(FIX, "replay_tail"))
    rows = _rows(trend)
    assert rows[2]["fresh"] is False
    assert rows[2]["delta"] is None and not rows[2]["eligible"]
    assert trend["latest_delta"] is None
    assert trend["ok"]
    assert any("delta-eligible" in n for n in trend["notes"])


def test_regression_flips_verdict_and_exit_code():
    d = os.path.join(FIX, "regress")
    trend = benchtrend.build_trend(d)
    assert trend["latest_delta"] == pytest.approx(-0.25)
    assert trend["regression"] and not trend["ok"]
    ok, text = benchtrend.run_cli(bench_dir=d)
    assert not ok and "REGRESSION" in text
    # A looser threshold keeps the same history green: configurable.
    ok2, _ = benchtrend.run_cli(bench_dir=d, threshold=0.30)
    assert ok2


def test_round_file_headline_extracted_from_tail():
    trend = benchtrend.build_trend(os.path.join(FIX, "round_file"))
    rows = _rows(trend)
    assert rows[1]["value"] == pytest.approx(900.0)
    assert rows[2]["delta"] == pytest.approx(0.10)


def test_no_history_raises():
    with pytest.raises(ValueError, match="no BENCH_r"):
        benchtrend.build_trend("/nonexistent/dir")


def test_unreadable_round_is_reported_not_fatal(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text("{not json")
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"metric": "m", "value": 5.0, "fresh": True}))
    trend = benchtrend.build_trend(str(tmp_path))
    rows = _rows(trend)
    assert "unreadable" in rows[1]["note"]
    assert rows[2]["eligible"] and trend["ok"]


def test_json_mode_is_machine_readable():
    ok, text = benchtrend.run_cli(bench_dir=os.path.join(FIX, "ok"),
                                  as_json=True)
    doc = json.loads(text)
    assert ok and doc["ok"] and doc["schema"] == benchtrend.SCHEMA


def test_script_exits_1_on_regression_and_0_on_ok():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    script = os.path.join(REPO, "scripts", "bench_trend.py")
    r = subprocess.run([sys.executable, script, "--dir",
                        os.path.join(FIX, "regress")],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout
    r = subprocess.run([sys.executable, script, "--dir",
                        os.path.join(FIX, "ok"), "--json"],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.loads(r.stdout)["ok"]


def test_checked_in_history_is_green():
    # The repo's own BENCH_r*.json trajectory must pass its own gate.
    trend = benchtrend.build_trend()  # repo root
    assert trend["ok"], trend
    # r05 (legacy replay with error) and r06 (fresh: false) never carry
    # a delta — the provenance rule on the real history, not a fixture.
    for r in trend["rounds"]:
        if r["round"] in (5, 6):
            assert not r["eligible"] and r["delta"] is None
