"""CIFAR-10 pickle reader round-trip + RGB engine path."""

import pickle

import jax
import numpy as np

from distributedpytorch_tpu.data import io
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.train.engine import Engine, make_optimizer


def _write_cifar(tmp_path, n_per_batch=5):
    rng = np.random.default_rng(0)
    base = tmp_path / "cifar-10-batches-py"
    base.mkdir()
    all_x, all_y = [], []

    def _one(name):
        x = rng.integers(0, 256, size=(n_per_batch, 3, 32, 32),
                         dtype=np.uint8)
        y = rng.integers(0, 10, size=(n_per_batch,)).tolist()
        with open(base / name, "wb") as f:
            pickle.dump({b"data": x.reshape(n_per_batch, -1),
                         b"labels": y}, f)
        return x.transpose(0, 2, 3, 1), np.asarray(y, np.int32)

    for i in range(1, 6):
        x, y = _one(f"data_batch_{i}")
        all_x.append(x)
        all_y.append(y)
    te_x, te_y = _one("test_batch")
    return np.concatenate(all_x), np.concatenate(all_y), te_x, te_y


def test_cifar10_reader_roundtrip(tmp_path):
    exp_x, exp_y, exp_te_x, exp_te_y = _write_cifar(tmp_path)
    tr_x, tr_y, te_x, te_y = io.load_cifar10(str(tmp_path))
    assert tr_x.shape == (25, 32, 32, 3)  # NHWC
    np.testing.assert_array_equal(tr_x, exp_x)
    np.testing.assert_array_equal(tr_y, exp_y)
    np.testing.assert_array_equal(te_x, exp_te_x)
    np.testing.assert_array_equal(te_y, exp_te_y)


def test_engine_trains_on_rgb_input():
    """CIFAR-shaped RGB batch through the full train step (cnn at 28:
    exercises the RGB branch of the augmentation warp + eval resize)."""
    model = get_model("cnn", 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 10, False)
    eng = Engine(model, "cnn", get_loss_fn("cross_entropy"), tx,
                 mean=0.47, std=0.25, input_size=28, half_precision=False)
    state = eng.init_state(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(16, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(16,)).astype(np.int32)
    valid = np.ones(16, dtype=bool)
    state, metrics = eng.train_step(state, images, labels, valid,
                                    jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))
    ev = eng.eval_step(state, images, labels, valid)
    assert float(ev["valid"]) == 16.0
