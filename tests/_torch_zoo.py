"""Plain-torch re-implementations of the three torchvision architectures
the pretrained converter supports, with torchvision's exact state_dict key
names (torchvision itself is not in this image).  Test harness only: used
to produce state_dicts in the torchvision wire format and reference logits
for conversion-parity checks (the same role bench.py's torch loop plays
for throughput).
"""

import torch
import torch.nn as nn


class _BasicBlock(nn.Module):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU(inplace=True)
        self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return self.relu(y + idt)


class TorchResNet18(nn.Module):
    """torchvision.models.resnet18 topology + key names."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        widths = (64, 128, 256, 512)
        cin = 64
        for i, w in enumerate(widths):
            stride = 1 if i == 0 else 2
            setattr(self, f"layer{i + 1}", nn.Sequential(
                _BasicBlock(cin, w, stride), _BasicBlock(w, w, 1)))
            cin = w
        self.fc = nn.Linear(512, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        for i in range(1, 5):
            x = getattr(self, f"layer{i}")(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


class TorchAlexNet(nn.Module):
    """torchvision.models.alexnet topology + key names."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2d(3, 64, 11, 4, 2), nn.ReLU(inplace=True),
            nn.MaxPool2d(3, 2),
            nn.Conv2d(64, 192, 5, 1, 2), nn.ReLU(inplace=True),
            nn.MaxPool2d(3, 2),
            nn.Conv2d(192, 384, 3, 1, 1), nn.ReLU(inplace=True),
            nn.Conv2d(384, 256, 3, 1, 1), nn.ReLU(inplace=True),
            nn.Conv2d(256, 256, 3, 1, 1), nn.ReLU(inplace=True),
            nn.MaxPool2d(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2d((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(), nn.Linear(256 * 36, 4096), nn.ReLU(inplace=True),
            nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(inplace=True),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(torch.flatten(x, 1))


class TorchVGG11BN(nn.Module):
    """torchvision.models.vgg11_bn topology + key names."""

    def __init__(self, num_classes=10):
        super().__init__()
        cfg = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512,
               "M")
        layers, cin = [], 3
        for v in cfg:
            if v == "M":
                layers.append(nn.MaxPool2d(2, 2))
            else:
                layers += [nn.Conv2d(cin, v, 3, 1, 1), nn.BatchNorm2d(v),
                           nn.ReLU(inplace=True)]
                cin = v
        self.features = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2d((7, 7))
        self.classifier = nn.Sequential(
            nn.Linear(512 * 49, 4096), nn.ReLU(inplace=True), nn.Dropout(),
            nn.Linear(4096, 4096), nn.ReLU(inplace=True), nn.Dropout(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(torch.flatten(x, 1))


TORCH_ZOO = {
    "resnet": TorchResNet18,
    "alexnet": TorchAlexNet,
    "vgg": TorchVGG11BN,
}


def randomize_bn_stats(model: nn.Module, seed: int = 0) -> None:
    """Give running_mean/var non-trivial values so a conversion-parity test
    actually exercises the batch_stats mapping."""
    g = torch.Generator().manual_seed(seed)
    for m in model.modules():
        if isinstance(m, nn.BatchNorm2d):
            m.running_mean.copy_(torch.randn(m.running_mean.shape,
                                             generator=g) * 0.1)
            m.running_var.copy_(
                torch.rand(m.running_var.shape, generator=g) * 0.5 + 0.75)
