"""Plain-torch re-implementations of the six torchvision architectures
the pretrained converter supports, with torchvision's exact state_dict key
names (torchvision itself is not in this image).  Test harness only: used
to produce state_dicts in the torchvision wire format and reference logits
for conversion-parity checks (the same role bench.py's torch loop plays
for throughput).
"""

import torch
import torch.nn as nn


class _BasicBlock(nn.Module):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.relu = nn.ReLU(inplace=True)
        self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        y = self.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return self.relu(y + idt)


class TorchResNet18(nn.Module):
    """torchvision.models.resnet18 topology + key names."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        widths = (64, 128, 256, 512)
        cin = 64
        for i, w in enumerate(widths):
            stride = 1 if i == 0 else 2
            setattr(self, f"layer{i + 1}", nn.Sequential(
                _BasicBlock(cin, w, stride), _BasicBlock(w, w, 1)))
            cin = w
        self.fc = nn.Linear(512, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        for i in range(1, 5):
            x = getattr(self, f"layer{i}")(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


class TorchAlexNet(nn.Module):
    """torchvision.models.alexnet topology + key names."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2d(3, 64, 11, 4, 2), nn.ReLU(inplace=True),
            nn.MaxPool2d(3, 2),
            nn.Conv2d(64, 192, 5, 1, 2), nn.ReLU(inplace=True),
            nn.MaxPool2d(3, 2),
            nn.Conv2d(192, 384, 3, 1, 1), nn.ReLU(inplace=True),
            nn.Conv2d(384, 256, 3, 1, 1), nn.ReLU(inplace=True),
            nn.Conv2d(256, 256, 3, 1, 1), nn.ReLU(inplace=True),
            nn.MaxPool2d(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2d((6, 6))
        self.classifier = nn.Sequential(
            nn.Dropout(), nn.Linear(256 * 36, 4096), nn.ReLU(inplace=True),
            nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(inplace=True),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(torch.flatten(x, 1))


class TorchVGG11BN(nn.Module):
    """torchvision.models.vgg11_bn topology + key names."""

    def __init__(self, num_classes=10):
        super().__init__()
        cfg = (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512,
               "M")
        layers, cin = [], 3
        for v in cfg:
            if v == "M":
                layers.append(nn.MaxPool2d(2, 2))
            else:
                layers += [nn.Conv2d(cin, v, 3, 1, 1), nn.BatchNorm2d(v),
                           nn.ReLU(inplace=True)]
                cin = v
        self.features = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2d((7, 7))
        self.classifier = nn.Sequential(
            nn.Linear(512 * 49, 4096), nn.ReLU(inplace=True), nn.Dropout(),
            nn.Linear(4096, 4096), nn.ReLU(inplace=True), nn.Dropout(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(torch.flatten(x, 1))


TORCH_ZOO = {
    "resnet": TorchResNet18,
    "alexnet": TorchAlexNet,
    "vgg": TorchVGG11BN,
}


def randomize_bn_stats(model: nn.Module, seed: int = 0) -> None:
    """Give running_mean/var non-trivial values so a conversion-parity test
    actually exercises the batch_stats mapping."""
    g = torch.Generator().manual_seed(seed)
    for m in model.modules():
        if isinstance(m, nn.BatchNorm2d):
            m.running_mean.copy_(torch.randn(m.running_mean.shape,
                                             generator=g) * 0.1)
            m.running_var.copy_(
                torch.rand(m.running_var.shape, generator=g) * 0.5 + 0.75)


class _TorchFire(nn.Module):
    def __init__(self, cin, s, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2d(cin, s, 1)
        self.squeeze_activation = nn.ReLU(inplace=True)
        self.expand1x1 = nn.Conv2d(s, e1, 1)
        self.expand1x1_activation = nn.ReLU(inplace=True)
        self.expand3x3 = nn.Conv2d(s, e3, 3, padding=1)
        self.expand3x3_activation = nn.ReLU(inplace=True)

    def forward(self, x):
        x = self.squeeze_activation(self.squeeze(x))
        return torch.cat([self.expand1x1_activation(self.expand1x1(x)),
                          self.expand3x3_activation(self.expand3x3(x))], 1)


class TorchSqueezeNet(nn.Module):
    """torchvision.models.squeezenet1_0 topology + key names."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2d(3, 96, 7, 2), nn.ReLU(inplace=True),
            nn.MaxPool2d(3, 2, ceil_mode=True),
            _TorchFire(96, 16, 64, 64), _TorchFire(128, 16, 64, 64),
            _TorchFire(128, 32, 128, 128),
            nn.MaxPool2d(3, 2, ceil_mode=True),
            _TorchFire(256, 32, 128, 128), _TorchFire(256, 48, 192, 192),
            _TorchFire(384, 48, 192, 192), _TorchFire(384, 64, 256, 256),
            nn.MaxPool2d(3, 2, ceil_mode=True),
            _TorchFire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2d(512, num_classes, 1),
            nn.ReLU(inplace=True), nn.AdaptiveAvgPool2d((1, 1)))

    def forward(self, x):
        return torch.flatten(self.classifier(self.features(x)), 1)


class _TorchDenseLayer(nn.Module):
    def __init__(self, cin, growth, bn_size):
        super().__init__()
        self.norm1 = nn.BatchNorm2d(cin)
        self.relu1 = nn.ReLU(inplace=True)
        self.conv1 = nn.Conv2d(cin, bn_size * growth, 1, bias=False)
        self.norm2 = nn.BatchNorm2d(bn_size * growth)
        self.relu2 = nn.ReLU(inplace=True)
        self.conv2 = nn.Conv2d(bn_size * growth, growth, 3, padding=1,
                               bias=False)

    def forward(self, x):
        y = self.conv1(self.relu1(self.norm1(x)))
        y = self.conv2(self.relu2(self.norm2(y)))
        return torch.cat([x, y], 1)


class _TorchTransition(nn.Module):
    def __init__(self, cin, cout):
        super().__init__()
        self.norm = nn.BatchNorm2d(cin)
        self.relu = nn.ReLU(inplace=True)
        self.conv = nn.Conv2d(cin, cout, 1, bias=False)
        self.pool = nn.AvgPool2d(2, 2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.norm(x))))


class TorchDenseNet121(nn.Module):
    """torchvision.models.densenet121 topology + key names."""

    def __init__(self, num_classes=10, growth=32, block_config=(6, 12, 24, 16),
                 init_features=64, bn_size=4):
        super().__init__()
        from collections import OrderedDict
        self.features = nn.Sequential(OrderedDict([
            ("conv0", nn.Conv2d(3, init_features, 7, 2, 3, bias=False)),
            ("norm0", nn.BatchNorm2d(init_features)),
            ("relu0", nn.ReLU(inplace=True)),
            ("pool0", nn.MaxPool2d(3, 2, 1))]))
        ch = init_features
        for b, n_layers in enumerate(block_config):
            block = nn.Sequential(OrderedDict([
                (f"denselayer{i + 1}",
                 _TorchDenseLayer(ch + i * growth, growth, bn_size))
                for i in range(n_layers)]))
            self.features.add_module(f"denseblock{b + 1}", block)
            ch += n_layers * growth
            if b != len(block_config) - 1:
                self.features.add_module(f"transition{b + 1}",
                                         _TorchTransition(ch, ch // 2))
                ch //= 2
        self.features.add_module("norm5", nn.BatchNorm2d(ch))
        self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = torch.relu(self.features(x))
        x = torch.nn.functional.adaptive_avg_pool2d(x, (1, 1))
        return self.classifier(torch.flatten(x, 1))


class _TBC(nn.Module):
    """torchvision BasicConv2d: conv(bias=False) + bn(eps=1e-3)."""

    def __init__(self, cin, cout, **kw):
        super().__init__()
        self.conv = nn.Conv2d(cin, cout, bias=False, **kw)
        self.bn = nn.BatchNorm2d(cout, eps=0.001)

    def forward(self, x):
        return torch.relu(self.bn(self.conv(x)))


class _TIncA(nn.Module):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.branch1x1 = _TBC(cin, 64, kernel_size=1)
        self.branch5x5_1 = _TBC(cin, 48, kernel_size=1)
        self.branch5x5_2 = _TBC(48, 64, kernel_size=5, padding=2)
        self.branch3x3dbl_1 = _TBC(cin, 64, kernel_size=1)
        self.branch3x3dbl_2 = _TBC(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = _TBC(96, 96, kernel_size=3, padding=1)
        self.branch_pool = _TBC(cin, pool_features, kernel_size=1)

    def forward(self, x):
        p = torch.nn.functional.avg_pool2d(x, 3, 1, 1)
        return torch.cat([
            self.branch1x1(x), self.branch5x5_2(self.branch5x5_1(x)),
            self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x))),
            self.branch_pool(p)], 1)


class _TIncB(nn.Module):
    def __init__(self, cin):
        super().__init__()
        self.branch3x3 = _TBC(cin, 384, kernel_size=3, stride=2)
        self.branch3x3dbl_1 = _TBC(cin, 64, kernel_size=1)
        self.branch3x3dbl_2 = _TBC(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = _TBC(96, 96, kernel_size=3, stride=2)

    def forward(self, x):
        return torch.cat([
            self.branch3x3(x),
            self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x))),
            torch.nn.functional.max_pool2d(x, 3, 2)], 1)


class _TIncC(nn.Module):
    def __init__(self, cin, c7):
        super().__init__()
        self.branch1x1 = _TBC(cin, 192, kernel_size=1)
        self.branch7x7_1 = _TBC(cin, c7, kernel_size=1)
        self.branch7x7_2 = _TBC(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7_3 = _TBC(c7, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_1 = _TBC(cin, c7, kernel_size=1)
        self.branch7x7dbl_2 = _TBC(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_3 = _TBC(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7dbl_4 = _TBC(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_5 = _TBC(c7, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch_pool = _TBC(cin, 192, kernel_size=1)

    def forward(self, x):
        b7 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
        bd = self.branch7x7dbl_1(x)
        bd = self.branch7x7dbl_3(self.branch7x7dbl_2(bd))
        bd = self.branch7x7dbl_5(self.branch7x7dbl_4(bd))
        p = torch.nn.functional.avg_pool2d(x, 3, 1, 1)
        return torch.cat([self.branch1x1(x), b7, bd, self.branch_pool(p)], 1)


class _TIncD(nn.Module):
    def __init__(self, cin):
        super().__init__()
        self.branch3x3_1 = _TBC(cin, 192, kernel_size=1)
        self.branch3x3_2 = _TBC(192, 320, kernel_size=3, stride=2)
        self.branch7x7x3_1 = _TBC(cin, 192, kernel_size=1)
        self.branch7x7x3_2 = _TBC(192, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7x3_3 = _TBC(192, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7x3_4 = _TBC(192, 192, kernel_size=3, stride=2)

    def forward(self, x):
        b7 = self.branch7x7x3_2(self.branch7x7x3_1(x))
        b7 = self.branch7x7x3_4(self.branch7x7x3_3(b7))
        return torch.cat([
            self.branch3x3_2(self.branch3x3_1(x)), b7,
            torch.nn.functional.max_pool2d(x, 3, 2)], 1)


class _TIncE(nn.Module):
    def __init__(self, cin):
        super().__init__()
        self.branch1x1 = _TBC(cin, 320, kernel_size=1)
        self.branch3x3_1 = _TBC(cin, 384, kernel_size=1)
        self.branch3x3_2a = _TBC(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3_2b = _TBC(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = _TBC(cin, 448, kernel_size=1)
        self.branch3x3dbl_2 = _TBC(448, 384, kernel_size=3, padding=1)
        self.branch3x3dbl_3a = _TBC(384, 384, kernel_size=(1, 3),
                                    padding=(0, 1))
        self.branch3x3dbl_3b = _TBC(384, 384, kernel_size=(3, 1),
                                    padding=(1, 0))
        self.branch_pool = _TBC(cin, 192, kernel_size=1)

    def forward(self, x):
        b3 = self.branch3x3_1(x)
        b3 = torch.cat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], 1)
        bd = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
        bd = torch.cat([self.branch3x3dbl_3a(bd), self.branch3x3dbl_3b(bd)], 1)
        p = torch.nn.functional.avg_pool2d(x, 3, 1, 1)
        return torch.cat([self.branch1x1(x), b3, bd, self.branch_pool(p)], 1)


class _TIncAux(nn.Module):
    def __init__(self, cin, num_classes):
        super().__init__()
        self.conv0 = _TBC(cin, 128, kernel_size=1)
        self.conv1 = _TBC(128, 768, kernel_size=5)
        self.fc = nn.Linear(768, num_classes)

    def forward(self, x):
        x = torch.nn.functional.avg_pool2d(x, 5, 3)
        x = self.conv1(self.conv0(x))
        x = torch.nn.functional.adaptive_avg_pool2d(x, (1, 1))
        return self.fc(torch.flatten(x, 1))


class TorchInceptionV3(nn.Module):
    """torchvision.models.inception_v3 topology + key names (eval fwd)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.Conv2d_1a_3x3 = _TBC(3, 32, kernel_size=3, stride=2)
        self.Conv2d_2a_3x3 = _TBC(32, 32, kernel_size=3)
        self.Conv2d_2b_3x3 = _TBC(32, 64, kernel_size=3, padding=1)
        self.Conv2d_3b_1x1 = _TBC(64, 80, kernel_size=1)
        self.Conv2d_4a_3x3 = _TBC(80, 192, kernel_size=3)
        self.Mixed_5b = _TIncA(192, 32)
        self.Mixed_5c = _TIncA(256, 64)
        self.Mixed_5d = _TIncA(288, 64)
        self.Mixed_6a = _TIncB(288)
        self.Mixed_6b = _TIncC(768, 128)
        self.Mixed_6c = _TIncC(768, 160)
        self.Mixed_6d = _TIncC(768, 160)
        self.Mixed_6e = _TIncC(768, 192)
        self.AuxLogits = _TIncAux(768, num_classes)
        self.Mixed_7a = _TIncD(768)
        self.Mixed_7b = _TIncE(1280)
        self.Mixed_7c = _TIncE(2048)
        self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        mp = torch.nn.functional.max_pool2d
        x = self.Conv2d_2b_3x3(self.Conv2d_2a_3x3(self.Conv2d_1a_3x3(x)))
        x = mp(x, 3, 2)
        x = self.Conv2d_4a_3x3(self.Conv2d_3b_1x1(x))
        x = mp(x, 3, 2)
        x = self.Mixed_5d(self.Mixed_5c(self.Mixed_5b(x)))
        x = self.Mixed_6e(self.Mixed_6d(self.Mixed_6c(
            self.Mixed_6b(self.Mixed_6a(x)))))
        x = self.Mixed_7c(self.Mixed_7b(self.Mixed_7a(x)))
        x = torch.nn.functional.adaptive_avg_pool2d(x, (1, 1))
        return self.fc(torch.flatten(x, 1))


TORCH_ZOO.update({
    "squeezenet": TorchSqueezeNet,
    "densenet": TorchDenseNet121,
    "inception": TorchInceptionV3,
})
