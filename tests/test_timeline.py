"""Cross-rank timeline merger (timeline.py): health-boundary clock
alignment across ranks with disjoint mono origins and skewed wall
clocks, the wall-clock fallback, the Chrome trace-event contract
(non-negative ts, per-rank ordering, metadata rows), skew + straggler
reporting, and the hostile inputs the CLI must degrade on — missing
rank dump, torn JSONL tail, no telemetry at all."""

import json
import os

import pytest

from distributedpytorch_tpu import timeline

# Synthetic physical timeline: both ranks live through the same real
# instants T, but each stamps them with its own clocks.  Rank 1's mono
# origin is 4000s away from rank 0's (fresh process) and its wall clock
# runs 0.25s ahead (host skew) — exactly what alignment must undo.
_WALL0 = 1.7e9
_MONO0 = 1000.0
_MONO1 = 5000.0
_SKEW1 = 0.25


def _stamp(rank, t):
    if rank == 0:
        return {"ts": _WALL0 + t, "mono": _MONO0 + t, "rank": 0}
    return {"ts": _WALL0 + t + _SKEW1, "mono": _MONO1 + t, "rank": 1}


def _span(rank, name, end_t, dur_s, **attrs):
    ev = {"kind": "span", "name": name, "dur_s": dur_s, **_stamp(rank, end_t)}
    if attrs:
        ev["attrs"] = attrs
    return ev


def _event(rank, name, t, **attrs):
    ev = {"kind": "event", "name": name, **_stamp(rank, t)}
    if attrs:
        ev["attrs"] = attrs
    return ev


def _write_rank(rsl, rank, events):
    tdir = os.path.join(rsl, "telemetry")
    os.makedirs(tdir, exist_ok=True)
    with open(os.path.join(tdir, f"rank{rank}.jsonl"), "a") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _write_dump(rsl, rank, records, reason="run_end"):
    doc = {"rank": rank, "ring_size": 64, "reason": reason,
           "reasons": [reason],
           "dumped_at": _stamp(rank, 9.0), "records": records}
    with open(os.path.join(rsl, f"flightrec-rank{rank}.json"), "w") as f:
        f.write(json.dumps(doc))


def _step(rank, step, end_t, step_s, wait_s=None):
    rec = {"kind": "step", "epoch": 0, "step": step, "step_s": step_s,
           **_stamp(rank, end_t)}
    del rec["rank"]  # flight records carry rank at the dump level
    if wait_s is not None:
        rec["wait_s"] = wait_s
    return rec


def _two_rank_run(rsl):
    """Two epochs, health boundaries at T=2 and T=4 on both ranks;
    rank 1 is the straggler (slower epochs).  Rank 0 also has a flight
    record with a heavy data-wait share."""
    for rank in (0, 1):
        slow = 0.05 * rank
        _write_rank(rsl, rank, [
            _span(rank, "epoch", 2.0, 1.9 + slow, epoch=0),
            _event(rank, "health_boundary", 2.0, epoch=0),
            _span(rank, "epoch", 4.0, 1.9 + slow, epoch=1),
            _event(rank, "health_boundary", 4.0, epoch=1),
        ])
    _write_dump(rsl, 0, [
        _step(0, 0, 0.5, step_s=0.1, wait_s=0.06),
        _step(0, 1, 0.7, step_s=0.1, wait_s=0.06),
    ])
    return rsl


# -- hostile inputs ----------------------------------------------------


def test_no_telemetry_at_all_is_actionable(tmp_path):
    with pytest.raises(ValueError, match="telemetry"):
        timeline.build_timeline(str(tmp_path))


def test_no_rank_stamped_events_is_actionable(tmp_path):
    # Old-build telemetry: records exist but none carry a rank stamp.
    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    (tdir / "rank0.jsonl").write_text(
        '{"kind": "event", "name": "x", "ts": 1.0, "mono": 1.0}\n')
    with pytest.raises(ValueError, match="rank-stamped"):
        timeline.build_timeline(str(tmp_path))


def test_torn_jsonl_tail_is_skipped(tmp_path):
    rsl = _two_rank_run(str(tmp_path))
    with open(os.path.join(rsl, "telemetry", "rank0.jsonl"), "a") as f:
        f.write('{"kind": "event", "name": "anomaly", "ts": 1.7')  # torn
    result = timeline.build_timeline(rsl)
    assert result["ranks"] == [0, 1]  # the torn line cost nothing else
    assert result["alignment"] == "health_boundary"


def test_missing_rank_dump_degrades_with_warning(tmp_path):
    rsl = _two_rank_run(str(tmp_path))  # rank 1 has no flight record
    result = timeline.build_timeline(rsl)
    assert any("flightrec-rank1.json" in w for w in result["warnings"])
    # rank 1 still contributes its telemetry spans to the trace
    assert any(e.get("pid") == 1 and e["ph"] == "X"
               for e in result["trace"]["traceEvents"])


# -- clock alignment ---------------------------------------------------


def test_two_rank_alignment_via_health_boundary(tmp_path):
    result = timeline.build_timeline(_two_rank_run(str(tmp_path)))
    assert result["alignment"] == "health_boundary"
    # The boundary instants name the same physical moment, so after
    # alignment the two ranks' instants coincide despite mono origins
    # 4000s apart and 0.25s of wall skew.
    instants = {e["pid"]: e["ts"]
                for e in result["trace"]["traceEvents"]
                if e["ph"] == "i" and e["name"] == "health_boundary"
                and e["args"].get("epoch") == 0}
    assert set(instants) == {0, 1}
    assert instants[0] == pytest.approx(instants[1], abs=1.0)  # µs


def test_wall_clock_skew_is_reported(tmp_path):
    result = timeline.build_timeline(_two_rank_run(str(tmp_path)))
    skew = result["skew"]
    assert skew["boundary_epochs"] == [0, 1]
    assert skew["max_wall_skew_s"] == pytest.approx(_SKEW1, abs=1e-6)
    assert skew["wall_skew_s_per_epoch"]["0"] == pytest.approx(
        _SKEW1, abs=1e-6)


def test_single_rank_falls_back_to_wall_clock(tmp_path):
    rsl = str(tmp_path)
    _write_rank(rsl, 0, [
        _span(0, "epoch", 2.0, 1.9, epoch=0),
        _event(0, "health_boundary", 2.0, epoch=0),
    ])
    result = timeline.build_timeline(rsl)
    assert result["alignment"] == "wall_clock"
    assert result["skew"]["max_wall_skew_s"] is None  # needs >= 2 ranks


def test_unshared_boundaries_fall_back_with_warning(tmp_path):
    rsl = str(tmp_path)
    _write_rank(rsl, 0, [_span(0, "epoch", 2.0, 1.9, epoch=0),
                         _event(0, "health_boundary", 2.0, epoch=0)])
    # rank 1 never reached a health boundary (crashed mid-epoch)
    _write_rank(rsl, 1, [_span(1, "epoch", 2.1, 2.0, epoch=0)])
    result = timeline.build_timeline(rsl)
    assert result["alignment"] == "wall_clock"
    assert any("health_boundary" in w for w in result["warnings"])


def _stamp2(t):
    """A third rank with its own mono origin and no wall skew."""
    return {"ts": _WALL0 + t, "mono": 9000.0 + t, "rank": 2}


def test_mixed_alignment_isolates_boundaryless_rank(tmp_path):
    # The elastic rank-loss shape: ranks 0/1 share boundaries; rank 2
    # died mid-epoch 0, before its first health_boundary.  One rank's
    # truncation must not cost the others their precise alignment.
    rsl = _two_rank_run(str(tmp_path))
    _write_rank(rsl, 2, [
        {"kind": "span", "name": "epoch", "dur_s": 0.9, **_stamp2(1.0)},
        {"kind": "event", "name": "anomaly", **_stamp2(1.1)},
    ])
    result = timeline.build_timeline(rsl)
    assert result["alignment"] == "mixed"
    assert result["ranks"] == [0, 1, 2]
    assert any("rank 2" in w and "wall clock" in w
               for w in result["warnings"])
    # ranks 0/1 keep the boundary-precise alignment despite the mix
    instants = {e["pid"]: e["ts"]
                for e in result["trace"]["traceEvents"]
                if e["ph"] == "i" and e["name"] == "health_boundary"
                and e["args"].get("epoch") == 0}
    assert instants[0] == pytest.approx(instants[1], abs=1.0)  # µs
    # rank 2's truncated stream still lands in the trace
    assert any(e.get("pid") == 2 and e["ph"] == "X"
               for e in result["trace"]["traceEvents"])


def test_elastic_reconfigure_boundary_is_named(tmp_path):
    # Survivors emit elastic/reconfigure; the departed rank's stream
    # just truncates.  The merged timeline must say so — a shrunken
    # world should read as a reconfigure, not as data loss.
    rsl = _two_rank_run(str(tmp_path))
    _write_rank(rsl, 2, [
        {"kind": "span", "name": "epoch", "dur_s": 0.9, **_stamp2(1.0)},
    ])
    for rank in (0, 1):
        _write_rank(rsl, rank, [
            _event(rank, "elastic/reconfigure", 4.5, generation=1,
                   old_world=3, new_world=2),
        ])
    result = timeline.build_timeline(rsl)
    named = [w for w in result["warnings"]
             if "elastic reconfigure" in w]
    assert len(named) == 1
    assert "generation(s) [1]" in named[0]
    assert "survivors [0, 1]" in named[0]
    assert "rank(s) [2] departed" in named[0]
    assert "not data loss" in named[0]


def _stamp2_new(t):
    """Rank 2's REJOINED incarnation: a fresh process with yet another
    mono origin (appending to the departed incarnation's file)."""
    return {"ts": _WALL0 + t, "mono": 20000.0 + t, "rank": 2}


def test_grow_names_joined_rank_and_aligns_both_segments(tmp_path):
    # Shrink-then-grow: rank 2 dies mid-epoch, the survivors shrink
    # (gen 1) and later admit it back (gen 2).  The rejoined process
    # appends to rank 2's telemetry file with a NEW mono origin, so the
    # merger must (a) name the join rather than calling the rank
    # departed, (b) align the rejoined stream from its first health
    # boundary, and (c) place the pre-join segment by wall clock
    # without letting it poison the boundary median.
    rsl = _two_rank_run(str(tmp_path))
    # first incarnation: one epoch span, then death (no boundary)
    _write_rank(rsl, 2, [
        {"kind": "span", "name": "epoch", "dur_s": 0.9, **_stamp2(1.0)},
    ])
    for rank in (0, 1):
        _write_rank(rsl, rank, [
            _event(rank, "elastic/reconfigure", 4.5, generation=1,
                   old_world=3, new_world=2),
            _event(rank, "elastic/reconfigure", 6.0, generation=2,
                   old_world=2, new_world=3, grow=True),
            _event(rank, "health_boundary", 7.0, epoch=2),
        ])
    _write_rank(rsl, 2, [
        {"kind": "event", "name": "elastic/join",
         "attrs": {"generation": 2, "new_rank": 2, "new_world": 3},
         **_stamp2_new(6.0)},
        {"kind": "span", "name": "epoch", "dur_s": 0.9,
         "attrs": {"epoch": 2}, **_stamp2_new(7.0)},
        {"kind": "event", "name": "health_boundary",
         "attrs": {"epoch": 2}, **_stamp2_new(7.0)},
    ])
    result = timeline.build_timeline(rsl)
    # the post-join boundary is shared by all three ranks: precise mode
    assert result["alignment"] == "health_boundary"
    named = [w for w in result["warnings"]
             if "elastic reconfigure" in w]
    assert len(named) == 1
    assert "generation(s) [1, 2]" in named[0]
    assert "survivors [0, 1]" in named[0]
    assert "rank(s) [2] joined in a grow generation" in named[0]
    assert "departed" not in named[0]
    assert any("rank 2 rejoined mid-run" in w and "wall clock" in w
               for w in result["warnings"])
    # (b) the rejoined stream aligns from its first boundary: the
    # epoch-2 boundary instants coincide across ranks 0 and 2 even
    # though their mono origins are 19000s apart.
    instants = {e["pid"]: e["ts"]
                for e in result["trace"]["traceEvents"]
                if e["ph"] == "i" and e["name"] == "health_boundary"
                and e["args"].get("epoch") == 2}
    assert set(instants) == {0, 1, 2}
    assert instants[2] == pytest.approx(instants[0], abs=1.0)  # µs
    # (c) the pre-join segment lands at its true physical instant: the
    # first incarnation's epoch span started at the same moment as
    # rank 0's epoch-0 span (T=0.1), despite the dead mono origin.
    rank0_epoch0 = [e for e in result["trace"]["traceEvents"]
                    if e.get("pid") == 0 and e["ph"] == "X"
                    and e["name"] == "epoch"
                    and e["args"].get("epoch") == 0][0]
    pre_span = min((e for e in result["trace"]["traceEvents"]
                    if e.get("pid") == 2 and e["ph"] == "X"
                    and e["name"] == "epoch"), key=lambda e: e["ts"])
    assert pre_span["ts"] == pytest.approx(rank0_epoch0["ts"], abs=1.0)


def test_fresh_joiner_named_without_rejoin_warning(tmp_path):
    # A NEVER-before-seen rank joining (fresh slot, no pre-join
    # segment) is named in the reconfigure warning but gets no
    # wall-clock-only caveat — there is nothing to misalign.
    rsl = _two_rank_run(str(tmp_path))
    for rank in (0, 1):
        _write_rank(rsl, rank, [
            _event(rank, "elastic/reconfigure", 6.0, generation=1,
                   old_world=2, new_world=3, grow=True),
            _event(rank, "health_boundary", 7.0, epoch=2),
        ])
    _write_rank(rsl, 2, [
        {"kind": "event", "name": "elastic/join",
         "attrs": {"generation": 1, "new_rank": 2, "new_world": 3},
         **_stamp2_new(6.0)},
        {"kind": "event", "name": "health_boundary",
         "attrs": {"epoch": 2}, **_stamp2_new(7.0)},
    ])
    result = timeline.build_timeline(rsl)
    named = [w for w in result["warnings"]
             if "elastic reconfigure" in w]
    assert "rank(s) [2] joined in a grow generation" in named[0]
    assert not any("rejoined mid-run" in w for w in result["warnings"])


# -- trace contract ----------------------------------------------------


def test_trace_event_contract(tmp_path):
    result = timeline.build_timeline(_two_rank_run(str(tmp_path)))
    trace = result["trace"]
    assert trace["displayTimeUnit"] == "ms"
    assert trace["otherData"]["ranks"] == [0, 1]
    events = trace["traceEvents"]
    assert {e.get("pid") for e in events} == {0, 1}
    for pid in (0, 1):
        per = [e for e in events if e.get("pid") == pid]
        meta = [e for e in per if e["ph"] == "M"]
        rest = [e for e in per if e["ph"] != "M"]
        # metadata rows lead; the rest is time-ordered and non-negative
        assert per[:len(meta)] == meta
        assert {m["name"] for m in meta} >= {"process_name", "thread_name"}
        ts = [e["ts"] for e in rest]
        assert all(t >= 0 for t in ts)
        assert ts == sorted(ts)
    for e in events:
        if e["ph"] == "X":
            assert e["dur"] >= 0
        elif e["ph"] == "i":
            assert e["s"] == "p"
    # rank 0's flight-record steps landed on their own thread row
    assert any(e["ph"] == "X" and e.get("cat") == "flightrec"
               and e["pid"] == 0 for e in events)


def test_straggler_attribution(tmp_path):
    result = timeline.build_timeline(_two_rank_run(str(tmp_path)))
    rows = {row["rank"]: row for row in result["stragglers"]}
    assert rows[1].get("straggler") is True  # slower mean epoch
    assert "straggler" not in rows[0]
    assert rows[0]["steps_recorded"] == 2
    assert rows[0]["data_wait_share"] == pytest.approx(0.6, abs=1e-6)
    assert rows[1]["mean_step_s"] is None  # no flight record for rank 1


# -- CLI surface -------------------------------------------------------


def test_write_timeline_and_summary(tmp_path):
    rsl = _two_rank_run(str(tmp_path))
    path, result = timeline.write_timeline(rsl)
    assert path == os.path.join(rsl, "timeline.json")
    trace = json.loads(open(path).read())  # valid JSON on disk
    assert trace["traceEvents"]
    summary = timeline.render_summary(result, path)
    assert "health_boundary" in summary
    assert "skew" in summary
    assert "<- straggler" in summary
    # --out redirects the trace file
    other = str(tmp_path / "elsewhere.json")
    assert timeline.write_timeline(rsl, out=other)[0] == other
    assert json.loads(open(other).read())["traceEvents"]
