"""Flight recorder + anomaly detector (flightrec.py): ring bounds, dump
format and reasons, the disabled no-op contract, detector triggers
(step-time, starvation, retry-burst) with the bounded capture state
machine (profiler calls monkeypatched — no real traces), and the
observe_step wiring that lands ``anomaly`` on both sinks."""

import json
import os

import jax
import pytest

from distributedpytorch_tpu import flightrec, telemetry


@pytest.fixture(autouse=True)
def clean_singletons():
    yield
    flightrec._active = flightrec.FlightRecorder(enabled=False)
    telemetry._active = telemetry.Telemetry(enabled=False)


@pytest.fixture
def profiler_calls(monkeypatch):
    """Count (and neuter) the programmatic profiler entry points."""
    calls = {"start": [], "stop": 0}
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda path, **kw: calls["start"].append(path))

    def _stop():
        calls["stop"] += 1

    monkeypatch.setattr(jax.profiler, "stop_trace", _stop)
    return calls


def _detector(tmp_path, **kw):
    kw.setdefault("window", 4)
    kw.setdefault("min_excess_s", 0.05)
    return flightrec.AnomalyDetector(
        trace_dir=str(tmp_path / "traces"), **kw)


def _fill(det, n=None, value=0.01):
    for i in range(det.window if n is None else n):
        assert det.observe_step(epoch=0, step=i, step_s=value) is None


# -- recorder ----------------------------------------------------------


def test_ring_is_bounded_and_dump_has_contract(tmp_path):
    rec = flightrec.FlightRecorder(enabled=True, rsl_path=str(tmp_path),
                                   rank=3, ring_size=16)
    for i in range(50):
        rec.record_step(epoch=0, step=i, step_s=0.01, dispatch_s=0.004,
                        wait_s=0.001, queue_depth=2)
    assert len(rec._ring) == 16  # fixed memory: oldest evicted
    path = rec.dump("on_demand")
    assert path == str(tmp_path / "flightrec-rank3.json")
    doc = json.loads(open(path).read())
    assert doc["rank"] == 3 and doc["ring_size"] == 16
    assert doc["reason"] == "on_demand"
    assert doc["reasons"] == ["on_demand"]
    assert set(doc["dumped_at"]) == {"ts", "mono"}
    assert len(doc["records"]) == 16
    first = doc["records"][0]
    # every record carries the paired-stamp contract + step payload
    assert {"ts", "mono", "step_s", "dispatch_s", "wait_s",
            "queue_depth"} <= set(first)
    assert first["step"] == 34  # 50 - 16: the ring kept the newest


def test_dump_reasons_accumulate_and_close_disables(tmp_path):
    rec = flightrec.FlightRecorder(enabled=True, rsl_path=str(tmp_path))
    rec.record_event("preempt_signal", signum=15)
    rec.dump("preempt_signal")
    rec.close("run_end")
    doc = json.loads(open(tmp_path / "flightrec-rank0.json").read())
    assert doc["reasons"] == ["preempt_signal", "run_end"]
    assert not rec.enabled
    rec.record_step(epoch=0, step=0, step_s=1.0)  # no-op after close
    assert doc["records"] == json.loads(
        open(tmp_path / "flightrec-rank0.json").read())["records"]


def test_disabled_recorder_touches_nothing(tmp_path):
    rec = flightrec.FlightRecorder(enabled=False, rsl_path=str(tmp_path))
    rec.record_step(epoch=0, step=0, step_s=1.0)
    rec.record_event("retry", site="data.read")
    assert rec.dump("whatever") is None
    rec.close()
    assert os.listdir(tmp_path) == []


def test_configure_closes_previous_instance(tmp_path):
    first = flightrec.configure(str(tmp_path), True, rank=0)
    first.record_step(epoch=0, step=0, step_s=0.5)
    flightrec.configure(str(tmp_path), True, rank=0)
    doc = json.loads(open(tmp_path / "flightrec-rank0.json").read())
    assert doc["reason"] == "reconfigure"
    assert not first.enabled


def test_load_dumps_skips_torn_files(tmp_path):
    rec = flightrec.FlightRecorder(enabled=True, rsl_path=str(tmp_path),
                                   rank=1)
    rec.record_step(epoch=0, step=0, step_s=0.1)
    rec.dump("run_end")
    (tmp_path / "flightrec-rank2.json").write_text('{"rank": 2, "rec')
    dumps = flightrec.load_dumps(str(tmp_path))
    assert sorted(dumps) == [1]  # the torn rank-2 dump is skipped


# -- anomaly detector triggers ----------------------------------------


def test_no_judging_until_window_full(tmp_path, profiler_calls):
    det = _detector(tmp_path)
    # A huge outlier among the first `window` steps must NOT trigger:
    # the baseline would include compile steps.
    assert det.observe_step(epoch=0, step=0, step_s=60.0) is None
    assert det.anomalies == 0 and not profiler_calls["start"]


def test_step_time_trigger_fires_once_window_full(tmp_path,
                                                  profiler_calls):
    det = _detector(tmp_path)
    _fill(det)
    assert det.observe_step(epoch=0, step=9, step_s=0.5) == "step_time"
    assert det.anomalies == 1
    assert profiler_calls["start"] == [
        str(tmp_path / "traces" / "capture-0")]


def test_small_jitter_never_triggers(tmp_path, profiler_calls):
    # Excess below the absolute min_excess_s floor: micro-jitter on
    # millisecond steps stays silent even at 5x the median.
    det = _detector(tmp_path, rel_factor=3.0)
    _fill(det, value=0.005)
    for step_s in (0.006, 0.009, 0.025):
        assert det.observe_step(epoch=0, step=9, step_s=step_s) is None
    assert det.anomalies == 0 and not profiler_calls["start"]


def test_starvation_trigger(tmp_path, profiler_calls):
    det = _detector(tmp_path)
    _fill(det)
    got = det.observe_step(epoch=0, step=9, step_s=0.02, wait_s=0.3)
    assert got == "starvation"


def test_retry_burst_trigger_needs_no_window(tmp_path, profiler_calls):
    det = _detector(tmp_path, retry_burst=3)
    for _ in range(3):
        det.note_retry()
    assert det.observe_step(epoch=0, step=0, step_s=0.01) == "retry_burst"
    # counted retries reset after each observed step
    det.note_retry()
    assert det.observe_step(epoch=0, step=1, step_s=0.01) is None


def test_capture_runs_k_steps_then_stops(tmp_path, profiler_calls):
    det = _detector(tmp_path, capture_steps=2)
    _fill(det)
    det.observe_step(epoch=0, step=9, step_s=0.5)
    assert profiler_calls["stop"] == 0
    # the anomalous region is not re-judged into more captures
    det.observe_step(epoch=0, step=10, step_s=0.9)
    assert profiler_calls["stop"] == 0 and det.anomalies == 1
    det.observe_step(epoch=0, step=11, step_s=0.9)
    assert profiler_calls["stop"] == 1  # budget exhausted -> stop_trace


def test_capture_budget_is_bounded(tmp_path, profiler_calls):
    # mad_k=0 so the absolute-excess arm is just min_excess_s: spikes
    # interleaved with normal steps (which restore the window median)
    # re-trigger reliably, and only the capture budget limits us.
    det = _detector(tmp_path, capture_steps=1, max_captures=2,
                    mad_k=0.0, rel_factor=1.5)
    _fill(det)
    anomalies = 0
    for step in range(6):  # 50.0, 0.01, 50.0, 0.01, 50.0, 0.01
        got = det.observe_step(epoch=0, step=step,
                               step_s=50.0 if step % 2 == 0 else 0.01)
        anomalies += got is not None
    assert anomalies == 3          # every spike is still *detected*...
    assert det.captures_started == 2  # ...but only 2 captures started
    assert len(profiler_calls["start"]) == 2
    assert profiler_calls["stop"] == 2


def test_close_stops_inflight_capture(tmp_path, profiler_calls):
    det = _detector(tmp_path, capture_steps=10)
    _fill(det)
    det.observe_step(epoch=0, step=9, step_s=0.5)  # capture starts
    det.close()
    assert profiler_calls["stop"] == 1
    det.close()  # idempotent: nothing in flight anymore
    assert profiler_calls["stop"] == 1


# -- observe_step wiring ----------------------------------------------


def test_observe_step_emits_anomaly_on_both_sinks(tmp_path,
                                                  profiler_calls):
    tel = telemetry.configure(str(tmp_path), True)
    rec = flightrec.configure(str(tmp_path), True)
    det = flightrec.attach_detector(rec, trace_dir=str(tmp_path / "t"),
                                    window=4, retry_burst=1)
    assert det is not None
    rec.record_event("retry", site="data.read", attempt=1)  # feeds burst
    flightrec.observe_step(rec, epoch=2, step=7, step_s=0.01)
    ring_names = [r.get("name") for r in rec._ring
                  if r.get("kind") == "event"]
    assert "anomaly" in ring_names
    tel.close()
    ev = [json.loads(line) for line in
          open(tmp_path / "telemetry" / "rank0.jsonl")]
    anoms = [e for e in ev if e.get("kind") == "event"
             and e.get("name") == "anomaly"]
    assert len(anoms) == 1
    assert anoms[0]["attrs"]["trigger"] == "retry_burst"
    assert anoms[0]["attrs"]["epoch"] == 2


def test_attach_detector_refuses_disabled_recorder(tmp_path):
    rec = flightrec.FlightRecorder(enabled=False)
    assert flightrec.attach_detector(
        rec, trace_dir=str(tmp_path)) is None
