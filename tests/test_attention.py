"""Sequence-parallel ring attention (ops/attention.py) pinned against the
full-attention reference on the 8-device virtual mesh: outputs AND
gradients, causal and bidirectional — plus the ViT model family that
consumes it (ABSENT in the reference, which is CNN-only: framework-added
long-context capability)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu import runtime
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.ops import attention

B, S, H, D = 2, 64, 4, 16  # S=64 -> 8 per device on the 8-way axis


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    shape = (B, S, H, D)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.fixture(scope="module")
def seq_mesh():
    # all 8 devices on the sequence ('model') axis
    return runtime.make_mesh(data_parallel=1, model_parallel=8)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(qkv, seq_mesh, causal):
    q, k, v = qkv
    want = attention.full_attention(q, k, v, causal=causal)
    sharding = attention.sequence_sharding(seq_mesh)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    got = attention.ring_attention(qs, ks, vs, seq_mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_gradients_match_full(qkv, seq_mesh, causal):
    q, k, v = qkv
    # weight the outputs so the loss is not permutation-invariant
    w = jax.random.normal(jax.random.PRNGKey(7), (B, S, H, D), jnp.float32)

    def loss_full(q, k, v):
        return jnp.sum(attention.full_attention(q, k, v, causal=causal) * w)

    def loss_ring(q, k, v):
        return jnp.sum(
            attention.ring_attention(q, k, v, seq_mesh, causal=causal) * w)

    want = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    sharding = attention.sequence_sharding(seq_mesh)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    got = jax.grad(loss_ring, argnums=(0, 1, 2))(qs, ks, vs)
    for g, wv, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wv),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{name} mismatch")


def test_ring_rejects_indivisible_sequence(seq_mesh):
    x = jnp.zeros((1, 30, 2, 8))  # 30 % 8 != 0
    with pytest.raises(ValueError, match="not divisible"):
        attention.ring_attention(x, x, x, seq_mesh)


def test_vit_forward_and_train_step():
    """ViT trains through the standard engine path: finite loss, params
    move, logits shaped (B, classes)."""
    from distributedpytorch_tpu.ops.losses import get_loss_fn
    from distributedpytorch_tpu.train.engine import Engine, make_optimizer

    model = get_model("vit", 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 10, False)
    engine = Engine(model, "vit", get_loss_fn("cross_entropy"), tx,
                    mean=0.45, std=0.2, input_size=28, half_precision=False)
    state = engine.init_state(jax.random.PRNGKey(0))

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (8, 28, 28), np.uint8)
    labels = rng.integers(0, 10, (8,)).astype(np.int32)
    valid = np.ones(8, bool)
    # snapshot BEFORE the step: train_step donates its state argument
    before = jax.tree_util.tree_leaves(jax.device_get(state.params))
    new_state, metrics = engine.train_step(
        state, jnp.asarray(imgs), jnp.asarray(labels), jnp.asarray(valid),
        jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))
    after = jax.tree_util.tree_leaves(jax.device_get(new_state.params))
    assert any(not np.allclose(a, b) for a, b in zip(before, after))


def test_vit_with_ring_attention_matches_default(seq_mesh):
    """The SAME ViT params produce the same logits whether attention runs
    fused on one device or ring-style over the 8-way sequence axis."""
    from distributedpytorch_tpu.models.vit import ViT

    x = jax.random.normal(jax.random.PRNGKey(3), (2, 28, 28, 3))
    # patch 7 -> 16 tokens, divisible by the 8-way sequence axis
    base = ViT(num_classes=10, patch=7, dtype=jnp.float32)
    params = base.init({"params": jax.random.PRNGKey(0)}, x)["params"]
    want = base.apply({"params": params}, x)

    def ring_fn(q, k, v):
        return attention.ring_attention(q, k, v, seq_mesh)

    ring = ViT(num_classes=10, patch=7, dtype=jnp.float32,
               attention_fn=ring_fn)
    got = ring.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_make_ring_attention_ragged_matches_full(seq_mesh):
    """The padding closure (what --attention ring installs): S=49 tokens
    over an 8-way ring pads to 56 with masked keys — outputs AND grads
    equal full attention on the real tokens."""
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (jax.random.normal(kk, (2, 49, 4, 16), jnp.float32)
               for kk in ks)
    attn = attention.make_ring_attention(seq_mesh)
    want = attention.full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(attn(q, k, v)), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    w = jax.random.normal(jax.random.PRNGKey(8), (2, 49, 4, 16))
    g_full = jax.grad(
        lambda a, b, c: jnp.sum(attention.full_attention(a, b, c) * w),
        argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda a, b, c: jnp.sum(attn(a, b, c) * w),
                      argnums=(0, 1, 2))(q, k, v)
    for g, wv, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wv),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{name} mismatch (ragged)")


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_full(qkv, seq_mesh, causal):
    """The ring x flash composition (each ring step's local attention on
    the Pallas kernel, interpret mode on the CPU mesh): outputs pinned
    to full attention, causal included — the kernel masks by GLOBAL
    positions that rotate with the K/V blocks."""
    q, k, v = qkv
    want = attention.full_attention(q, k, v, causal=causal)
    sharding = attention.sequence_sharding(seq_mesh)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    got = attention.ring_attention(qs, ks, vs, seq_mesh, causal=causal,
                                   use_flash=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_gradients_match_full(qkv, seq_mesh, causal):
    """Gradients through the composition: the flash kernel's lse output
    feeds the ring merge, so its cotangent must flow back through the
    kernel's backward (the delta-folding in _flash_bwd_impl) — this is
    the test that catches a dropped dlse term.  Causal included: the
    position-masked blocks (fully-masked partials, where exp(sc - lse)
    relies on exactly-zero cotangents to cancel) must contribute
    exactly nothing to the gradient."""
    q, k, v = qkv
    w = jax.random.normal(jax.random.PRNGKey(7), (B, S, H, D), jnp.float32)

    def loss_full(q, k, v):
        return jnp.sum(
            attention.full_attention(q, k, v, causal=causal) * w)

    def loss_ring(q, k, v):
        return jnp.sum(attention.ring_attention(
            q, k, v, seq_mesh, causal=causal, use_flash=True) * w)

    want = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    sharding = attention.sequence_sharding(seq_mesh)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    got = jax.grad(loss_ring, argnums=(0, 1, 2))(qs, ks, vs)
    for g, wv, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wv),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{name} mismatch (ring+flash)")


def test_ring_flash_ragged_matches_full(seq_mesh):
    """make_ring_attention(use_flash=True) — the --attention ring_flash
    product closure: S=49 pads to 56 across the ring AND to the kernel's
    block inside each shard; both paddings masked.  Outputs AND
    gradients pinned (the ragged kv_valid mask must zero padded-key
    gradient contributions exactly)."""
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (jax.random.normal(kk, (2, 49, 4, 16), jnp.float32)
               for kk in ks)
    attn = attention.make_ring_attention(seq_mesh, use_flash=True)
    want = attention.full_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(attn(q, k, v)), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    w = jax.random.normal(jax.random.PRNGKey(8), (2, 49, 4, 16))
    g_full = jax.grad(
        lambda a, b, c: jnp.sum(attention.full_attention(a, b, c) * w),
        argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda a, b, c: jnp.sum(attn(a, b, c) * w),
                      argnums=(0, 1, 2))(q, k, v)
    for g, wv, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(wv),
                                   rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{name} mismatch "
                                           "(ring_flash ragged)")


def test_ring_flash_bfloat16_io(qkv, seq_mesh):
    """bf16 in/out (the product dtype): partials stay f32 through the
    merge — one rounding at the end, same as the plain kernel — so the
    result matches the f32 reference to bf16 tolerance."""
    q, k, v = (x.astype(jnp.bfloat16) for x in qkv)
    want = attention.full_attention(*qkv)  # f32 reference
    sharding = attention.sequence_sharding(seq_mesh)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    got = attention.ring_attention(qs, ks, vs, seq_mesh, use_flash=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want), rtol=2e-2, atol=2e-2)


def test_ring_long_sequence(seq_mesh):
    """Long-context shape: S=2048 over 8 devices (256 per device) — the
    regime ring attention exists for; value-pinned to full attention."""
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    shape = (1, 2048, 2, 16)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32) for kk in ks)
    want = attention.full_attention(q, k, v, causal=True)
    sh = attention.sequence_sharding(seq_mesh)
    got = attention.ring_attention(
        jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh),
        seq_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-5, atol=3e-5)
