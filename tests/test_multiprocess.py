"""Multi-process (multi-host) SPMD execution — the reference's core
capability (ref main.py:92-135: per-node launch, env:// rendezvous, global
ranks), exercised for real.

Launches N=2 python subprocesses, each a simulated host with 2 local
virtual CPU devices, joined through ``jax.distributed.initialize`` (gloo
collectives).  Each runs one epoch of ``run_train`` over the global
4-device mesh on BOTH data paths (device-resident and streaming), which
drives the ``jax.make_array_from_process_local_data`` branches in
pipeline.py and the global ``is_main`` gating.  Asserts:

  (i)  every process ends with bitwise-identical parameters (the gradient
       all-reduce leaves replicated state consistent across hosts);
  (ii) the multi-process run matches a single-process run over the same
       4-device world (process topology is an implementation detail —
       ref DDP semantics: N hosts x M GPUs == 1 host x N*M GPUs);
  (iii) only the global main process wrote checkpoints/logs.
"""

import os
import sys

import numpy as np
import pytest

from tests._subproc import REPO, await_all, free_port, launch_logged

# subprocess worlds / full CLI chains: the slow tier (scripts/gate.sh runs -m 'not slow')
pytestmark = pytest.mark.slow

CHILD = os.path.join(REPO, "tests", "_mp_child.py")
NPROC = 2
DEVICES_PER_PROC = 2


def _log_path(tmp: str, nproc: int, rank: int) -> str:
    return os.path.join(tmp, f"log_n{nproc}_r{rank}.txt")


def _launch(rank: int, nproc: int, devices: int, port: int, tmp: str):
    return launch_logged(
        [sys.executable, CHILD, "--coord", f"localhost:{port}",
         "--nproc", str(nproc), "--pid", str(rank),
         "--devices-per-proc", str(devices),
         "--rsl", os.path.join(tmp, f"n{nproc}"),
         "--out", os.path.join(tmp, f"out_n{nproc}_r{rank}.npz")],
        _log_path(tmp, nproc, rank))


@pytest.fixture(scope="module")
def mp_runs(tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp("mp"))

    # Multi-process world: 2 hosts x 2 devices, one shared coordinator.
    port = free_port()
    procs = [_launch(r, NPROC, DEVICES_PER_PROC, port, tmp)
             for r in range(NPROC)]
    await_all(procs, [_log_path(tmp, NPROC, r) for r in range(NPROC)])

    # Single-process control: 1 host x 4 devices — same world size.
    ctrl = _launch(0, 1, NPROC * DEVICES_PER_PROC, free_port(), tmp)
    await_all([ctrl], [_log_path(tmp, 1, 0)])

    return tmp


def _load(tmp: str, nproc: int, rank: int) -> dict:
    return dict(np.load(os.path.join(tmp, f"out_n{nproc}_r{rank}.npz")))


def test_ranks_agree_bitwise(mp_runs):
    r0, r1 = _load(mp_runs, NPROC, 0), _load(mp_runs, NPROC, 1)
    assert set(r0) == set(r1) and len(r0) > 0
    for k in r0:
        np.testing.assert_array_equal(
            r0[k], r1[k], err_msg=f"{k} differs across processes")


def test_matches_single_process_world(mp_runs):
    multi = _load(mp_runs, NPROC, 0)
    single = _load(mp_runs, 1, 0)
    assert set(multi) == set(single)
    for k in multi:
        np.testing.assert_allclose(
            multi[k], single[k], rtol=2e-5, atol=2e-6,
            err_msg=f"{k}: 2x2 multi-process != 1x4 single-process")


def test_only_global_main_writes(mp_runs):
    rank0 = os.path.join(mp_runs, f"n{NPROC}", "rank0")
    rank1 = os.path.join(mp_runs, f"n{NPROC}", "rank1")
    assert any(f.startswith("checkpoint-") for f in os.listdir(rank0))
    # Non-main host: no checkpoints, no log truncation artifacts.
    assert (not os.path.isdir(rank1)
            or not any(f.startswith(("checkpoint-", "bestmodel-"))
                       for f in os.listdir(rank1)))


def test_training_made_progress(mp_runs):
    import json
    with open(os.path.join(mp_runs, f"out_n{NPROC}_r0.npz.history.json")) as f:
        hist = json.load(f)
    for mode in ("resident", "stream"):
        h = hist[mode][0]
        assert np.isfinite(h["train_loss"]) and 0 <= h["train_acc"] <= 1
