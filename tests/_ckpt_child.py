"""Child process for checkpoint topology/format tests
(tests/test_ckpt_topology.py) and the async-checkpoint crash test
(tests/test_async_ckpt.py).

One simulated host: provisions local virtual CPU devices, optionally joins
a gloo rendezvous, runs ``run_train`` with the requested checkpoint format
/ model-parallelism / resume file, and dumps its local copy of the final
(gathered) parameters plus the run history.

Unlike _mp_child.py, the ``--rsl`` directory is SHARED between processes:
orbax multi-host checkpointing writes every host's shards into the same
checkpoint directory (checkpoint.py _save_orbax barriers), which is the
behavior under test.

``--async-crash`` mode (tests/test_async_ckpt.py): saves a v1 bestmodel
synchronously, kicks off an ASYNC v2 save whose background write is
slowed via monkeypatch, and ``os._exit``s the moment the background
thread reports it is inside the write — a deterministic stand-in for
"process killed mid-background-checkpoint-write".  The parent asserts the
v1 file is still fully loadable (the tmp->rename protocol's guarantee).
"""

import argparse
import json
import os
import sys
import time


def _tiny_state():
    """A minimal real TrainState (mlp) without running the driver."""
    import jax

    from distributedpytorch_tpu.models import get_model
    from distributedpytorch_tpu.ops.losses import get_loss_fn
    from distributedpytorch_tpu.train.engine import Engine, make_optimizer

    model = get_model("mlp", 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 4, False)
    engine = Engine(model, "mlp", get_loss_fn("cross_entropy"), tx,
                    mean=0.45, std=0.2, input_size=28,
                    half_precision=False)
    return engine.init_state(jax.random.PRNGKey(0))


def async_crash(rsl: str, fmt: str) -> None:
    """Sync-save v1, async-save v2 with a stalled background write, die."""
    from distributedpytorch_tpu import checkpoint as ckpt

    state = _tiny_state()
    best = ckpt.best_model_path(rsl, "synthetic", "mlp")
    ckpt.save_checkpoint(best, "mlp", state, 1, 0.5, fmt=fmt)

    marker = os.path.join(rsl, "bg_started")

    def stall(orig):
        def slow(*args, **kwargs):
            with open(marker, "w") as f:
                f.write("1")
            time.sleep(30)  # far longer than the child will live
            return orig(*args, **kwargs)
        return slow

    if fmt == "orbax":
        ckpt._orbax_finalize = stall(ckpt._orbax_finalize)
    else:
        ckpt._write_msgpack = stall(ckpt._write_msgpack)

    saver = ckpt.AsyncSaver()
    ckpt.save_checkpoint_async(saver, best, "mlp", state, 2, 0.25,
                               fmt=fmt)
    deadline = time.monotonic() + 20
    while not os.path.exists(marker):
        if time.monotonic() > deadline:
            print("background write never started", file=sys.stderr)
            os._exit(3)
        time.sleep(0.01)
    print("dying mid-background-write", file=sys.stderr)
    sys.stderr.flush()
    os._exit(0)  # daemon writer thread dies with the process


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coord", default=None)
    ap.add_argument("--nproc", type=int, default=1)
    ap.add_argument("--pid", type=int, default=0)
    ap.add_argument("--devices-per-proc", type=int, default=2)
    ap.add_argument("--rsl", required=True)
    ap.add_argument("--out", default=None)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--ckpt-format", default="msgpack")
    ap.add_argument("--model-parallel", type=int, default=1)
    ap.add_argument("--resume-from", default=None)
    ap.add_argument("--async-crash", action="store_true")
    a = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={a.devices_per_proc}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from distributedpytorch_tpu import runtime

    if a.nproc > 1:
        runtime.initialize_distributed(coordinator_address=a.coord,
                                       num_processes=a.nproc,
                                       process_id=a.pid)
        assert jax.process_count() == a.nproc

    if a.async_crash:
        async_crash(a.rsl, a.ckpt_format)
        return  # unreachable (async_crash _exits)

    import numpy as np

    from distributedpytorch_tpu import checkpoint as ckpt
    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    cfg = Config(action="train", data_path="/tmp/nodata", rsl_path=a.rsl,
                 dataset="synthetic", model_name="mlp", batch_size=4,
                 nb_epochs=a.epochs, debug=True, half_precision=False,
                 ckpt_format=a.ckpt_format,
                 model_parallel=a.model_parallel,
                 checkpoint_file=a.resume_from)
    result = run_train(cfg)

    gathered = ckpt.gather_replicated(result["state"])
    out = {}
    for i, leaf in enumerate(jax.tree_util.tree_leaves(gathered.params)):
        out[f"p{i}"] = (np.asarray(leaf.addressable_shards[0].data)
                        if hasattr(leaf, "addressable_shards")
                        else np.asarray(leaf))
    np.savez(a.out, **out)
    with open(a.out + ".history.json", "w") as f:
        json.dump({"history": result["history"],
                   "preempted": result["preempted"]}, f)
    print(f"rank {a.pid} done", file=sys.stderr)


if __name__ == "__main__":
    main()
