"""Child process for checkpoint topology/format tests
(tests/test_ckpt_topology.py).

One simulated host: provisions local virtual CPU devices, optionally joins
a gloo rendezvous, runs ``run_train`` with the requested checkpoint format
/ model-parallelism / resume file, and dumps its local copy of the final
(gathered) parameters plus the run history.

Unlike _mp_child.py, the ``--rsl`` directory is SHARED between processes:
orbax multi-host checkpointing writes every host's shards into the same
checkpoint directory (checkpoint.py _save_orbax barriers), which is the
behavior under test.
"""

import argparse
import json
import os
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coord", default=None)
    ap.add_argument("--nproc", type=int, default=1)
    ap.add_argument("--pid", type=int, default=0)
    ap.add_argument("--devices-per-proc", type=int, default=2)
    ap.add_argument("--rsl", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--ckpt-format", default="msgpack")
    ap.add_argument("--model-parallel", type=int, default=1)
    ap.add_argument("--resume-from", default=None)
    a = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={a.devices_per_proc}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from distributedpytorch_tpu import runtime

    if a.nproc > 1:
        runtime.initialize_distributed(coordinator_address=a.coord,
                                       num_processes=a.nproc,
                                       process_id=a.pid)
        assert jax.process_count() == a.nproc

    import numpy as np

    from distributedpytorch_tpu import checkpoint as ckpt
    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    cfg = Config(action="train", data_path="/tmp/nodata", rsl_path=a.rsl,
                 dataset="synthetic", model_name="mlp", batch_size=4,
                 nb_epochs=a.epochs, debug=True, half_precision=False,
                 ckpt_format=a.ckpt_format,
                 model_parallel=a.model_parallel,
                 checkpoint_file=a.resume_from)
    result = run_train(cfg)

    gathered = ckpt.gather_replicated(result["state"])
    out = {}
    for i, leaf in enumerate(jax.tree_util.tree_leaves(gathered.params)):
        out[f"p{i}"] = (np.asarray(leaf.addressable_shards[0].data)
                        if hasattr(leaf, "addressable_shards")
                        else np.asarray(leaf))
    np.savez(a.out, **out)
    with open(a.out + ".history.json", "w") as f:
        json.dump({"history": result["history"],
                   "preempted": result["preempted"]}, f)
    print(f"rank {a.pid} done", file=sys.stderr)


if __name__ == "__main__":
    main()
