"""Roofline attribution (distributedpytorch_tpu/roofline.py, ISSUE 12
tentpole): the trace parser must attribute nested op slices exactly once
(self-time), exclude the python dispatch thread from the step-time
denominator, survive torn captures with an explicit warning, join ops
against HLO-derived analytic costs, degrade to name heuristics with an
explicit residual when no cost metadata exists, and round-trip a real
CPU ``jax.profiler`` capture end to end.

The ``wellformed`` fixture is hand-built so every expected number is
derivable on paper: a device thread with a 100us runtime envelope, a
40us ``dot.1``, a 30us ``fusion.2``, and a 20us ``while.3`` whose body
re-runs ``dot.1`` for 10us (nesting!), plus a python thread with a
1000us epoch-long slice that must NOT count toward step time.
"""

import gzip
import json
import os

import pytest

from distributedpytorch_tpu import costs, roofline

FIX = os.path.join(os.path.dirname(__file__), "fixtures", "roofline")
WELLFORMED = os.path.join(FIX, "wellformed")
TORN = os.path.join(FIX, "torn")
TORN_ONLY = os.path.join(FIX, "torn_only")

# HLO whose instruction names match the fixture trace's op names, in the
# exact textual shape ``compiled.as_text()`` emits on jax 0.4.37.
FIXTURE_HLO = """\
HloModule jit_step, entry_computation_layout={(f32[64,64]{1,0}, f32[64,64]{1,0})->f32[64,64]{1,0}}

%fused_computation (param_0.1: f32[64,64]) -> f32[64,64] {
  %param_0.1 = f32[64,64]{1,0} parameter(0)
  ROOT %add.9 = f32[64,64]{1,0} add(f32[64,64]{1,0} %param_0.1, f32[64,64]{1,0} %param_0.1)
}

ENTRY %main.10 (Arg_0.1: f32[64,64], Arg_1.2: f32[64,64]) -> f32[64,64] {
  %Arg_0.1 = f32[64,64]{1,0} parameter(0)
  %Arg_1.2 = f32[64,64]{1,0} parameter(1)
  %dot.1 = f32[64,64]{1,0} dot(f32[64,64]{1,0} %Arg_0.1, f32[64,64]{1,0} %Arg_1.2), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %fusion.2 = f32[64,64]{1,0} fusion(f32[64,64]{1,0} %dot.1), kind=kLoop, calls=%fused_computation
}
"""

COSTS_DATA = {"device_kind": "cpu",
              "programs": {"step": {"hlo": FIXTURE_HLO}}}


# -- trace parsing -----------------------------------------------------


def test_wellformed_parse_exact_numbers():
    parsed = roofline.parse_trace_dir(WELLFORMED)
    # Step time is the device thread's activity union, NOT the python
    # thread's 1000us slice.
    assert parsed["step_time_us"] == pytest.approx(100.0)
    # dot.1(0,40) + fusion.2(50,80) + while.3(80,100) union = 90us.
    assert parsed["attributed_us"] == pytest.approx(90.0)
    assert parsed["residual_us"] == pytest.approx(10.0)
    assert parsed["coverage"] == pytest.approx(0.9)
    assert parsed["warnings"] == []
    ops = parsed["ops"]
    # Self-time: the while body's nested dot.1 (10us) is charged to
    # dot.1, not double-counted under while.3.
    assert ops[("jit_step", "dot.1")] == {"time_us": pytest.approx(50.0),
                                          "count": 2}
    assert ops[("jit_step", "fusion.2")]["time_us"] == pytest.approx(30.0)
    assert ops[("jit_step", "while.3")]["time_us"] == pytest.approx(10.0)


def test_torn_file_warns_but_result_survives():
    parsed = roofline.parse_trace_dir(TORN)
    assert any("torn" in w for w in parsed["warnings"])
    assert parsed["n_trace_files"] == 1  # the intact sibling
    assert ("jit_step", "dot.1") in parsed["ops"]


def test_all_torn_raises():
    with pytest.raises(ValueError, match="torn or unparseable"):
        roofline.parse_trace_dir(TORN_ONLY)


def test_empty_dir_raises(tmp_path):
    with pytest.raises(ValueError, match="no profiler trace files"):
        roofline.parse_trace_dir(str(tmp_path))


def test_trace_without_op_events_raises(tmp_path):
    d = tmp_path / "plugins" / "profile" / "t"
    d.mkdir(parents=True)
    (d / "h.trace.json").write_text(json.dumps({"traceEvents": [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 5,
         "name": "host_only"}]}))
    with pytest.raises(ValueError, match="no per-op"):
        roofline.parse_trace_dir(str(tmp_path))


# -- cost join + classification ----------------------------------------


def test_analytic_join_and_bound_classes():
    rep = roofline.analyze(WELLFORMED, costs_data=COSTS_DATA)
    rows = {r["name"]: r for r in rep["ops"]}
    dot = rows["dot.1"]
    # 2 * 64*64 result elems * K=64 contracted.
    assert dot["flops"] == pytest.approx(2 * 64 * 64 * 64)
    assert dot["bytes"] == pytest.approx(3 * 64 * 64 * 4)
    assert dot["class_source"] == "analytic"
    # AI = 524288/49152 = 10.67 >= generic ridge 10 -> compute-bound.
    assert dot["bound"] == "compute"
    fus = rows["fusion.2"]
    # Fusion flops = fused computation's add (4096 elems); bytes = its
    # own operand + result footprint only.
    assert fus["flops"] == pytest.approx(64 * 64)
    assert fus["bound"] == "memory"
    assert fus["class_source"] == "analytic"
    # while.3 has no HLO-derived costs -> name heuristic, still a class.
    wh = rows["while.3"]
    assert wh["class_source"] == "heuristic"
    assert wh["bound"] == "memory"
    assert all(r["bound"] in ("compute", "memory") for r in rep["ops"])
    # CPU has no peak tables: the ceiling degrades to the best observed
    # rate in this trace, labeled empirical, never silently "device".
    assert dot["ceiling_source"] == "empirical"
    assert 0.0 < dot["utilization"] <= 1.0


def test_missing_cost_metadata_degrades_with_explicit_residual():
    rep = roofline.analyze(WELLFORMED)  # no costs.json anywhere
    assert any("no costs.json" in w for w in rep["warnings"])
    assert rep["residual_us"] == pytest.approx(10.0)
    for r in rep["ops"]:
        assert r["class_source"] == "heuristic"
        assert r["bound"] in ("compute", "memory")
    rows = {r["name"]: r for r in rep["ops"]}
    assert rows["dot.1"]["bound"] == "compute"  # name hint
    txt = roofline.render_report(rep)
    assert "unattributed residual: 0.01 ms" in txt
    assert "heuristic" in txt


def test_device_ridge_when_peaks_known():
    cls = roofline.bound_class(1e9, 1e6, device_kind="TPU v4", dtype="bf16")
    assert cls["ridge_source"] == "device"
    assert cls["bound"] == "compute"
    cls2 = roofline.bound_class(1.0, 1e6, device_kind="TPU v4",
                                dtype="bf16")
    assert cls2["bound"] == "memory"


# -- persistence, telemetry, CLI ---------------------------------------


def test_save_report_roundtrips(tmp_path):
    rep = roofline.analyze(WELLFORMED, costs_data=COSTS_DATA)
    path = roofline.save_report(rep, str(tmp_path))
    with open(path) as f:
        back = json.load(f)
    assert back["coverage"] == pytest.approx(0.9)
    assert back["schema"] == roofline.SCHEMA
    assert len(back["ops"]) == 3


def test_run_cli_persists_and_emits_telemetry(tmp_path):
    out = roofline.run_cli(str(tmp_path), trace_dir=WELLFORMED)
    assert "roofline attribution" in out
    assert os.path.exists(tmp_path / "roofline.json")
    tel_dir = tmp_path / "telemetry"
    events = []
    for f in os.listdir(tel_dir):
        with open(tel_dir / f) as fh:
            events += [json.loads(line) for line in fh if line.strip()]
    roof = [e for e in events if e.get("name") == "roofline"]
    assert roof and roof[0]["attrs"]["coverage"] == pytest.approx(0.9)
    assert roof[0]["attrs"]["top_ops"][0]["name"] == "dot.1"


def test_run_cli_json_mode(tmp_path):
    out = roofline.run_cli(str(tmp_path), trace_dir=WELLFORMED,
                           as_json=True, emit_events=False)
    doc = json.loads(out)
    assert doc["coverage"] == pytest.approx(0.9)


def test_run_cli_from_anomaly_reads_manifest(tmp_path):
    cap = tmp_path / "anomaly_traces" / "capture-0"
    src = os.path.join(WELLFORMED, "plugins", "profile",
                       "2026_08_05_00_00_00", "host.trace.json")
    dst = cap / "plugins" / "profile" / "t" / "host.trace.json"
    dst.parent.mkdir(parents=True)
    dst.write_text(open(src).read())
    (cap / "manifest.json").write_text(json.dumps(
        {"trigger": {"trigger": "loss_spike"}, "epoch": 3, "step": 17,
         "capture": 0}))
    out = roofline.run_cli(str(tmp_path), from_anomaly=True,
                           emit_events=False)
    assert "anomaly capture 0" in out
    assert "loss_spike" in out
    with open(tmp_path / "roofline.json") as f:
        assert json.load(f)["anomaly"]["step"] == 17


def test_run_cli_no_anomaly_captures_raises(tmp_path):
    with pytest.raises(ValueError, match="no anomaly captures"):
        roofline.run_cli(str(tmp_path), from_anomaly=True,
                         emit_events=False)


# -- HLO per-op cost parser --------------------------------------------


def test_hlo_op_costs_fixture_text():
    m = costs.hlo_op_costs(FIXTURE_HLO)
    assert m["dot.1"]["flops"] == pytest.approx(2 * 64 * 64 * 64)
    assert m["dot.1"]["opcode"] == "dot"
    assert m["fusion.2"]["flops"] == pytest.approx(64 * 64)
    assert m["fusion.2"]["dtype"] == "f32"
    assert "Arg_0.1" not in m  # parameters carry no cost rows


def test_hlo_op_costs_against_real_compiled_text():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    def f(a, b):
        return jnp.tanh(a @ b).sum()

    a = jnp.ones((32, 16), jnp.float32)
    b = jnp.ones((16, 8), jnp.float32)
    text = jax.jit(f).lower(a, b).compile().as_text()
    m = costs.hlo_op_costs(text)
    dots = [v for v in m.values() if v["opcode"] == "dot"]
    assert dots and dots[0]["flops"] == pytest.approx(2 * 32 * 8 * 16)


# -- end-to-end: capture a real CPU trace, parse it back ---------------


def test_cpu_profiler_capture_round_trip(tmp_path):
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    @jax.jit
    def step(a, b):
        return jnp.tanh(a @ b).sum()

    a = jnp.ones((64, 64), jnp.float32)
    b = jnp.ones((64, 64), jnp.float32)
    step(a, b).block_until_ready()  # compile outside the capture
    trace_dir = str(tmp_path / "trace")
    jax.profiler.start_trace(trace_dir)
    try:
        for _ in range(5):
            step(a, b).block_until_ready()
    finally:
        jax.profiler.stop_trace()
    rep = roofline.analyze(trace_dir)
    assert rep["n_ops"] >= 1
    assert 0.0 < rep["coverage"] <= 1.0
    assert all(r["bound"] in ("compute", "memory") for r in rep["ops"])
    # and the renderer handles a real report without blowing up
    assert "attributed" in roofline.render_report(rep)
