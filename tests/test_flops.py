"""FLOP accounting (ops/flops.py): pinned against hand-computed counts for
the flagship models and torchvision's published number for resnet18."""

import jax
import jax.numpy as jnp
import numpy as np

from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.ops import flops


def _params(model, size):
    x = jnp.zeros((1, size, size, 3), jnp.float32)
    v = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    return v["params"], v.get("batch_stats", {})


def test_small_cnn_flops_match_hand_count():
    model = get_model("cnn", 10, half_precision=False)
    p, bs = _params(model, 28)
    got = flops.forward_flops(model, p, bs, batch=1, input_size=28)
    # conv 3->32@28^2 + 32->32@28^2 + 32->64@14^2 + 64->64@14^2
    # + dense 3136->256 + 256->10, all as 2*MACs
    expect = (2 * 9 * 3 * 32 * 784 + 2 * 9 * 32 * 32 * 784
              + 2 * 9 * 32 * 64 * 196 + 2 * 9 * 64 * 64 * 196
              + 2 * 3136 * 256 + 2 * 256 * 10)
    assert got == expect, (got, expect)
    # scales linearly with batch
    got64 = flops.forward_flops(model, p, bs, batch=64, input_size=28)
    assert got64 == 64 * expect


def test_mlp_flops_match_hand_count():
    model = get_model("mlp", 10, half_precision=False)
    p, bs = _params(model, 28)
    got = flops.forward_flops(model, p, bs, batch=1, input_size=28)
    expect = 2 * (28 * 28 * 3) * 512 + 2 * 512 * 256 + 2 * 256 * 10
    assert got == expect, (got, expect)


def test_resnet18_flops_near_published():
    """resnet18 @224 is published at 1.814 GMACs (torchvision's table);
    in the 2xMACs FLOP convention that is 3.628 GFLOPs — the analytic
    count over our Flax module must land within 5%."""
    model = get_model("resnet", 10, half_precision=False)
    p, bs = _params(model, 224)
    got = flops.forward_flops(model, p, bs, batch=1, input_size=224)
    assert abs(got - 2 * 1.814e9) / (2 * 1.814e9) < 0.05, got


def test_train_flops_is_3x_forward():
    model = get_model("mlp", 10, half_precision=False)
    p, bs = _params(model, 28)
    fwd = flops.forward_flops(model, p, bs, batch=8, input_size=28)
    per_sample = flops.train_flops_per_sample(model, p, bs, batch=8,
                                              input_size=28)
    np.testing.assert_allclose(per_sample, 3 * fwd / 8)


def test_peak_flops_per_dtype_pinned():
    """Both MFU denominators pinned per device generation: bf16 is the
    published datasheet rate, f32 is half of it (F32_PEAK_FRACTION — the
    repo's documented convention, ops/flops.py)."""
    assert flops.F32_PEAK_FRACTION == 0.5
    for kind, bf16 in (("TPU v5e", 197e12), ("TPU v4", 275e12),
                       ("TPU v3", 123e12), ("TPU v5p", 459e12),
                       ("TPU v6e", 918e12)):
        assert flops.peak_flops(kind) == bf16, kind            # historical
        assert flops.peak_flops(kind, "bf16") == bf16, kind
        assert flops.peak_flops(kind, "f32") == bf16 * 0.5, kind
        assert flops.peak_flops(kind, jnp.float32) == bf16 * 0.5, kind
        # no native MXU f16 path: denominator must be absent, not faked
        assert flops.peak_flops(kind, "f16") is None, kind


def test_peak_flops_unknown_kind_and_dtype():
    # unknown device kinds (incl. CPU hosts) report None at every dtype
    for dt in ("bf16", "f32", "f16"):
        assert flops.peak_flops("cpu", dt) is None
        assert flops.peak_flops("Radeon", dt) is None


def test_dtype_label_normalization():
    assert flops.dtype_label(jnp.bfloat16) == "bf16"
    assert flops.dtype_label(jnp.float32) == "f32"
    assert flops.dtype_label(jnp.float16) == "f16"
    assert flops.dtype_label(np.dtype("float32")) == "f32"
    assert flops.dtype_label("bf16") == "bf16"
    # unknown dtypes come back verbatim (lowercased), never raise
    assert flops.dtype_label("int8") == "int8"
