"""PrecisionPolicy subsystem (precision.py + engine threading): preset
semantics, fused-step equivalence, remat numerics, loss scaling, and the
config/CLI/checkpoint round trip."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu import checkpoint as ckpt
from distributedpytorch_tpu.config import Config, config_from_argv
from distributedpytorch_tpu.models.registry import (REMAT_BLOCK_MODELS,
                                                    get_model)
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.precision import (PRESETS, LossScaleState,
                                              all_finite, from_flags,
                                              get_policy, tree_select)
from distributedpytorch_tpu.train.engine import Engine, make_optimizer


def _engine(model_name="mlp", preset="f32", remat="none", grad_accum=1,
            optimizer="adam"):
    # equivalence tests pass optimizer="SGD": its update is linear in the
    # gradient, so grad-level equality shows through (Adam's first-step
    # g/(sqrt(v)+eps) amplifies fp noise on near-zero grads — the same
    # rationale as tests/test_grad_accum.py)
    pol = get_policy(preset)
    model = get_model(model_name, 10, precision=pol, remat=remat)
    tx = make_optimizer(optimizer, 1e-3, 0.9, 0.1, 10, False)
    eng = Engine(model, model_name, get_loss_fn("cross_entropy"), tx,
                 0.13, 0.3, 28, precision=pol, remat=remat,
                 grad_accum=grad_accum)
    return eng, eng.init_state(jax.random.PRNGKey(0))


def _batch(n=8, size=28, seed=3):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 255, (n, size, size, 3)).astype(np.uint8),
            rng.integers(0, 10, (n,)).astype(np.int32),
            np.ones((n,), bool))


# -- policy semantics --------------------------------------------------

def test_presets_dtype_table():
    f32 = get_policy("f32")
    assert (f32.param_dtype, f32.compute_dtype, f32.accum_dtype) \
        == (jnp.float32, jnp.float32, jnp.float32)
    bf16 = get_policy("bf16")
    assert bf16.param_dtype == jnp.float32          # f32 masters
    assert bf16.compute_dtype == jnp.bfloat16
    assert bf16.accum_dtype == jnp.float32
    full = get_policy("bf16_full")
    assert full.param_dtype == jnp.bfloat16
    assert full.accum_dtype == jnp.float32          # accum stays f32
    f16 = get_policy("f16")
    assert f16.scales_loss and f16.loss_scale == 2.0 ** 15
    # every preset guarantees f32 accumulation
    assert all(p.accum_dtype == jnp.float32 for p in PRESETS.values())


def test_from_flags_precedence_and_compat():
    assert from_flags("bf16_full", False).name == "bf16_full"  # wins
    assert from_flags(None, True).name == "bf16"    # historical default
    assert from_flags(None, False).name == "f32"
    with pytest.raises(ValueError):
        get_policy("fp8")


def test_param_dtypes_follow_policy():
    for preset, want in (("f32", jnp.float32), ("bf16", jnp.float32),
                         ("bf16_full", jnp.bfloat16),
                         ("f16", jnp.float32)):
        _, state = _engine(preset=preset)
        dts = {leaf.dtype for leaf in
               jax.tree_util.tree_leaves(state.params)}
        assert dts == {jnp.dtype(want)}, (preset, dts)


# -- fused step --------------------------------------------------------

def test_fused_step_equals_unfused_bitwise_f32():
    imgs, labels, valid = _batch()
    key = jax.random.PRNGKey(5)
    eng_f, st_f = _engine()
    eng_u, st_u = _engine()
    for _ in range(3):
        st_f, m_f = eng_f.train_step(st_f, imgs, labels, valid, key)
        st_u, m_u = eng_u.train_step_unfused(st_u, imgs, labels, valid,
                                             key)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(st_f.params)),
                    jax.tree_util.tree_leaves(jax.device_get(st_u.params))):
        assert np.array_equal(np.asarray(a).view(np.uint8),
                              np.asarray(b).view(np.uint8))
    assert float(m_f["loss"]) == float(m_u["loss"])


def test_unfused_rejects_grad_accum():
    eng, state = _engine(grad_accum=2)
    imgs, labels, valid = _batch()
    with pytest.raises(ValueError, match="grad_accum"):
        eng.train_step_unfused(state, imgs, labels, valid,
                               jax.random.PRNGKey(0))


def test_grad_accum_matches_single_shot():
    """K=2 microbatches over the same samples == one big batch (f32:
    the accumulation is exact up to summation order)."""
    imgs, labels, valid = _batch(n=8)
    key = jax.random.PRNGKey(5)
    eng1, st1 = _engine(grad_accum=1, optimizer="SGD")
    st1, m1 = eng1.train_step(st1, imgs, labels, valid, key)
    eng2, st2 = _engine(grad_accum=2, optimizer="SGD")
    st2, m2 = eng2.train_step(st2, imgs, labels, valid, key)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(st1.params)),
                    jax.tree_util.tree_leaves(jax.device_get(st2.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-6)


# -- remat -------------------------------------------------------------

def test_remat_blocks_grads_allclose_vit():
    """--remat blocks wraps the zoo's block boundaries in jax.checkpoint;
    recomputation must not change the gradients (same params: the
    explicit block names keep the tree identical)."""
    assert "vit" in REMAT_BLOCK_MODELS
    pol = get_policy("f32")
    x = jnp.asarray(np.random.default_rng(0).normal(
        0, 1, (2, 32, 32, 3)), jnp.float32)

    def grads_for(remat):
        model = get_model("vit", 10, precision=pol, remat=remat)
        variables = model.init({"params": jax.random.PRNGKey(0)}, x,
                               train=False)

        def loss(params):
            out = model.apply({"params": params}, x, train=True,
                              rngs={"dropout": jax.random.PRNGKey(1)})
            logits = out[0] if isinstance(out, tuple) else out
            return jnp.sum(logits.astype(jnp.float32) ** 2)

        return variables["params"], jax.grad(loss)(variables["params"])

    p0, g0 = grads_for("none")
    p1, g1 = grads_for("blocks")
    assert jax.tree_util.tree_structure(p0) \
        == jax.tree_util.tree_structure(p1)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_remat_full_train_step_matches_none():
    imgs, labels, valid = _batch()
    key = jax.random.PRNGKey(9)
    eng_n, st_n = _engine(remat="none", optimizer="SGD")
    eng_r, st_r = _engine(remat="full", optimizer="SGD")
    st_n, m_n = eng_n.train_step(st_n, imgs, labels, valid, key)
    st_r, m_r = eng_r.train_step(st_r, imgs, labels, valid, key)
    np.testing.assert_allclose(float(m_n["loss"]), float(m_r["loss"]),
                               rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(st_n.params)),
                    jax.tree_util.tree_leaves(jax.device_get(st_r.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_remat_choice_validated():
    with pytest.raises(ValueError, match="remat"):
        _engine(remat="everything")


# -- loss scaling ------------------------------------------------------

def test_loss_scale_overflow_skips_update_but_advances_step():
    eng, state = _engine(preset="f16")
    assert state.loss_scale is not None
    scale0 = float(state.loss_scale.scale)
    inf_grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, jnp.inf, p.dtype), state.params)
    zeros_bs = state.batch_stats
    new_state, _ = eng._finish_step(state, inf_grads, zeros_bs,
                                    jnp.zeros(()), jnp.zeros(()),
                                    jnp.ones((8,)))
    # params and opt state untouched, scale halved, step advanced
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(new_state.params)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert float(new_state.loss_scale.scale) == scale0 / 2
    assert int(new_state.step) == int(state.step) + 1


def test_loss_scale_growth_and_floor():
    ls = LossScaleState.create(4.0)
    for _ in range(2):
        ls = ls.adjust(jnp.asarray(True), growth_interval=2)
    assert float(ls.scale) == 8.0           # doubled at the interval
    for _ in range(10):
        ls = ls.adjust(jnp.asarray(False), growth_interval=2)
    assert float(ls.scale) >= 1.0           # floored, never 0


def test_all_finite_and_tree_select():
    good = {"a": jnp.ones((2,)), "b": jnp.zeros((3,))}
    bad = {"a": jnp.array([1.0, jnp.nan]), "b": jnp.zeros((3,))}
    assert bool(all_finite(good)) and not bool(all_finite(bad))
    sel = tree_select(jnp.asarray(False), good, bad)
    assert np.isnan(np.asarray(sel["a"])).any()


def test_f16_train_step_runs_and_keeps_finite_loss():
    imgs, labels, valid = _batch()
    eng, state = _engine(preset="f16")
    state, metrics = eng.train_step(state, imgs, labels, valid,
                                    jax.random.PRNGKey(2))
    assert np.isfinite(float(metrics["loss"]))
    assert float(state.loss_scale.scale) > 0


# -- config / CLI / checkpoint round trip ------------------------------

def test_cli_precision_flags_round_trip():
    cfg = config_from_argv(["train", "-d", "/nodata", "--precision",
                            "bf16_full", "--remat", "blocks"])
    assert cfg.precision == "bf16_full" and cfg.remat == "blocks"
    assert cfg.precision_policy().name == "bf16_full"
    # legacy flag still works and maps through from_flags
    cfg2 = config_from_argv(["train", "-d", "/nodata", "--no-bf16"])
    assert cfg2.precision is None
    assert cfg2.precision_policy().name == "f32"
    # programmatic Config default: half_precision=True -> bf16
    assert Config(action="train",
                  data_path="/nodata").precision_policy().name == "bf16"


def test_checkpoint_round_trip_preserves_param_dtype(tmp_path):
    """A bf16_full checkpoint restored into a bf16_full template keeps
    bf16 params (the policy, not the serializer, owns param_dtype)."""
    eng, state = _engine(preset="bf16_full")
    imgs, labels, valid = _batch()
    state, _ = eng.train_step(state, imgs, labels, valid,
                              jax.random.PRNGKey(1))
    path = os.path.join(str(tmp_path), "ckpt-test.ckpt")
    ckpt.save_checkpoint(path, "mlp", state, epoch=0,
                         best_valid_loss=1.0)
    _, template = _engine(preset="bf16_full")
    restored_state, _, _ = ckpt.load_checkpoint(path, template)
    dts = {leaf.dtype for leaf in
           jax.tree_util.tree_leaves(restored_state.params)}
    assert dts == {jnp.dtype(jnp.bfloat16)}
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(state.params)),
                    jax.tree_util.tree_leaves(
                        jax.device_get(restored_state.params))):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_from_scaling_run_restores_into_nonscaling(tmp_path):
    """An f16 checkpoint (carries a LossScaleState) restored into an f32
    template drops the scale; an f32 checkpoint restored into an f16
    template keeps the template's fresh scale — both directions load."""
    eng16, st16 = _engine(preset="f16")
    imgs, labels, valid = _batch()
    st16, _ = eng16.train_step(st16, imgs, labels, valid,
                               jax.random.PRNGKey(1))
    p16 = os.path.join(str(tmp_path), "f16.ckpt")
    ckpt.save_checkpoint(p16, "mlp", st16, epoch=0, best_valid_loss=1.0)
    _, tmpl32 = _engine(preset="f32")
    restored_state, _, _ = ckpt.load_checkpoint(p16, tmpl32)
    assert restored_state.loss_scale is None

    eng32, st32 = _engine(preset="f32")
    st32, _ = eng32.train_step(st32, imgs, labels, valid,
                               jax.random.PRNGKey(1))
    p32 = os.path.join(str(tmp_path), "f32.ckpt")
    ckpt.save_checkpoint(p32, "mlp", st32, epoch=0, best_valid_loss=1.0)
    _, tmpl16 = _engine(preset="f16")
    restored16, _, _ = ckpt.load_checkpoint(p32, tmpl16)
    assert restored16.loss_scale is not None
    assert float(restored16.loss_scale.scale) == 2.0 ** 15
