"""Persistent compilation cache + AOT warmup (--compilation-cache-dir /
--no-compile-cache / --aot-warmup): config resolution, the gauges the
warmup records, and the acceptance criterion — a second run of the same
config against the same cache dir records compile/cache_hit = 1 with a
lower compile/warmup_s than the cold run."""

import json
import os

import jax
import pytest

from distributedpytorch_tpu import telemetry
from distributedpytorch_tpu.cli import run_train
from distributedpytorch_tpu.config import Config, config_from_argv


@pytest.fixture
def restore_global():
    yield
    telemetry._active = telemetry.Telemetry(enabled=False)


# -- config resolution --------------------------------------------------


def test_cache_dir_resolution_defaults_to_rsl_path():
    cfg = Config(rsl_path="/some/rsl")
    assert cfg.compilation_cache_path() == "/some/rsl/xla_cache"
    assert Config(rsl_path="/r", no_compile_cache=True) \
        .compilation_cache_path() is None
    assert Config(compilation_cache_dir="/explicit") \
        .compilation_cache_path() == "/explicit"
    # opt-out wins over an explicit dir
    assert Config(compilation_cache_dir="/explicit",
                  no_compile_cache=True).compilation_cache_path() is None


def test_cli_flags_roundtrip():
    cfg = config_from_argv(["train", "-d", "/x",
                            "--compilation-cache-dir", "/cache",
                            "--aot-warmup", "--ckpt-async",
                            "--producer-threads", "3"])
    assert cfg.compilation_cache_dir == "/cache"
    assert cfg.aot_warmup and cfg.ckpt_async
    assert cfg.producer_threads == 3
    assert not cfg.no_compile_cache
    cfg = config_from_argv(["train", "-d", "/x", "--no-compile-cache"])
    assert cfg.no_compile_cache
    assert cfg.compilation_cache_path() is None
    # defaults: cache on (under rsl), one producer thread, sync ckpts
    cfg = config_from_argv(["train", "-d", "/x"])
    assert cfg.compilation_cache_path().endswith("xla_cache")
    assert cfg.producer_threads == 1 and not cfg.ckpt_async


# -- the acceptance criterion ------------------------------------------


def _warmup_gauges(rsl):
    events = [json.loads(line)
              for line in open(os.path.join(rsl, "telemetry",
                                            "rank0.jsonl"))]
    out = {}
    for e in events:
        if e["kind"] == "gauge" and e["name"].startswith("compile/"):
            out[e["name"]] = e["value"]
    return out


def test_second_run_hits_cache_with_lower_warmup(tmp_path,
                                                 restore_global):
    cache = str(tmp_path / "cache")
    gauges = []
    for i in (0, 1):
        if i == 1:
            # drop the in-memory jit caches so the second run's compiles
            # must go through the persistent cache — the cross-process
            # situation the cache exists for, pinned in-process
            jax.clear_caches()
        cfg = Config(action="train", data_path="/tmp/nodata",
                     rsl_path=str(tmp_path / f"run{i}"),
                     dataset="synthetic", model_name="mlp", batch_size=8,
                     nb_epochs=1, debug=True, half_precision=False,
                     telemetry=True, aot_warmup=True,
                     compilation_cache_dir=cache)
        run_train(cfg)
        gauges.append(_warmup_gauges(cfg.rsl_path))
    cold, warm = gauges
    assert cold["compile/cache_hit"] == 0.0
    assert warm["compile/cache_hit"] == 1.0
    assert warm["compile/warmup_s"] < cold["compile/warmup_s"]
    assert os.listdir(cache)  # the cold run populated the cache
    # run_train detached the cache on exit: later compiles must not
    # write into (a possibly deleted) run directory
    assert jax.config.jax_compilation_cache_dir is None


def test_no_compile_cache_leaves_no_cache_dir(tmp_path, restore_global):
    rsl = str(tmp_path / "rsl")
    cfg = Config(action="train", data_path="/tmp/nodata", rsl_path=rsl,
                 dataset="synthetic", model_name="mlp", batch_size=8,
                 nb_epochs=1, debug=True, half_precision=False,
                 no_compile_cache=True)
    run_train(cfg)
    assert not os.path.exists(os.path.join(rsl, "xla_cache"))
