"""Pretrained-weight conversion parity: torch state_dict -> Flax params.

For each supported architecture a seeded random-weight torch model (exact
torchvision topology + key names, tests/_torch_zoo.py) produces reference
eval-mode logits; the converted Flax model must match on the same input.
This validates the full mapping — conv/linear transposes, the NCHW->NHWC
flatten permutation, BN param/stat split — so real torchvision ImageNet
weights load correctly whenever the user supplies them
(ref utils.py:38-105 use_pretrained).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from distributedpytorch_tpu import models
from distributedpytorch_tpu.models import pretrained
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.train.engine import Engine, make_optimizer

from tests._torch_zoo import TORCH_ZOO, randomize_bn_stats

RNGS = {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)}


def _flax_init(name, size):
    m = models.get_model(name, 10, half_precision=False)
    x = jnp.zeros((1, size, size, 3), jnp.float32)
    v = m.init(RNGS, x, train=True)
    return m, v["params"], v.get("batch_stats", {})


# Where each torch model keeps the classifier the reference replaces
# (ref utils.py:42-99).
_TORCH_HEAD = {
    "resnet": lambda m: m.fc,
    "alexnet": lambda m: m.classifier[6],
    "vgg": lambda m: m.classifier[6],
    "squeezenet": lambda m: m.classifier[1],  # a 1x1 Conv2d
    "densenet": lambda m: m.classifier,
    "inception": lambda m: m.fc,
}
@pytest.mark.parametrize("name", sorted(TORCH_ZOO))
def test_converted_logits_match_torch(name):
    torch.manual_seed(42)
    tmodel = TORCH_ZOO[name](num_classes=10)
    randomize_bn_stats(tmodel, seed=7)
    tmodel.eval()

    # the registry's own size table (224 for all, 299 for inception)
    size = models.get_model_input_size(name)
    m, params, batch_stats = _flax_init(name, size)
    params, batch_stats = pretrained.convert_state_dict(
        name, {k: v.numpy() for k, v in tmodel.state_dict().items()},
        params, batch_stats)

    # The head stays freshly initialized (replace-after-load semantics,
    # ref utils.py:46-48); copy it INTO the torch model for comparison.
    head_t = _TORCH_HEAD[name](tmodel)
    kernel = np.asarray(params["head"]["kernel"])
    with torch.no_grad():
        if kernel.ndim == 4:  # squeezenet's conv head: HWIO -> OIHW
            head_t.weight.copy_(
                torch.from_numpy(kernel.transpose(3, 2, 0, 1)))
        else:
            head_t.weight.copy_(torch.from_numpy(kernel.T))
        head_t.bias.copy_(torch.from_numpy(
            np.asarray(params["head"]["bias"])))

    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, size, size, 3)).astype(np.float32)
    with torch.no_grad():
        ref = tmodel(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()

    variables = {"params": params}
    if jax.tree_util.tree_leaves(batch_stats):
        variables["batch_stats"] = batch_stats
    got = np.asarray(m.apply(variables, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_unsupported_arch_raises():
    _, params, stats = _flax_init("cnn", 28)
    with pytest.raises(ValueError, match="not supported"):
        pretrained.convert_state_dict("cnn", {}, params, stats)


def test_missing_path_raises():
    with pytest.raises(ValueError, match="pretrained-path"):
        pretrained.load_pretrained("resnet", None, {}, {})


def test_shape_mismatch_raises():
    torch.manual_seed(0)
    tmodel = TORCH_ZOO["resnet"](num_classes=10)
    sd = {k: v.numpy() for k, v in tmodel.state_dict().items()}
    sd["conv1.weight"] = sd["conv1.weight"][:, :1]  # break a shape
    _, params, stats = _flax_init("resnet", 64)
    with pytest.raises(ValueError, match="shape mismatch"):
        pretrained.convert_state_dict("resnet", sd, params, stats)


def test_feature_extract_finetune_trains_head_only(tmp_path):
    """The reference's whole fine-tuning story (ref config.py:48-51):
    pretrained backbone + feature_extract trains ONLY the head."""
    torch.manual_seed(1)
    tmodel = TORCH_ZOO["resnet"](num_classes=10)
    path = tmp_path / "resnet18.pth"
    torch.save(tmodel.state_dict(), str(path))

    size = 64  # reduced input: resnet is size-agnostic (global pool)
    model = models.get_model("resnet", 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, steps_per_epoch=4,
                        feature_extract=True)
    engine = Engine(model, "resnet", get_loss_fn("cross_entropy"), tx,
                    mean=0.45, std=0.2, input_size=size,
                    half_precision=False)
    state = engine.init_state(jax.random.PRNGKey(0))
    params, stats = pretrained.load_pretrained(
        "resnet", str(path), state.params, state.batch_stats)
    state = state.replace(params=params, batch_stats=stats)

    backbone_before = np.asarray(params["Conv_0"]["kernel"]).copy()
    head_before = np.asarray(params["head"]["kernel"]).copy()
    # backbone got the torch weights
    np.testing.assert_allclose(
        backbone_before,
        tmodel.state_dict()["conv1.weight"].numpy().transpose(2, 3, 1, 0))

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(2, size, size), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(2,)).astype(np.int32)
    state, metrics = engine.train_step(state, images, labels,
                                       np.ones(2, bool),
                                       jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))
    np.testing.assert_array_equal(
        np.asarray(state.params["Conv_0"]["kernel"]), backbone_before)
    assert not np.allclose(np.asarray(state.params["head"]["kernel"]),
                           head_before)


def test_inception_aux_convs_converted():
    """The aux tower is eval-invisible (train-only branch), so pin its
    converted weights tensor-to-tensor instead."""
    torch.manual_seed(5)
    tmodel = TORCH_ZOO["inception"](num_classes=10)
    _, params, stats = _flax_init("inception", 299)
    params, stats = pretrained.convert_state_dict(
        "inception", {k: v.numpy() for k, v in tmodel.state_dict().items()},
        params, stats)
    sd = tmodel.state_dict()
    for i, t in enumerate(("conv0", "conv1")):
        np.testing.assert_array_equal(
            np.asarray(params["AuxHead_0"][f"BasicConv_{i}"]["Conv_0"]
                       ["kernel"]),
            sd[f"AuxLogits.{t}.conv.weight"].numpy().transpose(2, 3, 1, 0))
        np.testing.assert_array_equal(
            np.asarray(stats["AuxHead_0"][f"BasicConv_{i}"]["BatchNorm_0"]
                       ["mean"]),
            sd[f"AuxLogits.{t}.bn.running_mean"].numpy())
    # the aux fc itself stays fresh (both heads replaced, ref utils.py:93-98)
