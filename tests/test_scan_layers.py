"""--scan-layers (models/scan.py): homogeneous block runs under one
``lax.scan`` with params stacked on a leading (depth,) axis — O(1) HLO
in depth instead of O(depth).  The transform must be invisible except
for compile time: same math (forward AND gradients) as the unrolled
loop, same checkpoint compatibility (the '*_scan' <-> '*_layers'
layout pairs convert bidirectionally at restore time, exactly like the
vit 'stacked' <-> 'blocks' pair), and a measurable program-size win
(costs.hlo_instruction_count)."""

import os

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest
from flax import serialization

from distributedpytorch_tpu import costs
from distributedpytorch_tpu.cli import run_train, run_test
from distributedpytorch_tpu.config import Config
from distributedpytorch_tpu.models import scan
from distributedpytorch_tpu.models.registry import get_model
from distributedpytorch_tpu.models.densenet import DenseNet
from distributedpytorch_tpu.models.vit import ViT


def _grads_match(plain, sc, vp, vars_scan, x, back_layout, loss_args,
                 tol=2e-4):
    """Compare d(sum(out^2))/d(params) between the unrolled and scanned
    model after converting the scanned grads back to the plain layout.
    Leaves whose true gradient is ~0 (conv bias under BN) compare on
    absolute tolerance; everything else relative to the leaf's own
    scale."""
    def loss(mdl, variables, p):
        out = mdl.apply({**variables, "params": p}, x, *loss_args)
        if isinstance(out, tuple) and not hasattr(out, "shape"):
            out = out[0]
        if isinstance(out, tuple):
            out = out[0]
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g1 = jax.grad(lambda p: loss(plain, vp, p))(vp["params"])
    g2 = jax.grad(lambda p: loss(sc, vars_scan, p))(vars_scan["params"])
    g2c = scan.convert_layout(serialization.to_state_dict(g2),
                              back_layout)
    flat2 = {jtu.keystr(k): v
             for k, v in jtu.tree_flatten_with_path(g2c)[0]}
    flat1 = jtu.tree_flatten_with_path(
        serialization.to_state_dict(g1))[0]
    assert set(jtu.keystr(k) for k, _ in flat1) == set(flat2)
    for k, v in flat1:
        a, b = np.asarray(v), np.asarray(flat2[jtu.keystr(k)])
        scale = max(float(np.abs(a).max()), 1.0)
        np.testing.assert_allclose(b, a, atol=tol * scale,
                                   err_msg=f"grad {jtu.keystr(k)}")


def test_vit_scan_matches_loop():
    """Forward and gradients of the scanned ViT equal the unrolled loop
    after converting params across the 'blocks' <-> 'scan' pair."""
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 28, 28, 1))
    plain = ViT(num_classes=10, dtype=jnp.float32)
    sc = ViT(num_classes=10, dtype=jnp.float32, scan_layers=True)
    vp = plain.init(rng, x, True)
    vs = sc.init(rng, x, True)
    sd = serialization.to_state_dict(vp)
    assert scan.params_layout(sd["params"]) == "blocks"
    assert scan.params_layout(
        serialization.to_state_dict(vs["params"])) == "scan"
    vars_scan = serialization.from_state_dict(
        vs, scan.convert_layout(sd, "scan"))
    o1 = plain.apply(vp, x, True)
    o2 = sc.apply(vars_scan, x, True)
    np.testing.assert_allclose(np.asarray(o2), np.asarray(o1),
                               atol=1e-5)
    _grads_match(plain, sc, vp, vars_scan, x, "blocks", (True,))


def test_vit_scan_layout_round_trip_bitwise():
    rng = jax.random.PRNGKey(3)
    x = jnp.zeros((1, 28, 28, 1))
    sd = serialization.to_state_dict(
        ViT(num_classes=10).init(rng, x, False))
    there = scan.convert_layout(sd, "scan")
    back = scan.convert_layout(there, "blocks")
    for (k1, v1), (k2, v2) in zip(
            jtu.tree_flatten_with_path(sd)[0],
            jtu.tree_flatten_with_path(back)[0]):
        assert jtu.keystr(k1) == jtu.keystr(k2)
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))


def test_layout_detection_and_shape_level_round_trips():
    """params_layout names every family's tree, and the converters run
    at SHAPE level (ShapeDtypeStruct leaves — the orbax abstract-target
    path) with scan->layers->scan structure identity.  jax.eval_shape
    keeps this cheap enough for tier-1 even on the 58-layer densenet
    and 299px inception."""
    from distributedpytorch_tpu.models.vgg import VGG11BN
    from distributedpytorch_tpu.models.inception import InceptionV3

    cases = [
        (DenseNet(num_classes=10), (1, 32, 32, 3),
         "dense_layers", "dense_scan"),
        (DenseNet(num_classes=10, scan_layers=True), (1, 32, 32, 3),
         "dense_scan", "dense_layers"),
        (VGG11BN(num_classes=10), (1, 32, 32, 3),
         "vgg_layers", "vgg_scan"),
        (VGG11BN(num_classes=10, scan_layers=True), (1, 32, 32, 3),
         "vgg_scan", "vgg_layers"),
        (InceptionV3(num_classes=10), (1, 299, 299, 3),
         "inception_blocks", "inception_scan"),
        (InceptionV3(num_classes=10, scan_layers=True),
         (1, 299, 299, 3), "inception_scan", "inception_blocks"),
    ]
    for mdl, shape, layout, other in cases:
        variables = jax.eval_shape(
            lambda m=mdl, s=shape: m.init(jax.random.PRNGKey(0),
                                          jnp.zeros(s), False))
        sd = serialization.to_state_dict(variables)
        assert scan.params_layout(sd["params"]) == layout, mdl
        there = scan.convert_layout(sd, other)
        assert scan.params_layout(there["params"]) == other
        back = scan.convert_layout(there, layout)
        want = jtu.tree_flatten_with_path(sd)[0]
        got = jtu.tree_flatten_with_path(back)[0]
        assert len(want) == len(got)
        for (k1, v1), (k2, v2) in zip(want, got):
            assert jtu.keystr(k1) == jtu.keystr(k2)
            assert v1.shape == v2.shape and v1.dtype == v2.dtype


@pytest.mark.slow
def test_hlo_instruction_count_collapses_with_depth():
    """The tentpole's compile-side claim on the cheap model: a depth-8
    scanned ViT's optimized HLO carries >=3x fewer instructions than
    the unrolled one (densenet's >=4x reduction is the CI scan_gate's
    job, which also keeps this contract out of the tier-1 wall-clock
    budget — scan_gate enforces the floor on every gate run)."""
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((2, 28, 28, 1))
    counts = {}
    for name, flag in (("noscan", False), ("scan", True)):
        m = ViT(num_classes=10, dtype=jnp.float32, depth=8,
                scan_layers=flag)
        v = m.init(rng, x, False)
        compiled = jax.jit(
            lambda vv, xx, m=m: m.apply(vv, xx, False)
        ).lower(v, x).compile()
        counts[name] = costs.hlo_instruction_count(compiled.as_text())
    assert counts["scan"] * 3 <= counts["noscan"], counts


def test_registry_validation():
    with pytest.raises(ValueError, match="scan-layers"):
        get_model("cnn", 10, scan_layers=True)
    with pytest.raises(ValueError, match="pipelined vit"):
        get_model("vit", 10, scan_layers=True, pipeline_parallel=True)
    with pytest.raises(ValueError, match="moe"):
        get_model("vit", 10, scan_layers=True, moe_experts=4)


def _train_cfg(rsl, scan_layers):
    return Config(action="train", data_path="/tmp/nodata", rsl_path=rsl,
                  dataset="synthetic", model_name="vit", batch_size=8,
                  nb_epochs=1, debug=True, half_precision=False,
                  scan_layers=scan_layers)


def _test_cfg(rsl, ckpt, scan_layers):
    return Config(action="test", data_path="/tmp/nodata", rsl_path=rsl,
                  dataset="synthetic", debug=True, half_precision=False,
                  checkpoint_file=ckpt, scan_layers=scan_layers)


@pytest.mark.slow
def test_checkpoint_converts_across_scan_flag(tmp_path):
    """Bidirectional restore through the CLI: a checkpoint trained under
    --scan-layers `test -f`s as the plain model (scan -> blocks at load),
    and a blocks-layout file restores under --scan-layers (blocks ->
    scan).  One training run feeds both directions — the reverse-layout
    file is the same payload converted offline, exactly what a plain
    training run would have written (msgpack path; orbax shares the
    converters and is covered by the CI scan_gate, which also runs both
    directions end to end on every gate invocation — that, plus the
    ~25 s of CLI runs here, keeps this out of the tier-1 budget)."""
    rsl = str(tmp_path / "sc")
    run_train(_train_cfg(rsl, True))
    ckpt = f"{rsl}/bestmodel-synthetic-vit.ckpt"
    res = run_test(_test_cfg(rsl, ckpt, False))
    assert res["model_name"] == "vit"
    assert np.isfinite(res["test_loss"])
    assert 0.0 <= res["test_acc"] <= 1.0

    with open(ckpt, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    assert scan.params_layout(payload["state"]["params"]) == "scan"
    payload["state"] = scan.convert_layout(payload["state"], "blocks")
    rsl2 = str(tmp_path / "plain")  # fresh dir: no lineage ledger entry
    ckpt2 = f"{rsl2}/bestmodel-synthetic-vit.ckpt"
    os.makedirs(rsl2, exist_ok=True)
    with open(ckpt2, "wb") as f:
        f.write(serialization.msgpack_serialize(payload))
    res2 = run_test(_test_cfg(rsl2, ckpt2, True))
    assert res2["model_name"] == "vit"
    np.testing.assert_allclose(res2["test_loss"], res["test_loss"],
                               rtol=1e-5)
    assert res2["test_acc"] == res["test_acc"]


@pytest.mark.slow
def test_densenet_scan_matches_layers():
    """The deep-zoo flagship, full densenet121 geometry: eval-mode
    forward and gradients equal the unrolled loop after layout
    conversion, and the padded-buffer scan body's padding stays inert
    (exactly zero gradient into padded BN rows / conv kernel rows).
    Eval mode pins BN to stored stats: train-mode equality holds too
    but only in f64 — 58 stacked BN stat reductions amplify f32
    reduction-order noise chaotically (verified out-of-band)."""
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    plain = DenseNet(num_classes=10, dtype=jnp.float32)
    sc = DenseNet(num_classes=10, dtype=jnp.float32, scan_layers=True)
    vp = plain.init(rng, x, False)
    vs = sc.init(rng, x, False)
    sd = serialization.to_state_dict(
        {"params": vp["params"], "batch_stats": vp["batch_stats"]})
    vars_scan = serialization.from_state_dict(
        {"params": vs["params"], "batch_stats": vs["batch_stats"]},
        scan.convert_layout(sd, "dense_scan"))
    o1 = plain.apply(vp, x, False)
    o2 = sc.apply(vars_scan, x, False)
    np.testing.assert_allclose(np.asarray(o2), np.asarray(o1),
                               atol=1e-4)
    _grads_match(plain, sc, vp, vars_scan, x, "dense_layers", (False,))
    # padding inertness: zero grads beyond each step's live channel
    # count (the mask kills gradient flow into padded parameters)
    g = jax.grad(lambda p: jnp.sum(sc.apply(
        {"params": p, "batch_stats": vars_scan["batch_stats"]},
        x, False) ** 2))(vars_scan["params"])
    gsd = serialization.to_state_dict(g)
    bn0 = np.asarray(gsd["DenseBlockScan_0"]["BatchNorm_0"]["scale"])
    k0 = np.asarray(gsd["DenseBlockScan_0"]["Conv_0"]["kernel"])
    for i in range(bn0.shape[0]):
        c_i = 64 + i * 32
        assert np.abs(bn0[i, c_i:]).max() == 0.0
        assert np.abs(k0[i, :, :, c_i:, :]).max() == 0.0
