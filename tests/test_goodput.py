"""Goodput ledger (distributedpytorch_tpu/goodput.py): wall-clock
attribution sums exactly, nested windows never double-count, the
persisted artifact round-trips, and the live /metrics exporter serves
valid Prometheus text then shuts down clean (no leaked thread/socket).
"""

import json
import os
import socket
import threading
import time
import urllib.request

import pytest

from distributedpytorch_tpu import goodput, telemetry


@pytest.fixture
def restore_global():
    yield
    goodput.stop_exporter()
    goodput._active = goodput.GoodputLedger(enabled=False)
    telemetry._active = telemetry.Telemetry(enabled=False)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.headers.get("Content-Type", ""), \
            r.read().decode("utf-8")


# -- ledger attribution ------------------------------------------------


def test_disabled_ledger_is_a_noop(tmp_path):
    led = goodput.GoodputLedger(enabled=False, rsl_path=str(tmp_path))
    led.add("compute", 1.0)
    with led.timed("ckpt_blocking"):
        pass
    led.begin_steps()
    # disabled step() still classifies (the flight recorder may be on)
    assert led.step(dispatch_s=0.2, wait_s=0.1) == "compute"
    assert led.step(dispatch_s=0.1, wait_s=0.2) == "data_wait"
    led.end_steps()
    assert led.reconcile(0) == {}
    led.close()
    assert list(tmp_path.iterdir()) == []  # no file I/O


def test_reconcile_sums_to_wall_with_explicit_residual(tmp_path):
    led = goodput.GoodputLedger(enabled=True, rsl_path=str(tmp_path))
    with led.timed("compute"):
        time.sleep(0.02)
    time.sleep(0.01)  # unattributed — must surface as "other"
    row = led.reconcile(0)
    assert row["epoch"] == 0
    assert sum(row["categories"].values()) == pytest.approx(
        row["wall_s"], abs=1e-4)
    assert row["categories"]["other"] >= 0.005
    assert row["residual_s"] == pytest.approx(
        row["categories"]["other"], abs=1e-4)
    # next window starts from zero: categories are per-window deltas
    row2 = led.reconcile(1)
    assert sum(row2["categories"].values()) == pytest.approx(
        row2["wall_s"], abs=1e-4)
    snap = led.snapshot()
    assert snap["accounted_s"] <= snap["wall_s"] + 1e-4


def test_nested_timed_windows_never_double_count(tmp_path):
    led = goodput.GoodputLedger(enabled=True, rsl_path=str(tmp_path))
    t0 = time.perf_counter()
    with led.timed("ckpt_blocking"):
        time.sleep(0.02)
        with led.timed("retry_backoff"):  # retry inside a ckpt save
            time.sleep(0.04)
    elapsed = time.perf_counter() - t0
    cats = led.snapshot()["categories"]
    assert cats["retry_backoff"] >= 0.04
    # the ckpt window shrank by the nested retry: counted once, not twice
    assert cats["ckpt_blocking"] < 0.04
    assert cats["ckpt_blocking"] + cats["retry_backoff"] \
        <= elapsed + 1e-3
    assert led.current() == "ckpt_blocking"


def test_step_wait_subtracts_nested_hooks(tmp_path):
    led = goodput.GoodputLedger(enabled=True, rsl_path=str(tmp_path))
    led.begin_steps()
    # a retry hook fired inside the inter-step wait window
    led.add("retry_backoff", 0.04)
    led.step(dispatch_s=0.01, wait_s=0.05)
    cats = led.snapshot()["categories"]
    assert cats["retry_backoff"] == pytest.approx(0.04)
    assert cats["data_wait"] == pytest.approx(0.01, abs=1e-6)
    assert cats["compute"] == pytest.approx(0.01, abs=1e-6)
    # the subtraction accumulator reset: a clean step charges in full
    led.step(dispatch_s=0.02, wait_s=0.03)
    cats = led.snapshot()["categories"]
    assert cats["data_wait"] == pytest.approx(0.04, abs=1e-6)
    led.end_steps()


def test_off_main_thread_contributions_are_dropped(tmp_path):
    led = goodput.GoodputLedger(enabled=True, rsl_path=str(tmp_path))

    def producer():
        led.add("retry_backoff", 5.0)  # producer-thread sleep: not
        # driver wall time

    t = threading.Thread(target=producer)
    t.start()
    t.join()
    assert led.snapshot()["categories"]["retry_backoff"] == 0.0


def test_write_load_roundtrip_and_rank_naming(tmp_path):
    led0 = goodput.GoodputLedger(enabled=True, rsl_path=str(tmp_path),
                                 rank=0, world=2)
    led0.add("compute", 1.5)
    led0.close()
    led1 = goodput.GoodputLedger(enabled=True, rsl_path=str(tmp_path),
                                 rank=1, world=2)
    led1.add("data_wait", 0.5)
    led1.close()
    assert os.path.exists(tmp_path / "goodput.json")
    assert os.path.exists(tmp_path / "goodput-rank1.json")
    docs = goodput.load_ledgers(str(tmp_path))
    assert sorted(docs) == [0, 1]
    assert docs[0]["categories"]["compute"] == pytest.approx(1.5)
    assert docs[1]["categories"]["data_wait"] == pytest.approx(0.5)
    assert docs[0]["version"] == 1 and docs[0]["world"] == 2
    # close() is idempotent and final: ledger disabled, no re-write
    mtime = os.path.getmtime(tmp_path / "goodput.json")
    led0.close()
    assert not led0.enabled
    assert os.path.getmtime(tmp_path / "goodput.json") == mtime


def test_unreadable_ledger_is_skipped_not_fatal(tmp_path):
    (tmp_path / "goodput.json").write_text("{torn")
    led = goodput.GoodputLedger(enabled=True, rsl_path=str(tmp_path),
                                rank=1)
    led.add("compute", 1.0)
    led.close()
    docs = goodput.load_ledgers(str(tmp_path))
    assert sorted(docs) == [1]


def test_report_summarizes_and_names_top_badput(tmp_path):
    for rank, cats in ((0, {"compute": 8.0, "data_wait": 2.0}),
                       (1, {"compute": 6.0, "data_wait": 4.0})):
        led = goodput.GoodputLedger(enabled=True, rsl_path=str(tmp_path),
                                    rank=rank, world=2)
        for c, v in cats.items():
            led.add(c, v)
        led.close()
    out = goodput.report(str(tmp_path))
    assert "rank 0" in out and "rank 1" in out
    assert "top badput cause: data_wait" in out
    assert "fleet — 2 rank(s)" in out


def test_report_errors_without_ledger(tmp_path):
    with pytest.raises(ValueError, match="goodput"):
        goodput.report(str(tmp_path))


def test_configure_swaps_the_global(tmp_path, restore_global):
    led = goodput.configure(str(tmp_path), enabled=True, rank=0)
    assert goodput.get() is led and led.enabled
    led.add("compute", 1.0)
    # reconfiguring closes (and persists) the previous instance
    goodput.configure(str(tmp_path), enabled=False)
    assert not goodput.get().enabled
    assert os.path.exists(tmp_path / "goodput.json")


# -- live exporter -----------------------------------------------------


def test_exporter_serves_metrics_and_healthz(tmp_path, restore_global):
    tel = telemetry.configure(str(tmp_path), enabled=True)
    tel.counter("data/batches").add(7)
    tel.gauge("throughput/mfu").set(None)  # null gauge: skipped
    tel.gauge("throughput/samples_per_sec_per_chip").set(123.0)
    h = tel.histogram("step/dispatch_s")
    for v in (0.1, 0.2, 0.3):
        h.observe(v)
    led = goodput.configure(str(tmp_path), enabled=True)
    led.add("compute", 2.0)
    port = _free_port()
    exp = goodput.start_exporter(port, rank=0, world_size_fn=lambda: 4,
                                 generation_fn=lambda: 1)
    assert exp is not None
    try:
        status, ctype, body = _get(f"http://127.0.0.1:{port}/metrics")
        assert status == 200 and ctype.startswith("text/plain")
        assert "dpt_data_batches_total 7" in body
        assert "dpt_throughput_samples_per_sec_per_chip 123" in body
        assert "dpt_throughput_mfu" not in body
        assert 'dpt_step_dispatch_s{quantile="0.5"}' in body
        assert "dpt_step_dispatch_s_count 3" in body
        assert 'dpt_goodput_seconds_total{category="compute"} 2' in body
        assert body.endswith("dpt_up 1\n")
        # every non-comment line is "name[{labels}] value" — the
        # Prometheus text contract a scraper actually parses
        for line in body.strip().splitlines():
            if not line.startswith("#"):
                assert len(line.rsplit(" ", 1)) == 2
        status, ctype, body = _get(f"http://127.0.0.1:{port}/healthz")
        assert status == 200 and ctype.startswith("application/json")
        health = json.loads(body)
        assert health["status"] == "ok" and health["rank"] == 0
        assert health["world_size"] == 4
        assert health["elastic_generation"] == 1
        assert health["last_step_age_s"] is None  # no step yet
        exp.note_step()
        health = json.loads(_get(f"http://127.0.0.1:{port}/healthz")[2])
        assert health["last_step_age_s"] is not None
        assert health["last_step_age_s"] < 5.0
    finally:
        goodput.stop_exporter()
    # clean shutdown: thread joined, socket released (port rebindable)
    assert not exp._thread.is_alive()
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("0.0.0.0", port))
    s.close()
    assert goodput.exporter() is None


def test_exporter_healthz_degrades_mid_reconfigure(restore_global):
    port = _free_port()

    def boom():
        raise RuntimeError("backend mid-reconfigure")

    exp = goodput.start_exporter(port, rank=0, world_size_fn=boom,
                                 generation_fn=boom)
    try:
        health = json.loads(_get(f"http://127.0.0.1:{port}/healthz")[2])
        assert health["world_size"] == -1
        assert health["elastic_generation"] == -1
    finally:
        goodput.stop_exporter()


def test_exporter_bind_failure_degrades_not_raises(restore_global):
    port = _free_port()
    blocker = socket.socket()
    blocker.bind(("0.0.0.0", port))
    blocker.listen(1)
    try:
        assert goodput.start_exporter(port, rank=0) is None
        assert goodput.exporter() is None  # training continues
    finally:
        blocker.close()


def test_stop_exporter_is_idempotent(restore_global):
    goodput.stop_exporter()  # nothing running: no-op
    port = _free_port()
    exp = goodput.start_exporter(port, rank=0)
    assert exp is not None
    goodput.stop_exporter()
    goodput.stop_exporter()
    assert goodput.exporter() is None


# -- driver integration (the run artifact) -----------------------------


def test_train_run_writes_ledger_and_accounts_wall(tmp_path,
                                                   restore_global):
    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    rsl = str(tmp_path / "rsl")
    run_train(Config(action="train", data_path="/tmp/nodata", rsl_path=rsl,
                     dataset="synthetic", model_name="mlp", batch_size=8,
                     nb_epochs=2, debug=True, half_precision=False,
                     telemetry=True, data_mode="stream"))
    docs = goodput.load_ledgers(rsl)
    assert 0 in docs
    doc = docs[0]
    assert doc["wall_s"] > 0
    # the acceptance criterion: >= 99% of wall clock attributed (the
    # residual itself is a category, so the sum is exact by design —
    # this asserts the bookkeeping didn't leak anything)
    assert doc["accounted_s"] >= 0.99 * doc["wall_s"]
    assert doc["categories"]["compute"] > 0
    assert doc["categories"]["compile"] >= 0
    # per-epoch rows exist (2 epochs + final tail window)
    epochs = [row["epoch"] for row in doc["epochs"]]
    assert 0 in epochs and 1 in epochs and None in epochs
    for row in doc["epochs"]:
        assert sum(row["categories"].values()) == pytest.approx(
            row["wall_s"], abs=1e-3)
    # the CLI summary renders from the real artifact
    out = goodput.report(rsl)
    assert "rank 0" in out and "compute" in out


def test_goodput_cli_subcommand_roundtrip():
    from distributedpytorch_tpu.config import config_from_argv

    cfg = config_from_argv(["goodput", "--rsl_path", "/some/dir"])
    assert cfg.action == "goodput" and cfg.rsl_path == "/some/dir"
    cfg = config_from_argv(["train", "-d", "/x", "--metrics-port", "9100"])
    assert cfg.metrics_port == 9100
    assert config_from_argv(["train", "-d", "/x"]).metrics_port == 0
