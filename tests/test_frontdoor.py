"""The fleet front door (serving/frontdoor.py, ISSUE 19 tentpole 1).

Pure layers first: ejection/readmission, routable filtering, the
least-pending pick and fleet-level admission are clock-free functions
over snapshots.  The live layer stands a real FrontDoor listener over
scriptable in-process fake replicas and exercises the issue's three
HTTP contracts: shed-with-Retry-After at the pending budget, a hung
upstream cut off at the deadline and retried on a second replica with
the upstream ``X-DPT-Request-Id`` preserved, and ejection after
consecutive probe failures.
"""

import http.server
import json
import threading
import time
import urllib.error
import urllib.request

from distributedpytorch_tpu.serving.frontdoor import (FrontDoor,
                                                      admission,
                                                      decide_health,
                                                      pick_upstream,
                                                      routable_ids)

# -- pure policy -------------------------------------------------------


def _rep(uid, fails=0, age=None, ejected=False, alive=True,
         draining=False):
    return {"id": uid, "alive": alive, "ejected": ejected,
            "draining": draining, "consecutive_failures": fails,
            "last_step_age_s": age}


def test_decide_health_ejects_on_failure_streak():
    cfg = {"eject_after": 3}
    assert decide_health(cfg, [_rep(0, fails=2)]) == []
    out = decide_health(cfg, [_rep(0, fails=3)])
    assert out == [{"id": 0, "action": "eject",
                    "reason": "3 consecutive failures"}]


def test_decide_health_ejects_on_stale_age_only_when_enabled():
    stale = [_rep(0, age=99.0)]
    assert decide_health({"max_step_age_s": 0.0}, stale) == []
    out = decide_health({"max_step_age_s": 30.0}, stale)
    assert out[0]["action"] == "eject" and "stale" in out[0]["reason"]


def test_decide_health_readmits_on_recovery():
    cfg = {"eject_after": 3, "max_step_age_s": 30.0}
    out = decide_health(cfg, [_rep(0, fails=0, ejected=True)])
    assert out[0]["action"] == "readmit"
    # still failing, or still stale: stays out
    assert decide_health(cfg, [_rep(0, fails=1, ejected=True)]) == []
    assert decide_health(cfg, [_rep(0, age=99.0, ejected=True)]) == []


def test_routable_ids_filters_dead_ejected_draining():
    snaps = [_rep(0), _rep(1, ejected=True), _rep(2, alive=False),
             _rep(3, draining=True), _rep(4)]
    assert routable_ids(snaps) == [0, 4]


def test_pick_upstream_least_pending_with_rr_tiebreak():
    assert pick_upstream([0, 1, 2], {0: 3, 1: 0, 2: 1}, rr=0) == 1
    # all tied: round-robin walks the pool deterministically
    picks = [pick_upstream([0, 1, 2], {}, rr=r) for r in range(4)]
    assert picks == [0, 1, 2, 0]
    assert pick_upstream([0, 1], {}, rr=0, exclude=[0]) == 1
    assert pick_upstream([0], {}, rr=0, exclude=[0]) is None
    assert pick_upstream([], {}, rr=0) is None


def test_admission_budget():
    cfg = {"pending_budget": 2, "retry_after_s": 1.5}
    assert admission(cfg, 1) == {"admit": True, "retry_after_s": 0.0}
    assert admission(cfg, 2) == {"admit": False, "retry_after_s": 1.5}


# -- live front door over fake replicas --------------------------------

class FakeReplica:
    """A scriptable serve replica: ``behavior(hit_n) -> (status,
    payload)`` answers /predict (optionally sleeping first via
    ``delay_s``); /livez reports a stats-shaped health body."""

    def __init__(self, behavior=None, delay_s=0.0):
        self.behavior = behavior or (lambda n: (200, {"label": 1}))
        self.delay_s = delay_s
        self.hits = 0
        rep = self

        class _H(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                rep.hits += 1
                if rep.delay_s:
                    time.sleep(rep.delay_s)
                status, payload = rep.behavior(rep.hits)
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("X-DPT-Request-Id",
                                 f"r7-{rep.hits:06d}")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                body = json.dumps({"ok": True, "queue_depth": 0,
                                   "draining": False,
                                   "checkpoint": None}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass

        self.server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), _H)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def _mk_fd(ports, **kw):
    replicas = {i: {"predict_port": p, "health_port": p,
                    "health_path": "/livez"}
                for i, p in enumerate(ports)}
    kw.setdefault("upstream_timeout_s", 2.0)
    kw.setdefault("probe_timeout_s", 1.0)
    kw.setdefault("interval_s", 0.05)
    fd = FrontDoor(0, replicas, **kw)
    fd.start()
    return fd


def _post(port, timeout=10.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"image": [[0]]}).encode())
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_frontdoor_round_trip_preserves_request_id():
    rep = FakeReplica()
    fd = _mk_fd([rep.port])
    try:
        fd.tick()  # probe marks the replica alive
        status, body, headers = _post(fd.port)
        assert status == 200 and body["label"] == 1
        assert headers["X-DPT-Request-Id"] == "r7-000001"
        assert headers["X-DPT-Upstream"] == "0"
        doc = fd.status_doc()
        assert doc["answered"] == 1
        assert doc["upstreams"]["0"]["requests"] == 1
    finally:
        fd.close()
        rep.close()


def test_frontdoor_sheds_at_pending_budget_with_retry_after():
    rep = FakeReplica()
    fd = _mk_fd([rep.port], policy={"pending_budget": 0,
                                    "retry_after_s": 2.5})
    try:
        fd.tick()
        status, body, headers = _post(fd.port)
        assert status == 503 and "capacity" in body["error"]
        assert headers["Retry-After"] == "2.5"
        assert fd.status_doc()["shed"] == 1
        assert rep.hits == 0   # shed BEFORE touching any upstream
    finally:
        fd.close()
        rep.close()


def test_frontdoor_hung_upstream_deadline_then_retry_on_second():
    """The issue's hung-replica contract: the first attempt is cut off
    at upstream_timeout_s, the SAME request retries on the other
    replica, and the client sees its 200 — with the answering
    replica's request id."""
    hung = FakeReplica(delay_s=10.0)
    good = FakeReplica()
    fd = _mk_fd([hung.port, good.port], upstream_timeout_s=0.4)
    try:
        fd.tick()
        # pin the first pick to the hung replica: round-robin over a
        # fresh tie starts at slot rr % 2 == 0
        t0 = time.monotonic()
        status, _, headers = _post(fd.port)
        elapsed = time.monotonic() - t0
        assert status == 200
        assert headers["X-DPT-Upstream"] == "1"
        assert headers["X-DPT-Request-Id"].startswith("r7-")
        assert elapsed < 5.0   # deadline cut the hang, not the client
        doc = fd.status_doc()
        assert doc["retries"] == 1
        assert doc["upstreams"]["0"]["errors"] == 1  # unreachable
    finally:
        fd.close()
        hung.close()
        good.close()


def test_frontdoor_5xx_retries_once_on_another_replica():
    bad = FakeReplica(behavior=lambda n: (500, {"error": "boom"}))
    good = FakeReplica()
    fd = _mk_fd([bad.port, good.port])
    try:
        fd.tick()
        codes = {_post(fd.port)[0] for _ in range(4)}
        assert codes == {200}   # every request lands on the good one
        doc = fd.status_doc()
        assert doc["retries"] >= 1
        assert doc["upstreams"]["0"]["errors"] >= 1
    finally:
        fd.close()
        bad.close()
        good.close()


def test_frontdoor_no_routable_replica_answers_503():
    fd = _mk_fd([1])  # port 1: nothing listening, never probed alive
    try:
        status, body, headers = _post(fd.port)
        assert status == 503 and "no routable" in body["error"]
        assert "Retry-After" in headers
        assert fd.status_doc()["no_upstream"] == 1
    finally:
        fd.close()


def test_frontdoor_ejects_dead_replica_and_keeps_serving():
    dying = FakeReplica()
    good = FakeReplica()
    fd = _mk_fd([dying.port, good.port],
                policy={"eject_after": 2})
    try:
        fd.tick()
        assert routable_ids(
            [u.snapshot() for u in fd._ups.values()]) == [0, 1]
        dying.close()
        for _ in range(3):
            fd.tick()
        snaps = [u.snapshot() for u in fd._ups.values()]
        assert routable_ids(snaps) == [1]
        assert fd.status_doc()["upstreams"]["0"]["ejected"]
        # clients never notice: every request routes to the survivor
        assert _post(fd.port)[0] == 200
    finally:
        fd.close()
        good.close()
