"""Elastic world manager (elastic.py, --elastic): filesystem-rendezvous
election without any live collectives, peer-loss classification, the
bounded health agreement (--health-timeout), and the world
re-derivation property in BOTH directions — a world-(N±1) loader
enumerates exactly the full dataset, identically whether re-derived
via ``reshard`` or born at that size.  The grow half: join claims,
the admission policy (--elastic-target / --elastic-min-world), the
grow rendezvous publishing admit/decline markers, and the
restore-into-a-larger-mesh round trip.  The end-to-end proofs (a real
rank vanishing mid-epoch over gloo; a shrink-then-grow rejoin) live in
``scripts/chaos_gate.py --stage elastic`` / ``--stage grow``.
"""

import json
import os
import time

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from distributedpytorch_tpu import checkpoint as ckpt
from distributedpytorch_tpu import elastic, faults, runtime
from distributedpytorch_tpu.config import config_from_argv
from distributedpytorch_tpu.data.datasets import Split
from distributedpytorch_tpu.data.pipeline import ShardedLoader
from distributedpytorch_tpu.data.sampler import ShardedSampler
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.runtime import DATA_AXIS
from distributedpytorch_tpu.train.engine import Engine, make_optimizer


@pytest.fixture(autouse=True)
def _fresh_generation():
    elastic._reset_for_tests()
    yield
    elastic._reset_for_tests()


@pytest.fixture
def fast_settle(monkeypatch):
    """Shrink the rendezvous windows so failure cases stay sub-second."""
    monkeypatch.setattr(elastic, "SETTLE_S", 0.2)
    monkeypatch.setattr(elastic, "WORLD_WAIT_S", 2.0)
    monkeypatch.setattr(elastic, "RENDEZVOUS_DEADLINE_S", 5.0)


def _claim(gen_dir: str, rank: int) -> None:
    os.makedirs(gen_dir, exist_ok=True)
    with open(os.path.join(gen_dir, f"rank-{rank}.json"), "w") as f:
        json.dump({"old_rank": rank, "pid": 0}, f)


# -- filesystem rendezvous --------------------------------------------

def test_lowest_claimant_elects_itself(tmp_path, fast_settle):
    # Old world of 4; rank 3 died; peers 1 and 2 already claimed.
    gen_dir = str(tmp_path / "gen-1")
    _claim(gen_dir, 1)
    _claim(gen_dir, 2)
    doc = elastic._rendezvous(str(tmp_path), gen=1, old_rank=0,
                              old_world=4)
    assert doc["generation"] == 1
    assert doc["members"] == [0, 1, 2]
    host, port = doc["coordinator"].rsplit(":", 1)
    assert host == "localhost" and int(port) > 0
    with open(os.path.join(gen_dir, "world.json")) as f:
        assert json.load(f) == doc


def test_follower_joins_published_world(tmp_path, fast_settle):
    gen_dir = str(tmp_path / "gen-1")
    os.makedirs(gen_dir)
    published = {"generation": 1, "members": [0, 1],
                 "coordinator": "localhost:12345"}
    with open(os.path.join(gen_dir, "world.json"), "w") as f:
        json.dump(published, f)
    doc = elastic._rendezvous(str(tmp_path), gen=1, old_rank=1,
                              old_world=3)
    assert doc == published


def test_straggler_missing_from_members_fails_loudly(tmp_path,
                                                     fast_settle):
    gen_dir = str(tmp_path / "gen-1")
    os.makedirs(gen_dir)
    with open(os.path.join(gen_dir, "world.json"), "w") as f:
        json.dump({"generation": 1, "members": [0, 1],
                   "coordinator": "localhost:12345"}, f)
    with pytest.raises(RuntimeError, match="missed generation"):
        elastic._rendezvous(str(tmp_path), gen=1, old_rank=2,
                            old_world=3)


def test_full_claim_set_refuses_to_reconfigure(tmp_path, fast_settle):
    # Every rank of the old world claims: nothing died — reconfiguring
    # would re-elect an identical world off a spurious verdict.
    gen_dir = str(tmp_path / "gen-1")
    _claim(gen_dir, 1)
    _claim(gen_dir, 2)
    with pytest.raises(RuntimeError, match="nothing actually died"):
        elastic._rendezvous(str(tmp_path), gen=1, old_rank=0,
                            old_world=3)


def test_no_world_published_times_out(tmp_path, fast_settle):
    # Follower (not lowest rank), nobody publishes: bounded failure.
    _claim(str(tmp_path / "gen-1"), 0)
    with pytest.raises(RuntimeError, match="no world.json"):
        elastic._rendezvous(str(tmp_path), gen=1, old_rank=2,
                            old_world=4)


# -- peer-loss classification -----------------------------------------

def test_is_peer_loss_matches_gloo_and_verdict_errors():
    assert elastic.is_peer_loss(ValueError(
        "UNKNOWN: Gloo AllGather failed: [..] Connection closed by peer"))
    assert elastic.is_peer_loss(ValueError("Connection reset by peer"))
    assert elastic.is_peer_loss(faults.HealthTimeoutError("timed out"))
    assert elastic.is_peer_loss(faults.PeerFailureError("rank 1 fatal"))


def test_is_peer_loss_rejects_ordinary_errors():
    assert not elastic.is_peer_loss(None)
    assert not elastic.is_peer_loss(KeyError("params"))
    assert not elastic.is_peer_loss(ValueError("shape mismatch"))


# -- bounded health agreement (--health-timeout) ----------------------

def test_agree_health_times_out_on_hung_allgather(monkeypatch):
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda arr: time.sleep(30))
    t0 = time.monotonic()
    with pytest.raises(faults.HealthTimeoutError):
        runtime.agree_health(False, False, timeout_s=0.2)
    assert time.monotonic() - t0 < 5.0  # bounded, not the 30s hang


def test_agree_health_timeout_propagates_gather_error(monkeypatch):
    from jax.experimental import multihost_utils

    def _boom(arr):
        raise ValueError("Gloo AllGather failed: Connection closed "
                         "by peer")

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "process_allgather", _boom)
    with pytest.raises(ValueError, match="Gloo") as e:
        runtime.agree_health(False, False, timeout_s=5.0)
    assert elastic.is_peer_loss(e.value)


def test_agree_health_timeout_path_returns_flags(monkeypatch):
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda arr: np.array([[False, True, False],
                              [False, False, False]]))
    assert runtime.agree_health(False, True, timeout_s=5.0) \
        == (False, True, False)


def test_agree_health_gathers_peer_grow_vote(monkeypatch):
    # One rank saw a join claim (filesystem polling races are OR-repaired
    # by the vote): EVERY rank must come out agreeing to grow.
    from jax.experimental import multihost_utils

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        multihost_utils, "process_allgather",
        lambda arr: np.array([[False, False, True],
                              [False, False, False]]))
    assert runtime.agree_health(False, False, timeout_s=5.0,
                                grow=False) == (False, False, True)


def test_agree_health_single_process_short_circuits():
    assert runtime.agree_health(True, False, timeout_s=0.001) \
        == (True, False, False)
    assert runtime.agree_health(False, False, grow=True) \
        == (False, False, True)


# -- flags + module state ---------------------------------------------

def test_elastic_flags_parse():
    cfg = config_from_argv(["train", "-d", "/nodata", "--elastic",
                            "--health-timeout", "20",
                            "--max-reconfigures", "2",
                            "--elastic-dir", "/tmp/e"])
    assert cfg.elastic and cfg.health_timeout == 20.0
    assert cfg.max_reconfigures == 2 and cfg.elastic_dir == "/tmp/e"


def test_elastic_flags_default_off():
    cfg = config_from_argv(["train", "-d", "/nodata"])
    assert not cfg.elastic and cfg.health_timeout == 0.0
    assert cfg.elastic_dir is None
    assert elastic.default_elastic_dir("/runs/x") == "/runs/x/elastic"


def test_generation_state_and_reset():
    assert elastic.generation() == 0 and not elastic.reconfigured()
    elastic._generation, elastic._reconfigured = 2, True
    assert elastic.generation() == 2 and elastic.reconfigured()
    elastic._reset_for_tests()
    assert elastic.generation() == 0 and not elastic.reconfigured()


# -- shrunken-world re-derivation property ----------------------------

def _covered(num_samples: int, world: int, batch: int, epoch: int):
    """Union of every rank's valid (unmasked) sample indices."""
    out = []
    for rank in range(world):
        s = ShardedSampler(num_samples=num_samples, world_size=world,
                           rank=rank, batch_size=batch, seed=3)
        idx, valid = s.epoch_indices(epoch)
        out.extend(idx[valid].tolist())
    return out


@pytest.mark.parametrize("num_samples", [37, 101, 200])
@pytest.mark.parametrize("world", [4, 3, 2])
def test_shrunken_world_covers_dataset_exactly(num_samples, world):
    # The elastic resume re-derives samplers at world-1: every sample
    # must appear EXACTLY once per epoch — no duplicates from the
    # wraparound padding, no drops from the re-sliced rank space.
    for epoch in (0, 1, 5):
        shrunk = _covered(num_samples, world - 1, batch=4, epoch=epoch)
        assert sorted(shrunk) == list(range(num_samples))


def test_rederived_sampler_equals_fresh_sampler():
    # Survivor's re-derived (N-1)-world sampler vs one born at N-1:
    # identical plans, rank by rank — the property that makes the
    # elastic resume match an uninterrupted small-world run.
    for rank in range(2):
        a = ShardedSampler(num_samples=200, world_size=2, rank=rank,
                           batch_size=4, seed=0)
        b = ShardedSampler(num_samples=200, world_size=2, rank=rank,
                           batch_size=4, seed=0)
        for epoch in (0, 1, 2):
            ia, va = a.epoch_indices(epoch)
            ib, vb = b.epoch_indices(epoch)
        np.testing.assert_array_equal(ia, ib)
        np.testing.assert_array_equal(va, vb)


def _data_mesh(n: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:n]), (DATA_AXIS,))


def test_reshard_equals_loader_born_at_new_world():
    split = Split(
        images=np.arange(37 * 4, dtype=np.uint8).reshape(37, 2, 2),
        labels=np.arange(37, dtype=np.int32) % 10)
    old = ShardedLoader(split, _data_mesh(3), batch_per_replica=4,
                        shuffle=True, seed=5)
    fresh = ShardedLoader(split, _data_mesh(2), batch_per_replica=4,
                          shuffle=True, seed=5)
    shrunk = old.reshard(_data_mesh(2))
    assert shrunk.world == 2
    assert shrunk.batches_per_epoch == fresh.batches_per_epoch
    for epoch in (0, 1):
        for (ai, al, av), (bi, bl, bv) in zip(shrunk.epoch(epoch),
                                              fresh.epoch(epoch)):
            np.testing.assert_array_equal(np.asarray(ai),
                                          np.asarray(bi))
            np.testing.assert_array_equal(np.asarray(al),
                                          np.asarray(bl))
            np.testing.assert_array_equal(np.asarray(av),
                                          np.asarray(bv))


def test_reshard_covers_dataset_via_valid_mask():
    split = Split(
        images=np.arange(50 * 4, dtype=np.uint8).reshape(50, 2, 2),
        labels=np.arange(50, dtype=np.int32) % 10)
    loader = ShardedLoader(split, _data_mesh(4), batch_per_replica=4,
                           shuffle=True, seed=1).reshard(_data_mesh(3))
    seen = []
    for images, labels, valid in loader.epoch(0):
        img = np.asarray(images)
        v = np.asarray(valid)
        # row i of the split is filled with i*4..i*4+3, so the [0,0]
        # pixel // 4 recovers the sample index
        seen.extend((img[v][:, 0, 0] // 4).tolist())
    assert sorted(seen) == list(range(50))


# -- join claims + admission policy (grow) ----------------------------

def _join_claim(elastic_dir, jid: str) -> None:
    joins = elastic._joins_dir(str(elastic_dir))
    os.makedirs(joins, exist_ok=True)
    with open(os.path.join(joins, f"join-{jid}.json"), "w") as f:
        json.dump({"id": jid, "host": "h", "pid": 1}, f)


def test_request_join_roundtrips_through_pending(tmp_path):
    jid = elastic.request_join(str(tmp_path))
    assert elastic.pending_joins(str(tmp_path)) == [jid]


def test_duplicate_claim_files_dedupe_by_inner_id(tmp_path):
    # A torn retry can leave TWO files for one claimant; admission must
    # count the claimant once (dedupe by the id INSIDE the claim).
    _join_claim(tmp_path, "h-1")
    joins = elastic._joins_dir(str(tmp_path))
    with open(os.path.join(joins, "join-h-1-dup.json"), "w") as f:
        json.dump({"id": "h-1", "host": "h", "pid": 1}, f)
    assert elastic.pending_joins(str(tmp_path)) == ["h-1"]


def test_rank_join_fault_injects_duplicate_claim(tmp_path):
    # The injectable shape behind the test above: the rank_join kind at
    # site elastic.join copies the freshly written claim to a sibling.
    faults.install(faults.parse_plan("elastic.join:rank_join:0:1"))
    try:
        jid = elastic.request_join(str(tmp_path))
        joins = elastic._joins_dir(str(tmp_path))
        claims = [n for n in os.listdir(joins) if n.startswith("join-")]
        assert len(claims) == 2  # the claim and its injected duplicate
        assert elastic.pending_joins(str(tmp_path)) == [jid]
    finally:
        faults.install(None)


def test_torn_join_claim_is_skipped_loudly(tmp_path):
    joins = elastic._joins_dir(str(tmp_path))
    os.makedirs(joins)
    with open(os.path.join(joins, "join-h-2.json"), "w") as f:
        f.write('{"id": "h-')  # torn mid-write
    assert elastic.pending_joins(str(tmp_path)) == []


def test_answered_claims_leave_pending(tmp_path):
    _join_claim(tmp_path, "h-1")
    _join_claim(tmp_path, "h-2")
    elastic.decline_joins(str(tmp_path), [("h-1", "over target")], gen=1)
    assert elastic.pending_joins(str(tmp_path)) == ["h-2"]


def test_socket_sweep_spares_registered_app_ports():
    """The parked-generation socket sweep must not cut live HTTP
    traffic: an ESTABLISHED connection onto a registered application
    port (a serve replica's predict listener mid-request) survives the
    sweep, while an unregistered ephemeral<->ephemeral pair — the
    gloo-pair shape the sweep exists for — is closed at the fd level
    (ISSUE 19: zero-downtime through a reconfigure)."""
    import socket

    def pair():
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        port = lst.getsockname()[1]
        cli = socket.create_connection(("127.0.0.1", port))
        srv, _ = lst.accept()
        return lst, cli, srv, port

    app = pair()
    gloo = pair()
    saved = set(elastic._app_ports)
    try:
        elastic.register_app_ports(app[3], 0)   # 0: ignored
        elastic._close_stale_collective_sockets()
        app[1].sendall(b"ping")                 # still round-trips
        assert app[2].recv(4) == b"ping"
        for s in (gloo[1], gloo[2]):            # fds closed under us
            with pytest.raises(OSError):
                os.fstat(s.fileno())
    finally:
        elastic._app_ports.clear()
        elastic._app_ports.update(saved)
        for grp in (app, gloo):
            for s in grp[:3]:
                try:
                    s.close()
                except OSError:
                    pass


def test_join_policy_capacity_admits_all():
    admit, declined = elastic.evaluate_join_policy(
        2, ["b", "a"], "capacity", 1)
    assert admit == ["a", "b"] and declined == []


def test_join_policy_fixed_target_caps_admissions():
    admit, declined = elastic.evaluate_join_policy(
        2, ["a", "b", "c"], "fixed:4", 1)
    assert admit == ["a", "b"]
    assert [jid for jid, _ in declined] == ["c"]
    assert "fixed target 4" in declined[0][1]


def test_join_policy_declines_whole_batch_below_min_world():
    admit, declined = elastic.evaluate_join_policy(
        1, ["a", "b"], "capacity", 5)
    assert admit == []
    assert sorted(jid for jid, _ in declined) == ["a", "b"]
    assert "--elastic-min-world 5" in declined[0][1]


def test_join_policy_rejects_junk_target():
    with pytest.raises(ValueError, match="elastic-target"):
        elastic.evaluate_join_policy(1, [], "bogus", 1)
    with pytest.raises(ValueError, match="N must be"):
        elastic.evaluate_join_policy(1, [], "fixed:0", 1)


def test_wait_for_admission_decline_raises(tmp_path):
    elastic.decline_joins(str(tmp_path), [("h-9", "below the floor")],
                          gen=2)
    with pytest.raises(elastic.JoinDeclinedError, match="below the floor"):
        elastic.wait_for_admission(str(tmp_path), "h-9", timeout_s=2.0)


def test_late_joiner_times_out_loudly(tmp_path):
    # A claim dropped after the run ended (or with no --elastic run on
    # this dir at all) must fail bounded, not wait forever.
    with pytest.raises(TimeoutError, match="no admit/decline"):
        elastic.wait_for_admission(str(tmp_path), "h-9", timeout_s=0.3)


# -- grow rendezvous --------------------------------------------------

def test_grow_rendezvous_publishes_joiners_and_admit_marker(
        tmp_path, fast_settle):
    # Old world 2 fully alive (grow suppresses the nothing-died refusal)
    # plus one pending join: the coordinator publishes the joiner and
    # answers its claim with an admit marker carrying rank 2 of 3.
    _claim(str(tmp_path / "gen-1"), 1)
    _join_claim(tmp_path, "hostx-77")
    doc = elastic._rendezvous(str(tmp_path), gen=1, old_rank=0,
                              old_world=2, grow=True)
    assert doc["members"] == [0, 1]
    assert doc["joiners"] == ["hostx-77"]
    with open(os.path.join(elastic._joins_dir(str(tmp_path)),
                           "admit-hostx-77.json")) as f:
        admit = json.load(f)
    assert admit["generation"] == 1
    assert admit["new_rank"] == 2 and admit["new_world"] == 3
    assert admit["coordinator"] == doc["coordinator"]
    # The claim is now answered: no longer pending for later boundaries.
    assert elastic.pending_joins(str(tmp_path)) == []


def test_grow_rendezvous_declines_over_fixed_target(tmp_path,
                                                    fast_settle):
    # fixed:2 with a live world of 2: the claim gets a decline marker,
    # the published world is the identity (safe fallback, no new ranks).
    _claim(str(tmp_path / "gen-1"), 1)
    _join_claim(tmp_path, "hostx-88")
    doc = elastic._rendezvous(str(tmp_path), gen=1, old_rank=0,
                              old_world=2, grow=True, target="fixed:2")
    assert doc["members"] == [0, 1] and doc["joiners"] == []
    with open(os.path.join(elastic._joins_dir(str(tmp_path)),
                           "decline-hostx-88.json")) as f:
        assert "fixed target 2" in json.load(f)["reason"]


# -- grown-world re-derivation property -------------------------------

@pytest.mark.parametrize("num_samples", [37, 101, 200])
@pytest.mark.parametrize("world", [1, 2, 3])
def test_grown_world_covers_dataset_exactly(num_samples, world):
    # The N-1 exact-once property generalizes to N+1: after a grow the
    # resumed samplers at world+1 cover every sample exactly once per
    # epoch — no duplicates from the wraparound padding, no drops.
    for epoch in (0, 1, 5):
        grown = _covered(num_samples, world + 1, batch=4, epoch=epoch)
        assert sorted(grown) == list(range(num_samples))


def test_reshard_up_equals_loader_born_at_larger_world():
    split = Split(
        images=np.arange(37 * 4, dtype=np.uint8).reshape(37, 2, 2),
        labels=np.arange(37, dtype=np.int32) % 10)
    old = ShardedLoader(split, _data_mesh(2), batch_per_replica=4,
                        shuffle=True, seed=5)
    fresh = ShardedLoader(split, _data_mesh(3), batch_per_replica=4,
                          shuffle=True, seed=5)
    grown = old.reshard(_data_mesh(3))
    assert grown.world == 3
    assert grown.batches_per_epoch == fresh.batches_per_epoch
    for epoch in (0, 1):
        for (ai, al, av), (bi, bl, bv) in zip(grown.epoch(epoch),
                                              fresh.epoch(epoch)):
            np.testing.assert_array_equal(np.asarray(ai),
                                          np.asarray(bi))
            np.testing.assert_array_equal(np.asarray(al),
                                          np.asarray(bl))
            np.testing.assert_array_equal(np.asarray(av),
                                          np.asarray(bv))


# -- restore into a larger mesh ---------------------------------------

@pytest.fixture(scope="module")
def mlp_state():
    model = get_model("mlp", 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 4, False)
    engine = Engine(model, "mlp", get_loss_fn("cross_entropy"), tx,
                    mean=0.45, std=0.2, input_size=28,
                    half_precision=False)
    return engine, engine.init_state(jax.random.PRNGKey(7))


def test_checkpoint_restores_into_larger_mesh(tmp_path, mlp_state):
    # Shrink-then-grow resume: a snapshot saved from a 2-device mesh
    # restores into a 3-device mesh bit-identically — checkpoints are
    # replicated host state, so world size is not part of the format.
    engine, state = mlp_state
    placed = jax.device_put(state,
                            runtime.replicated_sharding(_data_mesh(2)))
    path = ckpt.checkpoint_path(str(tmp_path), "synthetic", "mlp", 3)
    ckpt.save_checkpoint(path, "mlp", placed, 3, 0.25)

    template = engine.init_state(jax.random.PRNGKey(99))  # differs
    restored, start_epoch, best = ckpt.load_checkpoint_with_fallback(
        path, template, str(tmp_path), "synthetic", "mlp")
    restored = jax.device_put(
        restored, runtime.replicated_sharding(_data_mesh(3)))
    assert start_epoch == 4 and best == 0.25
    saved_leaves = jax.tree_util.tree_leaves(placed.params)
    got_leaves = jax.tree_util.tree_leaves(restored.params)
    assert len(saved_leaves) == len(got_leaves)
    for a, b in zip(saved_leaves, got_leaves):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- grow flags -------------------------------------------------------

def test_grow_flags_parse():
    cfg = config_from_argv(["train", "-d", "/nodata", "--elastic",
                            "--elastic-join",
                            "--elastic-target", "fixed:4",
                            "--elastic-min-world", "2"])
    assert cfg.elastic_join and cfg.elastic_target == "fixed:4"
    assert cfg.elastic_min_world == 2


def test_grow_flags_default_off():
    cfg = config_from_argv(["train", "-d", "/nodata"])
    assert not cfg.elastic_join
    assert cfg.elastic_target == "capacity"
    assert cfg.elastic_min_world == 1
