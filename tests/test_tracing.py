"""Request tracing (distributedpytorch_tpu/tracing.py, ISSUE 16).

The span-chain contract first as pure units (sum(spans) == total_s by
construction, terminal spans for shed/timeout, exactly-once records),
then the wired tier: an in-process ServingTier with a stub infer_fn and
a live tracer must hand every client an ``X-DPT-Request-Id`` header,
land one reconciling record per request in trace-rank<N>.jsonl, and
give timeline a per-request track.  The full CLI path (main.py serve
with tracing always-on) is the serve gate's job.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributedpytorch_tpu import tracing
from distributedpytorch_tpu.serving import ServingTier

SHAPE = (4, 4)


@pytest.fixture
def tracer(tmp_path):
    """A live tracer writing under tmp_path, restored to the disabled
    default afterward so other tests see the zero-cost path."""
    t = tracing.configure(str(tmp_path), True, rank=0)
    yield t
    tracing.configure(".", False)


def _stub_infer(arr):
    return (arr.reshape(arr.shape[0], -1).max(axis=1).astype(np.int32),
            np.full((arr.shape[0],), 0.5, np.float64))


def _make_tier(**kw):
    args = dict(infer_fn=_stub_infer, sample_shape=SHAPE,
                sample_dtype=np.uint8, buckets=(1, 4), max_queue=8,
                max_latency_s=0.01, port=0, request_timeout_s=5.0)
    args.update(kw)
    return ServingTier(**args)


def _post(port, image, timeout=5.0):
    """(status, body, headers) — the traced variant of the round trip."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"image": image}).encode())
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


# -- the span chain as a unit ------------------------------------------

def test_span_chain_sums_to_total_and_ids_are_deterministic(tracer):
    t1 = tracer.start()
    t2 = tracer.start()
    assert (t1.id, t2.id) == ("r0-000001", "r0-000002")
    t1.mark_admitted()
    time.sleep(0.002)
    t1.mark_dequeued()
    t1.mark_infer_start(4)
    t1.mark_infer_end()
    t1.note_latency(2.5)
    t1.finish(200, "answered")
    t2.finish(503, "shed", queue_depth=8)
    recs = tracing.load_records(tracer.path.rsplit("/", 1)[0])
    assert [r["id"] for r in recs] == ["r0-000001", "r0-000002"]
    answered, shed = recs
    assert set(answered["spans"]) == {"queue_wait", "batch_form",
                                      "infer", "respond"}
    assert answered["spans"]["queue_wait"] >= 0.002
    assert answered["bucket"] == 4 and answered["latency_ms"] == 2.5
    assert shed["outcome"] == "shed" and "shed" in shed["spans"]
    assert shed["attrs"]["queue_depth"] == 8
    assert tracing.reconcile(recs) == []


def test_reconcile_flags_torn_chain_and_latency_mismatch(tracer):
    t = tracer.start()
    t.mark_admitted()
    t.mark_dequeued()
    t.mark_infer_start(1)
    t.mark_infer_end()
    t.note_latency(5000.0)  # nothing slept 5s: must not reconcile
    t.finish(200, "answered")
    recs = tracing.load_records(str(tracer.path.rsplit("/", 1)[0]))
    problems = tracing.reconcile(recs)
    assert len(problems) == 1 and "latency_ms" in problems[0]
    torn = dict(recs[0], total_s=recs[0]["total_s"] + 1.0)
    assert any("torn" in p for p in tracing.reconcile([torn]))


def test_finish_writes_exactly_once(tracer):
    """The 504-then-late-complete race: the handler's timeout record
    wins and the driver's later finish is a no-op."""
    t = tracer.start()
    t.finish(504, "timeout")
    t.finish(200, "answered")
    recs = tracing.load_records(str(tracer.path.rsplit("/", 1)[0]))
    assert len(recs) == 1 and recs[0]["outcome"] == "timeout"


def test_disabled_tracer_is_free_and_sink_failure_degrades(tmp_path):
    assert tracing.Tracer(enabled=False).start() is None
    bad = tracing.Tracer(enabled=True,
                         rsl_path=str(tmp_path / "file-not-dir"))
    (tmp_path / "file-not-dir").write_text("occupied")
    t = bad.start()
    t.finish(200, "answered")  # must not raise
    assert bad.write_errors == 1
    t2 = bad.start()
    assert t2 is not None  # still serving, just not recording


def test_rank_of_id():
    assert tracing.rank_of_id("r1-000007") == 1
    assert tracing.rank_of_id("garbage") is None
    assert tracing.rank_of_id("") is None


# -- wired through the tier --------------------------------------------

def test_tier_returns_request_id_header_and_reconciling_records(
        tmp_path, tracer):
    tier = _make_tier()
    tier.start()
    driver = threading.Thread(target=tier.run, daemon=True)
    driver.start()
    try:
        img = np.full(SHAPE, 7, np.uint8).tolist()
        ids = []
        for _ in range(3):
            status, body, headers = _post(tier.port, img)
            assert status == 200
            assert headers["X-DPT-Request-Id"].startswith("r0-")
            ids.append(headers["X-DPT-Request-Id"])
        assert len(set(ids)) == 3
    finally:
        tier.close()
        driver.join(timeout=5)
    recs = tracing.load_records(str(tmp_path))
    answered = [r for r in recs if r["outcome"] == "answered"]
    assert sorted(r["id"] for r in answered) == sorted(ids)
    assert tracing.reconcile(recs) == []
    for r in answered:
        assert set(r["spans"]) == {"queue_wait", "batch_form", "infer",
                                   "respond"}


def test_tier_shed_path_gets_terminal_span_and_header(tmp_path, tracer):
    tier = _make_tier(max_queue=1)
    tier.start()  # driver deliberately absent: the queue fills
    try:
        img = np.zeros(SHAPE, np.uint8).tolist()
        results = []
        threads = [threading.Thread(
            target=lambda: results.append(_post(tier.port, img, 5.0)))
            for _ in range(3)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5.0
        while len(results) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(results) >= 2
        for status, body, headers in results:
            assert status == 503
            assert headers["X-DPT-Request-Id"].startswith("r0-")
    finally:
        tier.close()
    shed = [r for r in tracing.load_records(str(tmp_path))
            if r["outcome"] == "shed"]
    assert len(shed) >= 2
    for r in shed:
        assert "shed" in r["spans"] and r["status"] == 503
        assert r["attrs"]["queue_depth"] >= 1


def test_timeline_gains_request_track(tmp_path, tracer):
    from distributedpytorch_tpu import timeline

    t = tracer.start()
    t.mark_admitted()
    t.mark_dequeued()
    t.mark_infer_start(1)
    t.mark_infer_end()
    t.note_latency(0.1)
    t.finish(200, "answered")
    tel_dir = tmp_path / "telemetry"
    tel_dir.mkdir()
    (tel_dir / "rank0.jsonl").write_text(json.dumps({
        "kind": "event", "name": "run_start", "rank": 0,
        "ts": time.time(), "mono": time.monotonic()}) + "\n")
    result = timeline.build_timeline(str(tmp_path))
    reqs = [e for e in result["trace"]["traceEvents"]
            if e.get("cat") == "request"]
    assert [e["name"] for e in reqs] == ["queue_wait", "batch_form",
                                         "infer", "respond"]
    assert all(e["tid"] == timeline._TID_REQUESTS for e in reqs)
    assert reqs[0]["args"]["id"] == "r0-000001"
    # the chain property makes the slices tile: each starts where the
    # previous ended
    for a, b in zip(reqs, reqs[1:]):
        assert b["ts"] == pytest.approx(a["ts"] + a["dur"], abs=1.0)
    names = [e["args"]["name"] for e in result["trace"]["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"
             and e["pid"] == 0]
    assert "requests" in names


def test_records_carry_serving_lineage_when_set(tracer):
    """ISSUE 19 satellite: after set_lineage (startup or hot-swap),
    every record names WHICH checkpoint version answered."""
    t1 = tracer.start()
    t1.finish(200, "answered")
    tracer.set_lineage("c0ffee" * 10 + "beef")
    t2 = tracer.start()
    t2.finish(200, "answered")
    recs = tracing.load_records(tracer.path.rsplit("/", 1)[0])
    assert "lineage" not in recs[0]
    assert recs[1]["lineage"] == "c0ffeec0ffee"[:12]
    tracer.set_lineage(None)
    t3 = tracer.start()
    t3.finish(200, "answered")
    recs = tracing.load_records(tracer.path.rsplit("/", 1)[0])
    assert "lineage" not in recs[2]
