"""Checkpoints under real multi-process concurrency and across process
topologies / formats (VERDICT round-2 items #5 and #6).

World launches (each a fresh set of python subprocesses over gloo):

  A  2 procs x 2 devs | orbax, model-parallel=2, 2 epochs   (baseline)
  B  2 procs x 2 devs | orbax, mp=2, 1 epoch
  C  2 procs x 2 devs | orbax, mp=2, resume B -> epoch 2
  D  1 proc  x 4 devs | msgpack, 1 epoch
  E  2 procs x 2 devs | resume D's msgpack, save orbax -> epoch 2
  F  1 proc  x 4 devs | orbax, 1 epoch
  G  2 procs x 2 devs | resume F's orbax, save msgpack -> epoch 2
  H  1 proc  x 4 devs | msgpack, 2 epochs                   (mp=1 baseline)
  P  2 procs x 2 devs | orbax, mp=2, SIGTERM to ONE process mid-run,
     then a resume world from the checkpoint the preempted run wrote

Asserted:
  * C == A: the multi-process orbax save (every host writing shards into
    the SAME directory through the checkpoint.py barriers) round-trips
    training state exactly — the "validated single-host only" caveat is
    retired by this test;
  * E == H and G == H: checkpoints written on a 1x4 world restore on a
    2x2 world (and vice versa formats msgpack<->orbax both directions) —
    the "loads anywhere" contract (checkpoint.py docstring) across
    topologies, not just same-topology;
  * orbax rotation under concurrency: only the newest rolling directory
    remains, bestmodel dir valid (meta.json present);
  * P: a SIGTERM to one of two hosts yields clean exits (rc 0) on both, a
    complete orbax checkpoint from the agreed epoch boundary, and a
    successful multi-process resume continuing at the next epoch.
"""

import json
import os
import signal
import sys

import numpy as np
import pytest

from tests._subproc import (REPO, await_all, free_port, launch_logged,
                            wait_for_epoch_line)

# subprocess worlds / full CLI chains: the slow tier (scripts/gate.sh runs -m 'not slow')
pytestmark = pytest.mark.slow

CHILD = os.path.join(REPO, "tests", "_ckpt_child.py")


def _launch_world(tmp, name, nproc, devices, *, epochs, fmt, mp=1,
                  resume=None):
    """Launch one world (nproc processes) and wait for clean exits."""
    rsl = os.path.join(tmp, name)
    port = free_port()
    procs, logs = [], []
    for r in range(nproc):
        cmd = [sys.executable, CHILD, "--nproc", str(nproc),
               "--pid", str(r), "--devices-per-proc", str(devices),
               "--rsl", rsl, "--out", _out(tmp, name, r),
               "--epochs", str(epochs), "--ckpt-format", fmt,
               "--model-parallel", str(mp)]
        if nproc > 1:
            cmd += ["--coord", f"localhost:{port}"]
        if resume:
            cmd += ["--resume-from", resume]
        log = os.path.join(tmp, f"{name}_r{r}.log")
        logs.append(log)
        procs.append(launch_logged(cmd, log))
    await_all(procs, logs)
    return rsl


def _out(tmp, name, rank):
    return os.path.join(tmp, f"{name}_out{rank}.npz")


def _params(tmp, name, rank=0):
    return dict(np.load(_out(tmp, name, rank)))


def _ckpt(rsl, epoch):
    return os.path.join(rsl, f"checkpoint-synthetic-mlp-{epoch:03d}.ckpt")


@pytest.fixture(scope="module")
def runs(tmp_path_factory):
    tmp = str(tmp_path_factory.mktemp("ckpt_topo"))

    rsl_a = _launch_world(tmp, "A", 2, 2, epochs=2, fmt="orbax", mp=2)
    rsl_b = _launch_world(tmp, "B", 2, 2, epochs=1, fmt="orbax", mp=2)
    _launch_world(tmp, "C", 2, 2, epochs=2, fmt="orbax", mp=2,
                  resume=_ckpt(rsl_b, 0))
    rsl_d = _launch_world(tmp, "D", 1, 4, epochs=1, fmt="msgpack")
    _launch_world(tmp, "E", 2, 2, epochs=2, fmt="orbax",
                  resume=_ckpt(rsl_d, 0))
    rsl_f = _launch_world(tmp, "F", 1, 4, epochs=1, fmt="orbax")
    _launch_world(tmp, "G", 2, 2, epochs=2, fmt="msgpack",
                  resume=_ckpt(rsl_f, 0))
    _launch_world(tmp, "H", 1, 4, epochs=2, fmt="msgpack")
    return tmp, rsl_a


def test_multiprocess_orbax_resume_matches_continuous(runs):
    tmp, _ = runs
    a, c = _params(tmp, "A"), _params(tmp, "C")
    assert set(a) == set(c) and len(a) > 0
    for k in a:
        np.testing.assert_allclose(c[k], a[k], rtol=1e-6, atol=1e-7,
                                   err_msg=f"{k}: resumed != continuous")


def test_multiprocess_ranks_agree(runs):
    tmp, _ = runs
    for name in ("A", "C", "E", "G"):
        r0, r1 = _params(tmp, name, 0), _params(tmp, name, 1)
        for k in r0:
            np.testing.assert_array_equal(
                r0[k], r1[k], err_msg=f"{name}/{k} differs across ranks")


def test_cross_topology_msgpack_to_orbax(runs):
    tmp, _ = runs
    e, h = _params(tmp, "E"), _params(tmp, "H")
    for k in e:
        np.testing.assert_allclose(
            e[k], h[k], rtol=2e-5, atol=2e-6,
            err_msg=f"{k}: 1x4-saved msgpack resumed on 2x2 != continuous")


def test_cross_topology_orbax_to_msgpack(runs):
    tmp, _ = runs
    g, h = _params(tmp, "G"), _params(tmp, "H")
    for k in g:
        np.testing.assert_allclose(
            g[k], h[k], rtol=2e-5, atol=2e-6,
            err_msg=f"{k}: 1x4-saved orbax resumed on 2x2 != continuous")


def test_orbax_rotation_and_layout_under_concurrency(runs):
    _, rsl_a = runs
    entries = sorted(os.listdir(rsl_a))
    rolling = [e for e in entries if e.startswith("checkpoint-")]
    # rotation deleted epoch 000; the epoch-001 directory remains
    assert rolling == ["checkpoint-synthetic-mlp-001.ckpt"], entries
    best = os.path.join(rsl_a, "bestmodel-synthetic-mlp.ckpt")
    assert os.path.isdir(best)
    with open(os.path.join(best, "meta.json")) as f:
        meta = json.load(f)
    assert meta["model_name"] == "mlp"
    # no stale .tmp staging dirs left behind by the barrier'd swap
    assert not [e for e in entries if e.endswith(".tmp")], entries


def test_sigterm_one_host_then_multiprocess_resume(tmp_path):
    """Kill-and-resume under orbax + model-parallel: SIGTERM ONE host of
    two; both must exit 0 after writing the agreed-epoch checkpoint; a
    fresh 2-process world resumes it for one more epoch."""
    tmp = str(tmp_path)
    rsl = os.path.join(tmp, "P")
    port = free_port()
    logs = [os.path.join(tmp, f"P_r{r}.log") for r in range(2)]
    procs = [launch_logged(
        [sys.executable, CHILD, "--coord", f"localhost:{port}",
         "--nproc", "2", "--pid", str(r), "--devices-per-proc", "2",
         "--rsl", rsl, "--out", _out(tmp, "P", r),
         "--epochs", "100", "--ckpt-format", "orbax",
         "--model-parallel", "2"],
        logs[r]) for r in range(2)]
    try:
        wait_for_epoch_line(os.path.join(rsl, "test.log"), procs,
                            proc_logs=logs)
        procs[1].send_signal(signal.SIGTERM)
        for p in procs:
            p.wait(timeout=600)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, p in enumerate(procs):
        assert p.returncode == 0, \
            f"rank {r}:\n{open(logs[r]).read()[-3000:]}"
    hist = json.load(open(_out(tmp, "P", 0) + ".history.json"))
    assert hist["preempted"]
    stopped = hist["history"][-1]["epoch"]

    rolling = [e for e in os.listdir(rsl) if e.startswith("checkpoint-")]
    assert rolling == [f"checkpoint-synthetic-mlp-{stopped:03d}.ckpt"], \
        rolling

    # resume the preempted checkpoint on a fresh 2-process world
    _launch_world(tmp, "PR", 2, 2, epochs=stopped + 2, fmt="orbax", mp=2,
                  resume=os.path.join(rsl, rolling[0]))
    hist2 = json.load(open(_out(tmp, "PR", 0) + ".history.json"))
    resumed_epochs = [h["epoch"] for h in hist2["history"]]
    assert resumed_epochs and resumed_epochs[0] == stopped + 1, hist2
