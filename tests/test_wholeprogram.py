"""Whole-program core (analysis/wholeprogram.py): symbol table, call
graph, lock inventory, and handler registry pinned on a small fixture
package — the resolution layer rules 17-19 stand on.

The fixture exercises the repo's real idioms: ``from x import y`` at
module level, function-local imports, factory functions with return
annotations (``def get() -> Tracer``), annotated module globals,
method calls on ``self`` and on typed locals, and ``signal.signal``
registration of a bound method.
"""

import textwrap

from distributedpytorch_tpu.analysis.core import lint_paths, load_project
from distributedpytorch_tpu.analysis.wholeprogram import (WholeProgram,
                                                          display,
                                                          module_name)

_UTIL = """
    import threading

    _lock = threading.Lock()
    _rlock = threading.RLock()

    def helper(x):
        return x + 1

    class Sink:
        def __init__(self):
            self._buf = []
            self._cond = threading.Condition(threading.Lock())

        def write(self, item):
            with _lock:
                self._buf.append(item)

        def flush(self):
            self.write(None)

    def get() -> Sink:
        return Sink()
"""

_APP = """
    import signal
    from util import get, helper
    from util import Sink

    _sink: Sink = None

    def work(x):
        y = helper(x)
        s = get()
        s.flush()                  # typed local -> Sink.flush
        get().write(y)             # chained factory -> Sink.write
        return y

    class Shutdown:
        def _handle(self, signum, frame):
            work(0)

        def install(self):
            signal.signal(signal.SIGTERM, self._handle)
"""


def _build(tmp_path):
    for name, src in (("util.py", _UTIL), ("app.py", _APP)):
        (tmp_path / name).write_text(textwrap.dedent(src))
    project, findings = load_project([str(tmp_path)],
                                     root=str(tmp_path))
    assert findings == []
    return WholeProgram(project)


def test_module_name_mapping():
    assert module_name("distributedpytorch_tpu/faults.py") \
        == "distributedpytorch_tpu.faults"
    assert module_name("distributedpytorch_tpu/analysis/__init__.py") \
        == "distributedpytorch_tpu.analysis"
    assert module_name("main.py") == "main"


def test_import_and_method_resolution(tmp_path):
    wp = _build(tmp_path)
    callees = wp.callees.get("app:work", set())
    assert "util:helper" in callees          # from util import helper
    assert "util:Sink.flush" in callees      # typed local
    assert "util:Sink.write" in callees      # chained factory call


def test_transitive_closure_crosses_methods(tmp_path):
    wp = _build(tmp_path)
    # work -> flush -> write: write reachable transitively
    assert "util:Sink.write" in wp.transitive_callees("app:work")
    # handler -> work -> ... -> write
    assert "util:Sink.write" \
        in wp.transitive_callees("app:Shutdown._handle")


def test_lock_inventory_kinds_and_reentrancy(tmp_path):
    wp = _build(tmp_path)
    assert wp.locks["util:_lock"] == "Lock"
    assert wp.locks["util:_rlock"] == "RLock"
    assert wp.locks["util:Sink._cond"] == "Condition(Lock)"
    assert wp.non_reentrant("util:_lock")
    assert wp.non_reentrant("util:Sink._cond")
    assert not wp.non_reentrant("util:_rlock")


def test_signal_handler_registry(tmp_path):
    wp = _build(tmp_path)
    assert [h for h, _mod, _line in wp.handlers] \
        == ["app:Shutdown._handle"]


def test_call_path_names_the_chain(tmp_path):
    wp = _build(tmp_path)
    path = wp.call_path("app:Shutdown._handle", {"util:Sink.write"})
    assert path[0] == "app:Shutdown._handle"
    assert path[-1] == "util:Sink.write"


def test_display_strips_package_prefix():
    assert display("distributedpytorch_tpu.faults:FaultPlan.fire") \
        == "faults.FaultPlan.fire"


def test_fixture_package_flags_handler_lock(tmp_path):
    """End to end: the fixture's handler reaches util._lock through
    work -> Sink.write, and rule 18 reports it."""
    for name, src in (("util.py", _UTIL), ("app.py", _APP)):
        (tmp_path / name).write_text(textwrap.dedent(src))
    findings, _ = lint_paths([str(tmp_path)], root=str(tmp_path))
    msgs = [f.message for f in findings
            if f.rule == "lock-order-cycle"]
    assert any("signal handler" in m and "_lock" in m for m in msgs)
