"""Switch mixture-of-experts / expert parallelism (models/moe.py) — the
EP leg of the taxonomy (ABSENT in the reference, SURVEY §2 checklist).

Pinned: the dense-dispatch einsum path equals a direct per-token
computation through each token's argmax expert (capacity permitting);
dropped tokens contribute exactly zero; the expert-sharded program
equals the replicated one; the load-balancing loss reaches the
training loss; and the CLI trains end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu import runtime
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.models.moe import SwitchMLP

DIM, HID, E = 16, 32, 4


def _mlp(capacity_factor, ep_constrain=None):
    return SwitchMLP(dim=DIM, hidden=HID, num_experts=E,
                     capacity_factor=capacity_factor,
                     dtype=jnp.float32, ep_constrain=ep_constrain)


def _direct_reference(params, x):
    """Every token through its argmax expert's FFN, scaled by the gate —
    what the dispatch/combine einsums must reproduce when capacity is
    unlimited."""
    b, s, d = x.shape
    tokens = x.reshape(-1, d)
    r = params["router"]
    logits = tokens @ r["kernel"] + r["bias"]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    outs = []
    for n in range(tokens.shape[0]):
        e = int(expert[n])
        h = jax.nn.gelu(tokens[n] @ params["w_up"][e] + params["b_up"][e])
        outs.append((h @ params["w_down"][e] + params["b_down"][e])
                    * gate[n])
    return jnp.stack(outs).reshape(b, s, d)


def test_dispatch_matches_direct_per_token_compute():
    mlp = _mlp(capacity_factor=float(E))  # capacity >= all tokens
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, DIM), jnp.float32)
    params = mlp.init({"params": jax.random.PRNGKey(1)}, x)["params"]
    got = mlp.apply({"params": params}, x)
    want = _direct_reference(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_dropped_tokens_contribute_exactly_zero():
    """capacity_factor tiny -> one slot per expert: at most E tokens in
    the whole batch produce output; every other row is exactly 0."""
    mlp = _mlp(capacity_factor=1e-6)
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 16, DIM), jnp.float32)
    params = mlp.init({"params": jax.random.PRNGKey(3)}, x)["params"]
    y = np.asarray(mlp.apply({"params": params}, x)).reshape(-1, DIM)
    nonzero_rows = np.abs(y).sum(axis=-1) > 0
    assert nonzero_rows.sum() <= E
    assert (np.abs(y[~nonzero_rows]) == 0).all()


def test_per_group_capacity_is_linear_in_tokens():
    """Round-4 advisor (medium): dispatch memory must scale linearly in
    total tokens, not quadratically.  Capacity is per GROUP of batch
    rows: doubling the batch doubles the group count but leaves the
    per-group capacity (and so the dispatch mask's trailing C dim)
    unchanged once groups are full-size."""
    from distributedpytorch_tpu.models import moe

    # once b*s > GROUP_TOKENS, capacity stops growing with batch
    s = 8
    rows = moe._rows_per_group(1024, s)
    assert rows * s <= moe.GROUP_TOKENS
    assert moe._rows_per_group(2048, s) == rows  # cap fixed, groups 2x
    # rows always divides b, with at least one row
    assert moe._rows_per_group(7, 5000) == 1
    for b in (1, 6, 511):
        assert b % moe._rows_per_group(b, 3) == 0

    # grouped dispatch (several groups) still equals the per-token
    # reference when per-group capacity is ample
    mlp = _mlp(capacity_factor=float(E))
    x = jax.random.normal(jax.random.PRNGKey(7), (6, 4, DIM), jnp.float32)
    params = mlp.init({"params": jax.random.PRNGKey(8)}, x)["params"]
    orig = moe.GROUP_TOKENS
    moe.GROUP_TOKENS = 8  # force 3 groups of 2 rows
    try:
        got = mlp.apply({"params": params}, x)
    finally:
        moe.GROUP_TOKENS = orig
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_direct_reference(params, x)),
                               rtol=2e-5, atol=2e-5)


def test_expert_sharded_equals_replicated():
    """EP: the same params with the expert axis pinned to the 'model'
    mesh axis produce the same outputs — sharding constraints change
    layout, never math (same contract as TP)."""
    from distributedpytorch_tpu.parallel import make_tp_constrain

    mesh = runtime.make_mesh(model_parallel=4)
    x = jax.random.normal(jax.random.PRNGKey(4), (8, 8, DIM), jnp.float32)
    plain = _mlp(capacity_factor=2.0)
    params = plain.init({"params": jax.random.PRNGKey(5)}, x)["params"]
    want = plain.apply({"params": params}, x)
    sharded = _mlp(capacity_factor=2.0,
                   ep_constrain=make_tp_constrain(mesh))
    with mesh:
        got = jax.jit(
            lambda p, a: sharded.apply({"params": p}, a))(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_expert_sharded_equals_replicated_multi_group():
    """EP x grouped dispatch: with SEVERAL dispatch groups (the round-5
    linear-cost formulation) the expert-sharded program still equals the
    replicated one — group axis sharding propagates from the batch while
    experts ride 'model'."""
    from distributedpytorch_tpu.models import moe
    from distributedpytorch_tpu.parallel import make_tp_constrain

    mesh = runtime.make_mesh(model_parallel=2)
    x = jax.random.normal(jax.random.PRNGKey(14), (16, 8, DIM),
                          jnp.float32)
    orig = moe.GROUP_TOKENS
    moe.GROUP_TOKENS = 32  # force 4 groups of 4 rows
    try:
        plain = _mlp(capacity_factor=2.0)
        params = plain.init({"params": jax.random.PRNGKey(15)},
                            x)["params"]
        want = plain.apply({"params": params}, x)
        sharded = _mlp(capacity_factor=2.0,
                       ep_constrain=make_tp_constrain(mesh))
        with mesh:
            got = jax.jit(
                lambda p, a: sharded.apply({"params": p}, a))(params, x)
    finally:
        moe.GROUP_TOKENS = orig
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_load_balance_loss_reaches_training_loss():
    """The sown aux loss must change the optimized scalar: the train-mode
    loss differs from the pure CE loss by the load-balance term, and the
    router receives gradient."""
    from distributedpytorch_tpu.ops.losses import get_loss_fn
    from distributedpytorch_tpu.train.engine import Engine, make_optimizer

    mesh = runtime.make_mesh(model_parallel=2)
    model = get_model("vit", 10, half_precision=False, moe_experts=4,
                      mesh=mesh)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 10, False)
    eng = Engine(model, "vit", get_loss_fn("cross_entropy"), tx,
                 mean=0.45, std=0.2, input_size=28, half_precision=False)
    state = eng.init_state(jax.random.PRNGKey(0))
    router_before = jax.device_get(
        state.params["block0"]["moe"]["router"]["kernel"])

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (8, 28, 28), np.uint8)
    labels = rng.integers(0, 10, (8,)).astype(np.int32)
    valid = np.ones(8, bool)
    # the sown aux loss is collected in train mode only and must be > 0
    # (the switch load-balance term is E * sum f_e P_e >= 1 scaled by
    # the coefficient, and exactly 0 when not wired through _apply)
    from distributedpytorch_tpu.data import augment

    imgs_f = augment.eval_transform(jnp.asarray(imgs), 0.45, 0.2, 28,
                                    out_dtype=jnp.float32)
    _, _, aux_train = eng._apply(state.params, state.batch_stats, imgs_f,
                                 True, jax.random.PRNGKey(2))
    _, _, aux_eval = eng._apply(state.params, state.batch_stats, imgs_f,
                                False, jax.random.PRNGKey(2))
    assert float(aux_train) > 0.0
    assert float(aux_eval) == 0.0

    new_state, metrics = eng.train_step(
        state, jnp.asarray(imgs), jnp.asarray(labels), jnp.asarray(valid),
        jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))
    router_after = jax.device_get(
        new_state.params["block0"]["moe"]["router"]["kernel"])
    assert not np.allclose(router_before, router_after)


@pytest.mark.slow
def test_moe_cli_trains_and_validates(tmp_path):
    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    res = run_train(Config(
        action="train", data_path="/tmp/nodata",
        rsl_path=str(tmp_path / "moe"), dataset="synthetic",
        model_name="vit", batch_size=4, nb_epochs=1, debug=True,
        half_precision=False, model_parallel=2, moe_experts=4))
    h = res["history"][0]
    assert np.isfinite(h["train_loss"]) and np.isfinite(h["valid_loss"])

    with pytest.raises(ValueError, match="moe-experts"):
        run_train(Config(
            action="train", data_path="/tmp/nodata",
            rsl_path=str(tmp_path / "bad"), dataset="synthetic",
            model_name="cnn", batch_size=4, nb_epochs=1, debug=True,
            moe_experts=4))
    with pytest.raises(ValueError, match="exclusive"):
        get_model("vit", 10, moe_experts=4, tensor_parallel=True,
                  mesh=runtime.make_mesh(model_parallel=2))
    # E not divisible by the model axis would silently replicate every
    # expert — must refuse instead
    with pytest.raises(ValueError, match="divisible"):
        get_model("vit", 10, moe_experts=3,
                  mesh=runtime.make_mesh(model_parallel=2))
