"""Shared scaffolding for tests that drive training in subprocesses."""

import os
import socket
import subprocess
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def await_all(procs, log_paths, timeout: float = 1800.0) -> None:
    """Wait for every child against ONE shared deadline; on nonzero exit
    or timeout, raise with the tail of the child's log; always kill
    stragglers."""
    deadline = time.monotonic() + timeout
    try:
        for r, p in enumerate(procs):
            try:
                rc = p.wait(timeout=max(1.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                raise AssertionError(
                    f"child {r} still running at deadline\n"
                    f"{_tail(log_paths[r])}") from None
            if rc != 0:
                raise AssertionError(
                    f"child {r} exited rc={rc}\n{_tail(log_paths[r])}")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def _tail(path: str, n: int = 4000) -> str:
    try:
        return open(path).read()[-n:]
    except OSError:
        return "<no log>"


def launch_logged(cmd, log_path: str) -> subprocess.Popen:
    """Start a child with stdout/stderr appended to ``log_path``.

    ALWAYS a file, never subprocess.PIPE: an undrained pipe backpressures
    a chatty child into blocking on print — for distributed children that
    stalls their collectives and deadlocks every process in the world.
    """
    out = open(log_path, "ab")
    return subprocess.Popen(cmd, cwd=REPO, env=child_env(),
                            stdout=out, stderr=out)


def child_env() -> dict:
    """Env for a child that pins its own JAX platform: drop anything the
    parent pytest session (conftest) injected, put the repo on the path."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def wait_for_epoch_line(log: str, procs, timeout: float = 300.0,
                        proc_logs=()) -> None:
    """Block until a completed-epoch line appears in ``log``; raise with
    the child's output if any proc dies first."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(log) and "Epoch: 0" in open(log).read():
            return
        for i, p in enumerate(procs):
            if p.poll() is not None:
                detail = (open(proc_logs[i]).read()[-3000:]
                          if i < len(proc_logs) else "")
                raise AssertionError(
                    f"child {i} exited rc={p.returncode}\n{detail}")
        time.sleep(1)
    raise AssertionError(f"no epoch completed within {timeout:.0f}s")
