"""Shared scaffolding for tests that drive training in subprocesses."""

import os
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def child_env() -> dict:
    """Env for a child that pins its own JAX platform: drop anything the
    parent pytest session (conftest) injected, put the repo on the path."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def wait_for_epoch_line(log: str, procs, timeout: float = 300.0) -> None:
    """Block until a completed-epoch line appears in ``log``; raise with
    the child's output if any proc dies first."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(log) and "Epoch: 0" in open(log).read():
            return
        for p in procs:
            if p.poll() is not None:
                raise AssertionError(p.communicate()[0].decode()[-3000:])
        time.sleep(1)
    raise AssertionError(f"no epoch completed within {timeout:.0f}s")
