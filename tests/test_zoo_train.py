"""Every registry model executes one REAL optimization step.

Round-1 gap: the heavy architectures were only ever shape-checked with
``jax.eval_shape`` — runtime-only failure modes (dropout rng wiring, BN
mutable collections under ``value_and_grad``, inception's train-mode
(logits, aux) tuple through the engine, bf16 numerics) were unexercised.
This runs the full engine step — on-device augmentation, forward, backward,
update — with real numerics for all 8 models (ref utils.py:38-105), at
reduced input sizes where the topology allows (adaptive pooling makes the
224/299 models size-agnostic) so the suite stays tractable on CPU.
"""

import jax
import numpy as np
import pytest

from distributedpytorch_tpu import models
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.train.engine import Engine, make_optimizer

# subprocess worlds / full CLI chains: the slow tier (scripts/gate.sh runs -m 'not slow')
pytestmark = pytest.mark.slow

# Reduced sizes for CPU tractability; the real registry sizes (224/299,
# ref utils.py:24-36) are covered by the shape suite in test_models.py.
# Inception must run at native 299: its aux head needs a 17x17 feature map
# (enforced with a trace-time error — see models/inception.py AuxHead).
_TEST_SIZES = {
    "cnn": 28, "mlp": 28, "resnet": 64, "alexnet": 64, "vgg": 64,
    "squeezenet": 64, "densenet": 64, "inception": 299, "vit": 28,
}


def _flat(params):
    return np.concatenate([np.asarray(p, np.float64).ravel()
                           for p in jax.tree_util.tree_leaves(params)])


@pytest.mark.parametrize("name", sorted(models.MODEL_REGISTRY))
def test_one_real_train_step(name):
    size = _TEST_SIZES[name]
    model = models.get_model(name, 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, steps_per_epoch=4,
                        feature_extract=False)
    engine = Engine(model, name, get_loss_fn("cross_entropy"), tx,
                    mean=0.45, std=0.2, input_size=size,
                    half_precision=False)
    state = engine.init_state(jax.random.PRNGKey(0))
    before = _flat(state.params)
    aux_before = (_flat(state.params["AuxHead_0"])
                  if name in models.registry.AUX_LOGIT_MODELS else None)

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(2, size, size), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(2,)).astype(np.int32)
    valid = np.ones(2, dtype=bool)

    state, metrics = engine.train_step(state, images, labels, valid,
                                       jax.random.PRNGKey(1))
    assert int(state.step) == 1
    assert np.isfinite(float(metrics["loss"])), name
    after = _flat(state.params)
    assert not np.allclose(before, after), f"{name}: params did not change"

    ev = engine.eval_step(state, images, labels, valid)
    assert np.isfinite(float(ev["loss_numer"])), name
    assert float(ev["valid"]) == 2.0

    if aux_before is not None:
        # the aux head must also receive gradient (loss1 + 0.4*loss2,
        # ref classif.py:49-53)
        aux_after = _flat(state.params["AuxHead_0"])
        assert not np.allclose(aux_before, aux_after), \
            f"{name}: aux head got no gradient"
