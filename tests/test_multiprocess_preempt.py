"""Cross-host preemption agreement: SIGTERM delivered to only ONE of two
processes must stop BOTH at the same epoch boundary (runtime.any_process),
with clean exits — a lone host breaking out alone would deadlock the other
in the next collective.  This is the multi-host half of the graceful
shutdown story (tests/test_preemption.py covers single-process)."""

import json
import os
import signal
import sys

import pytest

from tests._subproc import (REPO, free_port, launch_logged,
                            wait_for_epoch_line)

# subprocess worlds / full CLI chains: the slow tier (scripts/gate.sh runs -m 'not slow')
pytestmark = pytest.mark.slow

CHILD = os.path.join(REPO, "tests", "_mp_preempt_child.py")


def test_single_host_signal_stops_all_hosts(tmp_path):
    tmp = str(tmp_path)
    port = free_port()
    child_logs = [os.path.join(tmp, f"child{r}.txt") for r in range(2)]
    procs = [launch_logged(
        [sys.executable, CHILD, "--coord", f"localhost:{port}",
         "--nproc", "2", "--pid", str(r), "--rsl", tmp,
         "--out", os.path.join(tmp, f"out{r}.json")],
        child_logs[r]) for r in range(2)]
    try:
        # wait for at least one completed epoch on the main host
        log = os.path.join(tmp, "rank0", "test.log")
        wait_for_epoch_line(log, procs, proc_logs=child_logs)

        # preempt ONLY rank 1; rank 0 must stop too, via the agreement
        procs[1].send_signal(signal.SIGTERM)
        for p in procs:
            p.wait(timeout=300)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    for r, p in enumerate(procs):
        assert p.returncode == 0, \
            f"rank {r}:\n{open(child_logs[r]).read()[-3000:]}"
    results = [json.load(open(os.path.join(tmp, f"out{r}.json")))
               for r in range(2)]
    # both stopped early, at the SAME epoch, and report preemption
    assert results[0]["epochs"] == results[1]["epochs"], results
    assert results[0]["epochs"] < 100, results
    assert results[0]["preempted"] and results[1]["preempted"], results
    assert "preempted after epoch" in open(log).read()
