"""Gradient accumulation (--grad-accum K): a K-microbatch accumulated step
must equal the single big-batch step — not approximately, but to float
tolerance, because grads of the loss NUMERATOR are accumulated and scaled
by the total denominator once (engine._train_step_accum).  ABSENT in the
reference (SURVEY §2 parallelism checklist: no accumulation, no AMP)."""

import jax
import numpy as np
import pytest

from distributedpytorch_tpu.cli import run_train
from distributedpytorch_tpu.config import Config
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.train.engine import Engine, make_optimizer


def _engine(loss, grad_accum, model="cnn", optimizer="SGD"):
    # SGD for the equivalence check: its update is linear in the gradient,
    # so float-level grad equality shows through.  (Adam's first-step
    # g/(sqrt(v)+eps) normalization amplifies fp noise on near-zero
    # gradients into sign flips — a property of Adam, not of accumulation.)
    tx = make_optimizer(optimizer, 1e-3, 0.9, 0.1, steps_per_epoch=4,
                        feature_extract=False)
    from distributedpytorch_tpu.models import get_model

    weights = (np.linspace(0.5, 1.5, 10).astype(np.float32)
               if loss == "weighted_cross_entropy" else None)
    m = get_model(model, 10, half_precision=False)
    return Engine(m, model, get_loss_fn(loss, weights), tx, mean=0.45,
                  std=0.2, input_size=28, half_precision=False,
                  grad_accum=grad_accum)


def _batch(b=16, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(b, 28, 28), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(b,)).astype(np.int32)
    valid = np.ones(b, dtype=bool)
    valid[-3:] = False  # uneven masking across microbatches
    return images, labels, valid


@pytest.mark.parametrize("loss", ["cross_entropy", "weighted_cross_entropy",
                                  "focal_loss"])
def test_accumulated_step_equals_big_batch_step(loss):
    images, labels, valid = _batch()
    key = jax.random.PRNGKey(3)

    e1 = _engine(loss, grad_accum=1)
    e4 = _engine(loss, grad_accum=4)
    s1 = e1.init_state(jax.random.PRNGKey(0))
    s4 = e4.init_state(jax.random.PRNGKey(0))

    s1, m1 = e1.train_step(s1, images, labels, valid, key)
    s4, m4 = e4.train_step(s4, images, labels, valid, key)

    np.testing.assert_allclose(float(m4["loss"]), float(m1["loss"]),
                               rtol=1e-5)
    assert float(m4["correct"]) == float(m1["correct"])
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s4.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_indivisible_microbatch_raises():
    e = _engine("cross_entropy", grad_accum=5)
    s = e.init_state(jax.random.PRNGKey(0))
    images, labels, valid = _batch(b=16)
    with pytest.raises(ValueError, match="not divisible"):
        e.train_step(s, images, labels, valid, jax.random.PRNGKey(1))


def test_grad_accum_cli_e2e(tmp_path):
    cfg = Config(action="train", data_path="/tmp/nodata",
                 rsl_path=str(tmp_path), dataset="synthetic",
                 model_name="mlp", batch_size=8, nb_epochs=1, debug=True,
                 half_precision=False, grad_accum=2)
    result = run_train(cfg)
    assert np.isfinite(result["history"][0]["train_loss"])


def test_grad_accum_must_divide_batch(tmp_path):
    cfg = Config(action="train", data_path="/x", rsl_path=str(tmp_path),
                 batch_size=8, grad_accum=3)
    with pytest.raises(ValueError, match="grad-accum"):
        run_train(cfg)


def test_grad_accum_with_dropout_model():
    """Dropout architectures accumulate too (per-microbatch dropout keys):
    finite loss, params move."""
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 4, False)
    from distributedpytorch_tpu.models import get_model

    m = get_model("alexnet", 10, half_precision=False)
    e = Engine(m, "alexnet", get_loss_fn("cross_entropy"), tx, mean=0.45,
               std=0.2, input_size=64, half_precision=False, grad_accum=2)
    s = e.init_state(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(4, 64, 64), dtype=np.uint8)
    labels = rng.integers(0, 10, size=(4,)).astype(np.int32)
    before = jax.tree_util.tree_leaves(jax.device_get(s.params))
    s, metrics = e.train_step(s, images, labels, np.ones(4, bool),
                              jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))
    after = jax.tree_util.tree_leaves(jax.device_get(s.params))
    assert any(not np.allclose(a, b) for a, b in zip(before, after))
